#!/usr/bin/env python
"""shardcheck: the repo's static sharding-analysis gate (CI-runnable).

Three passes over the codebase, all pre-run (nothing executes a train or
serve step; the contract/jaxpr passes COMPILE entry points on an 8-device
emulated mesh, the AST pass only reads source):

* ``contracts`` — compile every registered jitted entry point
  (``analysis/entrypoints.py``: train step, ZeRO-1 update, serving
  prefill/decode, MoE dispatch, ring/Ulysses attention) and diff its
  collective inventory against the golden contracts in
  ``analysis/golden/*.json``. Catches: a new/missing collective per
  (op, mesh-axis) group, oversized wire buffers, collectives inside
  while bodies, oversized replicated constants.
* ``jaxpr``     — jaxpr + donation lint over the train-shaped entry
  points: silent f32 promotions in bf16 graphs, dead equations, and
  donations requested-but-dropped / eligible-but-never-requested
  (annotated with ``utils.memory.memory_plan`` bytes at stake).
* ``ast``       — repo-wide source lint (jit-in-loop, non-hashable
  static args, closure-captured device arrays, raw unsynced clocks,
  host syncs inside engine hot loops) under the
  ``analysis/baseline.json`` suppression budget.
* ``shardflow`` — the PRE-COMPILE pass: simulate GSPMD propagation over
  every entry point's jaxpr (``analysis/shardflow.py``), reconcile the
  predicted collective multiset against the same goldens the contract
  pass diffs, and price a roofline step time (``analysis/costmodel.py``).
  A compiled collective no predicted event explains is a gated
  ``unexplained-collective`` finding; ``--explain`` renders the
  per-source-line "why this collective exists" report.

``--memory`` adds the memflow pass (``analysis/memflow.py``): a
jaxpr-level liveness walk predicts per-device peak HBM per searchable
entry point (sharding-, donation- and scan-aware), reconciles it
against ``compiled.memory_analysis()`` under the tolerances pinned in
``analysis/baseline.json`` (``memflow_tolerance_pct``), and GATES
peaks over ``--memory-budget-bytes x --headroom`` — OOM as a
pre-compile review finding at the peak-owning buffer's source line.

``--comm`` adds the commscope pass (``telemetry/commscope.py``): time a
reduced calibration ladder of micro-collectives on the emulated mesh,
fit per-axis α–β link profiles (gated against ``baseline.json``'s
``commscope_tolerance_pct``), and print every entry point's per-line
predicted collective seconds under the pinned table NEXT TO the
measured profile — the static/measured reconciliation for comm cost.
On emulated-CPU hosts the "links" are memcpys, so the fit measures
host memory bandwidth; the reconciliation still gates.

``--topo`` adds the topology pass (``analysis/topology.py``): load the
checked-in two-tier (ICI|DCN) interconnect profile for this platform
and mesh (``analysis/profiles/topology_*.json``; calibrated live from
a reduced commscope ladder when absent), re-price every searchable
entry point under tier-correct α–β with the overlap-aware combination
(``max(compute, memory) + exposed comm``), reconcile against MEASURED
step seconds under ``baseline.json``'s ``topo_tolerance_pct``, and
gate ``unexplained-cross-tier-bytes`` — golden-contract collectives
crossing a DCN boundary the static model didn't predict, under the
per-entry ``topo_byte_slack``. Opt-in like ``--comm``: it times real
dispatches and pays one jit compile per entry point.

``--timings`` prints the per-program-family wall-clock breakdown
(train / zero1 / serving / engine / kv / reshard / ops), so the next
budget creep is attributable to a family instead of re-justified blind.

``--optimize`` adds the ADVISORY layout-search pass
(``analysis/layout_search.py``): for each train-shaped entry point it
searches the sharding space abstractly (no compiles) and reports when a
candidate layout prices >= ``--optimize-threshold`` percent cheaper than
the committed one. Advisories never gate the exit code — a cheaper
layout is a proposal to review with ``scripts/layout_search.py``, not a
regression.

Regenerating goldens after an INTENDED sharding change::

    python scripts/shardcheck.py --update-golden          # all entry points
    python scripts/shardcheck.py --update-golden --only train_step

then review the JSON diff like any other code change — the diff IS the
communication-pattern review.

The full run carries a WALL-TIME BUDGET (``--budget-seconds``, default
260): PERF.md shows pass creep of 38 s (round 8) -> 67 s (round 9) ->
117 s (round 13, entry points having grown 12 -> 22) -> 167 s
(round 17, the round-16 multi-step program families having landed
without a re-time) -> 239 s (round 22, the four ``*_q8`` compressed
entry points adding ~41 s of compiles); the budget is re-justified
against the measured wall each time it moves (PERF.md rounds 13, 17
and 22) and CI fails before shardcheck can eat the tier-1 window.

Exit codes: 0 clean, 1 findings, 2 infrastructure error. Findings also
land in the process flight recorder / a fresh registry and are written
as ``shardcheck.json`` under ``$LJST_ARTIFACT_DIR`` (when set), so the
static verdicts ride the same diagnosis surfaces as PR-2's runtime
layer.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

_REPO = pathlib.Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from learning_jax_sharding_tpu.parallel import force_emulated_devices  # noqa: E402

PASSES = ("contracts", "jaxpr", "ast", "shardflow")

#: Opt-in passes selectable with --pass but not part of the default
#: (budgeted) full run.
EXTRA_PASSES = ("memory", "comm", "topo")


def _family(name: str) -> str:
    """Program family for the --timings breakdown. spec_/adapter_
    variants time with their base program — they are the same family's
    compile cost, scaled."""
    base = name
    while True:
        for pre in ("spec_", "adapter_"):
            if base.startswith(pre):
                base = base[len(pre):]
                break
        else:
            break
    if base.startswith("train_step"):
        return "train"
    if base.startswith("zero1"):
        return "zero1"
    if base in ("first_prefill", "prefill", "decode_step"):
        return "serving"
    if base.endswith(("mixed_step", "multi_step")):
        return "engine"
    if base.startswith("kv_"):
        return "kv"
    if base.startswith("swap_"):
        return "reshard"
    return "ops"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--pass", dest="passes", action="append",
        choices=PASSES + EXTRA_PASSES,
        help="run only this pass (repeatable; default: all four — "
        "'memory' only via --memory or an explicit --pass memory)",
    )
    ap.add_argument(
        "--update-golden", action="store_true",
        help="(re)write analysis/golden/*.json from the current "
        "compilations instead of checking — review the diff",
    )
    ap.add_argument("--only", action="append", metavar="ENTRY",
                    help="restrict contract/jaxpr passes to this entry "
                    "point (repeatable)")
    ap.add_argument("--golden-dir", default=None,
                    help="golden contract directory "
                    "(default: analysis/golden)")
    ap.add_argument("--baseline", default=None,
                    help="AST suppression file "
                    "(default: analysis/baseline.json)")
    ap.add_argument("--devices", type=int, default=8,
                    help="emulated device count for the compile passes")
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument(
        "--explain", action="store_true",
        help="run the shardflow pass and print the per-source-line "
        "collective attribution + priced roofline per entry point",
    )
    ap.add_argument(
        "--budget-seconds", type=float, default=260.0,
        help="wall-time budget for the full multi-pass run; exceeding "
        "it is itself a gated finding (0 disables)",
    )
    ap.add_argument(
        "--memory", action="store_true",
        help="also run the memflow pass: per-entry-point predicted "
        "per-device peak HBM, reconciled against "
        "compiled.memory_analysis() and gated against the HBM budget",
    )
    ap.add_argument(
        "--comm", action="store_true",
        help="also run the commscope pass: time a reduced calibration "
        "ladder on the emulated mesh, fit per-axis α–β link profiles "
        "gated against baseline.json's commscope_tolerance_pct, and "
        "print each entry point's per-line pinned-prediction vs "
        "measured-profile collective seconds (opt-in — the ladder "
        "times real dispatches, so it stays out of the budgeted run)",
    )
    ap.add_argument(
        "--topo", action="store_true",
        help="also run the topology pass: re-price every searchable "
        "entry point under the two-tier ICI|DCN profile with the "
        "overlap-aware combination, reconcile against measured step "
        "seconds under baseline.json's topo_tolerance_pct, and gate "
        "unexplained-cross-tier-bytes (opt-in — it times real "
        "dispatches, so it stays out of the budgeted run)",
    )
    ap.add_argument(
        "--memory-budget-bytes", type=float, default=None,
        help="per-device HBM budget for the memflow pass (default: "
        "utils.memory.device_hbm_bytes(), which is None on emulated-CPU "
        "hosts — then only the reconciliation gates)",
    )
    ap.add_argument(
        "--headroom", type=float, default=0.8,
        help="fraction of the HBM budget a predicted peak may use "
        "before the memflow pass fails it (default 0.8)",
    )
    ap.add_argument(
        "--timings", action="store_true",
        help="print the per-program-family wall-clock breakdown and "
        "include program/family seconds in the JSON doc",
    )
    ap.add_argument(
        "--optimize", action="store_true",
        help="also run the layout search (analysis/layout_search.py) "
        "over the train-shaped entry points and REPORT when it finds a "
        "layout priced cheaper than the committed one — advisory only, "
        "never gates the exit code",
    )
    ap.add_argument(
        "--optimize-budget", type=int, default=32,
        help="candidate-evaluation budget per entry for --optimize "
        "(default 32 — sized so the full run stays inside "
        "--budget-seconds)",
    )
    ap.add_argument(
        "--optimize-threshold", type=float, default=5.0,
        help="report a layout-search win only when the priced gap is "
        ">= this percent (default 5)",
    )
    args = ap.parse_args(argv)

    passes = tuple(dict.fromkeys(args.passes)) if args.passes else PASSES
    if args.explain and "shardflow" not in passes:
        passes = passes + ("shardflow",)
    if args.memory and "memory" not in passes:
        passes = passes + ("memory",)
    if args.comm and "comm" not in passes:
        passes = passes + ("comm",)
    if args.topo and "topo" not in passes:
        passes = passes + ("topo",)
    needs_mesh = args.update_golden or args.optimize or (
        {"contracts", "jaxpr", "shardflow", "memory", "comm", "topo"}
        & set(passes)
    )
    if needs_mesh:
        try:
            force_emulated_devices(args.devices)
        except RuntimeError as e:  # backend already initialized differently
            print(f"shardcheck: {e}", file=sys.stderr)
            return 2

    from learning_jax_sharding_tpu.analysis import (
        BASELINE_PATH,
        GOLDEN_DIR,
        report_findings,
        run_ast_pass,
        run_comm_pass,
        run_contract_pass,
        run_jaxpr_pass,
        run_memflow_pass,
        run_shardflow_pass,
        run_topo_pass,
    )
    from learning_jax_sharding_tpu.analysis.findings import Finding
    from learning_jax_sharding_tpu.telemetry import MetricsRegistry
    from learning_jax_sharding_tpu.telemetry.flight_recorder import (
        artifact_dir,
        default_flight_recorder,
    )

    golden_dir = pathlib.Path(args.golden_dir or GOLDEN_DIR)
    baseline = pathlib.Path(args.baseline or BASELINE_PATH)

    if args.update_golden:
        from learning_jax_sharding_tpu.analysis.entrypoints import (
            build_entry_programs,
        )

        t0 = time.perf_counter()
        run_contract_pass(golden_dir, names=args.only, update=True)
        # Only the REGENERATED goldens — the operator is about to review
        # the JSON diff, and listing untouched contracts as written would
        # misstate what changed. (Program construction is lazy: building
        # the name list compiles nothing.)
        wrote = sorted(
            f"{p.name}.json" for p in build_entry_programs(args.only)
        )
        print(f"shardcheck: wrote goldens to {golden_dir} "
              f"({time.perf_counter() - t0:.1f}s): {wrote}")
        return 0

    # One entry-program list shared by the compile passes: their
    # per-program caches hold each built state/step and its single AOT
    # compile, so contracts + jaxpr don't pay the compiles twice.
    programs = None
    if {"contracts", "jaxpr", "shardflow", "comm"} & set(passes):
        from learning_jax_sharding_tpu.analysis.entrypoints import (
            build_entry_programs,
        )

        programs = build_entry_programs(args.only)

    t0 = time.perf_counter()
    findings = []
    timings: dict[str, float] = {}
    # Per-program wall-clock across all passes, for the --timings
    # family breakdown (always collected — two clock reads per program).
    program_seconds: dict[str, float] = {}
    shardflow_reports: list[dict] = []
    memory_reports: list[dict] = []
    comm_report: dict = {}
    topo_report: dict = {}
    for name in passes:
        tp = time.perf_counter()
        if name == "contracts":
            findings += run_contract_pass(
                golden_dir, names=args.only, programs=programs,
                baseline=baseline, program_seconds=program_seconds,
            )
        elif name == "jaxpr":
            findings += run_jaxpr_pass(
                names=args.only, baseline=baseline, programs=programs,
                program_seconds=program_seconds,
            )
        elif name == "shardflow":
            sf_findings, shardflow_reports = run_shardflow_pass(
                golden_dir, names=args.only, programs=programs,
                explain=args.explain,
                program_seconds=program_seconds,
            )
            findings += sf_findings
        elif name == "memory":
            mf_findings, memory_reports = run_memflow_pass(
                names=args.only, baseline=baseline,
                budget_bytes=args.memory_budget_bytes,
                headroom=args.headroom,
                program_seconds=program_seconds,
            )
            findings += mf_findings
        elif name == "comm":
            cm_findings, comm_report = run_comm_pass(
                names=args.only, baseline=baseline, programs=programs,
                program_seconds=program_seconds,
            )
            findings += cm_findings
        elif name == "topo":
            tp_findings, topo_report = run_topo_pass(
                names=args.only, baseline=baseline,
                golden_dir=golden_dir,
                program_seconds=program_seconds,
            )
            findings += tp_findings
        else:
            findings += run_ast_pass(_REPO, baseline=baseline)
        timings[name] = time.perf_counter() - tp

    # --optimize: the layout-search advisory pass. Kept OUT of the
    # gating findings list — a cheaper-priced layout is a suggestion to
    # review, not a regression (the committed layout still satisfies its
    # golden contract, or the contracts pass would have said so).
    advisories: list[dict] = []
    if args.optimize:
        from learning_jax_sharding_tpu.analysis import costmodel
        from learning_jax_sharding_tpu.analysis.entrypoints import (
            SEARCHABLE_ENTRIES,
        )
        from learning_jax_sharding_tpu.analysis.layout_search import (
            search_entry,
        )

        tp = time.perf_counter()
        entries = ("train_step", "zero1_update")
        if args.only:
            entries = tuple(
                e for e in args.only if e in SEARCHABLE_ENTRIES
            )
        profile = costmodel.table_profile("TPU v5 lite")
        for entry in entries:
            res = search_entry(
                entry, budget=args.optimize_budget, profile=profile
            )
            if res.gap_pct >= args.optimize_threshold and res.changed:
                advisories.append({
                    "entry": entry,
                    "gap_pct": round(res.gap_pct, 2),
                    "baseline_ms": round(
                        res.baseline.predicted_s * 1e3, 4
                    ),
                    "best_ms": round(res.best.predicted_s * 1e3, 4),
                    "evaluated": res.evaluated,
                    "pruned": res.pruned,
                    "changed": res.changed_lines(),
                })
        timings["optimize"] = time.perf_counter() - tp
    wall = time.perf_counter() - t0

    # Satellite: the CI wall-time budget. Only a FULL run is comparable
    # to the budget (a --pass/--only subset is always under it), and the
    # opt-in extra passes don't count against it — each one times real
    # dispatches (memory compiles, the comm ladder, the topo reconcile),
    # which is exactly why they're opt-in rather than part of the
    # budgeted compile-only window.
    extra_s = sum(timings.get(p, 0.0) for p in EXTRA_PASSES)
    budget_wall = wall - extra_s
    full_run = set(PASSES) <= set(passes) and not args.only
    if full_run and args.budget_seconds and budget_wall > args.budget_seconds:
        findings.append(Finding(
            "perf", "shardcheck-budget", "scripts/shardcheck.py",
            f"full shardcheck run took {budget_wall:.1f}s outside the "
            f"opt-in passes, over the "
            f"{args.budget_seconds:.0f}s CI budget — the compile passes "
            "crept past the tier-1 window (trim entry points, share "
            "more compiles, or re-justify the budget in PERF.md)",
            data={"wall_seconds": round(wall, 2),
                  "budgeted_wall_seconds": round(budget_wall, 2),
                  "budget_seconds": args.budget_seconds},
        ))

    registry = MetricsRegistry()
    report_findings(
        findings, recorder=default_flight_recorder(), registry=registry
    )
    doc = {
        "passes": list(passes),
        "wall_seconds": round(wall, 2),
        "pass_seconds": {k: round(v, 2) for k, v in timings.items()},
        "findings": [f.to_dict() for f in findings],
    }
    if shardflow_reports:
        doc["shardflow"] = shardflow_reports
    if memory_reports:
        doc["memory"] = memory_reports
    if comm_report:
        doc["comm"] = comm_report
    if topo_report:
        doc["topo"] = topo_report
    if args.optimize:
        doc["optimize"] = advisories
    family_seconds: dict[str, float] = {}
    for pname, secs in program_seconds.items():
        fam = _family(pname)
        family_seconds[fam] = family_seconds.get(fam, 0.0) + secs
    if args.timings:
        doc["program_seconds"] = {
            k: round(v, 2) for k, v in program_seconds.items()
        }
        doc["family_seconds"] = {
            k: round(v, 2) for k, v in family_seconds.items()
        }
    import os

    if os.environ.get("LJST_ARTIFACT_DIR"):
        adir = artifact_dir("shardcheck")
        (adir / "shardcheck.json").write_text(json.dumps(doc, indent=2))
        if comm_report:
            # The fitted profile stands alone too, loadable back through
            # CommProfile.load for reuse outside this run.
            (adir / "comm_profile.json").write_text(
                json.dumps(comm_report["profile"], indent=2,
                           sort_keys=True) + "\n")
        if topo_report:
            # Same standalone-reuse contract: loadable back through
            # TopologyProfile.load.
            (adir / "topology_profile.json").write_text(
                json.dumps(topo_report["topology"], indent=2,
                           sort_keys=True) + "\n")
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        if args.explain:
            for rep in shardflow_reports:
                cost = rep["cost"]
                rec = rep["reconcile"]
                print(f"== {rep['name']} — predicted "
                      f"{cost['predicted_s'] * 1e3:.3f} ms "
                      f"({cost['bound']}-bound, "
                      f"{cost['flops'] / 1e9:.2f} GFLOP, "
                      f"{cost['hbm_bytes'] / 1e6:.1f} MB HBM, "
                      f"{cost['wire_bytes'] / 1e6:.2f} MB wire) — "
                      f"{rec['matched']}/{rec['actual_total']} compiled "
                      f"collectives explained, "
                      f"{sum(rec['elided'].values())} predicted elided "
                      "by XLA")
                text = rep.get("explanation")
                if text:
                    print(text)
        for rep in memory_reports:
            r = rep["report"]
            rc = rep["reconciled"]
            line = (f"[memory] {rep['name']}: predicted peak "
                    f"{r['peak_mib']:.2f} MiB/device at {r['peak_where']}")
            if rc.get("measured_bytes") is not None:
                line += (f" — XLA measures "
                         f"{rc['measured_bytes'] / 2**20:.2f} MiB "
                         f"({rc['signed_err_pct']:+.1f}%)")
            print(line)
        if comm_report:
            for axis, ap in sorted(comm_report["profile"]["axes"].items()):
                err = comm_report["fit_errors_pct"].get(axis, 0.0)
                print(f"[comm] axis {axis} (n={ap['n_devices']}): "
                      f"alpha {ap['alpha_s'] * 1e6:.1f} us, "
                      f"beta {ap['beta_bytes_per_s'] / 1e9:.2f} GB/s "
                      f"(r2 {ap['r2']:.3f}, worst fit err {err:.1f}%)")
            for pr in comm_report["programs"]:
                print(f"[comm] {pr['name']}: predicted comm "
                      f"{pr['pinned_s'] * 1e3:.3f} ms pinned-table vs "
                      f"{pr['measured_s'] * 1e3:.3f} ms measured-profile")
                for ln in pr["lines"][:5]:
                    print(f"[comm]   {ln['where']}: "
                          f"{ln['pinned_s'] * 1e3:.3f} -> "
                          f"{ln['measured_s'] * 1e3:.3f} ms")
        if topo_report:
            tiers = {
                ax["axis"]: ax["tier"]
                for ax in topo_report["topology"]["axes"]
            }
            print(f"[topo] profile {topo_report['topology']['name']}: "
                  + ", ".join(f"{a}={t}" for a, t in sorted(tiers.items()))
                  + f" (ici domain = "
                  f"{topo_report['topology']['ici_domain_devices']} devs)")
            for pr in topo_report["programs"]:
                r = pr["realized"]["realized_overlap_ratio"]
                print(f"[topo] {pr['name']}: measured "
                      f"{pr['measured_s'] * 1e3:.2f} ms vs overlap-aware "
                      f"{pr['topo_predicted_s'] * 1e3:.2f} ms "
                      f"({pr['err_topo_pct']:+.1f}% err; serial-sum "
                      f"{pr['err_serial_pct']:+.1f}%), dcn "
                      f"{pr['dcn_bytes'] / 1e6:.2f} MB predicted / "
                      f"{pr['observed_dcn_bytes'] / 1e6:.2f} MB contract"
                      + (f", realized overlap {r:.2f}"
                         if r is not None else ""))
        if args.timings:
            attributed = sum(family_seconds.values())
            print(f"[timings] {attributed:.1f}s of {wall:.1f}s wall "
                  "attributed to entry programs; per family:")
            for fam, secs in sorted(family_seconds.items(),
                                    key=lambda kv: -kv[1]):
                n = sum(1 for p in program_seconds if _family(p) == fam)
                print(f"[timings]   {fam:<8} {secs:6.1f}s "
                      f"across {n} program(s)")
        for adv in advisories:
            print(f"[advisory] layout-search: {adv['entry']} has a "
                  f"layout priced {adv['gap_pct']:.1f}% cheaper "
                  f"({adv['baseline_ms']:.3f} -> {adv['best_ms']:.3f} ms "
                  f"predicted) — run `python scripts/layout_search.py "
                  f"--entry {adv['entry']}` for the full proposal")
        for f in findings:
            print(f)
        print(f"shardcheck: {len(findings)} finding(s) across "
              f"{'+'.join(passes)} in {wall:.1f}s "
              f"({', '.join(f'{k} {v:.1f}s' for k, v in timings.items())})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
