"""Speculative decoding with a TRAINED draft/target pair: the realistic
midpoint of the round-4 ladder.

`scripts/perf_serving2.py` bracketed the engine's speculative mechanism
with random-init weights (self-draft ceiling 1.58×, random-draft floor
0.58×) because a random draft never agrees with a random target. This
script produces the missing REAL point: train a small BPE LM target and a
4× smaller draft on the same corpus with the framework's own `fit()`,
then measure actual acceptance and throughput — generate-level (ragged,
per-row stats) and engine-level — in one process.

Run from /root/repo:  python - < scripts/perf_spec_trained.py
"""
import tempfile
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from learning_jax_sharding_tpu.data import MemmapTokenDataset, write_token_file
from learning_jax_sharding_tpu.data.tokenizer import BPETokenizer
from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.speculative import (
    make_speculative_generate_fn,
)
from learning_jax_sharding_tpu.models.transformer import TransformerConfig
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit
from learning_jax_sharding_tpu.utils.bench import time_fn

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
    "sphinx of black quartz, judge my vow. "
) * 150

SEQ = 64
TARGET = TransformerConfig(
    vocab_size=384, num_layers=4, features=256, num_heads=4, head_dim=64,
    rope=True, hidden=1024, max_seq_len=SEQ * 8,
    dtype=np.float32, param_dtype=np.float32,
)
DRAFT = TransformerConfig(
    vocab_size=384, num_layers=1, features=128, num_heads=4, head_dim=32,
    rope=True, hidden=256, max_seq_len=SEQ * 8,
    dtype=np.float32, param_dtype=np.float32,
)

mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
tok = BPETokenizer.train(CORPUS, vocab_size=TARGET.vocab_size)
tokens = tok.encode_to_array(CORPUS)
print(f"[spec-t] corpus {len(tokens)} BPE tokens", flush=True)

with tempfile.TemporaryDirectory() as tmp:
    path = write_token_file(Path(tmp) / "corpus.bin", tokens)
    data = MemmapTokenDataset(path, seq_len=SEQ)

    def train(cfg, steps, label):
        from learning_jax_sharding_tpu.models.transformer import Transformer

        t0 = time.perf_counter()
        state, hist = fit(
            Transformer(cfg), data, mesh, RULES_DP_TP,
            TrainLoopConfig(
                steps=steps, global_batch_size=16, learning_rate=1e-3,
                # Log exactly once (the final step): hist must be non-empty
                # for the loss print, without flooding the perf output.
                log_every=steps,
            ),
        )
        print(
            f"[spec-t] {label}: {steps} steps in "
            f"{time.perf_counter() - t0:.0f}s, final loss "
            f"{hist[-1]['loss']:.3f}",
            flush=True,
        )
        return state.params

    t_params = train(TARGET, 400, "target 4L x 256")
    d_params = train(DRAFT, 300, "draft 1L x 128")
    # An UNDER-trained draft gives the partial-acceptance point between
    # perf_serving2's random floor and the converged pair below.
    d_weak = train(DRAFT, 30, "weak draft 1L x 128 (30 steps)")

# Skewed prompt batch: corpus snippets at mixed lengths, right-padded.
rng = np.random.default_rng(0)
B, NEW, ND = 8, 64, 4
lens = rng.integers(8, 33, size=B)
starts = rng.integers(0, len(tokens) - 40, size=B)
maxlen = int(lens.max())
prompt = np.zeros((B, maxlen), np.int32)
for i, (st, ln) in enumerate(zip(starts, lens)):
    prompt[i, :ln] = tokens[st : st + ln]
lengths = jnp.asarray(lens, jnp.int32)

spec = make_speculative_generate_fn(
    TARGET, DRAFT, mesh, RULES_DP_TP, max_new_tokens=NEW, num_draft=ND,
    inference_dtype=jnp.bfloat16, ragged=True,
)
plain = make_generate_fn(
    TARGET, mesh, RULES_DP_TP, max_new_tokens=NEW,
    inference_dtype=jnp.bfloat16, ragged=True,
)

for tag, dp in (("converged", d_params), ("weak(30-step)", d_weak)):
    out, stats = spec(t_params, dp, prompt, lengths=lengths,
                      return_stats=True)
    acc = np.asarray(stats["accepted"], np.float64)
    rounds = np.asarray(stats["rounds"], np.float64)
    rate = acc / np.maximum(rounds * ND, 1)
    tpr = np.asarray(stats["emitted"], np.float64) / np.maximum(rounds, 1)
    print(
        f"[spec-t] {tag} draft acceptance per row: "
        f"{np.array2string(rate, precision=2)} (mean {rate.mean():.0%}); "
        f"mean tokens/round {tpr.mean():.2f}",
        flush=True,
    )

t_spec = time_fn(
    spec, t_params, d_params, prompt, lengths=lengths, min_time=2.0
)
t_plain = time_fn(plain, t_params, prompt, jax.random.key(0),
                  lengths=lengths, min_time=2.0)
print(
    f"[spec-t] ragged generate: plain {B * NEW / t_plain:,.0f} tok/s, "
    f"speculative {B * NEW / t_spec:,.0f} tok/s ({t_plain / t_spec:.2f}x)",
    flush=True,
)

# Engine-level: same trained pair through the continuous engine.
NREQ = 24
prompts = [
    tokens[int(s) : int(s) + int(n)].astype(np.int32)
    for s, n in zip(
        rng.integers(0, len(tokens) - 40, size=NREQ),
        rng.integers(8, 33, size=NREQ),
    )
]
common = dict(batch_size=8, max_new_tokens=NEW, refill_chunk=32,
              inference_dtype=jnp.bfloat16)
eng_plain = make_continuous_engine(TARGET, mesh, RULES_DP_TP, **common)
eng_spec = make_continuous_engine(
    TARGET, mesh, RULES_DP_TP, draft_config=DRAFT, num_draft=ND, **common
)
eng_plain_s = make_continuous_engine(
    TARGET, mesh, RULES_DP_TP, temperature=0.9, top_k=20, **common
)
eng_spec_s = make_continuous_engine(
    TARGET, mesh, RULES_DP_TP, draft_config=DRAFT, num_draft=ND,
    temperature=0.9, top_k=20, **common
)
for label, serve, kw in (
    ("plain engine", eng_plain, {}),
    ("speculative engine (trained draft)", eng_spec,
     {"draft_params": d_params}),
    ("speculative engine (weak draft)", eng_spec,
     {"draft_params": d_weak}),
    ("plain engine, sampled t=0.9", eng_plain_s, {}),
    ("speculative engine, SAMPLED t=0.9 (trained draft)", eng_spec_s,
     {"draft_params": d_params}),
):
    serve(t_params, prompts[:9], **kw)      # warm all executables
    t0 = time.perf_counter()
    outs = serve(t_params, prompts, **kw)
    dt = time.perf_counter() - t0
    toks = sum(len(o) - p.size for o, p in zip(outs, prompts))
    print(f"[spec-t] {label}: {toks / dt:,.0f} tok/s ({dt:.2f} s)",
          flush=True)

# SAMPLED acceptance is genuinely partial even for a converged pair
# (u·q < p rejects wherever the draft's distribution is off, not just
# where its argmax is) — the partial-acceptance point the greedy rows
# can't produce. Measured via the ragged generate's per-row stats.
spec_s = make_speculative_generate_fn(
    TARGET, DRAFT, mesh, RULES_DP_TP, max_new_tokens=NEW, num_draft=ND,
    temperature=0.9, top_k=20, inference_dtype=jnp.bfloat16, ragged=True,
)
_, stats = spec_s(t_params, d_params, prompt, jax.random.key(1),
                  lengths=lengths, return_stats=True)
acc = np.asarray(stats["accepted"], np.float64)
rounds = np.asarray(stats["rounds"], np.float64)
rate = acc / np.maximum(rounds * ND, 1)
print(
    f"[spec-t] SAMPLED acceptance per row (t=0.9, trained pair): "
    f"{np.array2string(rate, precision=2)} (mean {rate.mean():.0%})",
    flush=True,
)
