#!/usr/bin/env python
"""Longitudinal bench view: per-metric sparklines over BENCH_r*.json.

``bench_compare.py`` gates two adjacent rounds; this script shows the
WHOLE trajectory — every metric its pattern table can extract, one
unicode sparkline per metric across all recorded rounds, with the
first→last delta in the metric's own good/bad direction. The pattern
table (and so the set of tracked metrics) is imported from
``bench_compare.py`` — one source of truth, the history view can never
drift from the gate.

A metric absent from some rounds (benches come and go) renders a gap
(``·``) at those rounds; metrics seen in fewer than ``--min-rounds``
rounds are dropped (a one-round metric has no trajectory).

Usage:
    python scripts/bench_history.py [--repo DIR] [--filter SUBSTR]
                                    [--last N] [--min-rounds 2] [--json]

Exit codes: 0 ok, 2 fewer than two BENCH_r*.json found.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import pathlib
import re
import sys

_TICKS = "▁▂▃▄▅▆▇█"
_GAP = "·"


def _load_bench_compare():
    """Import bench_compare.py by file path (scripts/ is not a
    package) — its ``extract_metrics`` + ``_round_of`` are the single
    source of metric truth."""
    path = pathlib.Path(__file__).resolve().parent / "bench_compare.py"
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def sparkline(values: list[float | None]) -> str:
    """Eight-level unicode sparkline; ``None`` renders as a gap. A flat
    series sits mid-scale rather than dividing by zero."""
    present = [v for v in values if v is not None]
    if not present:
        return _GAP * len(values)
    lo, hi = min(present), max(present)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(_GAP)
        elif span <= 0:
            out.append(_TICKS[3])
        else:
            idx = int((v - lo) / span * (len(_TICKS) - 1))
            out.append(_TICKS[idx])
    return "".join(out)


def collect_history(repo: pathlib.Path, last: int | None = None):
    """``(rounds, {metric: {"values": [...], "higher": bool}})`` over
    the repo's BENCH_r*.json, oldest first."""
    bc = _load_bench_compare()
    paths = sorted(repo.glob("BENCH_r*.json"), key=bc._round_of)
    if last:
        paths = paths[-last:]
    rounds = [bc._round_of(p) for p in paths]
    series: dict[str, dict] = {}
    for i, p in enumerate(paths):
        doc = json.loads(p.read_text())
        for key, (val, higher) in bc.extract_metrics(doc).items():
            s = series.setdefault(
                key, {"values": [None] * len(paths), "higher": higher}
            )
            s["values"][i] = val
    return rounds, series


def render(rounds, series, *, min_rounds: int = 2) -> list[str]:
    lines = [
        f"bench_history: rounds r{rounds[0]:02d}..r{rounds[-1]:02d} "
        f"({len(rounds)} recorded)"
    ]
    for key in sorted(series):
        s = series[key]
        vals = [v for v in s["values"] if v is not None]
        if len(vals) < min_rounds:
            continue
        first, cur = vals[0], vals[-1]
        delta = (cur - first) / (abs(first) if first else 1.0)
        good = (delta >= 0) == s["higher"] or delta == 0
        tag = "ok" if good else "WORSE"
        arrow = "^" if delta > 0 else ("v" if delta < 0 else "=")
        lines.append(
            f"  {key:60s} {sparkline(s['values'])}  "
            f"{first:>12.3f} -> {cur:>12.3f}  "
            f"{arrow}{abs(delta) * 100.0:6.1f}%  {tag}"
        )
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=".",
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--filter", default=None,
                    help="only metrics whose name contains this substring")
    ap.add_argument("--last", type=int, default=None,
                    help="only the most recent N rounds")
    ap.add_argument("--min-rounds", type=int, default=2,
                    help="drop metrics seen in fewer rounds (default 2)")
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    repo = pathlib.Path(args.repo)
    rounds, series = collect_history(repo, last=args.last)
    if len(rounds) < 2:
        print(f"need >= 2 BENCH_r*.json in {repo}, found {len(rounds)}",
              file=sys.stderr)
        return 2
    if args.filter:
        pat = re.compile(re.escape(args.filter), re.I)
        series = {k: v for k, v in series.items() if pat.search(k)}

    if args.json:
        print(json.dumps({
            "rounds": rounds,
            "metrics": {
                k: {
                    "values": v["values"],
                    "higher_is_better": v["higher"],
                    "sparkline": sparkline(v["values"]),
                }
                for k, v in sorted(series.items())
                if sum(x is not None for x in v["values"])
                >= args.min_rounds
            },
        }, indent=2))
    else:
        for ln in render(rounds, series, min_rounds=args.min_rounds):
            print(ln)
    return 0


if __name__ == "__main__":
    sys.exit(main())
