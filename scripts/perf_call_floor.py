"""Per-pallas-call fixed cost on the v5e through this tunnel.

If ~15-20us/call, the 1.4B int4 decode story is 169 custom calls x floor,
and the fix is CALL COUNT (qkv fusion, whole-FF kernels), not VPU work.
"""
import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from learning_jax_sharding_tpu.utils.bench import time_fn

rng = np.random.default_rng(0)
CH = 64


def chained(fn_one, x0):
    def run(x):
        def body(i, x):
            out = fn_one(x)
            return x + (out[:, :1] * 1e-30).astype(x.dtype)
        return jax.lax.fori_loop(0, CH, body, x)
    return jax.jit(run), x0


def copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


x_small = jnp.asarray(rng.standard_normal((8, 128)), jnp.bfloat16)
noop = pl.pallas_call(
    copy_kernel, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
)
f, x0 = chained(lambda x: noop(x), x_small)
t = time_fn(f, x0, min_time=1.0) / CH
print(f"no-op pallas call: {t*1e6:.1f} us", flush=True)

# XLA elementwise of same size, chained — the non-custom-call control.
f, x0 = chained(lambda x: x * 1.0000001 + 0.0, x_small)
t = time_fn(f, x0, min_time=1.0) / CH
print(f"XLA elementwise chain step: {t*1e6:.1f} us", flush=True)

# Same REAL matmul work, pallas vs XLA, identical operands (8,2048)x(2048,8192).
K, N = 2048, 8192
w = jnp.asarray(rng.standard_normal((K, N)) * 0.02, jnp.bfloat16)
x = jnp.asarray(rng.standard_normal((8, K)), jnp.bfloat16)


def mm_kernel(x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


pmm = pl.pallas_call(
    mm_kernel,
    grid=(N // 512,),
    in_specs=[
        pl.BlockSpec((8, K), lambda j: (0, 0)),
        pl.BlockSpec((K, 512), lambda j: (0, j)),
    ],
    out_specs=pl.BlockSpec((8, 512), lambda j: (0, j)),
    out_shape=jax.ShapeDtypeStruct((8, N), jnp.bfloat16),
)
f, x0 = chained(lambda x: pmm(x, w), x)
t = time_fn(f, x0, min_time=1.0) / CH
print(f"pallas bf16 matmul call: {t*1e6:.1f} us", flush=True)
f, x0 = chained(lambda x: x @ w, x)
t = time_fn(f, x0, min_time=1.0) / CH
print(f"XLA    bf16 matmul step: {t*1e6:.1f} us", flush=True)

# Call-count scaling: one (8,2048)x(2048,8192) call vs four N=2048 calls.
def four_calls(x):
    outs = []
    for j in range(4):
        pj = pl.pallas_call(
            mm_kernel,
            grid=(4,),
            in_specs=[
                pl.BlockSpec((8, K), lambda j: (0, 0)),
                pl.BlockSpec((K, 512), lambda j: (0, j)),
            ],
            out_specs=pl.BlockSpec((8, 512), lambda j: (0, j)),
            out_shape=jax.ShapeDtypeStruct((8, 2048), jnp.bfloat16),
        )
        outs.append(pj(x, w[:, j * 2048 : (j + 1) * 2048]))
    return jnp.concatenate(outs, axis=1)

f, x0 = chained(four_calls, x)
t = time_fn(f, x0, min_time=1.0) / CH
print(f"4x pallas calls (same total work): {t*1e6:.1f} us", flush=True)
