"""Round-9 ablation: the mixed engine's token-budget ladder.

The fused ``mixed_step`` trades per-dispatch latency (what a decoding
row waits between its tokens) against refill throughput (how fast
queued prompts stream in): every dispatch advances all decode rows AND
up to ``token_budget - active`` refill tokens. This script records the
ladder that justifies the shipped default and the bench's tuned value:

1. the DECODE-ONLY floor — the staggered-arrival workload served with
   ``token_budget = batch_size`` (refill gets only what decode leaves,
   i.e. nothing while a full wave decodes): best possible ITL, worst
   queue wait;
2. the budget sweep — token_budget in {B, 64+B, 128+B, 256+B, inf};
3. the split-engine baseline (``mixed=False``) — the decode-stall
   regime the fused scheduler replaces.

Per rung: ITL p99, TTFT p50, queue-wait p50, tok/s, refill share, and
decode-stall share, from the engine's own telemetry. The staggered
16-arrival/20 req/s workload is bench.py's serving-latency headline.

Run from /root/repo:  python - < scripts/perf_mixed.py
"""
import dataclasses
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_125M,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

cfg = dataclasses.replace(
    CONFIG_125M, max_seq_len=1024, decode_attention="blocked"
)
mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
rng = np.random.default_rng(0)
model = Transformer(cfg)
probe = np.zeros((8, 64), np.int32)
params = nn.meta.unbox(
    jax.jit(lambda r, t: model.init({"params": r}, t))(
        jax.random.key(0), probe
    )["params"]
)
B, NEW, PLEN = 8, 32, 544
system = rng.integers(1, cfg.vocab_size, size=(512,)).astype(np.int32)
prompts = [
    np.concatenate(
        [system, rng.integers(1, cfg.vocab_size, size=(32,)).astype(np.int32)]
    )
    for _ in range(16)
]


def staggered(engine, gap=0.05):
    engine.decode_chain = 1
    engine.reset_stats()
    t0 = time.perf_counter()
    nxt = 0
    while engine.has_work() or nxt < len(prompts):
        while (
            nxt < len(prompts)
            and time.perf_counter() - t0 >= nxt * gap
        ):
            engine.add_request(prompts[nxt])
            nxt += 1
        engine.step(params)
    dt = time.perf_counter() - t0
    outs = engine.pop_finished()
    toks = sum(len(o) - PLEN for o in outs.values())
    lat = engine.latency_stats()
    return dict(
        itl_p99=lat["itl_p99"], ttft_p50=lat["ttft_p50"],
        queue_wait_p50=lat["queue_wait_p50"], tok_s=toks / dt,
        refill_share=lat["refill_frac"] or 0.0,
        stall_share=lat["decode_stall_share"] or 0.0,
    )


common = dict(
    batch_size=B, max_new_tokens=NEW, refill_chunk=64,
    inference_dtype=jnp.bfloat16, decode_block_steps=NEW,
)
BIG = 10**9   # effectively uncapped: the full-width refill regime
# In mixed mode decode_block_steps sizes only the PURE-DECODE fallback
# block (no refill to fuse), i.e. the tail's token-visibility gap — the
# K=8 rungs are the latency tuning bench.py ships.
rungs = [
    ("split engine (mixed=False)", dict()),
    (f"mixed, budget={B} (decode-only floor)", dict(mixed=True, token_budget=B)),
    (f"mixed, budget=64+{B}", dict(mixed=True, token_budget=64 + B)),
    (f"mixed, budget=128+{B}", dict(mixed=True, token_budget=128 + B)),
    (f"mixed, budget=128+{B}, tail K=8",
     dict(mixed=True, token_budget=128 + B, decode_block_steps=8)),
    (f"mixed, budget=256+{B}", dict(mixed=True, token_budget=256 + B)),
    ("mixed, budget=inf", dict(mixed=True, token_budget=BIG)),
]
print(f"{'variant':38s} {'ITL p99':>9s} {'TTFT p50':>9s} "
      f"{'wait p50':>9s} {'tok/s':>7s} {'refill':>7s} {'stall':>6s}")
for name, kw in rungs:
    serve = make_continuous_engine(cfg, mesh, RULES_DP_TP, **{**common, **kw})
    eng = serve.engine
    staggered(eng)              # warm every executable (compiles excluded)
    r = staggered(eng)
    print(
        f"{name:38s} {r['itl_p99'] * 1e3:7.1f}ms {r['ttft_p50'] * 1e3:7.0f}ms "
        f"{r['queue_wait_p50'] * 1e3:7.0f}ms {r['tok_s']:7.0f} "
        f"{r['refill_share']:6.0%} {r['stall_share']:5.0%}"
    )
