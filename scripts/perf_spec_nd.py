"""num_draft ladder at partial acceptance — the last speculative knob.

Extends ``perf_spec_partial2.py``: with the 125M-class trained pair and
TUNED dispatch granularity (K=64, chain=4; plain engine 1,694 tok/s),
sweep ``num_draft``. Measured (2026-08-01, PERF.md round 5):

    nd=1: acceptance 53%, 0.36x plain
    nd=2: acceptance 41%, 0.38x plain
    nd=4: acceptance 27%, 0.38x plain

Acceptance-per-proposal rises exactly as theory predicts as nd falls —
and the speedup does not move: the round cost is floor-bound per draft
TOKEN-STEP on this chip, so no num_draft rescues partial acceptance.
Speculation profits only near full acceptance; the lever is draft
QUALITY.

Run from /root/repo:  python - < scripts/perf_spec_nd.py
"""
import sysconfig, tempfile, time, dataclasses
from pathlib import Path
import jax, jax.numpy as jnp, numpy as np
from learning_jax_sharding_tpu.data import MemmapTokenDataset, write_token_file
from learning_jax_sharding_tpu.data.tokenizer import BPETokenizer
from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.transformer import Transformer, TransformerConfig
from learning_jax_sharding_tpu.ops.flash_attention import make_flash_attn_fn
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit

stdlib = Path(sysconfig.get_paths()["stdlib"])
texts, total = [], 0
for f in sorted(stdlib.glob("*.py")):
    try: t = f.read_text(errors="ignore")
    except OSError: continue
    texts.append(t); total += len(t)
    if total > 1_600_000: break
held_out = texts[-4:]
train_text = "\n".join(texts[:-4])
tok = BPETokenizer.train(train_text[:300_000], vocab_size=512)
tokens = tok.encode_to_array(train_text)
ho = tok.encode_to_array("\n".join(held_out))

mk = dict(vocab_size=512, rope=True, max_seq_len=512)
TARGET = TransformerConfig(num_layers=12, features=768, num_heads=12, head_dim=64,
                           hidden=3072, attn_fn=make_flash_attn_fn(), **mk)
DRAFT = TransformerConfig(num_layers=2, features=256, num_heads=4, head_dim=64,
                          hidden=1024, **mk)
mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
with tempfile.TemporaryDirectory() as tmp:
    data = MemmapTokenDataset(write_token_file(Path(tmp) / "c.bin", tokens), seq_len=128)
    def train(cfg, steps, label):
        t0 = time.perf_counter()
        state, hist = fit(Transformer(cfg), data, mesh, RULES_DP_TP,
                          TrainLoopConfig(steps=steps, global_batch_size=32,
                                          learning_rate=3e-4, log_every=steps))
        print(f"[nd] {label}: loss {hist[-1]['loss']:.3f} ({time.perf_counter()-t0:.0f}s)", flush=True)
        return state.params
    t_params = train(TARGET, 3000, "target 12Lx768")
    d_params = train(DRAFT, 3000, "draft 2Lx256")

rng = np.random.default_rng(0)
NREQ, NEW = 24, 64
prompts = [ho[int(s):int(s)+int(n)].astype(np.int32)
           for s, n in zip(rng.integers(0, len(ho)-40, size=NREQ),
                           rng.integers(12, 33, size=NREQ))]
t_serve = dataclasses.replace(TARGET, attn_fn=None)
d_serve = dataclasses.replace(DRAFT, attn_fn=None)
# Tuned dispatch granularity (round 5): K = max_new, chained refills.
common = dict(batch_size=8, max_new_tokens=NEW, refill_chunk=32,
              inference_dtype=jnp.bfloat16, decode_block_steps=NEW, decode_chain=4)

def run(label, serve, kw):
    serve(t_params, prompts[:9], **kw)
    t0 = time.perf_counter()
    outs = serve(t_params, prompts, **kw)
    dt = time.perf_counter() - t0
    toks = sum(len(o) - p.size for o, p in zip(outs, prompts))
    st = serve.last_stats or {}
    acc = st.get("spec_accept_rate")
    extra = f", acceptance {acc:.0%}" if acc is not None else ""
    print(f"[nd] {label}: {toks/dt:,.0f} tok/s ({dt:.2f} s){extra}", flush=True)
    return toks / dt

plain = make_continuous_engine(t_serve, mesh, RULES_DP_TP, **common)
base = run("plain engine (K=64, chain=4)", plain, {})
for nd in (1, 2, 4):
    eng = make_continuous_engine(t_serve, mesh, RULES_DP_TP,
                                 draft_config=d_serve, num_draft=nd, **common)
    r = run(f"speculative nd={nd}", eng, {"draft_params": d_params})
    print(f"[nd]   -> {r/base:.2f}x plain", flush=True)
