#!/usr/bin/env python
"""Regression gate over the bench trajectory: diff BENCH_r*.json rounds.

The driver appends one ``BENCH_r{N}.json`` per round — the headline JSON
line under ``"parsed"`` plus the full stderr context under ``"tail"``. This
script makes the trajectory MACHINE-CHECKABLE instead of eyeballed: it
extracts every named metric from the two most recent rounds (or any two
given explicitly), prints the per-metric % delta, and exits non-zero when
any metric regressed past the threshold in its OWN bad direction (tok/s,
TFLOP/s, MFU, MBU, agreement: lower is worse; ms/step, ms/token-step,
latency ms, seconds: higher is worse).

Usage:
    python scripts/bench_compare.py [--threshold 0.10] [--repo DIR] [--json]
    python scripts/bench_compare.py old.json new.json [--threshold 0.10]

The gate also cross-checks the NEW round's collective inventory (the
bench JSON line's ``telemetry.headline_collectives``) against the golden
SPMD contract ``analysis/golden/bench_headline.json`` (shardcheck's
declarative layer): a bench round whose headline executable suddenly
contains collectives the contract doesn't admit fails exactly like a
metric regression — communication drift IS a perf regression, it just
shows up in HLO before it shows up in tok/s. Rounds without a telemetry
block (pre-PR-1 rounds) skip the check with a note.

Exit codes: 0 clean, 1 regression past threshold or collective-inventory
drift, 2 not enough rounds.

Metrics that appear in only one round (benches come and go) are reported
as added/removed, never failed — the gate compares what is comparable.
The tunneled chip drifts ±30% across windows (PERF.md methodology), so
the default threshold is deliberately loose; tighten per-invocation when
comparing same-session runs.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys

#: (regex over one `[bench] name: ...` line tail, metric suffix,
#:  higher_is_better). Applied per line; the metric key is the bench line's
#: name plus the suffix, so every line's numbers stay distinct.
_PATTERNS: list[tuple[re.Pattern, str, bool]] = [
    (re.compile(r"([\d,.]+)\s*tok/s"), "tok_s", True),
    (re.compile(r"([\d.]+)\s*TFLOP/s/chip"), "tflops", True),
    (re.compile(r"(?<![-\w])MFU=([\d.]+)%"), "mfu_pct", True),
    (re.compile(r"activated-MFU=([\d.]+)%"), "act_mfu_pct", True),
    (re.compile(r"MBU=([\d.]+)%"), "mbu_pct", True),
    (re.compile(r"([\d.]+)\s*ms/step"), "ms_per_step", False),
    (re.compile(r"([\d.]+)\s*ms/token-step"), "ms_per_token", False),
    (re.compile(r"([\d.]+)\s*us/forward"), "us_per_forward", False),
    (re.compile(r"TTFT p50 ([\d.]+)\s*ms"), "ttft_p50_ms", False),
    (re.compile(r"p99 ([\d.]+)\s*ms"), "p99_ms", False),
    # Round-9 serving-latency gates: ITL p99 and queue wait are the
    # numbers the mixed engine exists to hold down; refill share and
    # decode-stall share regress UPWARD when decode re-stalls behind
    # refill — all four are direction-aware like every other metric.
    (re.compile(r"ITL p99 ([\d.]+)\s*ms"), "itl_p99_ms", False),
    (re.compile(r"queue wait p50 ([\d.]+)\s*ms"), "queue_wait_p50_ms",
     False),
    (re.compile(r"refill ([\d.]+)% of engine time"), "refill_share_pct",
     False),
    (re.compile(r"decode stalled ([\d.]+)%"), "decode_stall_share_pct",
     False),
    # Round-10 recovery-policy gates: with no faults injected the
    # tracked line must hold shed and deadline-miss at ~0 — a robustness
    # hook that starts shedding or missing TTLs under clean load IS a
    # latency regression, caught here before it ships.
    (re.compile(r"shed ([\d.]+)%"), "shed_rate_pct", False),
    (re.compile(r"deadline miss ([\d.]+)%"), "deadline_miss_pct", False),
    (re.compile(r"agreement vs plain: ([\d.]+)%"), "agreement_pct", True),
    # Round-11 fleet gates: the tracked fleet lines report AGGREGATE
    # throughput and router-side end-to-end tail latency per replica
    # count — both direction-aware (the generic tok/s pattern also
    # matches the aggregate number; these keep the fleet-specific names
    # stable even if the line's phrasing around them changes).
    (re.compile(r"aggregate ([\d,.]+)\s*tok/s"), "aggregate_tok_s", True),
    (re.compile(r"e2e p99 ([\d,.]+)\s*ms"), "e2e_p99_ms", False),
    # Round-12 tenancy gates: the hot-swap lines track the stall the
    # zero-downtime machinery exists to bound (stage → commit serve
    # gap, regresses UPWARD); the multi-LoRA lines track the fused
    # mixed-batch throughput, the serial solo baseline, and their ratio
    # — all higher-is-better (the ratio regressing means the per-row
    # adapter gather got more expensive relative to folded weights).
    (re.compile(r"swap stall p99 ([\d,.]+)\s*ms"), "swap_stall_p99_ms",
     False),
    # Round-13 shardflow gate: the cost model's predicted-vs-measured
    # step-time error per tracked line (bench.py's `[bench] shardflow
    # ...` lines). Lower is better — the error growing means the
    # propagation rules or the platform profile drifted from the real
    # machine, the analyzer's own regression signal.
    (re.compile(r"model err ([\d,.]+)%"), "predicted_vs_measured_pct",
     False),
    (re.compile(r"mixed ([\d,.]+)\s*tok/s"), "mixed_tok_s", True),
    (re.compile(r"solo ([\d,.]+)\s*tok/s"), "solo_tok_s", True),
    (re.compile(r"([\d.]+)x solo"), "vs_solo_ratio", True),
    # Round-14 goodput-ledger gates (bench.py's `[bench] goodput:` line):
    # host_share is the fraction of the engine's busy wall spent outside
    # the device bucket — THE number ROADMAP item 1 pushes down, so it
    # regresses UPWARD; goodput_ratio (roofline seconds over window
    # wall) regresses DOWNWARD; the telemetry self-overhead share must
    # stay pinned near zero (perf_goodput.py's <2% budget); the
    # trace-derived TTFT critical-path tails regress upward like every
    # latency metric.
    (re.compile(r"host_share ([\d,.]+)%"), "host_share_pct", False),
    (re.compile(r"goodput_ratio ([\d,.]+)%"), "goodput_ratio_pct", True),
    (re.compile(r"telemetry overhead ([\d,.]+)%"),
     "telemetry_overhead_pct", False),
    (re.compile(r"critical path p50 ([\d,.]+)\s*ms"), "ttft_cp_p50_ms",
     False),
    # Round-15 KV-economy gates (bench.py's `[bench] kv economy ...`
    # A/B lines): fleet TTFT p99 tracked explicitly (the generic `p99`
    # pattern predates comma grouping); the realized prefix-hit rate is
    # the placement-quality number (higher); the tier-miss rate counts
    # routing predictions admission could not realize — graceful
    # re-prefill, never a wrong token, but each one wasted a placement
    # (lower); kv moved is what the tier ladder pays the host/peer
    # buses per request — every byte is ledgered, fewer is cheaper
    # (lower).
    (re.compile(r"TTFT p99 ([\d,.]+)\s*ms"), "ttft_p99_ms", False),
    (re.compile(r"prefix hit ([\d,.]+)%"), "prefix_hit_rate_pct", True),
    (re.compile(r"tier miss ([\d,.]+)%"), "tier_miss_rate_pct", False),
    (re.compile(r"kv moved ([\d,.]+)\s*kB/req"),
     "kv_bytes_moved_per_req_kb", False),
    # Round-16 multi-step gates (bench.py's `[bench] multistep ...`
    # lines): steps/dispatch is engine iterations fused per host
    # round-trip — THE number the device-resident scheduler exists to
    # push up (1.0 means the host touched Python every token); it pairs
    # with host_share_pct above, which the same refactor pushes down.
    # Boundary-stall share is the fraction of engine busy time parked at
    # horizon boundaries waiting on the single sync + re-plan — the
    # async planner holds it down, so it regresses UPWARD.
    (re.compile(r"steps/dispatch ([\d,.]+)"), "steps_per_dispatch", True),
    (re.compile(r"boundary stall ([\d,.]+)%"), "boundary_stall_pct",
     False),
    # Round-17 layout-search gates (bench.py's `[bench] layout_search
    # ...` lines): `layout gap` is the priced searched-vs-hand gap — a
    # growing gap means the committed hand layouts drifted away from the
    # searchable optimum (down is better; 0 = hand layout already
    # argmin); `layout err` is the search-specific predicted-vs-measured
    # error on the two layouts it actually compiles (phrased distinctly
    # from the shardflow pass's `model err` so the two gates never
    # double-match one line).
    (re.compile(r"layout gap ([\d,.]+)%"), "layout_search_gap_pct",
     False),
    (re.compile(r"layout err ([\d,.]+)%"),
     "layout_predicted_vs_measured_pct", False),
    # Round-18 memflow gates (bench.py's `[bench] memflow ...` lines):
    # `memflow err` is the static liveness analyzer's per-entry
    # predicted-vs-measured peak-HBM error against XLA's
    # ``compiled.memory_analysis()`` — phrased distinctly from `model
    # err` (shardflow time) and `layout err` (layout search) so the
    # three analyzer gates never double-match one line. Lower is
    # better: the error growing means the liveness model (donation
    # credits, scan high-water, sharded buffer sizing) drifted from
    # what XLA actually allocates, which is the OOM-gate's accuracy.
    (re.compile(r"memflow err ([\d,.]+)%"),
     "memflow_predicted_vs_measured_pct", False),
    # Round-19 commscope gates (bench.py's `[bench] commscope ...`
    # lines): per-axis measured link bandwidth from the calibration
    # ladder (higher — the fitted β dropping means dispatch overheads
    # crept into the collectives themselves); `comm fit err` is the
    # α–β model's worst-cell error against its own ladder (lower);
    # `exposed comm` is the share of the serving window's device
    # seconds NOT hidden behind compute (lower — the overlap goal);
    # `comm prediction err` is the calibrated costmodel's serial
    # prediction vs the measured device bucket (lower; phrased
    # distinctly from `model err` / `layout err` / `memflow err` so
    # the four analyzer gates never double-match one line). The
    # `overlap ratio` on the same line is deliberately NOT gated:
    # overlapping more or less comm is a scheduling outcome, not
    # monotonic goodness.
    (re.compile(r"axis bandwidth ([\d,.]+)\s*GB/s"),
     "comm_axis_bandwidth_gb_s", True),
    (re.compile(r"comm fit err ([\d,.]+)%"), "comm_fit_err_pct", False),
    (re.compile(r"exposed comm ([\d,.]+)% of device"),
     "exposed_comm_share_pct", False),
    (re.compile(r"comm prediction err ([\d,.]+)%"),
     "comm_model_err_pct", False),
    # Round-20 workload-observatory gates (bench.py's `[bench] economics
    # ...` line): fleet-wide cost per generated token on the canonical
    # replayed day (lower — the economics JOIN pricing the same trace
    # getting dearer means capacity got wasted somewhere); the worst
    # tenant's SLO burn rate (lower; 0.00 on a clean round, and the
    # zero-old floor above means any burn past the threshold fails the
    # gate rather than sailing through on a div-by-zero pass). The
    # line's `goodput_ratio ...%` is picked up by the round-14 pattern.
    (re.compile(r"cost/token ([\d,.]+)\s*u\$"), "cost_per_token_uusd",
     False),
    (re.compile(r"worst tenant burn ([\d,.]+)"),
     "worst_tenant_burn_rate", False),
    # Round-21 topology gates (bench.py's `[bench] topo ...` lines):
    # `topo err` is the overlap-aware two-tier prediction's error vs the
    # measured step per searchable entry (lower; phrased distinctly from
    # `model err` / `layout err` / `memflow err` / `comm prediction
    # err` so the five analyzer gates never double-match one line);
    # `dcn B/token` is what the static model prices across the slow
    # tier per trained token (lower — growth means a layout or
    # propagation change started shipping gradients over DCN); `overlap
    # gap` is the pinned profile overlap ratio vs the ledger's realized
    # one in percentage points (lower — drift means the overlap table
    # no longer describes this host). `topo argmin gap` is the seeded
    # two-tier canary: flat-argmin re-priced under the hierarchy vs the
    # topology-aware argmin — deterministic abstract pricing, so it is
    # the one HIGHER-is-better analyzer gate (the gap collapsing to 0
    # means hierarchy pricing lost its discrimination power, not that
    # anything got faster).
    (re.compile(r"topo err ([\d,.]+)%"), "topo_reconcile_err_pct",
     False),
    (re.compile(r"([\d,.]+)\s*dcn B/token"), "dcn_bytes_per_token",
     False),
    (re.compile(r"overlap gap ([\d,.]+)\s*pp"),
     "overlap_predicted_vs_realized_pp", False),
    (re.compile(r"topo argmin gap ([\d,.]+)%"), "topo_argmin_gap_pct",
     True),
    # Round-22 comm-compression gates (bench.py's `[bench] comm
    # compression ...` lines): `compressed N tok/s` is the int8-wire
    # mixed engine's throughput (higher — on the emulated host it pays
    # the codec without the wire win, so the gate catches the codec
    # path bloating); `q8 agreement` is the greedy token match vs the
    # plain engine, which the drift oracle holds at 100% (phrased
    # distinctly from the speculative pass's `agreement vs plain:`);
    # `kv wire` is the post-codec kB the tier ladder actually moved per
    # request (lower; distinct from round-15's pre-codec `kv moved`);
    # `compression ratio` is raw/wire over the same window (higher —
    # it collapsing toward 1 means pages stopped compressing, e.g. a
    # dtype or codec regression upstream of the ledger).
    (re.compile(r"compressed ([\d,.]+)\s*tok/s"), "compressed_tok_s",
     True),
    (re.compile(r"q8 agreement ([\d,.]+)%"), "q8_agreement_pct", True),
    (re.compile(r"kv wire ([\d,.]+)\s*kB/req"),
     "kv_wire_bytes_per_req_kb", False),
    (re.compile(r"compression ratio ([\d,.]+)x"),
     "comm_compression_ratio", True),
    # Round-23 elastic-fleet gates (scripts/replay.py --autoscale's
    # `[bench] autoscale replay ...` line): `elastic N uusd/tok` is the
    # autoscaled fleet's provisioned cost per generated token on the
    # canonical day (lower — and the same line carries the best static
    # fleet's number as ungated context, phrased `static N uusd/tok`,
    # deliberately NOT matching round-20's `cost/token N u$` serving-
    # cost gate); `drain p99` is the scale-in drain-and-migrate wall
    # tail, THE latency the elastic path adds (lower); `planner gap`
    # is the capacity planner's K(t) integral vs the live controller's,
    # in % of planned replica-seconds (lower — widening means either
    # the planner's model or the controller's judgement drifted;
    # phrased distinctly from `layout gap` / `overlap gap` / `topo
    # argmin gap` so no two gap gates double-match one line).
    (re.compile(r"elastic ([\d,.]+)\s*uusd/tok"),
     "autoscale_cost_per_token_uusd", False),
    (re.compile(r"drain p99 ([\d,.]+)\s*ms"), "scale_in_drain_ms_p99",
     False),
    (re.compile(r"planner gap ([\d,.]+)%"), "planner_vs_live_gap_pct",
     False),
]

_NAME_RE = re.compile(r"\[bench\]\s+([^:]+):")


def _round_of(path: pathlib.Path) -> int:
    m = re.search(r"BENCH_r(\d+)\.json$", path.name)
    return int(m.group(1)) if m else -1


def extract_metrics(doc: dict) -> dict[str, tuple[float, bool]]:
    """``{metric: (value, higher_is_better)}`` from one round's record."""
    out: dict[str, tuple[float, bool]] = {}
    parsed = doc.get("parsed") or {}
    if isinstance(parsed.get("value"), (int, float)):
        out["headline:" + str(parsed.get("metric", "value"))] = (
            float(parsed["value"]), True,
        )
    if isinstance(parsed.get("vs_baseline"), (int, float)):
        out["headline:vs_baseline"] = (float(parsed["vs_baseline"]), True)
    for line in (doc.get("tail") or "").splitlines():
        nm = _NAME_RE.search(line)
        if nm is None:
            continue
        name = re.sub(r"\s+", "_", nm.group(1).strip())
        for pat, suffix, higher in _PATTERNS:
            m = pat.search(line)
            if m is None:
                continue
            key = f"{name}:{suffix}"
            if key in out:   # first occurrence wins (ladder lines repeat)
                continue
            out[key] = (float(m.group(1).replace(",", "")), higher)
    return out


def extract_collective_inventory(doc: dict) -> dict[str, int] | None:
    """The round's ``telemetry.headline_collectives`` per-op counts, from
    the bench's JSON line (``parsed`` when the driver kept it whole, else
    re-parsed out of the ``tail`` text). None when the round predates the
    telemetry block."""
    tel = (doc.get("parsed") or {}).get("telemetry")
    if isinstance(tel, dict) and "headline_collectives" in tel:
        return {k: int(v) for k, v in tel["headline_collectives"].items()}
    for line in (doc.get("tail") or "").splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"telemetry"' in line):
            continue
        try:
            tel = json.loads(line).get("telemetry") or {}
        except json.JSONDecodeError:
            continue
        if "headline_collectives" in tel:
            return {k: int(v) for k, v in tel["headline_collectives"].items()}
    return None


def check_collective_contract(
    inventory: dict[str, int], golden_path: pathlib.Path
) -> list[str]:
    """Diff per-op collective counts against a golden contract file
    (plain JSON read — the shardcheck golden's ``collectives`` section
    keyed ``op@axis``, summed per op here because the bench inventory is
    axis-blind). Returns human-readable drift lines; empty == clean."""
    golden = json.loads(golden_path.read_text())
    allowed: dict[str, int] = {}
    for key, grp in (golden.get("collectives") or {}).items():
        op = key.split("@", 1)[0]
        allowed[op] = allowed.get(op, 0) + int(grp["count"])
    drift = []
    for op in sorted(set(inventory) | set(allowed)):
        got, want = inventory.get(op, 0), allowed.get(op, 0)
        if got != want:
            drift.append(
                f"collective inventory drift vs {golden_path.name}: "
                f"{got} x {op} in the bench round, contract admits {want}"
            )
    return drift


def compare(
    old: dict, new: dict, threshold: float
) -> tuple[list[dict], list[str], list[str]]:
    """Per-metric deltas plus added/removed names. A REGRESSION is a move
    past ``threshold`` in the metric's own bad direction. A ZERO old
    value gets a 1-unit floor instead of a div-by-zero pass: the
    recovery/stall gates hold at exactly 0 in a clean round, and
    0% → 12% shed must fail the gate, not sail through as delta 0."""
    om, nm = extract_metrics(old), extract_metrics(new)
    rows: list[dict] = []
    for key in sorted(om.keys() & nm.keys()):
        (ov, higher), (nv, _) = om[key], nm[key]
        delta = (nv - ov) / (abs(ov) if ov else 1.0)
        worse = -delta if higher else delta
        rows.append(
            {
                "metric": key,
                "old": ov,
                "new": nv,
                "delta_pct": 100.0 * delta,
                "higher_is_better": higher,
                "regressed": worse > threshold,
            }
        )
    added = sorted(nm.keys() - om.keys())
    removed = sorted(om.keys() - nm.keys())
    return rows, added, removed


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*", help="two BENCH json files (old new);"
                    " default: the two most recent BENCH_r*.json in --repo")
    ap.add_argument("--repo", default=".", help="directory holding BENCH_r*.json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression threshold as a fraction (default 0.10)")
    ap.add_argument("--contracts", default=None,
                    help="golden contract dir for the collective-inventory "
                    "cross-check (default: the source checkout's "
                    "learning_jax_sharding_tpu/analysis/golden, resolved "
                    "from this script's location — NOT --repo, which may "
                    "be a bare artifacts dir; pass '' to disable)")
    ap.add_argument("--contract-name", default="bench_headline",
                    help="golden contract the bench inventory is held to")
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    if args.files:
        if len(args.files) != 2:
            ap.error("pass exactly two files (old new), or none")
        paths = [pathlib.Path(f) for f in args.files]
    else:
        found = sorted(
            pathlib.Path(args.repo).glob("BENCH_r*.json"), key=_round_of
        )
        if len(found) < 2:
            print(f"need >= 2 BENCH_r*.json in {args.repo}, "
                  f"found {len(found)}", file=sys.stderr)
            return 2
        paths = found[-2:]

    docs = [json.loads(p.read_text()) for p in paths]
    rows, added, removed = compare(docs[0], docs[1], args.threshold)
    regressed = [r for r in rows if r["regressed"]]

    drift: list[str] = []
    contracts = args.contracts
    if contracts is None:
        # Anchored to the script's checkout, not --repo: CI points
        # --repo at a bare BENCH-artifacts dir, and a default that
        # resolved there would silently skip the gate every run.
        contracts = str(
            pathlib.Path(__file__).resolve().parents[1]
            / "learning_jax_sharding_tpu" / "analysis" / "golden"
        )
    if contracts:
        golden = pathlib.Path(contracts) / f"{args.contract_name}.json"
        inventory = extract_collective_inventory(docs[1])
        if inventory is None:
            print(f"bench_compare: {paths[1].name} carries no collective "
                  "inventory (pre-telemetry round) — contract check skipped",
                  file=sys.stderr)
        elif not golden.exists():
            print(f"bench_compare: no golden contract at {golden} — "
                  "contract check skipped", file=sys.stderr)
        else:
            drift = check_collective_contract(inventory, golden)

    if args.json:
        print(json.dumps(
            {
                "old": str(paths[0]), "new": str(paths[1]),
                "threshold": args.threshold, "metrics": rows,
                "added": added, "removed": removed,
                "regressions": [r["metric"] for r in regressed],
                "collective_drift": drift,
            },
            indent=2,
        ))
    else:
        print(f"bench_compare: {paths[0].name} -> {paths[1].name} "
              f"(threshold {args.threshold:.0%})")
        for r in rows:
            arrow = "v" if r["delta_pct"] < 0 else "^"
            flag = "  REGRESSED" if r["regressed"] else ""
            print(f"  {r['metric']:60s} {r['old']:>12.3f} -> "
                  f"{r['new']:>12.3f}  {arrow}{abs(r['delta_pct']):6.1f}%"
                  f"{flag}")
        for k in added:
            print(f"  + {k} (new)")
        for k in removed:
            print(f"  - {k} (gone)")
        for d in drift:
            print(f"  ! {d}")
        n = len(regressed)
        print(f"bench_compare: {len(rows)} compared, {n} regression(s), "
              f"{len(drift)} collective drift(s)")
    return 1 if (regressed or drift) else 0


if __name__ == "__main__":
    sys.exit(main())
