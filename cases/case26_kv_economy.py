"""Case 26 — the KV economy: prefix-aware placement + tier ladder.

The round-15 subsystem on a 2-replica paged fleet ((1,1) sub-meshes on
the emulated mesh) serving a shared-prefix traffic mix (four "tenant"
system prompts, random tails):

* **prefix-aware placement** — the router scores each replica by
  ``depth + burn − prefix_weight × predicted-hit tokens``, where the
  prediction walks the prompt's page-aligned chain against every
  replica's HBM digest and host tier: same-tenant requests converge on
  the tenant's home replica and realize their predicted tokens;
* **the tier ladder** — ``maintain()`` write-backs cold retained
  chains to the per-replica host ``TierStore`` (LRU + SLO-burn
  demotion), ``promote()`` restores them (host first, then peer) on
  placement, and every moved byte flows through the counted transfer
  plans into the ledger's ``kv_handoff`` bucket;
* **economics in the books** — ``latency_stats()`` carries
  prefix_hit_rate / tier_miss_rate, ``tier_report()`` the per-tier
  occupancy and byte flows, and the fleet ledger still reconciles.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case26``, else a
temp dir): ``tier_report.json`` (per-replica tier occupancy + fleet
demotion/promotion/byte totals + hit rates), ``metrics.prom`` (the
labeled exposition carrying the ``fleet_tier_*`` and
``fleet_prefix_*`` series).

Run: ``python cases/case26_kv_economy.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.fleet import (  # noqa: E402
    FleetPolicy,
    FleetRouter,
    KvEconomy,
    make_replicas,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    artifact_dir,
)

K, NREQ, NEW, PAGE, TENANTS = 2, 16, 4, 4, 4


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case26")
    )
    out.mkdir(parents=True, exist_ok=True)

    cfg = dataclasses.replace(
        CONFIG_TINY, dtype=jnp.float32, decode_attention="blocked",
    )
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), np.zeros((2, 8), np.int32)
        )["params"]
    )
    rng = np.random.default_rng(26)
    bases = [
        rng.integers(1, cfg.vocab_size, size=(3 * PAGE,)).astype(np.int32)
        for _ in range(TENANTS)
    ]
    prompts = [
        np.concatenate([
            bases[i % TENANTS],
            rng.integers(1, cfg.vocab_size, size=(3,)).astype(np.int32),
        ])
        for i in range(NREQ)
    ]

    reps = make_replicas(
        cfg, RULES_DP_TP, params, count=K, mesh_shape=(1, 1),
        batch_size=2, max_new_tokens=NEW, refill_chunk=8,
        paged_pages=16, page_size=PAGE, prefix_cache=True,
    )
    econ = KvEconomy(hbm_retained_target=0, burn_threshold=1e9)
    router = FleetRouter(
        reps, policy=FleetPolicy(prefix_weight=0.5), kv_economy=econ,
    )

    # Warm pass: compiles (engine programs + the spill/fill pair and
    # their transfer plans) and one request per tenant, so every chain
    # has a home for placement to predict against.
    for i, b in enumerate(bases):
        router.add_request(
            np.concatenate([b, np.asarray([7 + i], np.int32)]),
            rid=1000 + i,
        )
    while router.has_work():
        router.step()
    router.pop_finished()

    print(f"case26: routing {NREQ} requests ({TENANTS} tenants, "
          f"3-page shared prefixes) through K={K} paged replicas")
    router.reset_stats()
    for i, p in enumerate(prompts):
        router.add_request(p, rid=i)
    results, steps = {}, 0
    while router.has_work():
        router.step()
        results.update(router.pop_finished())
        steps += 1
        if steps > 2000:
            raise RuntimeError("fleet wedged")
    results.update(router.pop_finished())
    assert len(results) == NREQ, sorted(results)

    stats = router.latency_stats()
    report = econ.tier_report()
    report["latency"] = {
        k: stats[k]
        for k in ("prefix_hit_rate", "tier_miss_rate", "requests",
                  "generated")
    }
    assert stats["prefix_hit_rate"] > 0.5, stats
    assert router.goodput_report()["reconcile_ok"]

    print(f"  prefix hit rate  {stats['prefix_hit_rate']:.0%}   "
          f"tier miss rate {stats['tier_miss_rate']:.0%}")
    print(f"  demotions {report['demotions']}  promotions "
          f"{report['promotions']} (peer {report['peer_promotions']})  "
          f"spill {report['spill_bytes'] / 1e3:.0f} kB  "
          f"fill {report['fill_bytes'] / 1e3:.0f} kB")
    for name, r in sorted(report["replicas"].items()):
        print(f"  {name}: hbm retained {r['hbm_retained_pages']} pages, "
              f"host tier {r['host_pages']} pages "
              f"({r['host_bytes'] / 1e3:.0f} kB)")

    (out / "tier_report.json").write_text(json.dumps(report, indent=2))
    (out / "metrics.prom").write_text(router.prometheus_text())
    print(f"case26: artifacts in {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
