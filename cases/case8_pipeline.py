"""Case 8 — pipeline parallelism (dp × tp × pp in one SPMD program).

Not in the reference (SURVEY.md §2.4 "Pipeline parallelism: absent"). The
transformer's block stack is split into contiguous stages on a ``pipe`` mesh
axis; microbatches stream through the stages with the circular GPipe schedule
of ``parallel.pipeline.spmd_pipeline`` (``lax.ppermute`` ring handoff — one
ICI hop per tick on hardware), while the data and model axes stay under
GSPMD for dp and tp inside every stage.

Run: ``python cases/case8_pipeline.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np
import optax

from learning_jax_sharding_tpu.models.pipelined import PipelinedTransformer
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, next_token_loss
from learning_jax_sharding_tpu.parallel import build_mesh, collective_counts
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, activate


def main():
    mesh = build_mesh((2, 2, 2), ("pipe", "data", "model"))
    print(f"mesh: {dict(mesh.shape)}  (pipe carries stages, data/model stay GSPMD)")

    cfg = CONFIG_TINY  # 2 layers → 2 stages × 1 layer
    model = PipelinedTransformer(
        cfg, mesh, RULES_DP_TP, num_stages=2, num_microbatches=4
    )
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(8, 33)).astype(np.int32)
    batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}

    params, shardings = model.init_sharded(jax.random.key(0), batch["inputs"])
    up = params["blocks"]["ff"]["up"]["kernel"]
    print(f"stacked FF up-kernel: global {up.shape}, spec {up.sharding.spec}, "
          f"per-device shard {up.addressable_shards[0].data.shape}")
    assert up.sharding.spec[0] == "pipe", "stage dim must ride the pipe axis"

    opt = optax.adamw(1e-3)
    carry = (params, model.init_optimizer(params, opt))
    step = model.make_train_step(opt, next_token_loss)

    with activate(mesh, RULES_DP_TP):
        counts = collective_counts(
            step.jitted.lower(carry, batch).compile().as_text()
        )
    print(f"collectives in the compiled step: {counts}")
    assert counts["collective-permute"] >= 1, "stage handoff must be a ppermute ring"

    losses = []
    for _ in range(5):
        carry, loss = step(carry, batch)
        losses.append(float(loss))
    print("losses:", [round(l, 4) for l in losses])
    assert losses[-1] < losses[0] and all(np.isfinite(losses))
    print("PASS: pipelined dp*tp*pp training step descends; "
          f"bubble fraction at M=4, P=2: {(2 - 1) / (4 + 2 - 1):.0%}")


if __name__ == "__main__":
    main()
