"""Case 3 — both operands fully 2D-sharded → fully sharded output (FSDP pattern).

Rebuild of `/root/reference/case3_fully_sharded.py`: A and B both sharded over
both mesh axes; the output lands fully sharded too — every device holds a
distinct (2,1) tile, zero redundancy anywhere. This is the placement pattern
underlying FSDP/ZeRO, shown on a single matmul (SURVEY.md §2.4). The
reference leaves a ``pdb.set_trace()`` at its end (`case3_fully_sharded.py:61`);
this version ends with assertions instead.

Run: ``python cases/case3_fully_sharded.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np

from learning_jax_sharding_tpu.parallel import (
    assert_shard_shape,
    build_mesh,
    put,
    shard_dims,
    unique_shard_count,
    visualize,
)


def main():
    mesh = build_mesh((2, 4), ("x", "y"))
    rng = np.random.default_rng(0)
    a_host = rng.standard_normal((4, 16)).astype(np.float32)
    b_host = rng.standard_normal((16, 4)).astype(np.float32)

    a = put(a_host, shard_dims(mesh, 2, x=0, y=1))
    print("A(4,16) — fully sharded:")
    visualize(a)
    assert_shard_shape(a, (2, 4))

    b = put(b_host, shard_dims(mesh, 2, x=0, y=1))
    print("B(16,4) — fully sharded:")
    visualize(b)
    assert_shard_shape(b, (8, 1))

    c = jax.jit(jax.lax.dot)(a, b)
    print("C = A·B:")
    visualize(c)

    np.testing.assert_allclose(np.asarray(c), a_host @ b_host, rtol=1e-5)
    assert_shard_shape(c, (2, 1))
    assert unique_shard_count(c) == 8, "every device must hold a distinct tile"
    print("PASS: fully-sharded operands → fully-sharded C, zero redundancy")


if __name__ == "__main__":
    main()
