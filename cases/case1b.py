"""Case 1b — mismatched contraction shardings → AllGather.

Rebuild of `/root/reference/case1b.py`: A's contraction dim is split over
mesh-Y while B's is split over mesh-X. No device pairing lines the shards up,
so GSPMD gathers operand shards back before multiplying — an AllGather, proved
from the HLO (the reference's banner at `case1b.py:15` says "AllReduce"; the
banners of 1a/1b are swapped, SURVEY.md §8).

Run: ``python cases/case1b.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np

from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    assert_replicated,
    assert_shard_shape,
    build_mesh,
    put,
    shard_dims,
    visualize,
)


def main():
    mesh = build_mesh((2, 4), ("x", "y"))
    rng = np.random.default_rng(0)
    a_host = rng.standard_normal((4, 16)).astype(np.float32)
    b_host = rng.standard_normal((16, 4)).astype(np.float32)

    a = put(a_host, shard_dims(mesh, 2, y=1))  # contraction dim over Y
    print("A(4,16) — inner dim split over Y:")
    visualize(a)
    assert_shard_shape(a, (4, 4))

    b = put(b_host, shard_dims(mesh, 2, x=0))  # contraction dim over X (mismatch!)
    print("B(16,4) — contraction dim split over X:")
    visualize(b)
    assert_shard_shape(b, (8, 4))

    c = jax.jit(jax.lax.dot)(a, b)
    print("C = A·B:")
    visualize(c)

    assert_replicated(c)
    np.testing.assert_allclose(np.asarray(c), a_host @ b_host, rtol=1e-5)
    counts = assert_collectives(jax.lax.dot, a, b, require=("all-gather",))
    print(f"collectives in compiled HLO: {counts}")
    print("PASS: mismatched contraction shardings → AllGather → replicated C")


if __name__ == "__main__":
    main()
