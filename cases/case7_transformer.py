"""Case 7 — the composed transformer training step (the north star).

Not in the reference: BASELINE.json's target composition — case-4's DP×MP
feed-forward and case-6's sharded attention joined into transformer blocks,
trained end-to-end as ONE SPMD program on a 2D data×model mesh with dp, tp,
and sp all active. Runs the tiny config on emulated devices so it works
anywhere; bench.py runs the 125M flagship on real hardware.

Run: ``python cases/case7_transformer.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import (
    build_mesh,
    collective_counts,
    mesh_sharding,
    put,
    shard_shapes,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP_SP, activate
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


def main():
    mesh = build_mesh((2, 4), ("data", "model"))
    cfg = CONFIG_TINY
    model = Transformer(cfg)

    rng = np.random.default_rng(0)
    b, s = 8, 32
    tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}

    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-4), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP_SP,
    )
    up = state.params["block_0"]["ff"]["up"]["kernel"]
    print(f"FF up-kernel {up.shape} shard: {shard_shapes(up)[0]} (cols over model)")
    emb = state.params["tok_embed"]["embedding"]
    print(f"embedding {emb.shape} shard: {shard_shapes(emb)[0]} (vocab over model)")

    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()},
        mesh, RULES_DP_TP_SP, loss_fn=next_token_loss,
    )
    losses = []
    for i in range(10):
        state, loss = step(state, batch)
        losses.append(float(loss))
    print("losses:", " ".join(f"{l:.3f}" for l in losses))
    assert losses[-1] < losses[0], "training must descend"

    with activate(mesh, RULES_DP_TP_SP):
        counts = collective_counts(step.jitted.lower(state, batch).compile().as_text())
    print(f"collectives inside the single SPMD train step: {counts}")
    assert counts["all-reduce"] >= 1

    print("PASS: composed transformer trains as one SPMD program (dp+tp+sp)")


if __name__ == "__main__":
    main()
