"""Case 1a — contraction-dim sharding on both operands → AllReduce.

Rebuild of `/root/reference/case1a.py` on the framework: A(4,16) is split
4-way on its inner dim over mesh-Y (replicated over X), B(16,4) likewise on
its inner dim, so each device holds a (4,4)×(4,4) partial product and XLA
GSPMD inserts an AllReduce to sum them — here *proved* from the compiled HLO,
not narrated (the reference's banner at `case1a.py:10` even mislabels the
collective; SURVEY.md §8).

Run: ``python cases/case1a.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np

from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    assert_replicated,
    assert_shard_shape,
    build_mesh,
    put,
    shard_dims,
    visualize,
)


def main():
    mesh = build_mesh((2, 4), ("x", "y"))
    rng = np.random.default_rng(0)
    a_host = rng.standard_normal((4, 16)).astype(np.float32)
    b_host = rng.standard_normal((16, 4)).astype(np.float32)

    # A: inner (contraction) dim split 4-way over Y, replicated over X
    # (reference: sharding.replicate(axis=0, keepdims=True), case1a.py:24).
    a = put(a_host, shard_dims(mesh, 2, y=1))
    print("A(4,16) — inner dim split over Y:")
    visualize(a)
    assert_shard_shape(a, (4, 4))

    # B: contraction dim split 4-way (reference: sharding.reshape(4,2)
    # .replicate(axis=1), case1a.py:30 — the NamedSharding way needs no
    # reshape trick).
    b = put(b_host, shard_dims(mesh, 2, y=0))
    print("B(16,4) — contraction dim split over Y:")
    visualize(b)
    assert_shard_shape(b, (4, 4))

    c = jax.jit(jax.lax.dot)(a, b)
    print("C = A·B:")
    visualize(c)

    # Every device computed a partial (4,4) product; the AllReduce summed
    # them, so C is fully replicated and numerically exact.
    assert_replicated(c, a_host @ b_host)
    counts = assert_collectives(
        jax.lax.dot, a, b, require=("all-reduce",), forbid=("all-gather",)
    )
    print(f"collectives in compiled HLO: {counts}")
    print("PASS: contraction-sharded matmul → AllReduce → replicated C")


if __name__ == "__main__":
    main()
