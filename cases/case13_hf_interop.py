"""Case 13 — checkpoint interop: a HuggingFace GPT-2 served by this framework.

"Switching frameworks" means bringing your checkpoints with you (the
reference has no model zoo or inference path at all — SURVEY.md §5). This
case builds a GPT-2 with ``transformers`` (randomly initialized: the
environment has no network, and parity, not pretraining, is the point),
then walks the interop chain:

  GPT2LMHeadModel → params_from_hf_gpt2                 (import)
  → logits parity vs torch on the same tokens           (proof)
  → sharded KV-cached generation on a data×model mesh   (serve, our stack)
  → int8 weight-only quantization of the converted tree (compress)
  → state_dict_from_params → fresh HF model → parity    (export round-trip)

Run: ``python cases/case13_hf_interop.py``
"""

import _bootstrap  # noqa: F401
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(4)

import numpy as np

import jax
import jax.numpy as jnp


def main():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    from learning_jax_sharding_tpu.models.convert import (
        config_from_hf_gpt2,
        params_from_hf_gpt2,
        state_dict_from_params,
    )
    from learning_jax_sharding_tpu.models.generate import make_generate_fn
    from learning_jax_sharding_tpu.models.quantize import (
        quantize_tree,
        quantized_bytes,
    )
    from learning_jax_sharding_tpu.models.transformer import Transformer
    from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
    from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

    torch.manual_seed(0)
    hf = GPT2LMHeadModel(GPT2Config(
        n_layer=2, n_embd=128, n_head=4, vocab_size=256, n_positions=128,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )).eval()

    # Import.
    cfg = config_from_hf_gpt2(hf.config)
    params = params_from_hf_gpt2(hf)
    print(f"imported GPT-2: {cfg.num_layers} layers, {cfg.features} wide, "
          f"use_bias={cfg.use_bias}, eps={cfg.norm_eps}")

    # Proof: same logits as torch.
    tok = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    with torch.no_grad():
        want = hf(torch.tensor(tok)).logits.numpy()
    got = np.asarray(
        Transformer(cfg).apply({"params": params}, jnp.asarray(tok, jnp.int32)),
        np.float32,
    )
    diff = np.abs(want - got).max()
    print(f"logit parity vs torch: max diff {diff:.2e}")
    assert diff < 5e-3 and (want.argmax(-1) == got.argmax(-1)).all()

    # Serve through OUR stack: sharded KV-cached greedy decode.
    mesh = build_mesh((2, 2), ("data", "model"))
    prompt = put(
        tok[:, :8].astype(np.int32), mesh_sharding(mesh, "data", None)
    )
    gen = make_generate_fn(cfg, mesh, RULES_DP_TP, max_new_tokens=16)
    out = np.asarray(gen(params, prompt))
    print(f"sharded generation: {out.shape}, continuation {out[0, 8:14].tolist()}")

    # Compress: int8 weight-only serving of the converted tree.
    q8 = quantize_tree(jax.tree.map(jnp.asarray, params))
    gen_q = make_generate_fn(
        cfg, mesh, RULES_DP_TP, max_new_tokens=16,
        inference_dtype=jnp.bfloat16, dequantize=True,
    )
    out_q = np.asarray(gen_q(q8, prompt))
    agree = (out_q[:, 8] == out[:, 8]).mean()
    print(f"int8-served first tokens agree on {agree:.0%} of rows; "
          f"weight bytes {quantized_bytes(params)/1e6:.1f} → "
          f"{quantized_bytes(q8)/1e6:.1f} MB")

    # Export round-trip: back to a fresh HF model, logits must survive.
    hf2 = GPT2LMHeadModel(hf.config).eval()
    hf2.load_state_dict(state_dict_from_params(params), strict=False)
    hf2.tie_weights()
    with torch.no_grad():
        back = hf2(torch.tensor(tok)).logits.numpy()
    rt = np.abs(back - want).max()
    print(f"export round-trip parity: max diff {rt:.2e}")
    assert rt < 1e-5

    print("PASS: HF checkpoint → framework serve (sharded, int8) → HF export")


if __name__ == "__main__":
    main()
