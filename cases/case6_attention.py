"""Case 6 — fully sharded multi-head attention: init/train/apply + benchmark.

Rebuild of `/root/reference/case6_attention.py`: the complete logically
partitioned MHA (8 heads × 64 on M=640) on a (2,2) data×model mesh —
parameters born sharded, jitted train step, jitted apply, and the timing loop
done right (the reference's loop at `case6_attention.py:234-238` includes
compile time and never syncs; this one uses the framework's warmup+sync
harness and reports TFLOP/s).

Run: ``python cases/case6_attention.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention
from learning_jax_sharding_tpu.parallel import build_mesh, put, shard_shapes, visualize
from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    RULES_DP_TP_SP,
    SEQ,
    logical_sharding,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_apply_fn,
    make_train_step,
    sharded_train_state,
)
from learning_jax_sharding_tpu.utils.bench import measure

B, S, M = 8, 256, 640  # reference dims (`case6_attention.py:149-151`)


def main():
    mesh = build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])
    rules = RULES_DP_TP_SP  # dp + tp + intentional sequence sharding
    model = MultiHeadAttention(
        features=M, num_heads=8, head_dim=64, dropout_rate=0.1
    )

    x_sharding = logical_sharding(mesh, rules, BATCH, SEQ, EMBED)
    x = put(
        np.random.default_rng(0).standard_normal((B, S, M)).astype(np.float32),
        x_sharding,
    )
    print(f"x{x.shape} shard: {shard_shapes(x)[0]}  (batch over data, seq over model)")
    visualize(jnp.squeeze(x[:, :, 0]))

    state, state_sh = sharded_train_state(
        model, optax.adam(1e-3), x, {"params": jax.random.key(0)}, mesh, rules
    )
    wq = state.params["query"]["kernel"]
    print(f"Wq {wq.shape} shard: {shard_shapes(wq)[0]}  (born sharded)")

    step = make_train_step(state_sh, x_sharding, mesh, rules)
    for i in range(3):
        state, loss = step(state, x)
        print(f"train step {i}: loss={float(loss):.2f}")

    apply_fn = make_apply_fn(state_sh, x_sharding, mesh, rules)
    y = apply_fn(state, x)
    print(f"y{y.shape} shard: {shard_shapes(y)[0]}")
    assert shard_shapes(y)[0] == (B // 2, S // 2, M)

    result = measure(apply_fn, state, x, min_time=0.3)
    t = result.tflops_per_chip
    print(
        f"apply: {result.seconds_per_iter * 1e3:.2f} ms/iter"
        + (f", {t:.2f} TFLOP/s/chip" if t else "")
    )
    print("PASS: sharded MHA init/train/apply on the data×model mesh")


if __name__ == "__main__":
    main()
