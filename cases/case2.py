"""Case 2 — sharding on non-contracting (outer) axes → sharded output, no conflict.

Rebuild of `/root/reference/case2.py`: A is fully 2D-sharded, B row-sharded
over X. The contraction pairing works out per-device, so the output is born
row-sharded over X (replicated over Y) with **no reduction collective** — each
X-row of devices holds its own distinct block of C.

Run: ``python cases/case2.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np

from learning_jax_sharding_tpu.parallel import (
    assert_shard_shape,
    build_mesh,
    put,
    shard_dims,
    unique_shard_count,
    visualize,
)


def main():
    mesh = build_mesh((2, 4), ("x", "y"))
    rng = np.random.default_rng(0)
    a_host = rng.standard_normal((4, 16)).astype(np.float32)
    b_host = rng.standard_normal((16, 4)).astype(np.float32)

    a = put(a_host, shard_dims(mesh, 2, x=0, y=1))  # fully 2D-sharded
    print("A(4,16) — fully sharded over (x,y):")
    visualize(a)
    assert_shard_shape(a, (2, 4))

    b = put(b_host, shard_dims(mesh, 2, x=0))  # rows over X
    print("B(16,4) — rows split over X:")
    visualize(b)
    assert_shard_shape(b, (8, 4))

    c = jax.jit(jax.lax.dot)(a, b)
    print("C = A·B:")
    visualize(c)

    np.testing.assert_allclose(np.asarray(c), a_host @ b_host, rtol=1e-5)
    assert_shard_shape(c, (2, 4))
    # Two distinct row-blocks (one per X row), each replicated over Y
    # (reference probes this with buffer comparisons, case2.py:48-59).
    assert unique_shard_count(c) == 2
    print("PASS: outer-axis sharding → C row-sharded over X, no reduction needed")


if __name__ == "__main__":
    main()
