"""Make the repo root importable when a case runs as ``python cases/caseN.py``
(the framework is also installable via ``pip install -e .``; the cases must
work from a bare checkout)."""

import pathlib
import sys

_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
