"""Case 25 — the goodput ledger + fleet tracing, end to end.

The round-14 observability layers on a SATURATED disaggregated fleet
(2 prefill + 2 decode replicas, (1,2) sub-meshes on the emulated 8-dev
mesh), every request admitted up front so the window prices the
machinery, not arrival gaps:

* **100% wall-clock accounting** — every replica engine's goodput
  ledger must RECONCILE (Σ exclusive buckets == window wall within ε);
  the fleet report rolls the four ledgers up into one bucket breakdown
  with ``host_share`` (1 − device/busy) and the NAMED top gap
  contributor — the "where did the 16× go" answer as data;
* **fleet-wide request tracing** — one trace id per request minted at
  router admission and carried across the prefill replica, the KV
  handoff, and the decode replica; every retired request yields a
  complete critical path (queue → prefill → handoff → decode → stall)
  with TTFT, printed here as a table;
* **one merged Perfetto timeline** — per-replica engine dispatch tracks
  and per-request journey tracks on a single clock
  (https://ui.perfetto.dev).

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case25``, else a
temp dir): ``goodput.json`` (the fleet ledger roll-up + per-replica
reconciliation), ``critical_paths.json`` (per-request decompositions),
``trace.json`` (the merged Perfetto timeline), ``metrics.prom`` (the
labeled exposition carrying ``ledger_seconds_total`` and
``trace_stage_seconds`` series per replica).

Run: ``python cases/case25_goodput.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.fleet import (  # noqa: E402
    FleetRouter,
    make_replicas,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    artifact_dir,
)

NREQ, NEW = 16, 8


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case25")
    )
    out.mkdir(parents=True, exist_ok=True)

    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), np.zeros((2, 8), np.int32)
        )["params"]
    )
    rng = np.random.default_rng(25)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in rng.integers(5, 14, size=NREQ)
    ]

    pre = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
        role="prefill", batch_size=2, max_new_tokens=1, refill_chunk=8,
    )
    dec = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
        role="decode", offset=4, batch_size=2, max_new_tokens=NEW,
        refill_chunk=8,
    )
    router = FleetRouter(pre + dec)

    # Warm pass: compiles (prefill, ingest-decode, the handoff programs)
    # stay out of the measured window — the window prices SERVING.
    for i, p in enumerate(prompts[:4]):
        router.add_request(p, rid=1000 + i)
    while router.has_work():
        router.step()
    router.pop_finished()

    print(f"case25: saturating 2 prefill + 2 decode replicas with "
          f"{NREQ} requests, goodput window armed")
    router.reset_stats()                 # begins every replica's window
    for i, p in enumerate(prompts):
        router.add_request(p, rid=i)
    results, steps = {}, 0
    while router.has_work():
        router.step()
        results.update(router.pop_finished())
        steps += 1
        if steps > 2000:
            raise RuntimeError("fleet wedged")
    results.update(router.pop_finished())
    assert len(results) == NREQ, sorted(results)

    # --- the ledger verdict -------------------------------------------------
    rep = router.goodput_report()
    assert rep["reconcile_ok"], {
        n: r["reconcile"] for n, r in rep["replicas"].items()
    }
    (out / "goodput.json").write_text(
        json.dumps(rep, indent=2, default=str)
    )

    # --- per-request critical paths -----------------------------------------
    cps = [
        cp for cp in router.traces.completed() if isinstance(cp["rid"], int)
        and cp["rid"] < 1000
    ]
    assert len(cps) == NREQ, f"traced {len(cps)} of {NREQ}"
    hdr = (f"{'trace':<12}{'rid':>4}{'queue':>9}{'prefill':>9}"
           f"{'handoff':>9}{'decode':>9}{'stall':>9}{'ttft':>9}"
           f"{'e2e':>9}")
    print(hdr)
    print("-" * len(hdr))
    for cp in cps:
        st = cp["stages"]
        ttft = f"{cp['ttft_s'] * 1e3:8.1f}" if cp["ttft_s"] else "     n/a"
        print(
            f"{cp['trace_id']:<12}{cp['rid']:>4}"
            f"{st.get('queue', 0) * 1e3:8.1f} {st.get('prefill', 0) * 1e3:8.1f} "
            f"{st.get('handoff', 0) * 1e3:8.1f} {st.get('decode', 0) * 1e3:8.1f} "
            f"{st.get('stall', 0) * 1e3:8.1f} {ttft} "
            f"{cp['e2e_s'] * 1e3:8.1f}"
        )
        # The completeness contract: a disaggregated request must show
        # all four named stages — a zero handoff/prefill would mean a
        # hop escaped the trace.
        for stage in ("queue", "prefill", "handoff", "decode"):
            assert st.get(stage, 0.0) > 0.0, (cp["trace_id"], stage, st)
        assert cp["ttft_s"] is not None and cp["ttft_s"] > 0.0
    (out / "critical_paths.json").write_text(
        json.dumps(cps, indent=2, default=str)
    )

    # --- the merged timeline + labeled exposition ---------------------------
    router.dump_merged_chrome_trace(out / "trace.json")
    prom = router.prometheus_text()
    assert 'ledger_seconds_total{bucket="device",replica="' in prom
    assert 'trace_stage_seconds_bucket{stage="handoff"' in prom
    (out / "metrics.prom").write_text(prom)

    buckets = rep["fleet_buckets"]
    top3 = sorted(buckets.items(), key=lambda kv: -kv[1])[:3]
    print(
        f"case25: {NREQ}/{NREQ} requests traced end-to-end; all 4 "
        f"replica ledgers reconcile; fleet host_share "
        f"{rep['host_share'] * 100:.1f}%, top buckets "
        + ", ".join(f"{b} {s * 1e3:,.0f} ms" for b, s in top3)
        + f"; artifacts in {out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
