"""Case 9 — KV-cached autoregressive generation on a sharded mesh.

Not in the reference (its only forward is a timing loop over full sequences,
`/root/reference/case6_attention.py:234-238`). This case trains the tiny
transformer briefly on a fully predictable token stream, then decodes with
the framework's KV-cached generate path — prefill + single-token steps as
two compiled executables — and shows the model reproduces the learned
pattern. Runs under a (data, model) mesh: the caches and per-step
collectives follow the same TP/DP shardings as training.

Run: ``python cases/case9_generate.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import numpy as np

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit


class CyclicDataset:
    """token(i+1) = token(i) + 1 (mod V): perfectly learnable in a few steps."""

    def __init__(self, vocab_size, seq_len):
        self.vocab_size, self.seq_len = vocab_size, seq_len

    def batch(self, index, rows=None, batch_size=8):
        rng = np.random.default_rng((13, index))
        starts = rng.integers(0, self.vocab_size, size=batch_size)
        if rows is not None:
            starts = starts[rows]
        toks = (starts[:, None] + np.arange(self.seq_len + 1)[None]) % self.vocab_size
        toks = toks.astype(np.int32)
        return {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def main():
    mesh = build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])
    cfg = CONFIG_TINY

    print("training 40 steps on the cyclic stream ...")
    state, history = fit(
        Transformer(cfg), CyclicDataset(cfg.vocab_size, 32), mesh, RULES_DP_TP,
        TrainLoopConfig(steps=40, global_batch_size=16, learning_rate=3e-3,
                        log_every=10),
    )
    print(f"loss: {history[0]['loss']:.3f} → {history[-1]['loss']:.3f}")

    gen = make_generate_fn(cfg, mesh, RULES_DP_TP, max_new_tokens=8)
    prompt = np.stack([np.arange(10, 16), np.arange(100, 106)]).astype(np.int32)
    out = np.asarray(gen(state.params, jax.numpy.asarray(prompt)))
    print("prompt → continuation:")
    correct = 0
    for row in out:
        print("  ", row[:6], "→", row[6:])
    want = (out[:, 5:-1] + 1) % cfg.vocab_size
    correct = (out[:, 6:] == want).mean()
    print(f"next-token accuracy on continuation: {correct:.0%}")
    assert correct > 0.7, "trained model should continue the cycle"
    print("PASS: KV-cached generation continues the learned sequence")


if __name__ == "__main__":
    main()
