"""Case 15 — production-shaped serving: ragged batches + continuous batching.

Not in the reference (it has no inference path at all, SURVEY.md §5). The
round-3 serving stack, demonstrated end to end on a (data, model) mesh:

1. Train the tiny transformer on a perfectly learnable cyclic stream.
2. RAGGED batch: mixed-length prompts decode together, each row at its own
   length (per-row cache positions; per-row kernel clamps on the blocked
   backend) — outputs proven bit-identical to per-prompt runs.
3. CONTINUOUS BATCHING: a queue of requests through a fixed batch of cache
   slots — retired slots refill immediately, long prompts stream through
   fixed refill chunks, greedy outputs again bit-identical.

Run: ``python cases/case15_ragged_serving.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses

import jax
import numpy as np

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit


class CyclicDataset:
    """token(i+1) = token(i) + 1 (mod V): learnable in a few steps."""

    def __init__(self, vocab_size, seq_len):
        self.vocab_size, self.seq_len = vocab_size, seq_len

    def batch(self, index, rows=None, batch_size=8):
        rng = np.random.default_rng((15, index))
        starts = rng.integers(0, self.vocab_size, size=batch_size)
        if rows is not None:
            starts = starts[rows]
        toks = (starts[:, None] + np.arange(self.seq_len + 1)[None]) % self.vocab_size
        return {"inputs": toks[:, :-1].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32)}


def main():
    mesh = build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jax.numpy.float32)
    new = 6

    print("training 40 steps on the cyclic stream ...")
    state, history = fit(
        Transformer(cfg), CyclicDataset(cfg.vocab_size, 32), mesh, RULES_DP_TP,
        TrainLoopConfig(steps=40, global_batch_size=16, learning_rate=3e-3,
                        log_every=20),
    )
    print(f"loss: {history[0]['loss']:.3f} -> {history[-1]['loss']:.3f}")
    import flax.linen as nn

    params = nn.meta.unbox(state.params)

    # Single-prompt references (the oracle for everything below).
    gen = make_generate_fn(cfg, mesh, RULES_DP_TP, max_new_tokens=new)

    def reference(prompt):
        out = np.asarray(
            gen(params, np.repeat(prompt[None], 2, axis=0), jax.random.key(0))
        )
        return out[0]

    # --- 2. Ragged batch: four prompts of different lengths, one batch ---
    lengths = np.asarray([3, 10, 6, 2], np.int32)
    pmax = int(lengths.max())
    rng = np.random.default_rng(2)
    prompt_mat = np.zeros((4, pmax), np.int32)
    prompts = []
    for i, ln in enumerate(lengths):
        start = int(rng.integers(0, cfg.vocab_size))
        p = (start + np.arange(ln)) % cfg.vocab_size
        prompts.append(p.astype(np.int32))
        prompt_mat[i, :ln] = p
    rag = make_generate_fn(
        cfg, mesh, RULES_DP_TP, max_new_tokens=new, ragged=True
    )
    out = np.asarray(rag(params, prompt_mat, jax.random.key(0), lengths))
    for i, (p, ln) in enumerate(zip(prompts, lengths)):
        ref = reference(p)
        assert (out[i, : ln + new] == ref).all(), (i, out[i], ref)
    print(f"PASS: ragged batch of lengths {lengths.tolist()} — every row "
          f"bit-identical to its single-prompt run")

    # --- 3. Continuous batching: 6 requests through 2 cache slots ---
    queue = [
        ((int(rng.integers(0, cfg.vocab_size)) + np.arange(n)) % cfg.vocab_size)
        .astype(np.int32)
        for n in (4, 12, 2, 30, 7, 5)   # the 30-token prompt streams
    ]                                    # through several 8-token refills
    serve = make_continuous_engine(
        cfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=new,
        refill_chunk=8, decode_block_steps=2,
    )
    outs = serve(params, queue)
    for p, got in zip(queue, outs):
        ref = reference(p)
        assert (got == ref[: len(got)]).all(), (p, got, ref)
    print(f"PASS: {len(queue)} queued requests through 2 slots (slot reuse, "
          f"multi-chunk refill) — all bit-identical to single runs")
    print("PASS: case15 — ragged + continuous serving, proven against "
          "single-prompt decoding")


if __name__ == "__main__":
    main()
