"""Case 23 — tenancy: multi-LoRA fused serving + zero-downtime hot-swap.

The round-12 subsystem, end to end on the emulated 8-device mesh:

* **multi-LoRA** — three tenants' rank-4 adapters paged into one
  :class:`~learning_jax_sharding_tpu.tenancy.AdapterPool`; base rows
  and all three tenants share ONE fused ``adapter_mixed_step`` batch,
  and every stream is BIT-IDENTICAL to a solo engine serving that
  tenant's ``merge_lora``-folded weights;
* **saturated hot-swap** — ``swap_weights`` staged mid-stream under a
  full queue (drain mode): zero dropped/failed requests, in-flight
  requests finish on v0, the post-commit backlog serves on v1, every
  response attributable to exactly one version
  (``finished_versions``), and the commit's serve gap lands in the
  ``engine.swap_commit`` flight-recorder events as ``stall_s``;
* **fleet rolling swap** — 2 unified replicas behind a
  :class:`~learning_jax_sharding_tpu.fleet.FleetRouter`;
  ``rolling_swap`` walks them one at a time (the fleet keeps serving
  throughout), and each response matches the per-version single-engine
  oracle: v0 responses equal a pure run on the old weights, v2
  responses a pure run on the new ones.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case23``, else a
temp dir): ``swap_timeline.json`` (the ``rolling_swap`` timeline via
``tenancy.write_swap_timeline``), ``metrics.prom`` (labeled fleet
exposition incl. ``engine_swap_*`` / ``engine_adapter_*`` counters),
``events.json`` (the recorder ring's swap/adapter/fleet timeline), and
``tenancy_summary.json``.

Run: ``python cases/case23_tenancy.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.fleet import (  # noqa: E402
    FleetRouter,
    make_replicas,
    replicated_params,
)
from learning_jax_sharding_tpu.models.serving import (  # noqa: E402
    ContinuousEngine,
    RequestFailure,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh  # noqa: E402
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    FlightRecorder,
    artifact_dir,
)
from learning_jax_sharding_tpu.tenancy import (  # noqa: E402
    AdapterPool,
    write_swap_timeline,
)
from learning_jax_sharding_tpu.training.lora import (  # noqa: E402
    init_lora,
    merge_lora,
)

NREQ, NEW, RANK = 10, 8, 4


def drive(eng, params, reqs, *, adapters=None, max_steps=500):
    for rid, p in reqs.items():
        eng.add_request(p, rid=rid, adapter=(adapters or {}).get(rid))
    out, steps = {}, 0
    while eng.has_work():
        eng.step(params)
        out.update(eng.pop_finished())
        steps += 1
        assert steps <= max_steps, "engine wedged"
    out.update(eng.pop_finished())
    return out


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case23")
    )
    out.mkdir(parents=True, exist_ok=True)

    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), np.zeros((2, 8), np.int32)
        )["params"]
    )
    rng = np.random.default_rng(23)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in rng.integers(5, 14, size=NREQ)
    ]
    rec = FlightRecorder(max_events=65536)
    summary: dict = {}

    # --- 1. multi-LoRA: one fused batch, three tenants + base --------
    mesh = build_mesh((2, 4), ("data", "model"))
    adapters = {
        f"t{i}": jax.tree.map(
            # B perturbed off zero — a fresh init's B=0 adapter IS the
            # base model and the bit-identity oracle would be vacuous.
            lambda x, i=i: x + 0.02 * (i + 1),
            init_lora(jax.random.key(i + 1), params, RANK),
        )
        for i in range(3)
    }
    pool = AdapterPool(params, slots=4, rank=RANK, mesh=mesh)
    for name, ad in adapters.items():
        pool.add(name, ad)
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, adapter_pool=pool, batch_size=4,
        max_new_tokens=NEW, refill_chunk=8, mixed=True, recorder=rec,
    )
    tenants = [None, "t0", "t1", "t2"]
    names = {i: tenants[i % len(tenants)] for i in range(NREQ)}
    mixed = drive(eng, params, dict(enumerate(prompts)), adapters=names)
    assert eng.compile_counts().get("adapter_mixed_step", 0) >= 1
    print(f"case23: {NREQ} requests across base + {len(adapters)} "
          f"tenants in one fused batch")

    for name in tenants:
        rids = [r for r, n in names.items() if n == name]
        merged = params if name is None else merge_lora(
            params, adapters[name]
        )
        solo = ContinuousEngine(
            cfg, mesh, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
            refill_chunk=8, mixed=True,
        )
        ref = drive(solo, merged, {r: prompts[r] for r in rids})
        solo.close()
        for r in rids:
            np.testing.assert_array_equal(mixed[r], ref[r])
    adapter_dispatches = int(
        eng.registry.counter("engine_adapter_dispatches_total").value
    )
    assert adapter_dispatches >= 1
    eng.close()
    print(f"  every stream bit-identical to its tenant's merge_lora "
          f"solo engine ✓ ({adapter_dispatches} adapter dispatches)")
    summary["multi_lora"] = {
        "tenants": len(adapters), "requests": NREQ,
        "bit_identical_to_solo": True,
        "adapter_dispatches": adapter_dispatches,
    }

    # --- 2. saturated hot-swap on one engine -------------------------
    new_params = jax.tree.map(lambda x: np.asarray(x) * 1.05, params)
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
        refill_chunk=8, mixed=True, recorder=rec,
    )
    for i, p in enumerate(prompts):
        eng.add_request(p, rid=i)
    eng.step(params)             # work in flight — the queue is saturated
    assert eng.swap_weights(new_params, version=1)
    swapped = {}
    steps = 0
    while eng.has_work():
        eng.step(params)         # stale tree: the commit overrides it
        swapped.update(eng.pop_finished())
        steps += 1
        assert steps <= 500, "engine wedged"
    swapped.update(eng.pop_finished())
    assert not any(isinstance(v, RequestFailure) for v in swapped.values())
    vers = dict(eng.finished_versions)
    assert sorted(vers) == list(range(NREQ))
    assert set(vers.values()) == {0, 1}, vers
    stalls = [e["stall_s"] for e in rec.events("engine.swap_commit")]
    assert len(stalls) == 1
    eng.close()
    n_old = sum(1 for v in vers.values() if v == 0)
    print(f"  saturated swap: 0 dropped, {n_old} responses on v0 / "
          f"{NREQ - n_old} on v1, commit stall "
          f"{stalls[0] * 1e3:.0f} ms")
    summary["hot_swap"] = {
        "requests": NREQ, "dropped": 0,
        "versions": {str(v): sum(1 for x in vers.values() if x == v)
                     for v in sorted(set(vers.values()))},
        "commit_stall_s": stalls[0],
    }

    # --- 3. fleet rolling swap, per-version oracle -------------------
    host_old = jax.tree.map(np.asarray, params)
    host_new = jax.tree.map(np.asarray, new_params)
    fmesh = build_mesh((1, 2), ("data", "model"), devices=jax.devices()[:2])
    oracle = ContinuousEngine(
        cfg, fmesh, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        refill_chunk=8,
    )
    ref_old = oracle.serve(replicated_params(host_old, fmesh), prompts)
    ref_new = oracle.serve(replicated_params(host_new, fmesh), prompts)
    oracle.close()

    reps = make_replicas(
        cfg, RULES_DP_TP, host_old, count=2, mesh_shape=(1, 2),
        batch_size=2, max_new_tokens=NEW, refill_chunk=8, recorder=rec,
    )
    router = FleetRouter(reps, recorder=rec)
    for i, p in enumerate(prompts):
        router.add_request(p, rid=i)
    for _ in range(2):           # in flight before the rollout begins
        router.step()
    timeline = router.rolling_swap(host_new, version=2)
    assert all(t["committed"] for t in timeline), timeline
    for i, p in enumerate(prompts):
        router.add_request(p, rid=100 + i)
    results = {}
    steps = 0
    while router.has_work():
        router.step()
        results.update(router.pop_finished())
        steps += 1
        assert steps <= 2000, "fleet wedged"
    results.update(router.pop_finished())
    failures = {r: v for r, v in results.items()
                if isinstance(v, RequestFailure)}
    assert not failures, f"rolling swap dropped requests: {failures}"
    versions = {}
    for rep in reps:
        versions.update(rep.engine.finished_versions)
    for i in range(NREQ):
        assert versions[i] in (0, 2), versions
        np.testing.assert_array_equal(
            results[i], ref_old[i] if versions[i] == 0 else ref_new[i]
        )
        assert versions[100 + i] == 2, versions
        np.testing.assert_array_equal(results[100 + i], ref_new[i])
    n_v0 = sum(1 for i in range(NREQ) if versions[i] == 0)
    print(f"  rolling swap: {len(timeline)}/2 replicas → v2, 0 dropped, "
          f"{n_v0}+{2 * NREQ - n_v0} responses matched the "
          f"v0/v2 single-engine oracles bit for bit")
    summary["rolling_swap"] = {
        "replicas": len(timeline),
        "committed": sum(1 for t in timeline if t["committed"]),
        "requests": 2 * NREQ, "dropped": 0,
        "responses_on_v0": n_v0,
        "per_version_bit_identical": True,
        "drain_steps": [t["drain_steps"] for t in timeline],
    }

    # --- artifacts ---------------------------------------------------
    write_swap_timeline(out / "swap_timeline.json", timeline)
    (out / "metrics.prom").write_text(router.prometheus_text())
    (out / "events.json").write_text(
        json.dumps(
            [e for e in rec.events() if not e["kind"].startswith("span")]
            [-2000:],
            indent=2, default=str,
        )
    )
    (out / "tenancy_summary.json").write_text(
        json.dumps(summary, indent=2, default=str)
    )
    print(f"case23: artifacts in {out}")
    print("case23 PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
