"""Case 19 — runtime diagnosis: the telemetry layer turns numbers into WHY.

Case 18 showed the stack measuring itself (spans, registry, compile
accounting). This driver induces three production incidents on the
8-device emulated mesh and shows stage 2 DIAGNOSING each one:

1. INDUCED NaN — a training run whose step-4 batch poisons the loss
   (0/0). The :class:`telemetry.Watchdog` probes loss + global grad-norm
   on device (async — no extra sync), names the failing step, the
   escalation re-runs the offending batch under
   ``utils.profiling.checking()`` to localize the first NaN-producing
   primitive, and the :class:`telemetry.FlightRecorder` dumps a
   post-mortem bundle (events + registry + trace + device memory stats).
2. INDUCED IMBALANCE — a parameter tree with one tensor accidentally
   committed to a single device. :func:`telemetry.shard_imbalance` reads
   exact per-device bytes off every leaf's sharding and flags the stray
   by path.
3. SLO BREACH — a :class:`telemetry.SLOMonitor` attached to a
   :class:`ContinuousEngine` run, with one impossible TTFT target (every
   request breaches: burn rate screams) and one loose target (healthy),
   streaming percentiles riding the same window.

Plus the devview memory report (predicted ``MemoryPlan`` vs live device
stats — PLAN-ONLY here: emulated CPU devices return no memory stats, the
guarded degradation tier-1 pins) and per-mesh-axis collective byte
attribution for the engine's decode step.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case19``, else a
temp dir): ``report.json`` + the post-mortem bundle under ``postmortem/``.

Run: ``python cases/case19_diagnosis.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses
import json
import pathlib
import sys

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.data.datasets import SyntheticLMDataset
from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    RULES_TP_SERVING,
)
from learning_jax_sharding_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    NonFiniteError,
    SLOMonitor,
    SLOTarget,
    Tracer,
    Watchdog,
    artifact_dir,
    axis_collective_volume,
    memory_report,
    shard_imbalance,
)
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit
from learning_jax_sharding_tpu.utils.memory import memory_plan

outdir = (
    pathlib.Path(sys.argv[1]) if len(sys.argv) > 1 else artifact_dir("case19")
)
outdir.mkdir(parents=True, exist_ok=True)
report: dict = {}

mesh = build_mesh((2, 4), ("data", "model"))
cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)

# --- incident 1: induced NaN → watchdog names the step, bundle dumps ----
POISON_INDEX = 4            # batch index 4 → train step 5 (1-based logging)
SENTINEL = cfg.vocab_size   # out-of-vocab marker (embedding lookup clamps)


class PoisonedDataset(SyntheticLMDataset):
    """Synthetic stream whose batch ``POISON_INDEX`` carries the sentinel."""

    def batch(self, index, rows=None, batch_size=8):
        b = super().batch(index, rows=rows, batch_size=batch_size)
        if index == POISON_INDEX:
            b["inputs"] = b["inputs"].copy()
            b["inputs"][0, 0] = SENTINEL
        return b


def trip_loss(y, batch):
    # 0/0 exactly when the sentinel is present: NaN from DATA, the shape
    # of incident the escalation localizes exactly.
    bad = jnp.any(batch["inputs"] >= SENTINEL).astype(jnp.float32)
    return next_token_loss(y, batch) + bad * 0.0 / (1.0 - bad)


recorder = FlightRecorder()
registry = MetricsRegistry()
tracer = Tracer()
watchdog = Watchdog(registry=registry, recorder=recorder, lag=2)
dataset = PoisonedDataset(vocab_size=cfg.vocab_size, seq_len=32, seed=19)
train_cfg = TrainLoopConfig(steps=8, global_batch_size=8, prefetch=0)

bundle = None
try:
    fit(
        Transformer(cfg), dataset, mesh, RULES_DP_TP, train_cfg,
        loss_fn=trip_loss, registry=registry, tracer=tracer,
        watchdog=watchdog, recorder=recorder,
    )
    raise AssertionError("poisoned run was supposed to trip the watchdog")
except NonFiniteError as e:
    assert e.step == POISON_INDEX + 1, (e.step, POISON_INDEX + 1)
    assert watchdog.first_bad_step == POISON_INDEX + 1
    assert e.localized and "nan" in e.localized.lower(), e.localized
    bundle = e.bundle
assert bundle is not None and bundle.is_dir()
events = json.loads((bundle / "events.json").read_text())["events"]
kinds = {ev["kind"] for ev in events}
assert "nonfinite" in kinds and "nan_localized" in kinds, kinds
assert "train_step" in kinds
assert (bundle / "registry.json").exists()
assert (bundle / "memory.json").exists()
assert (bundle / "error.txt").exists()
assert registry.get("watchdog_nonfinite_total").value >= 1
report["induced_nan"] = {
    "flagged_step": watchdog.first_bad_step,
    "bundle": str(bundle),
    "event_kinds": sorted(kinds),
}
print(
    f"PASS: induced NaN at step {POISON_INDEX + 1} — watchdog flagged step "
    f"{watchdog.first_bad_step}, escalation localized the primitive, "
    f"post-mortem bundle at {bundle}/"
)

# --- incident 2: induced shard imbalance --------------------------------
even = jax.device_put(
    np.ones((64, 128), np.float32), NamedSharding(mesh, P("data", "model"))
)
stray = jax.device_put(np.ones((512, 64), np.float32), jax.devices()[0])
audit = shard_imbalance({"layers": {"even": even, "stray_head": stray}})
assert audit["imbalanced"], audit
flagged = [f["path"] for f in audit["flagged"]]
assert any("stray_head" in p for p in flagged), flagged
assert not any("'even'" in p for p in flagged), flagged
report["imbalance"] = {
    "skew": audit["skew"],
    "flagged": flagged,
    "per_device_bytes": audit["per_device_bytes"],
}
print(
    f"PASS: shard-imbalance audit — skew {audit['skew']:.2f}x, flagged "
    + ", ".join(flagged)
)

# --- incident 3: SLO breach on a ContinuousEngine run -------------------
scfg = dataclasses.replace(cfg, decode_attention="blocked")
model = Transformer(scfg)
params = nn.meta.unbox(
    jax.jit(lambda r, t: model.init({"params": r}, t))(
        jax.random.key(3), np.zeros((2, 8), np.int32)
    )["params"]
)
slo = SLOMonitor(
    [
        SLOTarget("ttft", 1e-9, objective=0.9, name="ttft_impossible"),
        SLOTarget("ttft", 1e3, objective=0.9, name="ttft_loose"),
    ]
)
engine = ContinuousEngine(
    scfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=4,
    refill_chunk=4, slo=slo, recorder=recorder,
)
rng = np.random.default_rng(19)
prompts = [
    rng.integers(1, scfg.vocab_size, size=(n,)).astype(np.int32)
    for n in (3, 9, 5)
]
engine.serve(params, prompts)
snap = slo.snapshot()
assert slo.burn_rate("ttft_impossible") > 1.0, snap["targets"]
assert "ttft_impossible" in slo.breached()
assert "ttft_loose" not in slo.breached()
assert snap["metrics"]["ttft"]["p50"] > 0
assert snap["metrics"]["queue_wait"]["count"] == len(prompts)
prom = engine.registry.prometheus_text()
assert "slo_ttft_impossible_breaches_total" in prom
assert "slo_ttft_impossible_burn_rate" in prom
report["slo"] = snap
print(
    f"PASS: SLO monitor — ttft p50 {snap['metrics']['ttft']['p50'] * 1e3:.0f} "
    f"ms, impossible-target burn rate "
    f"{snap['targets']['ttft_impossible']['burn_rate']:.1f} (breached), "
    f"loose target healthy"
)

# --- devview: predicted-vs-actual memory + per-axis collective bytes ----
plan = memory_plan(cfg, 8, 32)
mem = memory_report(plan)
assert mem["predicted"]["total"] > 0
# Emulated CPU devices report no memory stats: the guarded plan-only path.
assert mem["actual_available"] is False
axis_vol = engine.collective_axis_volume()
decode = axis_vol["decode_block"]
moved = {k: v for k, v in decode.items() if v["bytes"]}
assert sum(v["bytes"] for v in decode.values()) > 0, decode
report["memory_report"] = mem
report["collective_axis_volume"] = axis_vol
print(
    "PASS: devview — memory report degraded to plan-only "
    f"(predicted total {mem['predicted']['total'] / 1e6:.1f} MB), decode "
    "collective bytes per axis: "
    + ", ".join(f"{k}={v['bytes']}" for k, v in moved.items())
)

with open(outdir / "report.json", "w") as f:
    json.dump(report, f, indent=2, sort_keys=True, default=str)
print(f"PASS: case19 — diagnosis report at {outdir}/report.json, "
      f"post-mortem bundle at {bundle}/")
