"""Case 18 — unified telemetry: one layer answers the three questions.

The reference's entire observability story is ``visualize_array_sharding``
plus one flawed timing loop (SURVEY.md §5; `case6_attention.py:234-238`
times async dispatch with no sync). This driver runs the serving engine
under the round-6 telemetry subsystem and shows that ONE layer answers,
per request and per step:

1. WHERE DID THE TIME GO — the engine's tracer records a per-request
   span timeline (arrival → admit → first token → finish) and
   per-dispatch refill/decode spans, exported as Perfetto-loadable
   Chrome trace JSON (plus JSONL); spans bridge into
   ``jax.profiler.TraceAnnotation`` so an XProf capture shows the same
   phases against device ops.
2. WHAT IS THE ENGINE DOING — the metrics registry (counters, gauges,
   fixed-bucket histograms) carries queue depth, page-pool live/high
   water, acceptance counters, latency histograms; exported as
   Prometheus text exposition and a JSON snapshot. ``last_stats`` /
   ``last_latency`` are window deltas over the SAME registry.
3. DID XLA DO WHAT WE THINK — compile_watch counts compiles and compile
   seconds (process-wide via jax.monitoring, per-program via the
   executable cache), and the engine's ``collective_inventory()`` reads
   the per-dispatch collective ops straight off its compiled HLO.

Artifacts (written to ``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case18``,
else a fresh temp dir — never the CWD; open trace.json in
https://ui.perfetto.dev):

* ``trace.json``   — Chrome trace events (Perfetto)
* ``events.jsonl`` — the same events, one JSON object per line
* ``metrics.prom`` — Prometheus text exposition
* ``report.json``  — run report: TTFT/TPOT percentiles, page-pool
  high-water, compile counts/seconds, per-step collective counts
* ``xprof/``       — a jax.profiler capture of the traced steps

Run: ``python cases/case18_observability.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses
import json
import pathlib
import sys

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.hlo import COLLECTIVE_OPS
from learning_jax_sharding_tpu.parallel.logical import RULES_TP_SERVING
from learning_jax_sharding_tpu.telemetry import CompileWatch, artifact_dir
from learning_jax_sharding_tpu.utils.profiling import trace

outdir = (
    pathlib.Path(sys.argv[1]) if len(sys.argv) > 1 else artifact_dir("case18")
)
outdir.mkdir(parents=True, exist_ok=True)

mesh = build_mesh((2, 4), ("data", "model"))
cfg = dataclasses.replace(
    CONFIG_TINY, dtype=jnp.float32, decode_attention="blocked"
)
model = Transformer(cfg)
params = nn.meta.unbox(
    jax.jit(lambda r, t: model.init({"params": r}, t))(
        jax.random.key(3), np.zeros((2, 8), np.int32)
    )["params"]
)
rng = np.random.default_rng(18)
NEW = 6
system = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
prompts = [system] + [
    rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
    for n in (3, 9, 12)
] + [system.copy()]

watch = CompileWatch()
engine = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4, paged_pages=12, page_size=16, prefix_cache=True,
)

# --- serve the queue under the watch, with an XProf capture ------------
with watch:
    # Streaming admission: the first two requests arrive up front, the
    # rest while the engine is mid-flight — a real arrival process, with
    # a jax.profiler capture around the traced steps so the engine's
    # TraceAnnotations land in XProf next to the device ops.
    rids, results, late = [], {}, list(prompts[2:])
    with trace(outdir / "xprof"):
        for p in prompts[:2]:
            rids.append(engine.add_request(p))
        steps = 0
        while engine.has_work() or late:
            engine.step(params)
            results.update(engine.pop_finished())
            steps += 1
            if late and steps >= 2:
                rids.append(engine.add_request(late.pop(0)))
lat = engine.latency_stats()      # the streaming session's window
compiles_after_stream = engine.compile_counts()
assert len(results) == len(prompts)

# A second windowed serve() call: last_stats/last_latency must be the
# registry-derived window, and the repeated system prompt must hit the
# prefix registry populated by the streaming session above.
out2 = engine.serve(params, [system.copy()])
assert engine.last_stats["prefix_hits"] == 1, engine.last_stats
np.testing.assert_array_equal(out2[0], results[rids[0]])
print(
    "PASS: streaming + one-shot serving under telemetry — prefix hit "
    "across sessions, outputs bit-identical"
)

# --- pillar 1: the trace ------------------------------------------------
engine.tracer.dump_chrome_trace(outdir / "trace.json")
engine.tracer.dump_jsonl(outdir / "events.jsonl")
events = engine.tracer.events
names = {e["name"] for e in events}
for needed in (
    "request.arrival", "request.admit", "request.first_token",
    "request", "engine.refill", "engine.decode",
):
    assert needed in names, (needed, sorted(names))
begins = [e for e in events if e["ph"] == "b" and e["name"] == "request"]
ends = [e for e in events if e["ph"] == "e" and e["name"] == "request"]
assert {e["id"] for e in begins} == {e["id"] for e in ends}
xplane = list((outdir / "xprof").rglob("*.xplane.pb"))
assert xplane, "no XProf capture landed"
print(
    f"PASS: {len(events)} trace events (complete/instant/async), "
    f"{len(begins)} request timelines, XProf capture at "
    f"{xplane[0].parent.name}/"
)

# --- pillar 2: the registry ---------------------------------------------
engine.registry.dump_prometheus(outdir / "metrics.prom")
prom = (outdir / "metrics.prom").read_text()
for needed in (
    "# TYPE engine_requests_finished_total counter",
    "# TYPE engine_pages_live gauge",
    "# TYPE engine_ttft_seconds histogram",
    "engine_ttft_seconds_bucket{le=\"+Inf\"}",
):
    assert needed in prom, needed
snap = engine.registry.snapshot()
assert snap["engine_requests_finished_total"] == len(prompts) + 1
assert snap["engine_pages_live__high_water"] >= 1
print(
    "PASS: Prometheus exposition + JSON snapshot — "
    f"{int(snap['engine_requests_finished_total'])} requests, "
    f"{int(snap['engine_tokens_generated_total'])} tokens, page "
    f"high-water {int(snap['engine_pages_live__high_water'])}"
)

# --- pillar 3: compile accounting + collective inventory ----------------
compiles = engine.compile_counts()
# Warmup is ≤2 executables per program (the 2nd call re-specializes to
# the steady-state cache shardings); the pinned claim is that the whole
# SECOND serving session compiled NOTHING — a mid-serve recompile is
# the failure this probe exists to catch.
assert compiles == compiles_after_stream, (compiles_after_stream, compiles)
assert all(v is not None and v <= 2 for v in compiles.values()), compiles
inventory = engine.collective_inventory()
assert "decode_block" in inventory and "refill_step" in inventory
for counts in inventory.values():
    assert set(counts) == set(COLLECTIVE_OPS)
# TP serving on the (2,4) mesh: the decode step must put collectives on
# the wire (GSPMD chooses which — the inventory makes it checkable).
assert sum(inventory["decode_block"].values()) > 0, inventory
cw = watch.report()
print(
    f"PASS: compile accounting — steady state after warmup "
    f"{compiles}, {cw['backend_compiles']} backend compiles / "
    f"{cw['backend_compile_seconds']:.1f} s under the watch; decode "
    f"collectives per step: "
    + ", ".join(f"{k}={v}" for k, v in inventory["decode_block"].items()
                if v)
)

# --- the run report ------------------------------------------------------
report = {
    "requests": lat["requests"] + 1,
    "ttft_p50": lat["ttft_p50"],
    "ttft_p99": lat["ttft_p99"],
    "tpot_p50": lat.get("tpot_p50"),
    "tpot_p99": lat.get("tpot_p99"),
    "queue_wait_p50": lat["queue_wait_p50"],
    "refill_frac": lat["refill_frac"],
    "page_pool": {
        "high_water": int(snap["engine_pages_live__high_water"]),
        "total": engine.last_stats["pages_total"],
        "prefix_hits_last_window": engine.last_stats["prefix_hits"],
    },
    "compile": {
        "per_program_compiles": compiles,
        "backend_compiles": cw["backend_compiles"],
        "backend_compile_seconds": cw["backend_compile_seconds"],
        "monitoring_available": cw["monitoring_available"],
    },
    "collectives_per_step": inventory,
    "registry": snap,
}
with open(outdir / "report.json", "w") as f:
    json.dump(report, f, indent=2, sort_keys=True)
for k in ("ttft_p50", "ttft_p99", "tpot_p50"):
    assert report[k] is not None and report[k] > 0, (k, report[k])
print(
    f"PASS: run report — TTFT p50 {report['ttft_p50'] * 1e3:.0f} ms / "
    f"p99 {report['ttft_p99'] * 1e3:.0f} ms, TPOT p50 "
    f"{report['tpot_p50'] * 1e3:.1f} ms, refill "
    f"{report['refill_frac']:.0%} of dispatched time"
)

print(
    f"PASS: case18 — telemetry artifacts in {outdir}/ (open trace.json "
    "in ui.perfetto.dev; point Prometheus at metrics.prom; xprof/ in "
    "TensorBoard)"
)
