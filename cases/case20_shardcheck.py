"""Case 20 — shardcheck: static analysis catches what PR 1/2 could only
watch happen.

The observability PRs (cases 18/19) MEASURE and DIAGNOSE the runtime;
this driver shows the static layer catching the same failure classes
BEFORE a step runs, on the 8-device emulated mesh:

1. SEEDED MISSED DONATION — the framework's own train step built with
   ``donate_state=False``: the donation pass reads the executable's
   input/output aliases, flags every state leaf as
   ``donation-missed``, and prices the regression with the
   ``utils.memory`` planner (the 2× params+moments HBM a real run would
   silently pay). The default (donating) step audits clean.
2. SEEDED WEIGHT GATHER — a column-parallel matmul goldened at zero
   collectives, then recompiled with the weight row-sharded (the classic
   wrong ``in_sharding``): GSPMD inserts communication and the contract
   diff names it (``added-collective``), instead of the bytes quietly
   riding every future step.
3. CLEAN-REPO BASELINE — all three passes over the repo as checked in:
   every entry-point contract (``analysis/golden/*.json``) holds, the
   donation audit of the shipped train/ZeRO-1 steps is clean, and the
   AST lint gates at zero new findings under ``analysis/baseline.json``.

All findings are also reported into a flight recorder + registry
(``analysis.findings.report_findings``), so static verdicts ride the
same diagnosis surfaces as case 19's runtime incidents.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case20``, else a
temp dir): ``report.json``.

Run: ``python cases/case20_shardcheck.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import json
import pathlib
import sys

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.analysis import (
    check_against_golden,
    check_train_step_donation,
    contract_of,
    report_findings,
    run_ast_pass,
    run_contract_pass,
    run_jaxpr_pass,
)
from learning_jax_sharding_tpu.analysis.entrypoints import (
    _mesh24,
    _train_state_and_step,
)
from learning_jax_sharding_tpu.parallel.logical import activate
from learning_jax_sharding_tpu.telemetry import MetricsRegistry
from learning_jax_sharding_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    artifact_dir,
)
from learning_jax_sharding_tpu.training.pipeline import make_train_step

outdir = (
    pathlib.Path(sys.argv[1]) if len(sys.argv) > 1 else artifact_dir("case20")
)
outdir.mkdir(parents=True, exist_ok=True)
recorder = FlightRecorder()
registry = MetricsRegistry()
report: dict = {}

mesh = _mesh24()

# --- seed 1: the deliberately missed donation ---------------------------
print("== seed 1: train step with donate_state=False ==")
cfg, state, batch, good_step, rules = _train_state_and_step(mesh)
bad_step = make_train_step(
    jax.tree.map(lambda x: x.sharding, state),
    {k: v.sharding for k, v in batch.items()},
    mesh, rules, donate_state=False,
)
with activate(mesh, rules):
    bad = check_train_step_donation(bad_step, state, batch, cfg=cfg)
    good = check_train_step_donation(good_step, state, batch, cfg=cfg)

missed = [f for f in bad["findings"] if f.rule == "donation-missed"]
assert missed, "undonated train step was not flagged"
assert not good["findings"], (
    f"the donating step must audit clean, got {good['findings']}"
)
print(f"   caught: {len(missed)} state leaves eligible-but-not-donated, "
      f"planner prices the miss at "
      f"{bad['missed_donation_bytes'] / 1e6:.1f} MB")
print(f"   e.g. {missed[0]}")
report_findings(missed, recorder=recorder, registry=registry)
report["seed_missed_donation"] = {
    "flagged_leaves": len(missed),
    "planner_bytes_at_stake": bad["missed_donation_bytes"],
    "donating_step_clean": not good["findings"],
}

# --- seed 2: forced weight gather via a wrong in_sharding ---------------
print("== seed 2: weight resharded against its golden contract ==")


def mm(x, w):
    return x @ w


x = np.ones((16, 64), np.float32)
w = np.ones((64, 128), np.float32)
out_sh = NamedSharding(mesh, P(None, "model"))
f = jax.jit(mm, out_shardings=out_sh)
x_rep = jax.device_put(x, NamedSharding(mesh, P()))
w_col = jax.device_put(w, NamedSharding(mesh, P(None, "model")))
golden = contract_of("case20_mm", f, x_rep, w_col, mesh=mesh)
assert golden.collectives == {}, golden.collectives  # column-parallel
golden_dir = outdir / "golden"
golden_dir.mkdir(exist_ok=True)
(golden_dir / "case20_mm.json").write_text(golden.to_json())

w_row = jax.device_put(w, NamedSharding(mesh, P("model", None)))
drifted = contract_of("case20_mm", f, x_rep, w_row, mesh=mesh)
drift = check_against_golden(golden_dir, drifted)
assert drift, "wrong weight sharding compiled to the same collectives"
assert all(fi.rule == "added-collective" for fi in drift)
print(f"   caught: {[str(fi) for fi in drift]}")
report_findings(drift, recorder=recorder, registry=registry)
report["seed_wrong_sharding"] = {
    "violations": [fi.to_dict() for fi in drift],
}

# --- clean-repo baseline: all three passes ------------------------------
print("== clean repo: contracts + jaxpr/donation + ast ==")
from learning_jax_sharding_tpu.analysis.entrypoints import (
    build_entry_programs,
)

# One shared program list: the jaxpr pass reuses the contract pass's
# cached AOT compiles instead of re-paying them (the CLI does the same).
programs = build_entry_programs()
contract_findings = run_contract_pass(programs=programs)
jaxpr_findings = run_jaxpr_pass(programs=programs)
ast_findings = run_ast_pass(pathlib.Path(__file__).resolve().parents[1])
for name, fs in (
    ("contracts", contract_findings),
    ("jaxpr", jaxpr_findings),
    ("ast", ast_findings),
):
    for fi in fs:
        print(f"   UNEXPECTED {fi}")
    assert not fs, f"clean-repo {name} pass found {len(fs)} finding(s)"
print("   contracts hold for all golden entry points; donation audit "
      "clean; AST lint at zero under baseline")

# The jaxpr budgets must be TIGHT, not just sufficient: a ceiling looser
# than reality silently absorbs that many NEW dead equations forever.
# (tests/test_repo_lint.py pins the same property for the AST budgets;
# this is the compile-side counterpart, checked here because this case
# already paid the compiles.)
from learning_jax_sharding_tpu.analysis import BASELINE_PATH

budgets = json.loads(BASELINE_PATH.read_text()).get("jaxpr_budgets", {})
for prog in programs:
    if prog.jaxpr is None:
        continue
    counts: dict = {}
    for fi in prog.jaxpr():
        counts[fi.rule] = counts.get(fi.rule, 0) + 1
    allowed = {
        k: v for k, v in budgets.get(prog.name, {}).items()
        if not k.startswith("_")
    }
    assert counts == allowed, (
        f"jaxpr budget for {prog.name} is stale/loose: "
        f"actual {counts} vs budget {allowed} — tighten baseline.json"
    )
print("   jaxpr budgets are tight (actual counts == ceilings)")
report["clean_repo"] = {
    "contracts": 0, "jaxpr": 0, "ast": 0, "jaxpr_budgets_tight": True,
}

# --- verdicts land in the diagnosis surfaces ----------------------------
events = recorder.events("shardcheck_finding")
assert len(events) == len(missed) + len(drift)
assert any(
    k.startswith("shardcheck_") for k in registry.snapshot()
)
report["telemetry_wiring"] = {
    "recorder_events": len(events),
    "registry_series": sorted(
        k for k in registry.snapshot() if k.startswith("shardcheck_")
    ),
}

(outdir / "report.json").write_text(json.dumps(report, indent=2))
print(f"case20 artifacts: {outdir}")
print("case20: all seeded violations caught; clean repo passes. OK")
