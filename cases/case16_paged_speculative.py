"""Case 16 — the round-4 serving engine: paged KV + speculative decoding.

Not in the reference (it has no inference path, SURVEY.md §5). The
production levers a serving engine runs with, demonstrated end to end on
a (data, model) mesh and proven against the case-15 oracles:

1. Train a target AND a 4× smaller draft on the same learnable stream.
2. PAGED KV: cache slots stop owning ``max_seq_len`` of HBM — physical
   pages are allocated as tokens arrive and freed at retirement, behind
   host-owned block tables the kernel indirects through. Outputs stay
   bit-identical; ``serve.last_stats`` shows the measured footprint.
3. SPECULATIVE decode blocks: the draft proposes, the target verifies in
   one chunk, acceptance and cache rewind are per-row. Greedy output is
   bit-identical to plain serving — the draft only changes how many
   target dispatches the tokens cost.
4. SPECULATIVE SAMPLING: temperature > 0 through the same blocks, every
   draw keyed by (request id, generated position, stream) — the same
   queue served with different batch sizes yields identical tokens.

Run: ``python cases/case16_paged_speculative.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses

import jax
import numpy as np

from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_TP_SERVING
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit


class CyclicDataset:
    """token(i+1) = token(i) + 1 (mod V): learnable in a few steps."""

    def __init__(self, vocab_size, seq_len):
        self.vocab_size, self.seq_len = vocab_size, seq_len

    def batch(self, index, rows=None, batch_size=8):
        rng = np.random.default_rng((16, index))
        starts = rng.integers(0, self.vocab_size, size=batch_size)
        if rows is not None:
            starts = starts[rows]
        toks = (starts[:, None] + np.arange(self.seq_len + 1)[None]) % self.vocab_size
        return {"inputs": toks[:, :-1].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32)}


def main():
    import flax.linen as nn

    # Paged pools are shared across rows (any row reads any page), so the
    # engine requires batch-replicated rules: model-parallel only.
    mesh = build_mesh((1, 4), ("data", "model"), devices=jax.devices()[:4])
    cfg = dataclasses.replace(
        CONFIG_TINY, dtype=jax.numpy.float32, decode_attention="blocked",
        decode_block_k=16,
    )
    draft_cfg = dataclasses.replace(cfg, num_layers=1, hidden=64)
    new, page = 8, 16

    def train(c, label):
        state, history = fit(
            Transformer(c), CyclicDataset(c.vocab_size, 32), mesh,
            RULES_TP_SERVING,
            TrainLoopConfig(steps=40, global_batch_size=16,
                            learning_rate=3e-3, log_every=40),
        )
        print(f"{label}: loss -> {history[-1]['loss']:.3f}")
        return nn.meta.unbox(state.params)

    print("training target (2L) and draft (1L) on the cyclic stream ...")
    params = train(cfg, "target")
    d_params = train(draft_cfg, "draft")

    rng = np.random.default_rng(3)
    queue = [
        ((int(rng.integers(0, cfg.vocab_size)) + np.arange(n))
         % cfg.vocab_size).astype(np.int32)
        for n in (4, 12, 2, 30, 7, 5, 9, 3)
    ]

    def engine(**kw):
        return make_continuous_engine(
            cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=new,
            refill_chunk=8, decode_block_steps=2, **kw,
        )

    # --- 1. The plain-engine reference (case 15's proven oracle) ---
    ref = engine()(params, queue)

    # --- 2. Paged KV: same outputs, measured footprint ---
    paged = engine(paged_pages=9, page_size=page)
    got = paged(params, queue)
    for r, g in zip(ref, got):
        assert (r == g).all(), (r, g)
    stats = paged.last_stats
    slot_pages = 2 * (cfg.max_seq_len // page)
    assert stats["page_high_water"] < slot_pages
    print(f"PASS: paged engine bit-identical; high-water "
          f"{stats['page_high_water']} pages vs {slot_pages} the slots "
          f"would reserve")

    # --- 3. Speculative decode blocks: greedy output unchanged ---
    spec = engine(draft_config=draft_cfg, num_draft=3,
                  paged_pages=9, page_size=page)
    got = spec(params, queue, draft_params=d_params)
    for r, g in zip(ref, got):
        assert (r == g).all(), (r, g)
    print("PASS: speculative (paged) engine — greedy outputs bit-identical "
          "to plain serving; the trained draft only changes dispatch count")

    # --- 4. Speculative SAMPLING: schedule-independent streams ---
    outs = []
    for bs in (2, 4):
        s = make_continuous_engine(
            cfg, mesh, RULES_TP_SERVING, batch_size=bs, max_new_tokens=new,
            refill_chunk=8, draft_config=draft_cfg, num_draft=3,
            temperature=1.0, top_k=8,
        )
        outs.append(s(params, queue, rng=jax.random.key(4),
                      draft_params=d_params))
    for a, b in zip(*outs):
        assert (a == b).all(), (a, b)
    print("PASS: speculative sampling — same queue, batch 2 vs 4, "
          "identical sampled tokens per request")
    print("PASS: case16 — paged + speculative serving, proven against the "
          "plain engine")


if __name__ == "__main__":
    main()
