"""Case 22 — fleet serving: disaggregated prefill/decode with a replica
kill mid-stream.

The round-11 subsystem, end to end on the emulated 8-device mesh:

* **topology** — 2 PREFILL replicas (``max_new_tokens=1``) on devices
  0-3 and 2 DECODE replicas on devices 4-7, each a (1,2) sub-mesh; one
  :class:`~learning_jax_sharding_tpu.fleet.FleetRouter` in front;
* **streamed KV handoff** — every finished prefill's cache row crosses
  to a decode replica through the explicit resharding transfer plan
  (``fleet.kv_transfer`` — page-granular segments, counted bytes; the
  device-side ``kv_export``/``kv_ingest`` programs are golden-pinned to
  ZERO collectives);
* **failover** — one decode replica is KILLED mid-stream; its in-flight
  requests drain with visible ``"rerouted"`` terminals and recompute —
  re-prefilled and re-handed-off — on the survivor;
* **the oracle** — every request's final token stream is BIT-IDENTICAL
  to a single engine of the same (1,2) mesh shape serving the same
  queue: disaggregation, handoff, routing, and the kill change
  throughput and placement, never results;
* **fleet telemetry** — the per-replica registries merge into one
  labeled Prometheus exposition; every routing/handoff/failover
  decision is in the flight-recorder events dump.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case22``, else a
temp dir): ``fleet_summary.json`` (latency + per-replica counters),
``metrics.prom`` (labeled fleet exposition), ``events.json`` (the
recorder ring's fleet.* / engine.* timeline).

Run: ``python cases/case22_fleet_serving.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.fleet import (  # noqa: E402
    FleetRouter,
    make_replicas,
    replicated_params,
)
from learning_jax_sharding_tpu.models.serving import (  # noqa: E402
    ContinuousEngine,
    RequestFailure,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh  # noqa: E402
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    FlightRecorder,
    artifact_dir,
)

NREQ, NEW = 12, 8


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case22")
    )
    out.mkdir(parents=True, exist_ok=True)

    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), np.zeros((2, 8), np.int32)
        )["params"]
    )
    rng = np.random.default_rng(22)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in rng.integers(5, 14, size=NREQ)
    ]

    # The single-engine oracle, same (1,2) mesh shape as every replica.
    mesh = build_mesh((1, 2), ("data", "model"), devices=jax.devices()[:2])
    baseline = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        refill_chunk=8,
    )
    ref = baseline.serve(replicated_params(params, mesh), prompts)

    rec = FlightRecorder(max_events=65536)
    pre = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
        role="prefill", batch_size=2, max_new_tokens=1, refill_chunk=8,
        recorder=rec,
    )
    dec = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
        role="decode", offset=4, batch_size=2, max_new_tokens=NEW,
        refill_chunk=8, recorder=rec,
    )
    router = FleetRouter(pre + dec, recorder=rec)

    print(f"case22: 2 prefill + 2 decode replicas, {NREQ} requests, "
          f"killing decode1 mid-stream")
    for i, p in enumerate(prompts):
        router.add_request(p, rid=i)
    results = {}
    steps = 0
    killed = False
    while router.has_work():
        router.step()
        results.update(router.pop_finished())
        steps += 1
        if not killed and dec[1].engine.has_work():
            # Mid-stream BY CONSTRUCTION: decode1 holds ingested
            # in-flight requests right now — the kill must visibly
            # reroute them, not land on an idle replica.
            router.kill_replica("decode1", error="case22 induced kill")
            killed = True
            print("case22: decode1 killed with work in flight; "
                  "failing over")
        if steps > 2000:
            raise RuntimeError("fleet wedged")
    results.update(router.pop_finished())
    assert killed, "decode1 never took work — topology bug"

    failures = {
        r: v for r, v in results.items() if isinstance(v, RequestFailure)
    }
    assert not failures, f"requests failed: {failures}"
    mismatches = [
        i for i in range(NREQ)
        if not np.array_equal(results[i], ref[i])
    ]
    assert not mismatches, f"streams diverged from baseline: {mismatches}"
    rerouted = int(
        dec[1].engine.registry.counter("engine_rerouted_total").value
    )
    assert rerouted >= 1, "the kill must visibly reroute in-flight work"
    lat = router.latency_stats()
    reg = router.registry
    summary = {
        "requests": NREQ,
        "bit_identical": True,
        "killed": "decode1",
        "rerouted_on_dead_replica": rerouted,
        "failovers": reg.counter("fleet_failovers_total").value,
        "reroutes": reg.counter("fleet_reroutes_total").value,
        "handoffs": reg.counter("fleet_handoffs_total").value,
        "kv_transfer_bytes": reg.counter(
            "fleet_kv_transfer_bytes_total").value,
        "kv_transfer_segments": reg.counter(
            "fleet_kv_transfer_segments_total").value,
        "latency": lat,
        "replicas": router.fleet_snapshot()["replicas"],
    }
    (out / "fleet_summary.json").write_text(
        json.dumps(summary, indent=2, default=str)
    )
    (out / "metrics.prom").write_text(router.prometheus_text())
    (out / "events.json").write_text(
        json.dumps(
            [e for e in rec.events() if not e["kind"].startswith("span")]
            [-2000:],
            indent=2, default=str,
        )
    )
    print(
        f"case22: {NREQ}/{NREQ} requests bit-identical to the "
        f"single-engine baseline across the kill "
        f"({summary['handoffs']:.0f} handoffs, "
        f"{summary['kv_transfer_bytes'] / 1e3:,.0f} kB streamed, "
        f"{rerouted} rerouted off the dead replica); artifacts in {out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
