"""Case 14 — sequence-parallel attention: ring vs Ulysses, side by side.

Not in the reference: it has no context parallelism of any kind (SURVEY.md
§2.4 — no ``ppermute``, no ``all_to_all``). The framework ships BOTH
standard strategies for sequences too long for one device, and this case
runs them against each other on the same model:

* **ring attention** (``ops/ring_attention.py``): the sequence stays
  sharded; k/v shards rotate around the mesh axis with ``lax.ppermute``
  (n−1 single-hop ICI transfers) under an online softmax. No head-count
  constraint; k/v traffic grows with the axis size.
* **Ulysses** (``ops/ulysses.py``): one ``all_to_all`` each way swaps the
  sequence shard for a head shard — every device computes COMPLETE
  attention for its subset of heads (so the flash kernel's tiling sees the
  full sequence). Four all-to-alls total, independent of sequence length;
  requires ``heads % axis == 0``.

Both are exact (parity against the single-device dense op, asserted below),
and both drive the SAME transformer through a sharded train step — the
attention backend is one constructor argument (``attn_fn``), which is the
point: sequence parallelism composes with the rest of the stack instead of
being a special mode.

Run: ``python cases/case14_sequence_parallel.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.ops.ring_attention import make_ring_attn_fn
from learning_jax_sharding_tpu.ops.ulysses import make_ulysses_attn_fn
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.hlo import collective_counts
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_SP
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


def main():
    # data=2 × model=4: the 'model' axis carries the sequence shards
    # (RULES_DP_SP maps SEQ→model and leaves heads unmapped, which Ulysses
    # needs — it re-shards heads over that axis itself).
    mesh = build_mesh((2, 4), ("data", "model"))
    rng = np.random.default_rng(0)

    # --- 1. op-level parity: both strategies == single-device dense --------
    B, S, N, H = 4, 64, 4, 16
    q = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    want = dot_product_attention(q, k, v, mask=causal_mask(S))

    ring = make_ring_attn_fn(mesh, RULES_DP_SP)
    uly = make_ulysses_attn_fn(mesh, RULES_DP_SP)
    with jax.default_matmul_precision("float32"):
        got_ring = jax.jit(lambda a, b, c: ring(a, b, c, causal=True))(q, k, v)
        got_uly = jax.jit(lambda a, b, c: uly(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got_ring), np.asarray(want), atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_uly), np.asarray(want), atol=2e-5)
    print(f"parity vs dense (B{B} S{S} N{N} H{H}): ring OK, ulysses OK")

    # --- 2. the collectives are what the designs say they are --------------
    counts_ring = collective_counts(
        jax.jit(lambda a, b, c: ring(a, b, c, causal=True)), q, k, v
    )
    counts_uly = collective_counts(
        jax.jit(lambda a, b, c: uly(a, b, c, causal=True)), q, k, v
    )
    print(f"ring HLO:    {counts_ring}")
    print(f"ulysses HLO: {counts_uly}")
    assert counts_ring["collective-permute"] >= 1, "ring must ppermute k/v"
    assert counts_uly["all-to-all"] >= 2, "Ulysses must all_to_all both ways"
    assert counts_uly["collective-permute"] == 0

    # --- 3. both drive a full sharded train step ---------------------------
    for tag, fn in (("ring", ring), ("ulysses", uly)):
        cfg = dataclasses.replace(CONFIG_TINY, attn_fn=fn)
        tokens = rng.integers(0, cfg.vocab_size, size=(8, 33)).astype(np.int32)
        sh = mesh_sharding(mesh, "data", None)
        batch = {
            "inputs": put(tokens[:, :-1], sh),
            "targets": put(tokens[:, 1:], sh),
        }
        model = Transformer(cfg)
        state, state_sh = sharded_train_state(
            model, optax.adamw(3e-4), batch["inputs"],
            {"params": jax.random.key(0)}, mesh, RULES_DP_SP,
        )
        step = make_train_step(
            state_sh, {k_: v_.sharding for k_, v_ in batch.items()},
            mesh, RULES_DP_SP, loss_fn=next_token_loss,
        )
        state, loss = step(state, batch)
        assert np.isfinite(float(loss))
        print(f"{tag} train step on dp×sp mesh {dict(mesh.shape)}: "
              f"loss {float(loss):.4f}")

    print("PASS: ring and Ulysses sequence parallelism, op parity through "
          "train step")


if __name__ == "__main__":
    main()
