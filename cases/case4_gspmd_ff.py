"""Case 4 — GSPMD einsum + combined data×model parallel feed-forward.

Rebuild of `/root/reference/case4_gspmd_ff.py` (GSPMD paper §3.2, arXiv
2105.04663): part 1 runs a batched einsum; part 2 shards the FF projection's
activation rows over the data axis and its weight columns over the model
axis — the product is born fully 2D-sharded with **no collective at all**,
the combined DP×MP pattern of GSPMD Fig. 3. Shown twice: implicitly (GSPMD
infers everything from placements) and explicitly (the same schedule written
out with shard_map).

Run: ``python cases/case4_gspmd_ff.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import jax.numpy as jnp
import numpy as np

from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    assert_shard_shape,
    build_mesh,
    col_sharded,
    put,
    row_sharded,
    visualize,
)
from learning_jax_sharding_tpu.parallel.collectives import dp_tp_matmul


def main():
    mesh = build_mesh((2, 4), ("x", "y"))
    rng = np.random.default_rng(0)

    # Part 1: batched einsum (reference `case4_gspmd_ff.py:26-33`).
    arr_a = jnp.asarray(rng.standard_normal((8, 4, 16)), jnp.float32)
    arr_b = jnp.asarray(rng.standard_normal((8, 16, 4)), jnp.float32)
    c = jnp.einsum("ABC,ACD->ABD", arr_a, arr_b)
    assert c.shape == (8, 4, 4)
    print(f"batched einsum ABC,ACD->ABD: {arr_a.shape} x {arr_b.shape} -> {c.shape}")

    # Part 2: DP×MP feed-forward projection (reference `:36-58`).
    a_host = rng.standard_normal((4, 16)).astype(np.float32)
    b_host = rng.standard_normal((16, 4)).astype(np.float32)
    a = put(a_host, row_sharded(mesh, "x"))   # activations: batch rows over X
    b = put(b_host, col_sharded(mesh, "y"))   # weights: output cols over Y
    print("A(4,16) — rows (batch) over X:")
    visualize(a)
    assert_shard_shape(a, (2, 16))
    print("B(16,4) — columns (features) over Y:")
    visualize(b)
    assert_shard_shape(b, (16, 1))

    c = jax.jit(jax.lax.dot)(a, b)
    print("C = A·B (born 2D-sharded, GSPMD Fig. 3):")
    visualize(c)
    np.testing.assert_allclose(np.asarray(c), a_host @ b_host, rtol=1e-5)
    assert_shard_shape(c, (2, 1))
    counts = assert_collectives(
        jax.lax.dot, a, b, forbid=("all-reduce", "all-gather", "reduce-scatter")
    )
    print(f"collectives in compiled HLO: {counts} (none needed)")

    # The same schedule written explicitly with shard_map.
    c2 = dp_tp_matmul(a_host, b_host, mesh=mesh, dp_axis="x", tp_axis="y")
    np.testing.assert_allclose(np.asarray(c2), a_host @ b_host, rtol=1e-5)
    print("PASS: DP×MP product born fully sharded, implicit == explicit")


if __name__ == "__main__":
    main()
