"""Case 28 — the comm observatory, end to end.

The round-19 observability layer on one saturated mixed-engine serving
window, on the emulated 8-device (2x4) mesh:

* **measured link profiles** — the commscope calibration ladder times
  micro-collectives (psum / all-gather / ppermute) per mesh axis across
  a byte-size sweep and fits per-axis α–β models
  (``t = α + wire_bytes / β``), persisted as versioned JSON;
* **realized overlap attribution** — the goodput ledger's device bucket
  decomposed into compute / exposed-comm / overlapped-comm per program
  family, with per-dispatch predictions priced from the MEASURED
  profile (``costmodel.calibrate_axis_profiles``, pinned table as
  fallback) — the decomposition sums back to the device bucket exactly,
  so ``reconcile()`` stays green;
* **per-source-line attribution** — each family's measured collective
  seconds split across the source lines that cause the collectives
  (``analysis.shardflow`` events x the calibrated per-event price);
* **fleet-merge export** — ``comm_axis_bandwidth_bytes_per_s{axis}``
  and ``comm_exposed_seconds_total{family,axis}`` gauges in the
  engine's registry, scraped as Prometheus text.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case28``, else a
temp dir): ``profiles.json`` (the fitted ``CommProfile``),
``comm_report.json`` (overlap decomposition + per-line tables),
``metrics.prom`` (the labeled exposition).

Emulated-CPU caveat: every "link" is a memcpy through one shared host
memory system, so β is memcpy bandwidth and the axes look alike — the
instrument is honest about what dispatches cost HERE; chip-class
numbers require real hardware.

Run: ``python cases/case28_commscope.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.models.serving import (  # noqa: E402
    ContinuousEngine,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh  # noqa: E402
from learning_jax_sharding_tpu.parallel.logical import (  # noqa: E402
    RULES_DP_TP,
    activate,
    tree_shardings,
)
from learning_jax_sharding_tpu.telemetry import commscope  # noqa: E402
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    artifact_dir,
)

NREQ, NEW = 12, 8


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case28")
    )
    out.mkdir(parents=True, exist_ok=True)

    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    mesh = build_mesh((2, 4), ("data", "model"))
    model = Transformer(cfg)
    # Params born sharded under the serving rules — the shardflow
    # predictions read shardings off the committed argument leaves, so
    # replicated host params would price every program at zero comm.
    probe = np.zeros((2, 8), np.int32)

    def init(r, t):
        return model.init({"params": r}, t)

    with activate(mesh, RULES_DP_TP):
        abstract = jax.eval_shape(init, jax.random.key(0), probe)
        shardings = tree_shardings(abstract, mesh, RULES_DP_TP)
        params = jax.jit(
            lambda r, t: nn.meta.unbox(init(r, t)),
            out_shardings=shardings,
        )(jax.random.key(0), probe)["params"]

    # --- 1. the calibration ladder ------------------------------------------
    print("case28: timing the calibration ladder (reduced sweep) ...")
    profile = commscope.calibrate_mesh(
        mesh, ops=("psum", "all_gather", "ppermute"),
        sizes_bytes=(1 << 16, 1 << 19, 1 << 22),
    )
    errs = commscope.fit_errors(profile.axes, profile.measurements)
    for axis, ap in sorted(profile.axes.items()):
        print(f"[comm] axis {axis} (n={ap.n_devices}): "
              f"alpha {ap.alpha_s * 1e6:.1f} us, "
              f"beta {ap.beta_bytes_per_s / 1e9:.2f} GB/s "
              f"(r2 {ap.r2:.3f}, worst fit err {errs.get(axis, 0.0):.1f}%)")
    (out / "profiles.json").write_text(
        json.dumps(profile.to_dict(), indent=2, sort_keys=True) + "\n"
    )

    # --- 2. one measured serving window -------------------------------------
    rng = np.random.default_rng(28)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in rng.integers(5, 12, size=NREQ)
    ]
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
        refill_chunk=8, decode_block_steps=4, mixed=True,
    )
    for p in prompts[:4]:                    # warm: compiles stay out
        eng.add_request(p)
    while eng.has_work():
        eng.step(params)
    eng.pop_finished()
    eng.ledger.begin_window()
    for p in prompts:
        eng.add_request(p)
    while eng.has_work():
        eng.step(params)
    eng.pop_finished()
    rec = eng.ledger.reconcile()
    assert rec["ok"], rec

    # --- 3. the observatory verdict ------------------------------------------
    report = eng.comm_report(comm_profile=profile)
    overlap = report["overlap"]
    for fam, row in overlap["families"].items():
        total = (row["compute_s"] + row["exposed_comm_s"]
                 + row["overlapped_comm_s"])
        assert abs(total - row["device_s"]) < 1e-9, (fam, row)
    (out / "comm_report.json").write_text(
        json.dumps(report, indent=2, sort_keys=True, default=float) + "\n"
    )

    print(f"{'family':<20}{'device ms':>11}{'compute':>9}{'exposed':>9}"
          f"{'hidden':>9}")
    for fam, row in sorted(overlap["families"].items()):
        print(f"{fam:<20}{row['device_s'] * 1e3:>11.2f}"
              f"{row['compute_s'] * 1e3:>9.2f}"
              f"{row['exposed_comm_s'] * 1e3:>9.2f}"
              f"{row['overlapped_comm_s'] * 1e3:>9.2f}")
    for fam, row in sorted(report["families"].items()):
        for ln in row["lines"][:3]:
            print(f"  {fam}: {ln['where']}: predicted "
                  f"{ln['predicted_s'] * 1e3:.3f} ms, measured "
                  f"{ln['measured_s'] * 1e3:.3f} ms")

    # --- 4. the fleet-merge exposition ---------------------------------------
    prom = eng.registry.prometheus_text()
    assert "comm_axis_bandwidth_bytes_per_s" in prom
    assert "comm_exposed_seconds_total" in prom
    (out / "metrics.prom").write_text(prom)

    exposed = overlap["exposed_comm_share"] * 100.0
    ratio = overlap["realized_overlap_ratio"]
    print(
        f"case28: ledger reconciles; exposed comm {exposed:.2f}% of "
        f"device, realized overlap "
        f"{(ratio or 0.0) * 100.0:.1f}%; artifacts in {out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
