"""Case 29 — the workload observatory, end to end.

The round-20 economics layer on one trace-driven fleet replay, on the
emulated 8-device mesh:

* **deterministic load generation** — a :class:`TraceSpec` (diurnal
  interactive traffic with an evening flash crowd, bursty batch, a calm
  free tier) compressed to a few replay-seconds, written as versioned
  JSONL whose bytes regenerate identically from the spec;
* **paced replay** — arrivals admit at their trace instants through
  ``FleetRouter.add_request(arrival_t=...)``, so queue-wait and SLO
  burn measure offered-load truth while a ~2 Hz sampler captures the
  per-tenant burn TIMELINE;
* **the economics JOIN** — per-request trace legs × per-replica goodput
  ledger windows × byte counters, apportioned into per-tenant
  device-seconds / tokens / bytes-moved and priced via the costmodel
  device table — with the conservation verdict (Σ per-tenant attributed
  device-seconds == the fleet ledger's device bucket) printed and
  asserted;
* **the exports** — ``economics_*{tenant=...}`` Prometheus gauges
  (hostile label values escaped) and tenant lanes in the merged
  Perfetto timeline.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case29``, else a
temp dir): ``trace.jsonl`` (the generated day), ``economics.json`` (the
priced bill), ``burn_timeline.json`` (per-tenant burn samples),
``replay_trace.json`` (Perfetto, tenant lanes), ``metrics.prom``.

Emulated-CPU caveat: device-seconds here are host-emulated seconds, so
the absolute $ figures exercise the plumbing, not a price list — the
INVARIANTS (conservation, one-roll-up-per-request, replay determinism)
are what carry to hardware.

Run: ``python cases/case29_workload_observatory.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import flax.linen as nn  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.fleet import (  # noqa: E402
    FlashCrowd,
    FleetRouter,
    TenantSpec,
    TraceSpec,
    make_replicas,
    read_trace,
    replay_trace,
    write_trace,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.telemetry import (  # noqa: E402
    SLOMonitor,
    SLOTarget,
    fleet_economics,
    write_economics,
)
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    artifact_dir,
)

K, NEW, SPEED = 2, 8, 4.0


def _spec() -> TraceSpec:
    """A small observatory day: 6 virtual seconds, three tenants, one
    flash crowd — enough traffic to exercise every attribution path
    without canonical-day runtime."""
    return TraceSpec(
        duration_s=6.0,
        seed=29,
        tenants=(
            TenantSpec(
                "interactive", rate_rps=2.0, burstiness=2.0,
                diurnal_amplitude=0.6, diurnal_phase=0.25,
                prompt_len_min=4, prompt_len_tail=4.0, prompt_len_max=20,
            ),
            TenantSpec(
                "batch", rate_rps=1.0, burstiness=3.0,
                prompt_len_min=8, prompt_len_tail=8.0, prompt_len_max=32,
            ),
            TenantSpec(
                "free-tier", rate_rps=0.7, prompt_len_min=3,
                prompt_len_tail=2.0, prompt_len_max=10,
            ),
        ),
        flash_crowds=(
            FlashCrowd(
                tenant="interactive", t_s=4.0, duration_s=1.0,
                multiplier=6.0,
            ),
        ),
    )


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case29")
    )
    out.mkdir(parents=True, exist_ok=True)

    # --- 1. the trace: generated, persisted, byte-stable ------------------
    spec = _spec()
    write_trace(out / "trace.jsonl", spec)
    header, events = read_trace(out / "trace.jsonl")
    by_tenant: dict = {}
    for ev in events:
        by_tenant[ev["tenant"]] = by_tenant.get(ev["tenant"], 0) + 1
    print(
        f"case29: trace v{header['trace_version']}: {len(events)} "
        f"arrivals over {spec.duration_s:g}s virtual — " + ", ".join(
            f"{t}={n}" for t, n in sorted(by_tenant.items())
        )
    )

    # --- 2. the fleet, warmed past its compiles ---------------------------
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(0), np.zeros((2, 8), np.int32)
        )["params"]
    )
    slo = SLOMonitor([
        SLOTarget("queue_wait", 0.25, objective=0.9),
        SLOTarget("ttft", 0.5, objective=0.9),
        SLOTarget("e2e", 2.0, objective=0.9),
    ])
    reps = make_replicas(
        cfg, RULES_DP_TP, params, count=K, mesh_shape=(1, 2),
        batch_size=4, max_new_tokens=NEW, refill_chunk=16,
        decode_block_steps=4, slo=slo,
    )
    router = FleetRouter(reps)
    rng = np.random.default_rng(7)
    warm = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in rng.integers(6, 14, size=6)
    ]
    for rep in reps:
        rep.engine.serve(rep.params, warm[: rep.engine._b + 1])
    for p in warm:
        router.add_request(p)
    router.drain(max_steps=2000)
    router.pop_finished()
    router.reset_stats()

    # --- 3. paced replay with the burn-timeline sampler -------------------
    timeline, last = [], [-1.0]

    def _tick(elapsed: float) -> None:
        if elapsed - last[0] < 0.5:
            return
        last[0] = elapsed
        timeline.append(
            {"t_s": round(elapsed, 3), "burn": slo.tenant_burn_rates()}
        )

    rep = replay_trace(
        router, events, seed=spec.seed, vocab_size=cfg.vocab_size,
        speed=SPEED, pace=True, on_tick=_tick,
    )
    print(
        f"case29: replayed {rep['offered']} arrivals at {SPEED:g}x in "
        f"{rep['wall_s']:.1f}s wall ({len(rep['admission_order'])} "
        f"admitted, {len(rep['shed'])} shed)"
    )

    # --- 4. the economics JOIN + the conservation verdict -----------------
    econ = fleet_economics(router, replay=rep, slo=slo)
    cons = econ["measured"]["conservation"]
    assert cons["ok"], cons
    rolls = econ["deterministic"]["tenants"]
    assert sum(r["requests"] for r in rolls.values()) == len(
        rep["admission_order"]
    ), "every admitted request lands in exactly one tenant roll-up"

    write_economics(out / "economics.json", econ)
    (out / "burn_timeline.json").write_text(
        json.dumps({"speed": SPEED, "samples": timeline}, indent=2)
    )
    (out / "replay_trace.json").write_text(
        json.dumps(router.merged_chrome_trace())
    )
    prom = router.registry.prometheus_text()
    assert "economics_cost_usd" in prom
    (out / "metrics.prom").write_text(prom)

    print(f"{'tenant':<16}{'req':>5}{'ok':>4}{'shed':>5}{'tok':>6}"
          f"{'device s':>10}{'cost u$':>9}{'u$/tok':>8}{'burn':>6}")
    m = econ["measured"]["tenants"]
    for ten in sorted(set(rolls) | set(m)):
        r = rolls.get(ten, {})
        mt = m.get(ten, {})
        cpt = mt.get("cost_per_token_usd")
        print(
            f"{ten:<16}{r.get('requests', 0):>5}{r.get('ok', 0):>4}"
            f"{r.get('shed', 0):>5}{r.get('generated_tokens', 0):>6}"
            f"{mt.get('device_seconds', 0.0):>10.3f}"
            f"{mt.get('cost_usd', 0.0) * 1e6:>9.2f}"
            + (f"{cpt * 1e6:>8.3f}" if cpt else f"{'—':>8}")
            + f"{mt.get('worst_burn_rate', 0.0):>6.2f}"
        )
    fleet = econ["measured"]["fleet"]
    print(
        f"case29: conservation residual "
        f"{cons['residual_s']:.2e}s <= eps {cons['eps']:.2e}s; "
        f"goodput_ratio {fleet['goodput_ratio'] * 100:.1f}%, "
        f"worst tenant {econ['measured']['worst_tenant']} "
        f"(burn {econ['measured']['worst_tenant_burn_rate']:.2f}); "
        f"{len(timeline)} burn samples; artifacts in {out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
