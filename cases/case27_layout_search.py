"""Case 27 — the layout-search closed loop recovers mis-shardings.

Case 24 showed the analyzer NAMING a mis-sharded weight before any
compile; this case closes the loop (round 17, ``analysis/
layout_search.py``): hand the SAME seeded mistakes to the search and
let it fix them — abstractly, by re-simulating the traced jaxpr per
candidate and pricing each collective multiset, never compiling a
candidate. The only compile in the whole story is the final argmin,
compiled once at the end to hold the chosen layout's predicted
contract against XLA's real partitioner.

* **micro** — case 24's FF block with the transposed ``w2``
  (``(None,'model')`` instead of ``('model',None)``): the search must
  return a layout priced at or below the hand-tuned one, and running
  it twice must produce byte-identical contracts (the determinism the
  CI story depends on).
* **macro** — case 24's tiny transformer with its largest
  model-sharded kernel transposed (``mis_shard_one`` — the classic
  checkpoint-resharding bug): same recovery requirement over the full
  param tree, factorized per-layer with dominance pruning doing the
  heavy cutting.

Artifacts (``$LJST_ARTIFACT_DIR`` or a temp dir):
``layout_search_micro.json`` / ``layout_search_macro.json`` (search
results, pricing, the reconcile record of the one compiled argmin) and
``argmin_micro.contract.json`` / ``argmin_macro.contract.json`` (the
emitted golden-format contracts).

Run: ``python cases/case27_layout_search.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from case24_shardflow import (  # noqa: E402
    ff_block,
    mis_shard_one,
    sharded_params,
)
from learning_jax_sharding_tpu.analysis import costmodel  # noqa: E402
from learning_jax_sharding_tpu.analysis.contracts import contract_of  # noqa: E402
from learning_jax_sharding_tpu.analysis.layout_search import (  # noqa: E402
    apply_assignment,
    default_vary,
    search_layout,
)
from learning_jax_sharding_tpu.analysis.shardflow import (  # noqa: E402
    reconcile,
    trace_shardflow,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import (  # noqa: E402
    build_mesh,
    mesh_sharding,
    put,
)
from learning_jax_sharding_tpu.parallel.hlo import (  # noqa: E402
    collective_counts,
    compiled_hlo,
)
from learning_jax_sharding_tpu.parallel.logical import (  # noqa: E402
    RULES_DP_TP,
    activate,
)
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    artifact_dir,
)

PROFILE = costmodel.table_profile("TPU v5 lite")
B, S, D, H = 16, 128, 256, 2048


def confirm_argmin(res, fn, *args):
    """Compile the argmin layout — the ONE compile this case performs
    per scenario — and require every actual collective to be claimed by
    the search's predicted events."""
    (fixed_args, _kw) = apply_assignment(res, args, _MESH)
    text = compiled_hlo(fn, *fixed_args)
    rec = reconcile(res.report, contract_of(res.name, text, mesh=_MESH))
    assert not rec["unexplained"], (
        f"{res.name}: compiled argmin has collectives the search did "
        f"not predict: {rec['unexplained']}"
    )
    return rec, collective_counts(text)


def micro(outdir):
    x = put(np.ones((B, S, D), np.float32),
            mesh_sharding(_MESH, "data", None, None))
    w1 = put(np.ones((D, H), np.float32), mesh_sharding(_MESH, None, "model"))
    w2_good = put(np.ones((H, D), np.float32),
                  mesh_sharding(_MESH, "model", None))
    w2_bad = put(np.ones((H, D), np.float32),
                 mesh_sharding(_MESH, None, "model"))

    # The hand-tuned yardstick the search must reach (or beat), priced
    # the same abstract way.
    hand = trace_shardflow("case27_ff_hand", ff_block, x, w1, w2_good,
                           mesh=_MESH)
    cost_hand = costmodel.price(hand, PROFILE)

    vary_weights = (lambda p, leaf: default_vary(p, leaf) and leaf.ndim == 2)
    res = search_layout(
        "case27_ff", ff_block, x, w1, w2_bad, mesh=_MESH,
        vary=vary_weights, budget=96, profile=PROFILE,
    )
    again = search_layout(
        "case27_ff", ff_block, x, w1, w2_bad, mesh=_MESH,
        vary=vary_weights, budget=96, profile=PROFILE,
    )
    assert res.contract.to_json() == again.contract.to_json(), (
        "layout search is not deterministic"
    )
    assert res.assignment == again.assignment

    # Recovery: the searched layout prices <= the hand-tuned one, and
    # far below the seeded mistake.
    assert res.best.predicted_s <= cost_hand.predicted_s * (1 + 1e-9), (
        res.best.predicted_s, cost_hand.predicted_s,
    )
    assert res.gap_pct > 50.0, res.gap_pct  # the mistake was expensive

    rec, counts = confirm_argmin(res, ff_block, x, w1, w2_bad)
    print(f"[case27] micro: transposed w2 start priced "
          f"{res.baseline.predicted_s * 1e6:.1f}us; search "
          f"({res.evaluated} evals, {res.pruned} pruned) found "
          f"{res.best.predicted_s * 1e6:.1f}us "
          f"(hand-tuned: {cost_hand.predicted_s * 1e6:.1f}us)")
    for line in res.changed_lines():
        print(f"[case27] micro:   {line}")
    print(f"[case27] micro: argmin compiled once — collectives {counts}, "
          f"unexplained {rec['unexplained']}")
    (outdir / "argmin_micro.contract.json").write_text(
        res.contract.to_json()
    )
    return {
        "hand_cost": cost_hand.to_dict(),
        "search": res.to_dict(),
        "reconcile": rec,
        "compiled_counts": counts,
    }


def macro(outdir):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = sharded_params(model, _MESH, RULES_DP_TP)
    tokens = put(
        np.random.default_rng(0).integers(1, cfg.vocab_size, size=(8, 32))
        .astype(np.int32),
        mesh_sharding(_MESH, "data", None),
    )

    def fwd(p, t):
        return model.apply({"params": p}, t)

    bad_params, swap = mis_shard_one(params, _MESH)
    with activate(_MESH, RULES_DP_TP):
        hand = trace_shardflow("case27_fwd_hand", fwd, params, tokens,
                               mesh=_MESH)
        cost_hand = costmodel.price(hand, PROFILE)
        res = search_layout(
            "case27_fwd", fwd, bad_params, tokens, mesh=_MESH,
            budget=128, profile=PROFILE,
        )

    assert res.best.predicted_s <= cost_hand.predicted_s * (1 + 1e-9), (
        res.best.predicted_s, cost_hand.predicted_s,
    )
    moved = {p for p in res.changed}
    assert any(swap["param"] in p for p in moved), (
        f"search did not move the seeded mis-sharded kernel "
        f"{swap['param']}; moved {sorted(moved)}"
    )

    with activate(_MESH, RULES_DP_TP):
        rec, counts = confirm_argmin(res, fwd, bad_params, tokens)
    print(f"[case27] macro: {swap['param']} arrived as "
          f"{swap['bad_spec']}; search ({res.evaluated} evals, "
          f"{res.pruned} pruned, {res.sweeps} sweep(s)) priced "
          f"{res.baseline.predicted_s * 1e6:.1f}us -> "
          f"{res.best.predicted_s * 1e6:.1f}us "
          f"(hand-tuned: {cost_hand.predicted_s * 1e6:.1f}us)")
    for line in res.changed_lines():
        print(f"[case27] macro:   {line}")
    print(f"[case27] macro: argmin compiled once — collectives {counts}, "
          f"unexplained {rec['unexplained']}")
    (outdir / "argmin_macro.contract.json").write_text(
        res.contract.to_json()
    )
    return {
        "swap": swap,
        "hand_cost": cost_hand.to_dict(),
        "search": res.to_dict(),
        "reconcile": rec,
        "compiled_counts": counts,
    }


def main():
    outdir = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case27")
    )
    outdir.mkdir(parents=True, exist_ok=True)

    micro_rec = micro(outdir)
    macro_rec = macro(outdir)

    (outdir / "layout_search_micro.json").write_text(
        json.dumps(micro_rec, indent=2, default=str)
    )
    (outdir / "layout_search_macro.json").write_text(
        json.dumps(macro_rec, indent=2, default=str)
    )
    print(f"[case27] artifacts: {outdir}")
    print("[case27] OK")


_MESH = build_mesh((2, 4), ("data", "model"))

if __name__ == "__main__":
    main()
