"""Case 10 — long context: flash attention, attention remat, ring attention.

Not in the reference: its attention materializes the full (B, N, S, S) score
tensor (`/root/reference/case6_attention.py:125-127`), capping sequence length
at a few thousand tokens (SURVEY.md §2.4 "Context parallelism: absent"). This
case shows the four long-context mechanisms the framework adds, on one model:

1. **flash attention** (``ops/flash_attention.py``) — blockwise-softmax Pallas
   kernel, O(S·H) memory instead of O(S²) (interpret mode here on emulated CPU
   devices; compiled Mosaic on real TPU);
2. **attention remat** (``remat_attention``) — the dense backend with its S²
   internals recomputed in backward, so even the fallback path stores no
   score tensors;
3. **ring attention** (``ops/ring_attention.py``) — the sequence axis itself
   sharded over the mesh, k/v blocks rotating by ``lax.ppermute`` (ICI
   neighbor hops on hardware) with an online softmax, so S scales with the
   number of devices: context parallelism;
4. **sliding-window attention** (``flash_attention(window=w)``) — banded
   kernel grids cut compute AND HBM traffic to O(S·window): cost grows
   linearly with context (measured 3.7× over full causal at S=16k on the
   v5e, PERF.md).

The first three compute the same function (the window variant its own banded
one, proven against a dense mask); the case proves each numerically, then
takes a sharded train step at a sequence length where the reference's dense
scores would need ~4× the activation memory.

Run: ``python cases/case10_long_context.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.ops.flash_attention import flash_attention
from learning_jax_sharding_tpu.ops.ring_attention import make_ring_attn_fn
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_SP,
    activate,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

B, S, N, H = 2, 1024, 4, 16  # long sequence relative to the tiny head count


def backends_agree():
    """Dense, flash, and ring attention compute the same causal function."""
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, S, N, H)).astype(np.float32))
        for _ in range(3)
    )
    dense = dot_product_attention(q, k, v, mask=causal_mask(S))
    flash = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(flash), atol=2e-5
    )

    # RULES_DP_SP maps SEQ to the 'model' mesh axis: a 2×4 mesh rings k/v
    # blocks around 4 devices while batch splits over the other 2.
    mesh = build_mesh((2, 4), ("data", "model"))
    ring = make_ring_attn_fn(mesh=mesh, rules=RULES_DP_SP)
    with activate(mesh, RULES_DP_SP):
        ring_out = jax.jit(lambda q, k, v: ring(q, k, v, causal=True))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(ring_out), atol=2e-5
    )
    print(f"PASS: dense == flash == ring at S={S} (causal, 2×4 seq ring)")


def windowed_attention():
    """Sliding-window == dense with the band mask; window ≥ S == causal."""
    from learning_jax_sharding_tpu.ops.attention import sliding_window_mask

    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, S, N, H)).astype(np.float32))
        for _ in range(3)
    )
    w = 96
    dense = dot_product_attention(q, k, v, mask=sliding_window_mask(S, w))
    flash = flash_attention(
        q, k, v, causal=True, window=w, interpret=True, block_q=128, block_k=128
    )
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash), atol=2e-5)
    print(f"PASS: sliding-window attention (w={w}) matches the dense band mask")


def long_context_train_step():
    """Sharded train step at S=1024 on the tiny model with attention remat:
    no (B, N, S, S) tensor is ever stored for backward."""
    mesh = build_mesh((2, 4), ("data", "model"))
    cfg = dataclasses.replace(
        CONFIG_TINY, max_seq_len=S, remat_attention=True, rope=True
    )
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(4, S + 1)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}

    from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

    state, state_sh = sharded_train_state(
        model, optax.adamw(1e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh, RULES_DP_TP,
        loss_fn=next_token_loss,
    )
    state, loss = step(state, batch)
    print(f"train step at S={S}, remat_attention+rope: loss={float(loss):.3f}")
    assert np.isfinite(float(loss))


def main():
    backends_agree()
    windowed_attention()
    long_context_train_step()
    print("PASS: long-context mechanisms (flash / window / remat / ring) all "
          "serve the same model")


if __name__ == "__main__":
    main()
