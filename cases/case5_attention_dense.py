"""Case 5 — logical partitioning introduced on a single projection.

Rebuild of `/root/reference/case5_attention_dense.py`: a minimal module with
one Dense kernel carrying logical axes ``(embed, kv)``, pushed through the
sharded-init pipeline. The lesson is what the *rules* do: the reference ships
with the ``('kv','model')`` rule commented out (`case5_attention_dense.py:111`)
so the kernel stays replicated on its kv dim — here both variants run so the
effect of mapping vs not mapping an axis is visible side by side.

Run: ``python cases/case5_attention_dense.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import flax.linen as nn
import jax
import numpy as np
import optax

from learning_jax_sharding_tpu.parallel import build_mesh, put, shard_shapes, visualize
from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    KV,
    SEQ,
    logical_sharding,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

B, S, M = 8, 256, 640
INNER = 8 * 64  # heads × head_dim, the reference's Wq output width


class QProjection(nn.Module):
    """The reference's minimal FlaxAttention: just the Q projection
    (`/root/reference/case5_attention_dense.py:41-71`), with its unused
    inner_dim/scale fields dropped (SURVEY.md §8 quirks)."""

    @nn.compact
    def __call__(self, x):
        x = nn.with_logical_constraint(x, (BATCH, SEQ, EMBED))
        q = nn.Dense(
            INNER,
            use_bias=False,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), (EMBED, KV)
            ),
            name="query",
        )(x)
        return nn.with_logical_constraint(q, (BATCH, SEQ, None))


def run(rules, label):
    mesh = build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])
    x = put(
        np.random.default_rng(0).standard_normal((B, S, M)).astype(np.float32),
        logical_sharding(mesh, rules, BATCH, SEQ, EMBED),
    )
    state, state_sh = sharded_train_state(
        QProjection(), optax.adam(1e-3), x, {"params": jax.random.key(0)}, mesh, rules
    )
    wq = state.params["query"]["kernel"]
    print(f"[{label}] rules={rules}")
    print(f"  Wq {wq.shape} -> shard {shard_shapes(wq)[0]}")
    visualize(wq)
    step = make_train_step(state_sh, x.sharding, mesh, rules)
    state, loss = step(state, x)
    print(f"  one train step OK, loss={float(loss):.3f}")
    return shard_shapes(wq)[0]


def main():
    # Reference configuration: 'kv' NOT mapped (the commented-out rule at
    # `case5_attention_dense.py:111`) — Wq replicated on its kv columns,
    # split on embed rows.
    shard_a = run(((BATCH, "data"), (EMBED, "model")), "kv unmapped (reference)")
    assert shard_a == (M // 2, INNER)

    # With the kv rule enabled the same kernel also splits its columns.
    shard_b = run(((BATCH, "data"), (EMBED, None), (KV, "model")), "kv -> model")
    assert shard_b == (M, INNER // 2)

    print("PASS: logical rules control kernel placement without touching the model")


if __name__ == "__main__":
    main()
