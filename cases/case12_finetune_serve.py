"""Case 12 — serve WHILE training: the tenancy loop on one model.

The pre-round-12 version of this case stopped the world to deploy —
pretrain, fine-tune, merge, then start a fresh decoder on the folded
weights. This rewrite runs the production loop the tenancy subsystem
exists for:

1. **pretrain** the tiny transformer on a base pattern (+1 mod V);
2. **serve while fine-tuning** — a live multi-LoRA
   :class:`~learning_jax_sharding_tpu.models.serving.ContinuousEngine`
   answers base-tenant traffic on every training step while
   ``training/lora.py`` fine-tunes a rank-8 adapter on the +SHIFT
   pattern next to it (base frozen, same mesh, no drain);
3. **hot-add** the trained adapter to the engine's
   :class:`~learning_jax_sharding_tpu.tenancy.AdapterPool` — the NEXT
   fused batch serves base rows and fine-tuned rows together, and every
   adapter-routed stream is bit-identical to a solo engine on the
   ``merge_lora``-folded weights;
4. **rolling-swap the deployment** — the folded model becomes base
   version 2 across a 2-replica fleet via
   ``FleetRouter.rolling_swap``: replicas drain one at a time behind
   the placement policy, zero requests drop, every response is
   attributable to exactly one weight version, and post-swap traffic
   continues the +SHIFT pattern with NO adapter attached.

Everything runs under (data, model) meshes: adapters inherit kernel
shardings, the staged swap tree is resharded into each replica's
serving layout off the hot path, and both decode paths run the same
GSPMD collectives as training.

Run: ``python cases/case12_finetune_serve.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from learning_jax_sharding_tpu.fleet import (  # noqa: E402
    FleetRouter,
    make_replicas,
)
from learning_jax_sharding_tpu.models.serving import (  # noqa: E402
    ContinuousEngine,
    RequestFailure,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import (  # noqa: E402
    build_mesh,
    mesh_sharding,
    put,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.tenancy import AdapterPool  # noqa: E402
from learning_jax_sharding_tpu.training.lora import (  # noqa: E402
    lora_train_state,
    make_lora_train_step,
    merge_lora,
)
from learning_jax_sharding_tpu.training.pipeline import (  # noqa: E402
    make_train_step,
    sharded_train_state,
)

SEQ = 32
SHIFT = 7   # fine-tune task: next token jumps by SHIFT instead of 1
NEW = 10    # generated tokens per served request
PLEN = 8    # served prompt length


def pattern_batch(mesh, vocab, step, batch_size=8, index=0):
    rng = np.random.default_rng((29, index))
    starts = rng.integers(0, vocab, size=batch_size)
    toks = (starts[:, None] + step * np.arange(SEQ + 1)[None]) % vocab
    toks = toks.astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    return {"inputs": put(toks[:, :-1], sh), "targets": put(toks[:, 1:], sh)}


def pattern_prompt(vocab, step, start):
    return ((start + step * np.arange(PLEN)) % vocab).astype(np.int32)


def pattern_frac(tokens, step, vocab):
    """Fraction of GENERATED transitions that advance by ``step``."""
    diffs = np.diff(np.asarray(tokens)[PLEN - 1:]) % vocab
    return float((diffs == step).mean())


def drain(eng, params, out, max_steps=400):
    steps = 0
    while eng.has_work():
        eng.step(params)
        out.update(eng.pop_finished())
        steps += 1
        assert steps <= max_steps, "engine wedged"
    out.update(eng.pop_finished())
    return out


def main():
    mesh = build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])
    cfg = CONFIG_TINY
    model = Transformer(cfg)
    vocab = cfg.vocab_size

    # 1. Pretrain on the +1 pattern.
    batch = pattern_batch(mesh, vocab, step=1)
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    for i in range(60):
        state, base_loss = step(state, pattern_batch(mesh, vocab, 1, index=i))
    base = state.params
    print(f"pretrain (+1 pattern): final loss {float(base_loss):.3f}")

    # 2. Serve WHILE fine-tuning: the live engine answers base-tenant
    #    traffic on every optimizer step — no drain, no second process.
    pool = AdapterPool(base, slots=2, rank=8, mesh=mesh)
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, adapter_pool=pool, batch_size=4,
        max_new_tokens=NEW, refill_chunk=8, mixed=True,
    )
    bg_prompts = {i: pattern_prompt(vocab, 1, 11 * i + 3) for i in range(8)}
    for rid, p in bg_prompts.items():
        eng.add_request(p, rid=rid)

    ls = lora_train_state(
        jax.random.key(1), base, optax.adamw(1e-2), rank=8, mesh=mesh
    )
    ft_batch = pattern_batch(mesh, vocab, step=SHIFT)
    lora_step = make_lora_train_step(
        model, state_sh.params, {k: v.sharding for k, v in ft_batch.items()},
        mesh, RULES_DP_TP, optax.adamw(1e-2), loss_fn=next_token_loss,
    )
    first = last = None
    served_during = {}
    for i in range(80):
        ls, loss = lora_step(
            base, ls, pattern_batch(mesh, vocab, SHIFT, index=i)
        )
        first = float(loss) if first is None else first
        last = float(loss)
        if eng.has_work():
            eng.step(base)
            served_during.update(eng.pop_finished())
    print(f"LoRA fine-tune (+{SHIFT} pattern): loss {first:.3f} → {last:.3f}"
          f" with {len(served_during)} requests served mid-training")
    assert last < first
    assert served_during, "the engine must serve WHILE training"
    drain(eng, base, served_during)
    assert not any(
        isinstance(v, RequestFailure) for v in served_during.values()
    )
    base_frac = np.mean([
        pattern_frac(served_during[r], 1, vocab) for r in bg_prompts
    ])
    print(f"  base tenant kept the +1 pattern throughout "
          f"({base_frac:.0%} of transitions)")
    assert base_frac > 0.5, base_frac

    # 3. Hot-add the trained adapter: no restart, no folded copy of the
    #    base — the next fused batch serves both tenants together.
    pool.add("shift7", ls)   # LoraState: the trained alpha rides along
    mix = {}
    adapter_of = {}
    for i in range(6):
        name = "shift7" if i % 2 else None
        p = pattern_prompt(vocab, SHIFT if name else 1, 17 * i + 5)
        rid = 100 + i
        eng.add_request(p, rid=rid, adapter=name)
        mix[rid] = p
        adapter_of[rid] = name
    out = drain(eng, base, {})
    tuned_rids = [r for r, n in adapter_of.items() if n == "shift7"]
    tuned_frac = np.mean([pattern_frac(out[r], SHIFT, vocab)
                          for r in tuned_rids])
    print(f"hot-added adapter rows continue the +{SHIFT} pattern "
          f"({tuned_frac:.0%}); base rows in the same batch stay +1")
    assert tuned_frac > 0.6, tuned_frac

    # The oracle: every adapter-routed stream equals a solo engine on
    # the merge_lora-folded weights, bit for bit.
    merged = merge_lora(base, ls)
    solo = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
        refill_chunk=8, mixed=True,
    )
    ref = solo.serve(merged, [mix[r] for r in tuned_rids])
    for r, want in zip(tuned_rids, ref):
        np.testing.assert_array_equal(out[r], want)
    solo.close()
    eng.close()
    print("  bit-identical to the merge_lora-folded solo engine ✓")

    # 4. Deploy: the folded model becomes base VERSION 2 across a
    #    2-replica fleet — a rolling swap behind the placement policy,
    #    zero dropped requests, per-version attribution.
    host_base = jax.tree.map(np.asarray, base)
    host_merged = jax.tree.map(np.asarray, merged)
    reps = make_replicas(
        cfg, RULES_DP_TP, host_base, count=2, mesh_shape=(1, 2),
        batch_size=2, max_new_tokens=NEW, refill_chunk=8,
    )
    router = FleetRouter(reps)
    for i in range(6):
        router.add_request(pattern_prompt(vocab, 1, 13 * i + 2), rid=i)
    for _ in range(2):          # get work in flight before the rollout
        router.step()
    timeline = router.rolling_swap(host_merged, version=2)
    assert all(t["committed"] for t in timeline), timeline
    for i in range(6):          # post-swap traffic, NO adapter attached
        router.add_request(
            pattern_prompt(vocab, SHIFT, 19 * i + 4), rid=200 + i
        )
    results = {}
    steps = 0
    while router.has_work():
        router.step()
        results.update(router.pop_finished())
        steps += 1
        assert steps <= 2000, "fleet wedged"
    results.update(router.pop_finished())
    failures = {r: v for r, v in results.items()
                if isinstance(v, RequestFailure)}
    assert not failures, f"rolling swap dropped requests: {failures}"
    versions = {}
    for rep in reps:
        versions.update(rep.engine.finished_versions)
    assert all(versions[200 + i] == 2 for i in range(6)), versions
    assert all(versions[i] in (0, 2) for i in range(6)), versions
    post_frac = np.mean([
        pattern_frac(results[200 + i], SHIFT, vocab) for i in range(6)
    ])
    print(f"rolling swap: {len(timeline)}/2 replicas committed v2, "
          f"0 dropped; post-swap base traffic continues +{SHIFT} "
          f"({post_frac:.0%}) with no adapter attached")
    assert post_frac > 0.6, post_frac
    print("case12 PASS")


if __name__ == "__main__":
    main()
