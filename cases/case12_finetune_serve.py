"""Case 12 — the post-training lifecycle: LoRA fine-tune → quantize → serve.

Nothing in the reference goes past a jitted forward
(`/root/reference/case6_attention.py:229-238`); this case composes the
framework's post-training stack on one model, end to end:

1. **pretrain** the tiny transformer on a base pattern (ascending mod-V);
2. **LoRA fine-tune** (``training/lora.py``) on a SHIFTED pattern with the
   base frozen — only rank-r adapters train, and merging them back yields a
   plain param tree;
3. **int8-quantize** the merged model (``models/quantize.py``) and serve it
   with in-jit dequantization;
4. **speculative decoding** (``models/speculative.py``): the PRETRAINED
   model drafts for the fine-tuned target — exactness holds by construction,
   and the acceptance rate shows how draft/target agreement pays.

Everything runs under one (data, model) mesh: adapters inherit kernel
shardings, int8 tensors inherit theirs, both decoders run the same GSPMD
collectives as training.

Run: ``python cases/case12_finetune_serve.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.quantize import (
    quantize_tree,
    quantized_bytes,
)
from learning_jax_sharding_tpu.models.speculative import (
    make_speculative_generate_fn,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.lora import (
    lora_train_state,
    make_lora_train_step,
    merge_lora,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

SEQ = 32
SHIFT = 7  # fine-tune task: next token jumps by SHIFT instead of 1


def pattern_batch(mesh, vocab, step, batch_size=8, index=0):
    rng = np.random.default_rng((29, index))
    starts = rng.integers(0, vocab, size=batch_size)
    toks = (starts[:, None] + step * np.arange(SEQ + 1)[None]) % vocab
    toks = toks.astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    return {"inputs": put(toks[:, :-1], sh), "targets": put(toks[:, 1:], sh)}


def main():
    mesh = build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])
    cfg = CONFIG_TINY
    model = Transformer(cfg)

    # 1. Pretrain on the +1 pattern.
    batch = pattern_batch(mesh, cfg.vocab_size, step=1)
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    for i in range(60):
        state, base_loss = step(state, pattern_batch(mesh, cfg.vocab_size, 1, index=i))
    base = state.params
    print(f"pretrain (+1 pattern): final loss {float(base_loss):.3f}")

    # 2. LoRA fine-tune on the +SHIFT pattern, base frozen.
    ls = lora_train_state(
        jax.random.key(1), base, optax.adamw(1e-2), rank=8, mesh=mesh
    )
    ft_batch = pattern_batch(mesh, cfg.vocab_size, step=SHIFT)
    lora_step = make_lora_train_step(
        model, state_sh.params, {k: v.sharding for k, v in ft_batch.items()},
        mesh, RULES_DP_TP, optax.adamw(1e-2), loss_fn=next_token_loss,
    )
    first = last = None
    for i in range(80):
        ls, loss = lora_step(base, ls, pattern_batch(mesh, cfg.vocab_size, SHIFT, index=i))
        first = float(loss) if first is None else first
        last = float(loss)
    print(f"LoRA fine-tune (+{SHIFT} pattern): loss {first:.3f} → {last:.3f}")
    assert last < first
    n_lora = sum(x.size for x in jax.tree.leaves(ls.adapters))
    n_base = sum(x.size for x in jax.tree.leaves(base))
    print(f"trained params: {n_lora:,} adapters vs {n_base:,} base "
          f"({n_lora / n_base:.1%})")

    merged = merge_lora(base, ls)

    # 3. Quantize the merged model; serve int8 with in-jit dequant.
    bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), merged)
    qtree = quantize_tree(bf16)
    print(f"serving bytes: bf16 {quantized_bytes(bf16):,} → int8 "
          f"{quantized_bytes(qtree):,}")
    prompt = np.stack([np.arange(10, 10 + 8), np.arange(40, 40 + 8)]).astype(np.int32)
    prompt = put(prompt, mesh_sharding(mesh, "data", None))
    gen_q = make_generate_fn(
        cfg, mesh, RULES_DP_TP, max_new_tokens=10,
        inference_dtype=jnp.bfloat16, dequantize=True,
    )
    out_q = np.asarray(gen_q(qtree, prompt, jax.random.key(2)))
    print("int8 serve, fine-tuned model continues the +7 pattern:")
    print(" ", out_q[0])
    # The fine-tuned model must continue with +SHIFT steps, not +1.
    diffs = np.diff(out_q[0, 7:]) % cfg.vocab_size
    assert (diffs == SHIFT).mean() > 0.6, diffs

    # 4. Speculative decoding: pretrained model drafts for the merged target.
    spec = make_speculative_generate_fn(
        cfg, cfg, mesh, RULES_DP_TP, max_new_tokens=10, num_draft=3,
    )
    plain = make_generate_fn(cfg, mesh, RULES_DP_TP, max_new_tokens=10)
    out_spec = np.asarray(spec(merged, base, prompt))
    out_plain = np.asarray(plain(merged, prompt, jax.random.key(0)))
    assert (out_spec == out_plain).all(), "speculative must equal plain greedy"
    print("speculative decode (pretrained drafts for fine-tuned): exact ✓")
    print("case12 PASS")


if __name__ == "__main__":
    main()
