"""Case 24 — shardflow: catch a mis-sharded weight BEFORE any compile.

The round-13 static-analysis subsystem, end to end on the emulated
8-device ``(data=2, model=4)`` mesh. The claim under demo: the GSPMD
propagation simulator (``analysis.shardflow``) reads the REAL shardings
off a program's arguments, traces the jaxpr (no compile), and names the
exact source line every collective comes from — so a sharding mistake
is caught and PRICED while ``jax.jit`` would still be partitioning.

* **micro: one wrong spec, one named line** — a two-matmul FF block
  written in this file. Correctly sharded (``w2: ('model', None)``) the
  simulator predicts exactly the Megatron all-reduce at the second
  matmul's line; with ``w2`` deliberately transposed to
  ``(None, 'model')`` it predicts the much larger all-gather of the
  hidden activations AT THE SAME LINE NUMBER IN THIS FILE, and the
  roofline model (priced on the seeded TPU v5e profile) puts a factor
  on the mistake. Both predictions are then CONFIRMED against the
  compiled HLO: ``reconcile`` matches every actual collective to a
  predicted event — zero unexplained, both variants.
* **macro: a transformer weight arrives mis-sharded** — the tiny
  Transformer's born-sharded params, with ONE kernel's partition spec
  deliberately swapped (the kind of mistake a checkpoint-resharding bug
  or a wrong logical rule produces). The per-line diff of the two
  traces attributes the new wire bytes to the model source line that
  consumes the weight, and the v5e pricing reports the predicted
  slowdown — again before any compile, again confirmed against the
  compiled contract afterwards.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case24``, else a
temp dir): ``shardflow_micro.json`` / ``shardflow_macro.json`` (both
traces, the per-line diff, pricing, and the reconcile records) and
``explain.txt`` (the rendered per-line attribution for all four
traces).

Run: ``python cases/case24_shardflow.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses  # noqa: E402
import inspect  # noqa: E402
import json  # noqa: E402
import pathlib  # noqa: E402
import sys  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from learning_jax_sharding_tpu.analysis import costmodel  # noqa: E402
from learning_jax_sharding_tpu.analysis.contracts import contract_of  # noqa: E402
from learning_jax_sharding_tpu.analysis.shardflow import (  # noqa: E402
    reconcile,
    render_explanation,
    trace_shardflow,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import (  # noqa: E402
    build_mesh,
    mesh_sharding,
    put,
)
from learning_jax_sharding_tpu.parallel.hlo import (  # noqa: E402
    collective_counts,
    compiled_hlo,
)
from learning_jax_sharding_tpu.parallel.logical import (  # noqa: E402
    RULES_DP_TP,
    activate,
)
from learning_jax_sharding_tpu.telemetry.flight_recorder import (  # noqa: E402
    artifact_dir,
)

PROFILE = costmodel.table_profile("TPU v5 lite")


def wire_by_line(report):
    """Per-source-line predicted wire bytes (trips multiplied in),
    ``slice`` events excluded — the diffable attribution signature."""
    out = {}
    for where, evs in report.by_line().items():
        total = sum(e.bytes * (e.trip or 1) for e in evs if e.kind != "slice")
        if total:
            out[where] = total
    return out


def line_diff(good, bad):
    """Lines whose predicted wire bytes GREW under the mis-sharding,
    worst first: the analyzer's answer to 'where is the mistake felt'."""
    g, b = wire_by_line(good), wire_by_line(bad)
    rows = [
        {"where": w, "good_bytes": g.get(w, 0), "bad_bytes": n,
         "extra_bytes": n - g.get(w, 0)}
        for w, n in b.items() if n > g.get(w, 0)
    ]
    rows.sort(key=lambda r: -r["extra_bytes"])
    return rows


def confirm(report, fn, *args):
    """The post-hoc proof: compile for real, extract the contract, and
    require every actual collective to be claimed by a predicted event."""
    text = compiled_hlo(fn, *args)
    rec = reconcile(report, contract_of(report.name, text, mesh=_MESH))
    assert not rec["unexplained"], (
        f"{report.name}: compiled collectives the trace cannot explain: "
        f"{rec['unexplained']}"
    )
    return rec, collective_counts(text)


# ---------------------------------------------------------------------------
# Part 1 — micro: the FF block, one transposed weight spec
# ---------------------------------------------------------------------------

B, S, D, H = 16, 128, 256, 2048


def ff_block(x, w1, w2):
    h = jax.nn.relu(x @ w1)
    y = h @ w2  # CASE24-LINE: the line the analyzer must name
    return y


def micro(outdir):
    x = put(np.ones((B, S, D), np.float32), mesh_sharding(_MESH, "data", None, None))
    w1 = put(np.ones((D, H), np.float32), mesh_sharding(_MESH, None, "model"))
    w2_good = put(np.ones((H, D), np.float32), mesh_sharding(_MESH, "model", None))
    # The deliberate mistake: the SAME weight, partitioned on the wrong
    # dim — its contracting rows now replicated, its output cols sharded.
    w2_bad = put(np.ones((H, D), np.float32), mesh_sharding(_MESH, None, "model"))

    good = trace_shardflow("case24_ff_good", ff_block, x, w1, w2_good, mesh=_MESH)
    bad = trace_shardflow("case24_ff_bad", ff_block, x, w1, w2_bad, mesh=_MESH)

    # The analyzer names the exact line in THIS file.
    src, first = inspect.getsourcelines(ff_block)
    lineno = first + next(i for i, l in enumerate(src) if "CASE24-LINE" in l)
    tag = f"case24_shardflow.py:{lineno}"
    culprits = [e for e in bad.events
                if e.kind != "slice" and e.where.endswith(tag)]
    assert culprits, f"no predicted event at {tag}: {wire_by_line(bad)}"
    ops_bad = {e.realizations[0][0] for e in culprits}
    assert "all-gather" in ops_bad, ops_bad
    ops_good = {e.realizations[0][0] for e in good.events
                if e.kind != "slice" and e.where.endswith(tag)}
    assert ops_good == {"all-reduce"}, ops_good

    # Price the mistake on the v5e profile — before any compile.
    cost_g, cost_b = costmodel.price(good, PROFILE), costmodel.price(bad, PROFILE)
    assert cost_b.collective_s > 1.5 * cost_g.collective_s, (
        cost_g.collective_s, cost_b.collective_s,
    )

    # Now let XLA partition it for real and hold the prediction to it.
    rec_g, counts_g = confirm(good, ff_block, x, w1, w2_good)
    rec_b, counts_b = confirm(bad, ff_block, x, w1, w2_bad)
    assert counts_g.get("all-reduce", 0) >= 1 and not counts_g.get("all-gather", 0), counts_g
    assert counts_b.get("all-gather", 0) >= 1, counts_b

    print(f"[case24] micro: mis-sharded w2 caught at {tag} (pre-compile)")
    print(f"[case24] micro: good  {ops_good} collective_s={cost_g.collective_s*1e6:.1f}us "
          f"predicted={cost_g.predicted_s*1e6:.1f}us ({cost_g.bound}-bound)")
    print(f"[case24] micro: bad   {ops_bad} collective_s={cost_b.collective_s*1e6:.1f}us "
          f"predicted={cost_b.predicted_s*1e6:.1f}us ({cost_b.bound}-bound)")
    print(f"[case24] micro: compile confirms — good {counts_g}, bad {counts_b}; "
          f"unexplained: {rec_g['unexplained']} / {rec_b['unexplained']}")
    return {
        "culprit_line": tag,
        "good": {"trace": good.to_dict(), "cost": cost_g.to_dict(),
                 "reconcile": rec_g, "compiled_counts": counts_g},
        "bad": {"trace": bad.to_dict(), "cost": cost_b.to_dict(),
                "reconcile": rec_b, "compiled_counts": counts_b},
        "collective_slowdown": cost_b.collective_s / max(cost_g.collective_s, 1e-12),
    }, (good, bad)


# ---------------------------------------------------------------------------
# Part 2 — macro: a transformer weight arrives mis-sharded
# ---------------------------------------------------------------------------


def sharded_params(model, mesh, rules):
    """Params born sharded under ``rules`` — the layout a trained or
    resharded checkpoint would arrive in."""
    import flax.linen as nn

    from learning_jax_sharding_tpu.parallel.logical import tree_shardings

    probe = np.zeros((2, 8), np.int32)

    def init(r, t):
        return model.init({"params": r}, t)

    with activate(mesh, rules):
        abstract = jax.eval_shape(init, jax.random.key(0), probe)
        shardings = tree_shardings(abstract, mesh, rules)
        return jax.jit(
            lambda r, t: nn.meta.unbox(init(r, t)),
            out_shardings=shardings,
        )(jax.random.key(0), probe)["params"]


def mis_shard_one(params, mesh):
    """Swap the LAST TWO partition-spec entries of the largest
    model-sharded kernel — a transposed-layout weight, the classic
    checkpoint-resharding bug."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    best = None
    for path, leaf in flat:
        spec = tuple(getattr(leaf.sharding, "spec", ()) or ())
        spec = spec + (None,) * (leaf.ndim - len(spec))
        if leaf.ndim >= 2 and "model" in spec[-2:] and spec[-1] != spec[-2]:
            if best is None or leaf.nbytes > best[1].nbytes:
                best = (path, leaf, spec)
    assert best is not None, "no model-sharded kernel found"
    path, leaf, spec = best
    bad_spec = spec[:-2] + (spec[-1], spec[-2])
    bad_leaf = put(leaf, mesh_sharding(mesh, *bad_spec))
    name = jax.tree_util.keystr(path)
    bad_params = jax.tree_util.tree_unflatten(
        treedef, [bad_leaf if p == path else v for p, v in flat]
    )
    return bad_params, {"param": name, "good_spec": list(map(str, spec)),
                        "bad_spec": list(map(str, bad_spec))}


def macro(outdir):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = sharded_params(model, _MESH, RULES_DP_TP)
    tokens = put(
        np.random.default_rng(0).integers(1, cfg.vocab_size, size=(8, 32))
        .astype(np.int32),
        mesh_sharding(_MESH, "data", None),
    )

    def fwd(p, t):
        return model.apply({"params": p}, t)

    bad_params, swap = mis_shard_one(params, _MESH)
    with activate(_MESH, RULES_DP_TP):
        good = trace_shardflow("case24_fwd_good", fwd, params, tokens, mesh=_MESH)
        bad = trace_shardflow("case24_fwd_bad", fwd, bad_params, tokens, mesh=_MESH)

    diff = line_diff(good, bad)
    assert diff, "mis-sharding predicted no extra wire traffic"
    culprit = diff[0]["where"]
    cost_g, cost_b = costmodel.price(good, PROFILE), costmodel.price(bad, PROFILE)
    slowdown = cost_b.predicted_s / max(cost_g.predicted_s, 1e-12)

    print(f"[case24] macro: {swap['param']} resharded "
          f"{swap['good_spec']} -> {swap['bad_spec']}")
    print(f"[case24] macro: extra wire attributed to {culprit} "
          f"(+{diff[0]['extra_bytes']:,} B; {len(diff)} line(s) regressed)")
    print(f"[case24] macro: v5e predicted step {cost_g.predicted_s*1e6:.1f}us -> "
          f"{cost_b.predicted_s*1e6:.1f}us ({slowdown:.2f}x, "
          f"{cost_b.bound}-bound) — priced before any compile")

    # Post-hoc proof on the real partitioner, both layouts.
    with activate(_MESH, RULES_DP_TP):
        rec_g, counts_g = confirm(good, fwd, params, tokens)
        rec_b, counts_b = confirm(bad, fwd, bad_params, tokens)
    extra_compiled = {
        k: counts_b.get(k, 0) - counts_g.get(k, 0)
        for k in counts_b if counts_b.get(k, 0) > counts_g.get(k, 0)
    }
    assert extra_compiled, (counts_g, counts_b)
    print(f"[case24] macro: compile confirms — extra collectives {extra_compiled}; "
          f"unexplained: {rec_g['unexplained']} / {rec_b['unexplained']}")
    return {
        "swap": swap,
        "culprit_line": culprit,
        "line_diff": diff[:10],
        "good": {"cost": cost_g.to_dict(), "reconcile": rec_g,
                 "compiled_counts": counts_g},
        "bad": {"cost": cost_b.to_dict(), "reconcile": rec_b,
                "compiled_counts": counts_b},
        "predicted_slowdown": slowdown,
    }, (good, bad)


def main():
    outdir = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case24")
    )
    outdir.mkdir(parents=True, exist_ok=True)

    micro_rec, micro_reports = micro(outdir)
    macro_rec, macro_reports = macro(outdir)

    (outdir / "shardflow_micro.json").write_text(
        json.dumps(micro_rec, indent=2, default=str)
    )
    (outdir / "shardflow_macro.json").write_text(
        json.dumps(macro_rec, indent=2, default=str)
    )
    explain = []
    for rep in (*micro_reports, *macro_reports):
        explain.append(f"=== {rep.name} ===\n{render_explanation(rep)}\n")
    (outdir / "explain.txt").write_text("\n".join(explain))
    print(f"[case24] artifacts: {outdir}")
    print("[case24] OK")


_MESH = build_mesh((2, 4), ("data", "model"))

if __name__ == "__main__":
    main()
