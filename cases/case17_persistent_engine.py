"""Case 17 — the round-5 serving engine: persistence, streaming, latency.

Not in the reference (it has no inference path, SURVEY.md §5). What
production serving adds ON TOP of a correct one-shot engine, each proven
here on an emulated (data, model) mesh:

1. PERSISTENCE: the engine OBJECT owns the KV cache, page pool, and
   prefix registry — a second ``serve()`` call with the same system
   prompt is admitted against the pages the first call retired (zero
   re-prefill of the shared prefix, across calls), and the
   cache-creating dispatch runs once per engine ever.
2. STREAMING ADMISSION: requests arrive over time
   (``add_request``/``step``/``pop_finished``) instead of as one queue —
   and outputs stay bit-identical to the one-shot drain.
3. LATENCY TELEMETRY: per-request TTFT / TPOT / queue-wait percentiles
   and the refill/decode wall-time split, from the engine itself.
4. RECOMPUTE PREEMPTION: under page-pool pressure a row is requeued and
   REGENERATED instead of erroring — exactly, because greedy decoding is
   deterministic and sampled draws are keyed by (request id, position),
   so preemption (like all scheduling) cannot change results.
5. DISPATCH GRANULARITY: ``decode_block_steps``/``decode_chain`` trade
   host round trips for scheduling granularity — chained serving is
   bit-identical to unchained (the correctness lever is free).

Run: ``python cases/case17_persistent_engine.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_TP_SERVING

mesh = build_mesh((2, 4), ("data", "model"))
cfg = dataclasses.replace(
    CONFIG_TINY, dtype=jnp.float32, decode_attention="blocked"
)
model = Transformer(cfg)
params = nn.meta.unbox(
    jax.jit(lambda r, t: model.init({"params": r}, t))(
        jax.random.key(3), np.zeros((2, 8), np.int32)
    )["params"]
)
rng = np.random.default_rng(17)
system = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
NEW = 6

# --- 1. persistence: prefix hits span serve() calls ---------------------
eng = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4, paged_pages=12, page_size=16, prefix_cache=True,
)
out1 = eng.serve(params, [system])
assert eng.last_stats["prefix_hits"] == 0
assert eng.last_stats["prefix_pages_retained"] >= 1
out2 = eng.serve(params, [system.copy()])
assert eng.last_stats["prefix_hits"] == 1, eng.last_stats
np.testing.assert_array_equal(out1[0], out2[0])
assert eng.cache_creations == 1          # one cache creation, EVER
print(
    "PASS: prefix hit from a PREVIOUS serve() call "
    f"({eng.last_stats['prefix_pages_reused']} page reused, cache created "
    "once)"
)

# --- 2. streaming arrivals == one-shot drain ----------------------------
prompts = [
    rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
    for n in (3, 9, 5, 12)
]
oneshot = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4,
)
ref = oneshot.serve(params, prompts)
stream = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4,
)
rids = [stream.add_request(p) for p in prompts[:2]]
results, late, steps = {}, list(prompts[2:]), 0
while stream.has_work() or late:
    stream.step(params)
    results.update(stream.pop_finished())
    steps += 1
    if late and steps >= 2:              # arrivals while mid-flight
        rids.append(stream.add_request(late.pop(0)))
for rid, r in zip(rids, ref):
    np.testing.assert_array_equal(results[rid], r)
print(f"PASS: {len(prompts)} streaming arrivals over {steps} steps — "
      "bit-identical to the one-shot drain")

# --- 3. latency telemetry ----------------------------------------------
lat = stream.latency_stats()
for key in ("ttft_p50", "tpot_p50", "queue_wait_p50", "refill_frac"):
    assert lat[key] is not None and lat[key] >= 0, (key, lat)
print(
    "PASS: engine telemetry — TTFT p50 "
    f"{lat['ttft_p50'] * 1e3:.0f} ms, TPOT p50 "
    f"{lat['tpot_p50'] * 1e3:.1f} ms, refill "
    f"{lat['refill_frac']:.0%} of dispatched time"
)

# --- 4. recompute preemption is exact ----------------------------------
fourteen = [
    rng.integers(1, cfg.vocab_size, size=(14,)).astype(np.int32)
    for _ in range(2)
]
roomy = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4, paged_pages=9, page_size=16,
)
tight = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4, paged_pages=4, page_size=16,
)
a = roomy.serve(params, fourteen)
b = tight.serve(params, fourteen)
assert tight.last_stats["preemptions"] >= 1
for x, y in zip(a, b):
    np.testing.assert_array_equal(y, x)
print(
    f"PASS: {tight.last_stats['preemptions']} preemption(s) under a "
    "3-page pool — outputs bit-identical to the unpressured engine"
)

# --- 5. chained dispatches are bit-identical ---------------------------
chained = ContinuousEngine(
    cfg, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
    refill_chunk=4, decode_block_steps=2, decode_chain=4,
)
c = chained.serve(params, prompts)
for x, y in zip(ref, c):
    np.testing.assert_array_equal(y, x)
print("PASS: decode_chain=4 (device-carried blocks, one sync per chain) "
      "— bit-identical to unchained serving")

print("PASS: case17 — persistent engine: state across calls, streaming "
      "admission, telemetry, exact preemption, chained dispatch")
