"""Case 11 — the whole framework end-to-end: raw text → trained BPE LM → text.

Every other case exercises one subsystem; this one chains all of them the way
a user would (none of this exists in the reference, whose training data is
`jax.random.normal` tensors, `/root/reference/case6_attention.py:158-161`):

  BPETokenizer.train → write_token_file → MemmapTokenDataset   (data)
  → fit(): born-sharded init, SPMD train steps, cosine LR, metrics,
           checkpoint/resume                              (training)
  → evaluate(): held-out loss / perplexity                (eval)
  → make_generate_fn(): KV-cached sampling from the model (serving)

on a 2×2 data×model mesh (emulated here; the same program runs on TPU chips).
The model is a tiny RoPE+GQA transformer over a BPE vocabulary learned
from the corpus itself; ~60 steps visibly drop the loss and the sample
echoes corpus n-grams.

Run: ``python cases/case11_char_lm.py``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(4)

import tempfile
from pathlib import Path

import jax
import numpy as np

from learning_jax_sharding_tpu.data import (
    BPETokenizer,
    MemmapTokenDataset,
    write_token_file,
)
from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, evaluate, fit
from learning_jax_sharding_tpu.utils.memory import memory_plan

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 120

SEQ = 64

#: BPE vocab budget (bytes + merges + specials), lane-friendly multiple.
CFG = TransformerConfig(
    vocab_size=384, num_layers=2, features=128, num_heads=4, head_dim=32,
    num_kv_heads=2, rope=True, hidden=256, max_seq_len=SEQ * 4,
    dtype=np.float32, param_dtype=np.float32,
)


def main():
    mesh = build_mesh((2, 2), ("data", "model"))
    # Learn a BPE vocabulary from the corpus itself (no downloaded files);
    # merges compress the byte stream several-fold, so each SEQ-token window
    # spans more text than the byte LM's would.
    tok = BPETokenizer.train(CORPUS, vocab_size=CFG.vocab_size)
    n_bytes = len(CORPUS.encode())
    n_tok = len(tok.encode(CORPUS))
    print(f"BPE: {len(tok.merges)} merges, {n_bytes} bytes -> {n_tok} tokens "
          f"({n_bytes / n_tok:.1f}x)")

    # unfused_loss=True matches fit()'s default next_token_loss below.
    plan = memory_plan(
        CFG, 8, SEQ, n_model_shards=2, n_data_shards=2, unfused_loss=True
    )
    print(f"memory plan: {plan.total / 1e6:.1f} MB/device estimated "
          f"(params {plan.params / 1e6:.1f} MB)")

    with tempfile.TemporaryDirectory() as tmp:
        path = write_token_file(
            Path(tmp) / "corpus.bin", tok.encode_to_array(CORPUS)
        )
        train_ds = MemmapTokenDataset(path, seq_len=SEQ)
        model = Transformer(CFG)
        loop_cfg = TrainLoopConfig(
            steps=60, global_batch_size=8, learning_rate=3e-3,
            warmup_steps=10, lr_schedule="cosine", grad_clip_norm=1.0,
            metrics_path=str(Path(tmp) / "metrics.jsonl"), log_every=20,
        )
        state, history = fit(model, train_ds, mesh, RULES_DP_TP, loop_cfg)
        first, last = history[0]["loss"], history[-1]["loss"]
        print(f"loss: {first:.3f} → {last:.3f} over {loop_cfg.steps} steps")
        assert last < first * 0.7, "training did not learn"

        # Held-out evaluation (same distribution here; the API is the point):
        # the state keeps the shardings fit() trained it under.
        ev = evaluate(
            state, train_ds, mesh, RULES_DP_TP, batch_size=8, num_batches=4,
        )
        print(f"eval: loss {ev['loss']:.3f}, perplexity {ev['perplexity']:.1f}")
        assert ev["perplexity"] < 60, "BPE perplexity should be far below uniform (384)"

        # Serve: sample from the trained model.
        gen = make_generate_fn(
            CFG, mesh, RULES_DP_TP, max_new_tokens=48,
            temperature=0.7, top_k=40,
            # The model vocab (384) is lane-padded past the learned BPE
            # vocab; the limit keeps undecodable pad ids out of the sample
            # (BPETokenizer.decode raises on them).
            vocab_limit=tok.vocab_size,
        )
        prompt_text = "the quick brown"  # no trailing space: BPE continuations are space-glued
        prompt = np.asarray([tok.encode(prompt_text)], np.int32)
        out = np.asarray(gen(state.params, prompt, jax.random.key(7)))
        sample = tok.decode(out[0])
        print(f"sample: {sample!r}")
        assert sample.startswith(prompt_text)

    print("PASS: text → tokens → sharded training → eval → generation, "
          "one framework")


if __name__ == "__main__":
    main()
