"""Case 21 — chaos recovery: inject every fault class, watch the stack heal.

Cases 18/19 made the stack observable and diagnosable; this driver
closes the loop by PROVING recovery. The full fault × policy matrix
(``robustness.matrix``) runs end to end on the emulated mesh:

* serving — a poison request (injected NaN-trap / hang-watchdog abort)
  is quarantined after ``max_dispatch_strikes`` while its batchmates
  recompute to bit-identical outputs; slowed dispatches trip per-request
  DEADLINES (terminal ``"deadline"`` status through ``pop_finished``,
  never a silent drop); an injected page-alloc OOM takes the
  recompute-preemption path; a corrupted queued prompt is failed as
  ``"malformed"``; offered load past the queue bound is SHED while the
  SLO burn rate walks the degradation ladder.
* training — a poisoned batch goes NaN INSIDE the jitted step and the
  on-device guard refuses the update (bounded skips); a loss spike
  rolls back to the last checkpoint and replays; SIGTERM forces an
  emergency checkpoint and the resumed run's trajectory is bit-identical
  to an uninterrupted one; a truncated newest checkpoint falls back to
  the previous retained step.

Every injection and every recovery action lands in the flight recorder
— the artifact bundle shows the incident timeline next to the verdict.

Artifacts (``sys.argv[1]``, else ``$LJST_ARTIFACT_DIR/case21``, else a
temp dir): ``chaos_matrix.json`` (per-cell verdicts) + ``events.json``
(the recorder ring).

Run: ``python cases/case21_chaos_recovery.py [outdir]``
"""

import _bootstrap  # noqa: F401  (repo-root import path)
from learning_jax_sharding_tpu.parallel import force_emulated_devices

force_emulated_devices(8)

import json
import pathlib
import sys

from learning_jax_sharding_tpu.robustness.matrix import run_matrix
from learning_jax_sharding_tpu.telemetry import default_flight_recorder
from learning_jax_sharding_tpu.telemetry.flight_recorder import artifact_dir


def main() -> int:
    out = (
        pathlib.Path(sys.argv[1]) if len(sys.argv) > 1
        else artifact_dir("case21")
    )
    out.mkdir(parents=True, exist_ok=True)

    print("case21: running the fault x policy matrix")
    results = run_matrix(verbose=True)
    bad = [r for r in results if not r["recovered"]]

    (out / "chaos_matrix.json").write_text(
        json.dumps(
            {
                "cells": len(results),
                "recovered": len(results) - len(bad),
                "results": results,
            },
            indent=2, default=str,
        )
    )
    rec = default_flight_recorder()
    (out / "events.json").write_text(
        json.dumps(rec.events()[-500:], indent=2, default=str)
    )
    print(f"case21: {len(results) - len(bad)}/{len(results)} cells "
          f"recovered; artifacts in {out}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
