"""Mixture-of-Experts FF + expert parallelism (SURVEY.md §2.4 "EP: absent").

Oracles: at full capacity the routed computation must equal the explicit
top-k mixture of per-expert FFNs computed directly from the params; under
RULES_DP_TP_EP the expert dim of the (E, M, H) kernels shards over 'model';
the MoE transformer trains end-to-end with the sown load-balancing loss.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.moe import MoEFeedForward
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY_MOE,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import assert_shard_shape, mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP_EP
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

B, S, M, H = 2, 16, 8, 32


def _x(rng, b=B, s=S, m=M):
    return jnp.asarray(rng.standard_normal((b, s, m)).astype(np.float32))


def _mixture_reference(params, x, top_k):
    """Explicit top-k mixture from the module's own params (numpy-side)."""
    wr = np.asarray(params["router"]["kernel"])          # (M, E)
    up = np.asarray(params["up"])                        # (E, M, H)
    down = np.asarray(params["down"])                    # (E, H, M)
    xt = np.asarray(x).reshape(-1, x.shape[-1])          # (T, M)
    probs = jax.nn.softmax(jnp.asarray(xt @ wr), axis=-1)
    vals, idx = jax.lax.top_k(probs, top_k)
    vals, idx = np.asarray(vals), np.asarray(idx)
    if top_k > 1:
        vals = vals / vals.sum(-1, keepdims=True)
    out = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        for r in range(top_k):
            e = idx[t, r]
            h = np.asarray(jax.nn.gelu(jnp.asarray(xt[t] @ up[e])))
            out[t] += vals[t, r] * (h @ down[e])
    return out.reshape(x.shape)


class TestMoEFeedForward:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_full_capacity_matches_explicit_mixture(self, rng, top_k):
        """capacity_factor = E/top_k → capacity = T: nothing drops, so the
        routed einsum path must equal the explicit per-token mixture."""
        moe = MoEFeedForward(
            features=M, hidden=H, num_experts=4, top_k=top_k,
            capacity_factor=4.0 / top_k,
        )
        x = _x(rng)
        params = moe.init({"params": jax.random.key(0)}, x)["params"]
        import flax.linen as nn

        params = nn.meta.unbox(params)
        y, _ = moe.apply({"params": params}, x, mutable=("losses",))
        expected = _mixture_reference(params, x, top_k)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-4, atol=2e-5)

    def test_single_expert_is_plain_ff(self, rng):
        moe = MoEFeedForward(
            features=M, hidden=H, num_experts=1, top_k=1, capacity_factor=1.0
        )
        x = _x(rng)
        params = moe.init({"params": jax.random.key(0)}, x)["params"]
        import flax.linen as nn

        params = nn.meta.unbox(params)
        y, _ = moe.apply({"params": params}, x, mutable=("losses",))
        up, down = np.asarray(params["up"][0]), np.asarray(params["down"][0])
        xt = np.asarray(x).reshape(-1, M)
        expected = (np.asarray(jax.nn.gelu(jnp.asarray(xt @ up))) @ down).reshape(x.shape)
        np.testing.assert_allclose(np.asarray(y), expected, rtol=2e-4, atol=2e-5)

    def test_tiny_capacity_drops_tokens(self, rng):
        """With ~1 slot per expert most tokens overflow → zero output rows
        (their residual path carries them in a full block)."""
        moe = MoEFeedForward(
            features=M, hidden=H, num_experts=4, top_k=1, capacity_factor=0.05
        )
        x = _x(rng)
        params = moe.init({"params": jax.random.key(0)}, x)["params"]
        y, _ = moe.apply({"params": params}, x, mutable=("losses",))
        row_norms = np.linalg.norm(np.asarray(y).reshape(-1, M), axis=-1)
        assert (row_norms == 0.0).sum() >= row_norms.size // 2

    def test_aux_loss_sown(self, rng):
        moe = MoEFeedForward(features=M, hidden=H, num_experts=4, top_k=2)
        x = _x(rng)
        params = moe.init({"params": jax.random.key(0)}, x)["params"]
        _, mut = moe.apply({"params": params}, x, mutable=("losses",))
        (aux,) = jax.tree.leaves(mut["losses"])
        # Switch aux: weight · E · Σ load·importance ≥ weight (min at balance).
        assert np.isfinite(float(aux)) and float(aux) > 0.0

    def test_top_k_guard(self, rng):
        moe = MoEFeedForward(features=M, hidden=H, num_experts=2, top_k=3)
        with pytest.raises(ValueError, match="top_k"):
            moe.init({"params": jax.random.key(0)}, _x(rng))


class TestMoETransformer:
    def _setup(self, mesh, cfg=CONFIG_TINY_MOE, b=8, s=32):
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
        sh = mesh_sharding(mesh, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        state, state_sh = sharded_train_state(
            model, optax.adamw(3e-4), batch["inputs"], {"params": jax.random.key(0)},
            mesh, RULES_DP_TP_EP,
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
            RULES_DP_TP_EP, loss_fn=next_token_loss, aux_loss_collection="losses",
        )
        return batch, state, step

    def test_expert_kernels_shard_over_model(self, mesh22):
        cfg = CONFIG_TINY_MOE
        batch, state, _ = self._setup(mesh22)
        up = state.params["block_0"]["moe"]["up"]
        assert up.shape == (cfg.num_experts, cfg.features, cfg.hidden)
        # EXPERT→model: 4 experts over 2 model devices → 2 per device.
        assert_shard_shape(up, (cfg.num_experts // 2, cfg.features, cfg.hidden))

    def test_moe_training_descends_with_aux_loss(self, mesh22):
        batch, state, step = self._setup(mesh22)
        losses = []
        for _ in range(10):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # Aux term present: initial loss ≳ ln(V) + aux_weight.
        assert losses[0] > np.log(CONFIG_TINY_MOE.vocab_size)

    def test_param_count_scales_with_experts(self):
        dense = dataclasses.replace(CONFIG_TINY_MOE, num_experts=0)
        assert CONFIG_TINY_MOE.param_count > dense.param_count


class TestMoEDecode:
    def test_moe_generates_under_ep_rules(self):
        """MoE models serve through the KV-cached decode path unchanged —
        the routed FF is stateless, so prefill + token steps just work under
        expert-parallel rules."""
        from learning_jax_sharding_tpu.models.generate import make_generate_fn
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP_EP
        from learning_jax_sharding_tpu.training.pipeline import sharded_train_state

        mesh = build_mesh((2, 4), ("data", "model"), devices=jax.devices())
        cfg = CONFIG_TINY_MOE
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 8)),
            jnp.int32,
        )
        x = put(np.asarray(prompt), mesh_sharding(mesh, "data", None))
        state, _ = sharded_train_state(
            Transformer(cfg), optax.sgd(1e-2), x,
            {"params": jax.random.key(0)}, mesh, RULES_DP_TP_EP,
        )
        out = make_generate_fn(cfg, mesh, RULES_DP_TP_EP, max_new_tokens=6)(
            state.params, prompt
        )
        assert out.shape == (4, 14)
        assert np.asarray(out[:, :8] == np.asarray(prompt)).all()


class TestSortDispatch:
    """dispatch="scatter": identical routing semantics to the einsum path
    (same priority, capacity, drops, gating) with scatter/gather movement
    instead of (T,E,C) contractions — outputs and gradients must match to
    fp32 reduction tolerance under the SAME params."""

    def _moe(self, dispatch, **kw):
        from learning_jax_sharding_tpu.models.moe import MoEFeedForward

        return MoEFeedForward(
            features=32, hidden=64, num_experts=4, dtype=jnp.float32,
            dispatch=dispatch, **kw,
        )

    @pytest.mark.parametrize("top_k,cap", [(1, 1.0), (2, 1.25), (2, 0.5)])
    def test_matches_einsum_path(self, top_k, cap):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
        ein = self._moe("einsum", top_k=top_k, capacity_factor=cap)
        srt = self._moe("scatter", top_k=top_k, capacity_factor=cap)
        params = ein.init({"params": jax.random.key(0)}, x)["params"]

        def run(mod, p):
            out, aux = mod.apply(
                {"params": p}, x, mutable=("losses",)
            )
            return out, aux["losses"]["load_balancing"]

        oe, le = run(ein, params)
        os_, ls = run(srt, params)
        np.testing.assert_allclose(
            np.asarray(os_), np.asarray(oe), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(float(ls), float(le), rtol=1e-6)

        ge = jax.grad(lambda p: jnp.sum(jnp.sin(run(ein, p)[0])))(params)
        gs = jax.grad(lambda p: jnp.sum(jnp.sin(run(srt, p)[0])))(params)
        for (kp, a), (_, e) in zip(
            jax.tree_util.tree_leaves_with_path(gs),
            jax.tree_util.tree_leaves_with_path(ge),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-4,
                err_msg=str(kp),
            )

    def test_unknown_dispatch_rejected(self):
        x = jnp.zeros((1, 4, 32))
        bad = self._moe("gather-scatter")
        with pytest.raises(ValueError, match="dispatch"):
            bad.init({"params": jax.random.key(0)}, x)

    def test_config_plumbing(self):
        import dataclasses as dc

        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY_MOE,
            Transformer,
        )

        rng = np.random.default_rng(1)
        tokens = rng.integers(
            0, CONFIG_TINY_MOE.vocab_size, size=(2, 16)
        ).astype(np.int32)
        cfg_e = dc.replace(CONFIG_TINY_MOE, dtype=jnp.float32)
        cfg_s = dc.replace(cfg_e, moe_dispatch="scatter")
        me, ms = Transformer(cfg_e), Transformer(cfg_s)
        params = nn.meta.unbox(
            me.init({"params": jax.random.key(0)}, tokens)["params"]
        )
        oe = me.apply({"params": params}, tokens, mutable=("losses",))[0]
        os_ = ms.apply({"params": params}, tokens, mutable=("losses",))[0]
        np.testing.assert_allclose(
            np.asarray(os_), np.asarray(oe), rtol=2e-5, atol=2e-5
        )


class TestAllToAllDispatch:
    """dispatch="alltoall" (ops/moe_dispatch.py): the EXPLICIT expert-
    parallel path — per-shard scatter bucketing + one lax.all_to_all each
    way over the expert mesh axis. Capacity is per TOKEN GROUP (GShard's
    grouped formulation), so the oracle is the einsum path run GROUP-WISE
    with the same params: outputs and grads must match, and the compiled
    HLO must contain the two all-to-alls."""

    E, K = 4, 2

    def _modules(self, mesh):
        from learning_jax_sharding_tpu.models.moe import MoEFeedForward
        from learning_jax_sharding_tpu.ops.moe_dispatch import make_moe_a2a_fn
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_EP_A2A

        kw = dict(
            features=32, hidden=64, num_experts=self.E, top_k=self.K,
            dtype=jnp.float32,
        )
        a2a = MoEFeedForward(
            dispatch="alltoall",
            dispatch_fn=make_moe_a2a_fn(mesh, RULES_DP_EP_A2A), **kw,
        )
        ein = MoEFeedForward(dispatch="einsum", **kw)
        return a2a, ein

    def _grouped_ref(self, ein, params, x, d):
        # The einsum path applied per token GROUP (one group per expert-
        # axis shard): same params, per-group capacity — the semantics
        # the all-to-all exchange implements.
        outs = [
            ein.apply({"params": params}, xg, mutable=("losses",))[0]
            for xg in jnp.split(x, d, axis=0)
        ]
        return jnp.concatenate(outs, axis=0)

    @pytest.mark.parametrize("cap", [1.25, 0.5])
    def test_matches_grouped_einsum(self, mesh22, cap):
        import dataclasses as dc

        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_EP_A2A
        from learning_jax_sharding_tpu.parallel.logical import activate

        a2a, ein = self._modules(mesh22)
        a2a = dc.replace(a2a, capacity_factor=cap)
        ein = dc.replace(ein, capacity_factor=cap)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
        params = ein.init({"params": jax.random.key(0)}, x)["params"]
        d = mesh22.shape["data"]

        with activate(mesh22, RULES_DP_EP_A2A):
            got = jax.jit(
                lambda p, x: a2a.apply(
                    {"params": p}, x, mutable=("losses",)
                )[0]
            )(params, x)
        ref = self._grouped_ref(ein, params, x, d)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_grads_match_grouped_einsum(self, mesh22):
        from learning_jax_sharding_tpu.parallel.logical import (
            RULES_DP_EP_A2A,
            activate,
        )

        a2a, ein = self._modules(mesh22)
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
        params = ein.init({"params": jax.random.key(0)}, x)["params"]
        d = mesh22.shape["data"]

        def loss_a2a(p):
            out = a2a.apply({"params": p}, x, mutable=("losses",))[0]
            return jnp.sum(jnp.sin(out))

        def loss_ref(p):
            return jnp.sum(jnp.sin(self._grouped_ref(ein, p, x, d)))

        with activate(mesh22, RULES_DP_EP_A2A):
            ga = jax.jit(jax.grad(loss_a2a))(params)
        gr = jax.grad(loss_ref)(params)
        for (kp, a), (_, e) in zip(
            jax.tree_util.tree_leaves_with_path(ga),
            jax.tree_util.tree_leaves_with_path(gr),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-4,
                err_msg=str(kp),
            )

    def test_hlo_has_all_to_alls(self, mesh22):
        from learning_jax_sharding_tpu.parallel.hlo import collective_counts
        from learning_jax_sharding_tpu.parallel.logical import (
            RULES_DP_EP_A2A,
            activate,
        )

        a2a, ein = self._modules(mesh22)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
        params = ein.init({"params": jax.random.key(0)}, x)["params"]
        with activate(mesh22, RULES_DP_EP_A2A):
            counts = collective_counts(
                jax.jit(
                    lambda p, x: a2a.apply(
                        {"params": p}, x, mutable=("losses",)
                    )[0]
                ),
                params, x,
            )
        # One exchange out, one back.
        assert counts.get("all-to-all", 0) >= 2, counts

    def test_divisibility_validation(self, mesh22):
        import dataclasses as dc

        from learning_jax_sharding_tpu.parallel.logical import (
            RULES_DP_EP_A2A,
            activate,
        )

        a2a, ein = self._modules(mesh22)
        a2a = dc.replace(a2a, num_experts=3)
        x = jnp.zeros((4, 16, 32), jnp.float32)
        with activate(mesh22, RULES_DP_EP_A2A):
            with pytest.raises(ValueError, match="divisible"):
                jax.jit(
                    lambda x: a2a.init({"params": jax.random.key(0)}, x)
                )(x)

    def test_requires_dispatch_fn(self):
        from learning_jax_sharding_tpu.models.moe import MoEFeedForward

        mod = MoEFeedForward(
            features=32, hidden=64, num_experts=4, dispatch="alltoall",
        )
        with pytest.raises(ValueError, match="dispatch_fn"):
            mod.init(
                {"params": jax.random.key(0)}, jnp.zeros((2, 4, 32))
            )

    def test_transformer_trains_a2a(self, mesh22):
        """End to end: a tiny MoE transformer train step under
        RULES_DP_EP_A2A with the all-to-all dispatch — compiles, runs,
        loss finite, expert grads nonzero."""
        import dataclasses as dc

        from learning_jax_sharding_tpu.ops.moe_dispatch import make_moe_a2a_fn
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_EP_A2A
        from learning_jax_sharding_tpu.training.pipeline import (
            make_train_step,
            sharded_train_state,
        )

        cfg = dc.replace(
            CONFIG_TINY_MOE, dtype=jnp.float32, num_experts=4,
            moe_dispatch="alltoall",
            moe_dispatch_fn=make_moe_a2a_fn(mesh22, RULES_DP_EP_A2A),
        )
        rng = np.random.default_rng(6)
        tokens = rng.integers(0, cfg.vocab_size, size=(4, 17)).astype(np.int32)
        batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put

        sh = mesh_sharding(mesh22, "data", None)
        batch = {k: put(v, sh) for k, v in batch.items()}
        state, state_sh = sharded_train_state(
            Transformer(cfg), optax.sgd(1e-2), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_EP_A2A,
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_EP_A2A, loss_fn=next_token_loss,
            aux_loss_collection="losses",
        )
        up0 = np.asarray(
            jax.tree_util.tree_leaves(state.params["block_0"]["moe"]["up"])[0]
        )
        state2, loss = step(state, batch)   # donates state
        assert np.isfinite(float(loss))
        up1 = np.asarray(
            jax.tree_util.tree_leaves(state2.params["block_0"]["moe"]["up"])[0]
        )
        assert not np.array_equal(up0, up1)
