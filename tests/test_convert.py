"""HF GPT-2 interop: converted weights reproduce the torch model's logits.

Built on randomly initialized ``transformers`` models — no downloads, so the
oracle runs in this network-isolated environment; real checkpoints convert
through the identical path. Tolerances reflect torch-CPU vs XLA matmul
accumulation-order noise (~2e-3 over two layers), not model disagreement —
argmax agreement is asserted exactly.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from learning_jax_sharding_tpu.models.convert import (  # noqa: E402
    config_from_hf_gpt2,
    params_from_hf_gpt2,
)
from learning_jax_sharding_tpu.models.transformer import Transformer  # noqa: E402


@pytest.fixture(scope="module")
def hf_pair():
    torch.manual_seed(0)
    hf_cfg = transformers.GPT2Config(
        n_layer=2, n_embd=64, n_head=4, vocab_size=128, n_positions=64,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = config_from_hf_gpt2(hf_cfg)
    return hf, cfg, params_from_hf_gpt2(hf)


def _tokens(b=2, s=16, seed=0, v=128):
    return np.random.default_rng(seed).integers(0, v, (b, s))


class TestGPT2Conversion:
    def test_logits_match_torch(self, hf_pair):
        hf, cfg, params = hf_pair
        tok = _tokens()
        with torch.no_grad():
            want = hf(torch.tensor(tok)).logits.numpy()
        got = np.asarray(
            Transformer(cfg).apply({"params": params}, jnp.asarray(tok, jnp.int32)),
            np.float32,
        )
        np.testing.assert_allclose(got, want, atol=5e-3)
        np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    def test_config_mapping(self, hf_pair):
        hf, cfg, _ = hf_pair
        assert cfg.vocab_size == 128 and cfg.num_layers == 2
        assert cfg.features == 64 and cfg.num_heads == 4 and cfg.head_dim == 16
        assert cfg.hidden == 256 and cfg.max_seq_len == 64
        assert cfg.use_bias and cfg.norm_eps == hf.config.layer_norm_epsilon
        assert not cfg.rope

    def test_unsupported_activation_rejected(self):
        hf_cfg = transformers.GPT2Config(activation_function="relu")
        with pytest.raises(ValueError, match="activation"):
            config_from_hf_gpt2(hf_cfg)

    def test_unsupported_attention_variants_rejected(self):
        for flag in ("scale_attn_by_inverse_layer_idx", "reorder_and_upcast_attn"):
            hf_cfg = transformers.GPT2Config(**{flag: True})
            with pytest.raises(ValueError, match=flag):
                config_from_hf_gpt2(hf_cfg)

    def test_n_inner_and_untied_head_honored(self):
        torch.manual_seed(2)
        hf_cfg = transformers.GPT2Config(
            n_layer=1, n_embd=32, n_head=2, vocab_size=64, n_positions=32,
            n_inner=96, tie_word_embeddings=False,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        )
        hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
        cfg = config_from_hf_gpt2(hf_cfg)
        assert cfg.hidden == 96
        params = params_from_hf_gpt2(hf)
        assert params["block_0"]["ff"]["up"]["kernel"].shape == (32, 96)
        tok = _tokens(b=2, s=8, seed=4, v=64)
        with torch.no_grad():
            want = hf(torch.tensor(tok)).logits.numpy()
        got = np.asarray(
            Transformer(cfg).apply({"params": params}, jnp.asarray(tok, jnp.int32)),
            np.float32,
        )
        np.testing.assert_allclose(got, want, atol=5e-3)
        # Exporting an untied head as tied would drop trained weights:
        # the default must refuse, and tie_head=False must round-trip.
        from learning_jax_sharding_tpu.models.convert import (
            state_dict_from_params,
        )

        with pytest.raises(ValueError, match="tie_head=False"):
            state_dict_from_params(params)
        hf2 = transformers.GPT2LMHeadModel(hf_cfg).eval()
        hf2.load_state_dict(
            state_dict_from_params(params, tie_head=False), strict=False
        )
        with torch.no_grad():
            back = hf2(torch.tensor(tok)).logits.numpy()
        np.testing.assert_allclose(back, want, atol=1e-5)

    def test_converted_model_serves_through_the_stack(self, mesh22, hf_pair):
        """The point of interop: a converted checkpoint runs the framework's
        own serving path (sharded KV-cached generation) unchanged."""
        from learning_jax_sharding_tpu.models.generate import make_generate_fn
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

        hf, cfg, params = hf_pair
        prompt_np = _tokens(b=4, s=8, seed=3)
        prompt = put(
            prompt_np.astype(np.int32), mesh_sharding(mesh22, "data", None)
        )
        gen = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=8)
        out = np.asarray(gen(params, prompt))
        assert out.shape == (4, 16)
        np.testing.assert_array_equal(out[:, :8], prompt_np)
        assert ((0 <= out) & (out < cfg.vocab_size)).all()

    def test_export_round_trip(self, hf_pair):
        """params → state dict → fresh HF model: logits identical to the
        original torch model (tied head re-tied by HF on load)."""
        from learning_jax_sharding_tpu.models.convert import (
            state_dict_from_params,
        )

        hf, cfg, params = hf_pair
        sd = state_dict_from_params(params)
        hf2 = transformers.GPT2LMHeadModel(hf.config).eval()
        hf2.load_state_dict(sd, strict=False)
        hf2.tie_weights()
        tok = _tokens(seed=9)
        with torch.no_grad():
            want = hf(torch.tensor(tok)).logits.numpy()
            got = hf2(torch.tensor(tok)).logits.numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_decode_cache_matches_full_forward(self, hf_pair):
        """Chunked decode through the converted model equals its own full
        forward — biases and norm eps flow through the cache path too."""
        import dataclasses

        hf, cfg, params = hf_pair
        tok = jnp.asarray(_tokens(b=2, s=12, seed=5), jnp.int32)
        full = Transformer(cfg).apply({"params": params}, tok)
        dec_model = Transformer(dataclasses.replace(cfg, decode=True))
        logits, variables = dec_model.apply(
            {"params": params}, tok[:, :6], mutable=("cache",)
        )
        outs = [logits]
        for i in range(6, 12):
            logits, variables = dec_model.apply(
                {"params": params, **variables}, tok[:, i : i + 1],
                mutable=("cache",),
            )
            outs.append(logits)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(full, np.float32), atol=2e-4
        )
