"""Mixed-precision master weights: bf16 params, fp32 update trajectory.

Oracle for WHY the wrapper exists: at magnitude ~1, bf16 resolution is
2⁻⁸ ≈ 0.004 — an SGD step of 1e-3 rounds to NOTHING, so naive bf16 training
freezes. With fp32 masters the same steps accumulate exactly and the bf16
params snap to each newly-rounded master value.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.precision import master_weights
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

LR = 1e-3
STEPS = 20


def _run_sgd(tx, dtype):
    """STEPS sgd updates with grad ≡ 1 on a scalar param starting at 1.0."""
    p = {"w": jnp.ones((), dtype)}
    state = tx.init(p)
    for _ in range(STEPS):
        g = {"w": jnp.ones((), dtype)}
        updates, state = tx.update(g, state, p)
        p = optax.apply_updates(p, updates)
    return float(jnp.asarray(p["w"], jnp.float32))


class TestMasterWeights:
    def test_naive_bf16_sgd_freezes(self):
        # Sanity of the premise: each 1e-3 step rounds away at bf16 near 1.0.
        assert _run_sgd(optax.sgd(LR), jnp.bfloat16) == 1.0

    def test_master_weights_accumulate(self):
        final = _run_sgd(master_weights(optax.sgd(LR)), jnp.bfloat16)
        # fp32 trajectory is 1 - 20*1e-3 = 0.98; bf16 rounding of the master.
        assert final == float(jnp.asarray(0.98, jnp.bfloat16).astype(jnp.float32))

    def test_fp32_reference_trajectory(self):
        assert _run_sgd(master_weights(optax.sgd(LR)), jnp.float32) == (
            _run_sgd(optax.sgd(LR), jnp.float32)
        )

    def test_params_track_rounded_master(self):
        """After every step, params == master.astype(bf16) exactly."""
        tx = master_weights(optax.adamw(3e-2))
        p = {"w": jnp.full((4,), 1.0, jnp.bfloat16)}
        state = tx.init(p)
        for i in range(5):
            g = {"w": jnp.full((4,), 0.5, jnp.bfloat16)}
            updates, state = tx.update(g, state, p)
            p = optax.apply_updates(p, updates)
            np.testing.assert_array_equal(
                np.asarray(p["w"]),
                np.asarray(state.master["w"].astype(jnp.bfloat16)),
            )

    def test_update_requires_params(self):
        tx = master_weights(optax.sgd(LR))
        p = {"w": jnp.ones(())}
        state = tx.init(p)
        try:
            tx.update({"w": jnp.ones(())}, state)
        except ValueError as e:
            assert "params" in str(e)
        else:
            raise AssertionError("expected ValueError without params")


class TestShardedIntegration:
    def test_bf16_param_training_learns(self, mesh22, rng):
        """Full pipeline: bf16 param_dtype + master weights, born sharded;
        masters inherit the params' shardings; loss decreases."""
        cfg = dataclasses.replace(CONFIG_TINY, param_dtype=jnp.bfloat16)
        model = Transformer(cfg)
        tokens = rng.integers(0, cfg.vocab_size, size=(8, 33)).astype(np.int32)
        sh = mesh_sharding(mesh22, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        state, state_sh = sharded_train_state(
            model, master_weights(optax.adamw(3e-3)), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        # Params landed in bf16; masters in fp32 with the SAME sharding spec.
        kernel = state.params["block_0"]["attn"]["query"]["kernel"]
        master_kernel = state.opt_state.master["block_0"]["attn"]["query"]["kernel"]
        assert kernel.dtype == jnp.bfloat16
        assert master_kernel.dtype == jnp.float32
        assert kernel.sharding.spec == master_kernel.sharding.spec

        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        losses = []
        for _ in range(8):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
