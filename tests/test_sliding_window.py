"""Sliding-window (local) attention: dense mask and flash kernel agree.

Not in the reference (full S² attention only). Window semantics: query i
attends to keys in (i-window, i] — Mistral-style causal SWA. Oracles:

* dense sliding_window_mask == flash(window=w), forward AND gradients
  (the kernel's block skipping + in-block band mask must match exactly);
* window == S reproduces plain causal attention;
* window=1 is pure self-attention: output == v;
* the transformer trains with a window config.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.ops.attention import (
    causal_mask,
    dot_product_attention,
    sliding_window_mask,
)
from learning_jax_sharding_tpu.ops.flash_attention import flash_attention

B, S, N, H = 2, 128, 2, 16


def _qkv(rng, s=S):
    return tuple(
        jnp.asarray(rng.standard_normal((B, s, N, H)).astype(np.float32))
        for _ in range(3)
    )


class TestMaskOracle:
    def test_window_mask_structure(self):
        m = np.asarray(sliding_window_mask(5, 2))[0, 0]
        expected = np.array([
            [1, 0, 0, 0, 0],
            [1, 1, 0, 0, 0],
            [0, 1, 1, 0, 0],
            [0, 0, 1, 1, 0],
            [0, 0, 0, 1, 1],
        ], bool)
        np.testing.assert_array_equal(m, expected)

    def test_window_geq_len_is_causal(self):
        np.testing.assert_array_equal(
            np.asarray(sliding_window_mask(6, 6)), np.asarray(causal_mask(6))
        )

    def test_bad_window_rejected(self):
        with pytest.raises(ValueError, match="window"):
            sliding_window_mask(4, 0)


class TestFlashWindow:
    @pytest.mark.parametrize("window", [1, 16, 100, S])
    def test_forward_matches_dense(self, rng, window):
        q, k, v = _qkv(rng)
        dense = dot_product_attention(q, k, v, mask=sliding_window_mask(S, window))
        flash = flash_attention(
            q, k, v, causal=True, window=window, interpret=True,
            block_q=32, block_k=32,
        )
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(flash), atol=2e-5
        )

    @pytest.mark.parametrize("window", [16, 100])
    def test_gradients_match_dense(self, rng, window):
        q, k, v = _qkv(rng)

        def dense_loss(q, k, v):
            return jnp.sum(
                dot_product_attention(q, k, v, mask=sliding_window_mask(S, window))
                ** 2
            )

        def flash_loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True, window=window, interpret=True,
                    block_q=32, block_k=32,
                ) ** 2
            )

        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gd, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)

    def test_window_one_is_self_attention(self, rng):
        q, k, v = _qkv(rng)
        out = flash_attention(
            q, k, v, causal=True, window=1, interpret=True,
            block_q=32, block_k=32,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(v), atol=2e-5)

    def test_window_requires_causal(self, rng):
        q, k, v = _qkv(rng)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8, interpret=True)


class TestModelWindow:
    def test_transformer_trains_with_window(self, rng):
        cfg = dataclasses.replace(CONFIG_TINY, window=8, rope=True)
        model = Transformer(cfg)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 32)), jnp.int32)
        import flax.linen as nn

        params = nn.meta.unbox(
            model.init({"params": jax.random.key(0)}, tokens)["params"]
        )
        logits = model.apply({"params": params}, tokens)
        assert np.isfinite(np.asarray(logits, np.float32)).all()

    def test_window_matches_dense_windowed_module(self, rng):
        """Model with window=W == model with full attention at S<=W."""
        s = 8
        cfg_w = dataclasses.replace(CONFIG_TINY, window=s)
        cfg_f = CONFIG_TINY
        tokens = jnp.asarray(rng.integers(0, cfg_w.vocab_size, size=(2, s)), jnp.int32)
        mw, mf = Transformer(cfg_w), Transformer(cfg_f)
        p = mw.init({"params": jax.random.key(0)}, tokens)
        np.testing.assert_allclose(
            np.asarray(mw.apply(p, tokens)), np.asarray(mf.apply(p, tokens)),
            atol=1e-5,
        )

    def test_custom_backend_with_window_rejected(self, rng):
        from learning_jax_sharding_tpu.models.attention import MultiHeadAttention

        model = MultiHeadAttention(
            features=32, num_heads=2, head_dim=16, causal=True, window=4,
            attn_fn=lambda q, k, v, causal: v,
        )
        x = jnp.zeros((1, 8, 32))
        with pytest.raises(ValueError, match="configure the backend"):
            model.init({"params": jax.random.key(0)}, x)
