"""Cases 1a/1b/2/3/4 as machine-checked tests.

Each reference case file asserts per-shard shapes and narrates (in prose) which
collective GSPMD inserts. Here both become assertions: shard-shape oracles from
SURVEY.md §8 (verified by execution against the reference semantics) plus HLO
collective checks the reference never had. Reference cites per test.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tests.conftest import matmul_operands
import pytest

from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    assert_replicated,
    assert_shard_shape,
    col_sharded,
    mesh_sharding,
    put,
    replicated,
    row_sharded,
    shard_dims,
    shard_shapes,
    unique_shard_count,
)


def _dot(a, b):
    return jax.lax.dot(a, b)




class TestCase1a:
    """Contraction-dim sharding on both operands → partial products → AllReduce.

    Reference: `/root/reference/case1a.py` (A replicated over X / split 4-way on
    inner dim over Y, `:24`; B inner dim split 4-way, `:30`; shard shapes
    asserted at `:36,:43`; AllReduce + replicated C narrated at `:57-62`).
    """

    def test_shard_shapes_and_result(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, shard_dims(mesh24, 2, y=1))  # A(4,16): inner dim 4-way over Y
        b = put(b_np, shard_dims(mesh24, 2, y=0))  # B(16,4): inner dim 4-way over Y
        assert_shard_shape(a, (4, 4))
        assert_shard_shape(b, (4, 4))
        c = jax.jit(_dot)(a, b)
        np.testing.assert_allclose(np.asarray(c), a_np @ b_np, rtol=1e-5)
        # C is fully replicated after the AllReduce (case1a.py:60-62).
        assert_replicated(c, a_np @ b_np)
        assert unique_shard_count(c) == 1

    def test_allreduce_inserted(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, shard_dims(mesh24, 2, y=1))
        b = put(b_np, shard_dims(mesh24, 2, y=0))
        assert_collectives(_dot, a, b, require=("all-reduce",), forbid=("all-gather",))


class TestCase1b:
    """Mismatched contraction shardings → AllGather.

    Reference: `/root/reference/case1b.py` (A dim1 split 4-way over Y `:24`;
    B dim0 split 2-way over X `:30`; shard shapes `:36,:42`; AllGather narrated
    at `:55-57`; C replicated, verified by execution in SURVEY.md §8).
    """

    def test_shard_shapes_and_result(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, shard_dims(mesh24, 2, y=1))   # (4,4) shards
        b = put(b_np, shard_dims(mesh24, 2, x=0))   # (8,4) shards
        assert_shard_shape(a, (4, 4))
        assert_shard_shape(b, (8, 4))
        c = jax.jit(_dot)(a, b)
        np.testing.assert_allclose(np.asarray(c), a_np @ b_np, rtol=1e-5)
        assert_replicated(c)

    def test_allgather_inserted(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, shard_dims(mesh24, 2, y=1))
        b = put(b_np, shard_dims(mesh24, 2, x=0))
        assert_collectives(_dot, a, b, require=("all-gather",))


class TestCase2:
    """Outer-axes sharding: no contraction-dim conflict → sharded output.

    Reference: `/root/reference/case2.py` (A fully sharded over both axes `:23`,
    shard (2,4) `:34-35`; B dim0 over X `:29`; C row-sharded over X, replicated
    over Y — shard (2,4) asserted `:52`, cross-X shards differ `:59`).
    """

    def test_shard_shapes_and_result(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, shard_dims(mesh24, 2, x=0, y=1))  # (2,4) shards
        b = put(b_np, shard_dims(mesh24, 2, x=0))       # (8,4) shards
        assert_shard_shape(a, (2, 4))
        assert_shard_shape(b, (8, 4))
        c = jax.jit(_dot)(a, b)
        np.testing.assert_allclose(np.asarray(c), a_np @ b_np, rtol=1e-5)
        assert_shard_shape(c, (2, 4))
        # 2 distinct row-blocks, each replicated 4× over Y (case2.py:48-59).
        assert unique_shard_count(c) == 2


class TestCase3:
    """Both operands fully 2D-sharded → fully sharded output, zero redundancy.

    This is the sharding pattern underlying FSDP/ZeRO shown on a single matmul
    (SURVEY.md §2.4). Reference: `/root/reference/case3_fully_sharded.py`
    (A `:23` shard (2,4); B `:29` shard (8,1) `:41`; C shard (2,1) `:52`;
    every device holds a distinct tile `:58-60`).
    """

    def test_shard_shapes_and_result(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, shard_dims(mesh24, 2, x=0, y=1))
        b = put(b_np, shard_dims(mesh24, 2, x=0, y=1))
        assert_shard_shape(a, (2, 4))
        assert_shard_shape(b, (8, 1))
        c = jax.jit(_dot)(a, b)
        np.testing.assert_allclose(np.asarray(c), a_np @ b_np, rtol=1e-5)
        assert_shard_shape(c, (2, 1))
        assert unique_shard_count(c) == 8  # distinct tile per device


class TestCase4:
    """GSPMD §3.2: DP operand × TP operand → combined data+model parallelism.

    Reference: `/root/reference/case4_gspmd_ff.py` (einsum warmup `:26-33`;
    A row-split over X `:46` shard (2,16); B col-split over Y `:49` shard
    (16,1); C fully 2D-sharded (2,1) with no collective needed `:52-58`).
    """

    def test_batched_einsum(self, rng):
        a = rng.standard_normal((8, 4, 16)).astype(np.float32)
        b = rng.standard_normal((8, 16, 4)).astype(np.float32)
        c = jnp.einsum("ABC,ACD->ABD", a, b)
        assert c.shape == (8, 4, 4)  # case4_gspmd_ff.py:32
        np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4)

    def test_dp_mp_ff_projection(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, row_sharded(mesh24, "x"))
        b = put(b_np, col_sharded(mesh24, "y"))
        assert_shard_shape(a, (2, 16))
        assert_shard_shape(b, (16, 1))
        c = jax.jit(_dot)(a, b)
        np.testing.assert_allclose(np.asarray(c), a_np @ b_np, rtol=1e-5)
        assert_shard_shape(c, (2, 1))

    def test_no_collective_needed(self, mesh24, rng):
        a_np, b_np = matmul_operands(rng)
        a = put(a_np, row_sharded(mesh24, "x"))
        b = put(b_np, col_sharded(mesh24, "y"))
        assert_collectives(
            _dot, a, b, forbid=("all-reduce", "all-gather", "reduce-scatter")
        )


class TestShardingHelpers:
    def test_replicated(self, mesh24, rng):
        x = put(rng.standard_normal((4, 4)).astype(np.float32), replicated(mesh24))
        assert_replicated(x)
        assert shard_shapes(x) == [(4, 4)] * 8

    def test_tupled_axes_split(self, mesh24, rng):
        # One array dim split 8-way using BOTH mesh axes — the NamedSharding
        # equivalent of PositionalSharding.reshape (case1a.py:30, SURVEY §7).
        x = put(rng.standard_normal((16, 4)).astype(np.float32),
                mesh_sharding(mesh24, ("x", "y"), None))
        assert_shard_shape(x, (2, 4))

    def test_shard_dims_validation(self, mesh24):
        with pytest.raises(ValueError):
            shard_dims(mesh24, 2, bogus=0)
        with pytest.raises(ValueError):
            shard_dims(mesh24, 2, x=5)
