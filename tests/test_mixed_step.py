"""Fused mixed prefill+decode step (models/serving.py, ``mixed=True``).

THE oracle, inherited from test_serving.py and applied to the fused
scheduler: scheduling must never change results. Every mixed-engine
output — fresh prompts, mid-stream admits, prefix hits, long prompts
spanning several chunks, budget starvation, chained links, speculative
rounds — must be BIT-IDENTICAL to the split refill/decode engine (which
is itself pinned to rectangular single runs), and sampled streams must
be identical too (draws are keyed by request id and generated position,
never by schedule).
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    RULES_TP_SERVING,
)

NEW = 6

DRAFT_CFG = dataclasses.replace(
    CONFIG_TINY, num_layers=1, hidden=64, dtype=jnp.float32
)


@pytest.fixture(scope="module")
def setup(mesh22):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    rng = np.random.default_rng(11)
    model = Transformer(cfg)
    probe = np.zeros((2, 8), np.int32)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), probe
        )["params"]
    )
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in (3, 9, 5, 1, 12, 7, 4)
    ]
    return cfg, params, prompts


@pytest.fixture(scope="module")
def classic_ref(setup, mesh22):
    """The split-engine outputs the fused engine is held bit-identical
    to (the split engine itself is pinned to rectangular single runs in
    test_serving.py)."""
    cfg, params, prompts = setup
    serve = make_continuous_engine(
        cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        refill_chunk=4,
    )
    return serve(params, prompts)


def _draft_params():
    model = Transformer(DRAFT_CFG)
    toks = np.zeros((2, 8), np.int32)
    return nn.meta.unbox(
        model.init({"params": jax.random.key(7)}, toks)["params"]
    )


class TestMixedEngine:
    def test_matches_split_engine(self, setup, mesh22, classic_ref):
        """7 mixed-length requests through 2 slots, refill_chunk 4 (the
        12-token prompt spans 3 chunks): every fused-engine output equals
        the split engine's bit for bit, and the fused program actually
        dispatched (the workload interleaves refilling and decoding
        slots)."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        outs = serve(params, prompts)
        for r, g in zip(classic_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert serve.engine._c_mixed_n.value > 0
        # Steady state: one executable per program, no recompiles.
        assert serve.engine.compile_counts()["mixed_step"] == 1

    @pytest.mark.slow
    def test_budget_starvation_exact(self, setup, mesh22, classic_ref):
        """A token budget SMALLER than one refill chunk forces prompts to
        trickle in over many dispatches while decode rows keep advancing
        — results must not move."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, token_budget=3,
        )
        outs = serve(params, prompts)
        for r, g in zip(classic_ref, outs):
            np.testing.assert_array_equal(g, r)

    @pytest.mark.slow
    def test_chained_links_bit_identical(self, setup, mesh22, classic_ref):
        """decode_chain > 1: links carry tok/active/remaining
        device-to-device with one host sync per chain — cannot change
        results."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, decode_chain=3,
        )
        outs = serve(params, prompts)
        for r, g in zip(classic_ref, outs):
            np.testing.assert_array_equal(g, r)

    @pytest.mark.slow
    def test_eos_retires_mid_stream(self, setup, mesh22):
        """EOS emitted by a decode row inside a fused dispatch retires
        the row exactly where the split engine stops it."""
        cfg, params, prompts = setup
        split = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        ref = split(params, prompts)
        eos = int(ref[0][len(prompts[0]) + 1])
        for mixed in (False, True):
            serve = make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2,
                max_new_tokens=NEW, refill_chunk=4, eos_id=eos,
                mixed=mixed,
            )
            outs = serve(params, prompts)
            if not mixed:
                eos_ref = outs
            else:
                for r, g in zip(eos_ref, outs):
                    np.testing.assert_array_equal(g, r)

    def test_streaming_mid_admits(self, setup, mesh22, classic_ref):
        """The arrival process the fused scheduler exists for: requests
        admitted WHILE other rows decode ride the same dispatches —
        admission at every mixed dispatch, outputs unchanged."""
        cfg, params, prompts = setup
        eng = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        ).engine
        eng.add_request(prompts[0], rid=0)
        eng.add_request(prompts[1], rid=1)
        outs, steps, pending = {}, 0, list(range(2, 7))
        while eng.has_work() or pending:
            eng.step(params)
            steps += 1
            if steps % 2 == 0 and pending:
                i = pending.pop(0)
                eng.add_request(prompts[i], rid=i)
            outs.update(eng.pop_finished())
        for i, r in enumerate(classic_ref):
            np.testing.assert_array_equal(outs[i], r)

    def test_long_prompt_chunked_paged(self, setup, mesh22):
        """A 44-token prompt through 8-token chunks on the PAGED fused
        engine: refill spans 6 budgeted dispatches while the short rows
        decode alongside; pages allocate for refill AND decode writes of
        the same dispatch."""
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, max_seq_len=64, decode_attention="blocked"
        )
        rng = np.random.default_rng(5)
        long_prompts = [
            rng.integers(1, cfg.vocab_size, size=(44,)).astype(np.int32),
            prompts[0], prompts[2],
        ]
        split = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8,
        )
        ref = split(params, long_prompts)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            paged_pages=16, page_size=8,
        )
        outs = serve(params, long_prompts)
        for r, g in zip(ref, outs):
            np.testing.assert_array_equal(g, r)

    def test_prefix_hits_across_calls(self, setup, mesh22):
        """Prefix caching under the fused scheduler: a second serve()
        call re-admits shared-prefix prompts with pages already mapped
        (reset_to > 0 riding the fused dispatch) — outputs bit-identical,
        hits counted."""
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, max_seq_len=64, decode_attention="blocked"
        )
        rng = np.random.default_rng(9)
        system = rng.integers(1, cfg.vocab_size, size=(16,)).astype(np.int32)
        queue = [
            np.concatenate([
                system,
                rng.integers(1, cfg.vocab_size, size=(4,)).astype(np.int32),
            ])
            for _ in range(4)
        ]
        split = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8,
        )
        ref = split(params, queue)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            paged_pages=16, page_size=8, prefix_cache=True,
        )
        cold = serve(params, queue)
        warm = serve(params, queue)
        for r, g in zip(ref, cold):
            np.testing.assert_array_equal(g, r)
        for r, g in zip(ref, warm):
            np.testing.assert_array_equal(g, r)
        assert serve.last_stats["prefix_hits"] == len(queue)

    @pytest.mark.slow
    def test_sampled_streams_schedule_independent(self, setup, mesh22):
        """temperature > 0: the fused engine (different batch size AND a
        starving budget — a maximally different schedule) must emit the
        IDENTICAL sampled stream per request: draws are keyed by (request
        id, generated position), never by schedule."""
        cfg, params, prompts = setup
        split = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, temperature=0.7, top_k=8,
        )
        fused = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
            refill_chunk=4, temperature=0.7, top_k=8, mixed=True,
            token_budget=5,
        )
        a = split(params, prompts, rng=jax.random.key(42))
        b = fused(params, prompts, rng=jax.random.key(42))
        for r, g in zip(a, b):
            np.testing.assert_array_equal(g, r)

    def test_stall_telemetry(self, setup, mesh22):
        """The metric this PR exists to move: the split engine records
        decode-stall seconds (refill dispatches that parked active
        decode rows); the fused engine records none and accrues its time
        under mixed_s."""
        cfg, params, prompts = setup
        split = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        split(params, prompts)
        lat = split.last_latency
        assert lat["decode_stall_s"] > 0
        assert lat["decode_stall_share"] > 0
        assert lat["mixed_s"] == 0
        fused = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        fused(params, prompts)
        lat = fused.last_latency
        assert lat["decode_stall_s"] == 0
        assert lat["decode_stall_share"] == 0
        assert lat["mixed_s"] > 0

    def test_scheduler_flight_recorder_events(self, setup, mesh22):
        """Every fused dispatch logs its scheduling decision (links,
        decode rows, refill tokens, starvation) to the flight recorder."""
        from learning_jax_sharding_tpu.telemetry import FlightRecorder

        cfg, params, prompts = setup
        rec = FlightRecorder()
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, token_budget=1, recorder=rec,
        )
        serve(params, prompts[:3])
        evs = rec.events("engine.mixed_schedule")
        assert evs, "no scheduler decisions recorded"
        assert all(
            {"links", "decode_rows", "refill_tokens", "starved", "budget"}
            <= set(e) for e in evs
        )
        # The tight budget must actually have starved someone at least once.
        assert any(e["starved"] > 0 for e in evs)

    def test_validation(self, setup, mesh22):
        cfg, params, prompts = setup
        with pytest.raises(ValueError, match="token_budget requires"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
                token_budget=8,
            )
        with pytest.raises(ValueError, match="token_budget"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
                mixed=True, token_budget=0,
            )


class TestSpeculativeMixed:
    """spec_mixed_step: budgeted refill through target AND draft plus one
    draft-verify round per link, per-row acceptance and rollback intact."""

    @pytest.mark.slow
    def test_matches_split_engine(self, setup, mesh22, classic_ref):
        """Weak draft (near-zero acceptance): per-row rollback runs every
        round while other slots refill in the same dispatch — outputs
        bit-identical to the plain split engine."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, draft_config=DRAFT_CFG,
            num_draft=3,
        )
        outs = serve(params, prompts, draft_params=_draft_params())
        for r, g in zip(classic_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert serve.engine._c_mixed_n.value > 0

    def test_per_row_rollback(self, setup, mesh22, classic_ref):
        """Self-draft (acceptance 1.0) next to a fresh admit mid-stream:
        one row fast-forwards num_draft+1 tokens per round while its
        neighbor refills in the same fused dispatch — each row's rollback
        index is its own. Acceptance stats must survive the fused path."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, draft_config=cfg, num_draft=2,
        )
        outs = serve(params, prompts, draft_params=params)
        for r, g in zip(classic_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert serve.last_stats["spec_accept_rate"] == 1.0

    @pytest.mark.slow
    def test_paged_speculative_mixed(self, setup, mesh22):
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, max_seq_len=64, decode_attention="blocked"
        )
        split = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8,
        )
        ref = split(params, prompts[:4])
        dcfg = dataclasses.replace(
            DRAFT_CFG, max_seq_len=64, decode_attention="blocked"
        )
        serve = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            draft_config=dcfg, num_draft=2, paged_pages=20, page_size=8,
        )
        outs = serve(params, prompts[:4], draft_params=_draft_params())
        for r, g in zip(ref, outs):
            np.testing.assert_array_equal(g, r)

    @pytest.mark.slow
    def test_sampled_speculative_schedule_independent(self, setup, mesh22):
        """Speculative SAMPLING through the fused path: same draws as the
        split speculative engine (position-keyed rejection streams)."""
        cfg, params, prompts = setup
        split = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, temperature=0.7, top_k=8,
            draft_config=DRAFT_CFG, num_draft=2,
        )
        fused = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, temperature=0.7, top_k=8, mixed=True,
            draft_config=DRAFT_CFG, num_draft=2, token_budget=6,
        )
        dp = _draft_params()
        a = split(params, prompts[:4], rng=jax.random.key(9), draft_params=dp)
        b = fused(params, prompts[:4], rng=jax.random.key(9), draft_params=dp)
        for r, g in zip(a, b):
            np.testing.assert_array_equal(g, r)
