"""Explicit shard_map collectives vs numpy and vs the implicit-GSPMD results.

Each explicit form must (a) match the dense product, (b) actually contain its
named collective in the compiled HLO — turning the reference's prose
narrations (`/root/reference/case1a.py:57-59`, `case1b.py:55-57`) into checked
facts about our own explicit implementations too.
"""

from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import matmul_operands

from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    build_mesh,
    assert_shard_shape,
    collective_counts,
)
from learning_jax_sharding_tpu.parallel.collectives import (
    allgather_matmul,
    dp_tp_matmul,
    psum_matmul,
    quantized_all_reduce,
    reduce_scatter_matmul,
    ring_allgather_matmul,
)




class TestPsumMatmul:
    def test_matches_dense(self, mesh24, rng):
        a, b = matmul_operands(rng)
        c = psum_matmul(a, b, mesh=mesh24, axis="y")
        np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-5)

    def test_emits_allreduce(self, mesh24, rng):
        a, b = matmul_operands(rng)
        fn = partial(psum_matmul, mesh=mesh24, axis="y")
        assert_collectives(fn, a, b, require=("all-reduce",))


class TestAllGatherMatmul:
    def test_matches_dense(self, mesh24, rng):
        a, b = matmul_operands(rng)
        c = allgather_matmul(a, b, mesh=mesh24, a_axis="y", b_axis="x")
        np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-5)

    def test_emits_allgather(self, mesh24, rng):
        a, b = matmul_operands(rng)
        fn = partial(allgather_matmul, mesh=mesh24, a_axis="y", b_axis="x")
        assert_collectives(fn, a, b, require=("all-gather",))


class TestReduceScatterMatmul:
    def test_matches_dense_and_sharded_output(self, mesh24, rng):
        a, b = matmul_operands(rng)
        c = reduce_scatter_matmul(a, b, mesh=mesh24, axis="y")
        np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-5)
        # Output arrives row-sharded over y (4-way on dim 0 of (4,4)).
        assert_shard_shape(c, (1, 4))

    def test_emits_reduce_scatter(self, mesh24, rng):
        a, b = matmul_operands(rng)
        fn = partial(reduce_scatter_matmul, mesh=mesh24, axis="y")
        counts = collective_counts(fn, a, b)
        # XLA may lower psum_scatter as reduce-scatter or as all-reduce +
        # dynamic-slice; on TPU it is reduce-scatter. Accept either lowering
        # but require that a reduction collective exists.
        assert counts["reduce-scatter"] + counts["all-reduce"] >= 1, counts


class TestDpTpMatmul:
    def test_matches_dense_no_collective(self, mesh24, rng):
        a, b = matmul_operands(rng)
        c = dp_tp_matmul(a, b, mesh=mesh24, dp_axis="x", tp_axis="y")
        np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-5)
        assert_shard_shape(c, (2, 1))  # born fully 2D-sharded (case4 oracle)
        fn = partial(dp_tp_matmul, mesh=mesh24, dp_axis="x", tp_axis="y")
        assert_collectives(
            fn, a, b, forbid=("all-reduce", "all-gather", "reduce-scatter")
        )


class TestRingAllGatherMatmul:
    def test_matches_dense(self, mesh24, rng):
        a, b = matmul_operands(rng, m=8, k=16, n=8)
        c = ring_allgather_matmul(a, b, mesh=mesh24, axis="y")
        # Ring accumulation reorders the K-dim sum; allow absolute slack too.
        np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4, atol=1e-5)

    def test_emits_collective_permute(self, mesh24, rng):
        a, b = matmul_operands(rng, m=8, k=16, n=8)
        fn = partial(ring_allgather_matmul, mesh=mesh24, axis="y")
        assert_collectives(fn, a, b, require=("collective-permute",))


class TestQuantizedAllReduce:
    def _contribs(self, rng, n=8, size=4097):
        # Deliberately NOT a multiple of n: exercises the pad/unpad path.
        return jnp.asarray(rng.standard_normal((n, size)).astype(np.float32))

    def test_close_to_exact_sum(self, rng):
        import jax

        mesh = build_mesh((8,), ("d",))
        contribs = self._contribs(rng)
        got = np.asarray(quantized_all_reduce(contribs, mesh=mesh, axis="d"))
        want = np.asarray(contribs).sum(0)
        rel = np.linalg.norm(got - want) / np.linalg.norm(want)
        # D-1 requantization hops at D=8: measured ~1.6% on gaussian data.
        assert rel < 0.03, rel

    def test_multidim_and_2d_mesh_axis(self, mesh24, rng):
        contribs = jnp.asarray(
            rng.standard_normal((4, 3, 65)).astype(np.float32)
        )
        got = np.asarray(quantized_all_reduce(contribs, mesh=mesh24, axis="y"))
        want = np.asarray(contribs).sum(0)
        assert got.shape == want.shape
        rel = np.linalg.norm(got - want) / np.linalg.norm(want)
        assert rel < 0.03, rel

    def test_wire_is_permutes_not_allreduce(self, rng):
        mesh = build_mesh((8,), ("d",))
        contribs = self._contribs(rng, size=512)
        fn = partial(quantized_all_reduce, mesh=mesh, axis="d")
        assert_collectives(fn, contribs, require=("collective-permute",))
        counts = collective_counts(fn, contribs)
        assert counts["all-reduce"] == 0

    def test_size_mismatch_rejected(self, rng):
        mesh = build_mesh((8,), ("d",))
        with pytest.raises(ValueError, match="mesh axis"):
            quantized_all_reduce(
                jnp.zeros((4, 16)), mesh=mesh, axis="d"
            )
