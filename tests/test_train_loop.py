"""End-to-end training loop: fit, metrics, checkpoint/resume determinism."""

import jax
import numpy as np
import pytest

from learning_jax_sharding_tpu.data import SyntheticLMDataset
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, fit


@pytest.fixture(scope="module")
def mesh_dm():
    return build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])


def _dataset():
    return SyntheticLMDataset(
        vocab_size=CONFIG_TINY.vocab_size, seq_len=32, seed=7
    )


class _CyclicDataset:
    """Fully learnable stream: token i+1 always follows token i (mod V) —
    loss must fall well below ln(V). (Uniform-random synthetic data starts AT
    its optimum ≈ ln V, so it cannot show descent.)"""

    def __init__(self, vocab_size, seq_len):
        self.vocab_size, self.seq_len = vocab_size, seq_len

    def batch(self, index, rows=None, batch_size=8):
        rng = np.random.default_rng((11, index))
        starts = rng.integers(0, self.vocab_size, size=batch_size)
        if rows is not None:
            starts = starts[rows]
        tokens = (
            starts[:, None] + np.arange(self.seq_len + 1)[None]
        ) % self.vocab_size
        tokens = tokens.astype(np.int32)
        return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


class TestFit:
    def test_trains_and_logs(self, mesh_dm, tmp_path):
        cfg = TrainLoopConfig(
            steps=6, global_batch_size=8, learning_rate=3e-3,
            metrics_path=str(tmp_path / "metrics.jsonl"),
        )
        state, history = fit(
            Transformer(CONFIG_TINY),
            _CyclicDataset(CONFIG_TINY.vocab_size, 32),
            mesh_dm, RULES_DP_TP, cfg,
        )
        assert int(state.step) == 6
        assert len(history) == 6
        losses = [h["loss"] for h in history]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # JSONL mirror exists and parses
        lines = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
        assert len(lines) == 6

    def test_resume_is_exact(self, mesh_dm, tmp_path):
        """Interrupted-then-resumed must equal uninterrupted: same batches
        (step-indexed loader), same state (checkpoint), same final loss."""
        model = Transformer(CONFIG_TINY)
        full_cfg = TrainLoopConfig(steps=6, global_batch_size=8)
        _, full_hist = fit(model, _dataset(), mesh_dm, RULES_DP_TP, full_cfg)

        ckpt_dir = str(tmp_path / "ckpt")
        part1 = TrainLoopConfig(
            steps=3, global_batch_size=8,
            checkpoint_dir=ckpt_dir, checkpoint_every=1,
        )
        fit(model, _dataset(), mesh_dm, RULES_DP_TP, part1)
        part2 = TrainLoopConfig(
            steps=6, global_batch_size=8,
            checkpoint_dir=ckpt_dir, checkpoint_every=1,
        )
        state, hist2 = fit(model, _dataset(), mesh_dm, RULES_DP_TP, part2)
        assert int(state.step) == 6
        # The resumed run executed only steps 4-6.
        assert [h["step"] for h in hist2] == [4, 5, 6]
        np.testing.assert_allclose(
            [h["loss"] for h in hist2],
            [h["loss"] for h in full_hist[3:]],
            rtol=1e-6,
        )

    def test_resume_noop_when_done(self, mesh_dm, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = TrainLoopConfig(
            steps=3, global_batch_size=8,
            checkpoint_dir=ckpt_dir, checkpoint_every=1,
        )
        model = Transformer(CONFIG_TINY)
        fit(model, _dataset(), mesh_dm, RULES_DP_TP, cfg)
        state, hist = fit(model, _dataset(), mesh_dm, RULES_DP_TP, cfg)
        assert int(state.step) == 3
        assert hist == []

    def test_warmup_schedule(self, mesh_dm):
        cfg = TrainLoopConfig(
            steps=4, global_batch_size=8, warmup_steps=10,
            learning_rate=1e-2,
        )
        state, history = fit(
            Transformer(CONFIG_TINY), _dataset(), mesh_dm, RULES_DP_TP, cfg
        )
        assert all(np.isfinite([h["loss"] for h in history]))
