"""Elastic fleet (round 23): autoscaler control loop + capacity planner.

Named to sort LAST alongside ``test_zfleet`` / ``test_zworkload`` (the
end-to-end oracles build multi-replica fleets; the tier-1 window spends
its budget on the fast oracles first). Four layers, cheapest first:

* the PLANNER as closed-form math — the parameter-count formula pinned
  against a real initialized tree, window/peak/pricing arithmetic on a
  hand-computable synthetic trace, the feasibility gates (HBM, device
  budget, ICI-domain carve), and the K(t) timeline integral the
  planner-vs-live score reduces to;
* the CONTROL LOOP on a live two-replica fleet — hysteresis holds
  before actions, occupancy-corroborated burn (history alone neither
  buys machines nor blocks their return), cooldown, fleet-size bounds,
  spot re-admission backoff that arms on preemption and doubles on the
  next one, and the canary that probes a FRESH replica end-to-end
  before adoption (a revived standby skips it);
* every committed action is a LOGGED DECISION — timeline entries and
  ``fleet.scale_decision`` records stay 1:1;
* DRAIN-AND-MIGRATE DETERMINISM, the round's acceptance bar — a
  scale-in mid-flight (and one mid-replay on the paced canonical day
  trace, with the replica re-adopted later) yields per-tenant token
  streams byte-identical to a static-fleet oracle, with the economics
  roll-up's conservation invariant intact.
"""

import dataclasses
import types

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.fleet import (
    Autoscaler,
    AutoscalerConfig,
    FleetRouter,
    PlannerAssumptions,
    canonical_trace_path,
    check_fit,
    make_replicas,
    plan_capacity,
    read_trace,
    replay_trace,
    score_timeline,
    synth_prompt,
    timeline_replica_seconds,
)
from learning_jax_sharding_tpu.fleet.capacity import _param_count
from learning_jax_sharding_tpu.models.serving import RequestFailure
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.robustness import ChaosInjector, Fault
from learning_jax_sharding_tpu.telemetry import (
    FlightRecorder,
    fleet_economics,
)


@pytest.fixture(scope="module")
def built():
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(5), np.zeros((2, 8), np.int32)
        )["params"]
    )
    return cfg, params


def _fleet(cfg, params, *, count=2, **over):
    kw = dict(batch_size=2, max_new_tokens=6, refill_chunk=8)
    kw.update(over)
    reps = make_replicas(
        cfg, RULES_DP_TP, params, count=count, mesh_shape=(1, 1), **kw,
    )
    # A PRIVATE recorder per fleet: the default is process-shared, and
    # these tests assert exact lifecycle-event counts.
    return reps, FleetRouter(reps, recorder=FlightRecorder())


def _flood(router, n, *, rid0=0, tokens=5):
    for i in range(n):
        router.add_request(
            np.arange(1, 1 + tokens, dtype=np.int32), rid=rid0 + i,
        )


# --- the planner as closed-form math ------------------------------------


def test_param_count_matches_real_tree(built):
    cfg, params = built
    real = sum(
        int(np.prod(x.shape))
        for x in jax.tree_util.tree_leaves(params)
    )
    assert _param_count(cfg) == real


def test_planner_windows_peak_and_pricing():
    # Two 2 s windows, hand-computable: w0 one request, w1 ten. Each
    # request is prompt 10 + decode 6 = 16 tokens. Deliverable supply
    # is 20 tok/s × 0.7 headroom = 14 tok/s per replica.
    events = [{"t": 0.5, "rid": 0, "prompt_len": 10}] + [
        {"t": 2.1 + 0.01 * i, "rid": 1 + i, "prompt_len": 10}
        for i in range(10)
    ]
    plan = plan_capacity(
        events, CONFIG_TINY, max_new_tokens=6, mesh_shape=(1, 1),
        min_replicas=1, max_replicas=4, replica_tok_s=20.0,
    )
    assert plan["throughput"]["deliverable_tok_s"] == pytest.approx(14.0)
    ks = [w["k"] for w in plan["windows"]]
    # w0: 16 tok / 2 s / 14 → k=1; w1: 160 / 2 / 14 = 5.71 → clamp 4.
    assert ks == [1, 4]
    assert plan["peak_k"] == 4
    assert plan["best_static_k"] == "4"
    assert plan["elastic"]["replica_s"] == pytest.approx(1 * 2 + 4 * 2)
    assert plan["static"]["4"]["covers_peak"]
    assert not plan["static"]["3"]["covers_peak"]
    # Static K=4 holds 4 replicas for the 4 s horizon; elastic holds 10
    # replica-seconds — the saving the autoscaler is scored against.
    assert plan["static"]["4"]["replica_s"] == pytest.approx(16.0)
    assert plan["elastic_vs_best_static_saving_pct"] == pytest.approx(
        100.0 * (1 - 10.0 / 16.0)
    )
    rate = plan["assumptions"]["usd_per_device_hour"] / 3600.0
    assert plan["elastic"]["cost_usd"] == pytest.approx(10.0 * 1 * rate)
    with pytest.raises(ValueError, match="empty trace"):
        plan_capacity([], CONFIG_TINY, max_new_tokens=6)


def test_planner_feasibility_gates():
    a = PlannerAssumptions(hbm_bytes_per_device=1.0)
    fit = check_fit(CONFIG_TINY, mesh_shape=(1, 1), assumptions=a)
    assert not fit["hbm_ok"] and not fit["ok"]
    assert fit["hbm_need_bytes"] > fit["hbm_have_bytes"]
    # Device budget: 8 replicas × 2 devices > 8 available.
    fit = check_fit(
        CONFIG_TINY, mesh_shape=(1, 2), max_replicas=8, total_devices=8,
    )
    assert not fit["carve_ok"] and "exceed" in fit["carve_why"]
    # ICI straddle: a 2-device sub-mesh over 1-device domains.
    topo = types.SimpleNamespace(ici_domain_devices=1)
    fit = check_fit(
        CONFIG_TINY, mesh_shape=(1, 2), max_replicas=2,
        total_devices=8, topology=topo,
    )
    assert not fit["carve_ok"] and "straddles" in fit["carve_why"]
    # Whole-domain carve: 8 devices in 3-device domains fragment into
    # only 2 intra-domain 2-device sub-meshes — 3 replicas fit the raw
    # device budget (6 <= 8) but not the carve.
    topo = types.SimpleNamespace(ici_domain_devices=3)
    fit = check_fit(
        CONFIG_TINY, mesh_shape=(1, 2), max_replicas=3,
        total_devices=8, topology=topo,
    )
    assert not fit["carve_ok"] and "only 2" in fit["carve_why"]
    fit = check_fit(
        CONFIG_TINY, mesh_shape=(1, 2), max_replicas=4,
        total_devices=8,
        topology=types.SimpleNamespace(ici_domain_devices=2),
    )
    assert fit["ok"]


def test_timeline_integral_and_score():
    timeline = [
        {"action": "canary", "t": 1.0},            # moves no capacity
        {"action": "grow", "t": 2.0, "k": 2},
        {"action": "rebalance", "t": 4.0, "k": 9},  # ignored: not grow/shrink
        {"action": "shrink", "t": 6.0, "k": 1},
        {"action": "grow", "t": 99.0, "k": 3},      # clamped to duration
    ]
    # k=1 on [0,2), k=2 on [2,6), k=1 on [6,10) → 2 + 8 + 4 = 14.
    assert timeline_replica_seconds(
        timeline[:4], k0=1, duration_s=10.0,
    ) == pytest.approx(14.0)
    assert timeline_replica_seconds(
        timeline, k0=1, duration_s=10.0,
    ) == pytest.approx(14.0)
    plan = {"horizon_s": 20.0, "elastic": {"replica_s": 28.0}}
    score = score_timeline(plan, timeline, k0=1, duration_s=10.0)
    assert score["time_scale"] == pytest.approx(2.0)
    assert score["live_replica_s"] == pytest.approx(28.0)
    assert score["gap_pct"] == pytest.approx(0.0)
    over = score_timeline(
        plan, [{"action": "grow", "t": 0.0, "k": 4}], k0=1,
        duration_s=10.0,
    )
    assert over["live_replica_s"] == pytest.approx(80.0)
    assert over["gap_pct"] == pytest.approx(100.0 * 52.0 / 28.0)


# --- the control loop on a live fleet -----------------------------------


def test_hysteresis_bounds_and_uncorroborated_burn(built):
    cfg, params = built
    reps, router = _fleet(cfg, params)
    asc = Autoscaler(router, config=AutoscalerConfig(
        hot_evals=2, cold_evals=3, cooldown_s=0.0,
        min_replicas=1, max_replicas=2,
    ))
    # A loud burn sensor with ZERO queues: uncorroborated history must
    # not block the shrink (nor, later at min, buy a machine).
    with ChaosInjector(
        Fault("fleet.scale_signal", "mutate", count=-1,
              mutate=lambda _burn: 50.0)
    ):
        assert asc.signals()[0] == 50.0
        for t in range(2):              # cold, but under cold_evals
            assert asc.step(now=0.1 * t) is None
        assert all(r.alive for r in reps)
        decided = asc.step(now=0.3)
        assert decided is not None and decided["action"] == "shrink"
        assert decided["k"] == 1
        # At min_replicas every further cold eval is a counted hold.
        holds0 = asc._c_holds.value
        for t in range(4):
            assert asc.step(now=1.0 + 0.1 * t) is None
        assert asc._c_holds.value > holds0
        assert [e["action"] for e in asc.timeline] == ["shrink"]
    assert router.drain_ms and len(router.drain_ms) == 1
    # Now real standing queues: occupancy alone reads hot, and the
    # grow REVIVES the drained standby (no canary for a warm replica).
    _flood(router, 8)
    assert asc.step(now=2.0) is None    # hot #1 of hot_evals=2
    grew = asc.step(now=2.1)
    assert grew is not None and grew["action"] == "grow"
    assert grew["revived"] and grew["k"] == 2
    assert [e["action"] for e in asc.timeline] == ["shrink", "grow"]
    assert sum(1 for r in reps if r.alive) == 2
    # Every committed action is a flight-recorded decision, 1:1.
    decisions = router.recorder.events("fleet.scale_decision")
    assert len(decisions) == len(asc.timeline)
    assert [e["action"] for e in decisions] == ["shrink", "grow"]
    out = router.drain()
    assert sorted(out) == list(range(8))
    assert not any(isinstance(v, RequestFailure) for v in out.values())


def test_cooldown_blocks_back_to_back_actions(built):
    cfg, params = built
    reps, router = _fleet(cfg, params)
    asc = Autoscaler(router, config=AutoscalerConfig(
        hot_evals=1, cold_evals=1, cooldown_s=100.0,
        min_replicas=1, max_replicas=2,
    ))
    assert asc.step(now=0.0)["action"] == "shrink"
    _flood(router, 8)
    holds0 = asc._c_holds.value
    for t in range(3):                  # hot, but inside the cooldown
        assert asc.step(now=1.0 + t) is None
    assert asc._c_holds.value == holds0 + 3
    grew = asc.step(now=200.0)
    assert grew is not None and grew["action"] == "grow"
    assert grew["t"] == 200.0
    router.drain()


def test_plan_floor_feeds_forward_and_pins_scale_in(built):
    """``step(..., floor=k)`` is the capacity plan's feed-forward lane:
    below the floor the loop buys a replica IMMEDIATELY — no hot
    streak, no cooldown (the plan priced the burst offline; waiting
    for burn to confirm it is how a reactive loop loses a crowd's
    front) — and scale-in never drops under it. Above the floor the
    normal reactive hysteresis owns the fleet."""
    cfg, params = built
    reps, router = _fleet(cfg, params)
    router.retire_replica("unified1", reason="standby")
    asc = Autoscaler(router, config=AutoscalerConfig(
        hot_evals=99, cold_evals=1, cooldown_s=1000.0,
        min_replicas=1, max_replicas=2,
    ))
    # Arm the cooldown with a real action... which the floor then
    # ignores: the idle fleet reads cold, but floor=1 == k blocks
    # shrink, so force the clock first via a floor-grow.
    grew = asc.step(now=0.0, floor=2)
    assert grew is not None and grew["action"] == "grow"
    assert grew["floor"] == 2 and grew["revived"]
    assert sum(1 for r in router.replicas.values() if r.alive) == 2
    # At the floor: nothing to do, and the 1000 s cooldown from the
    # floor-grow holds every reactive impulse.
    assert asc.step(now=0.1, floor=2) is None
    # Cold evals satisfied (cold_evals=1, idle fleet) — but the floor
    # pins scale-in: the shrink is refused, counted as a hold.
    holds0 = asc._c_holds.value
    assert asc.step(now=2000.0, floor=2) is None
    assert asc._c_holds.value == holds0 + 1
    assert sum(1 for r in router.replicas.values() if r.alive) == 2
    # Floor released: the same cold signal now shreds the headroom.
    shrank = asc.step(now=3000.0)
    assert shrank is not None and shrank["action"] == "shrink"
    assert sum(1 for r in router.replicas.values() if r.alive) == 1
    # A floor past max_replicas clamps; and with the pool exhausted
    # (no standby left once revived, no factory) the floor-grow that
    # wants a third replica holds instead of erroring.
    grew = asc.step(now=4000.0, floor=99)
    assert grew is not None and grew["floor"] == 2
    holds1 = asc._c_holds.value
    assert asc.step(now=4001.0, floor=99) is None   # k == clamped floor
    assert asc._c_holds.value == holds1 + 1         # cooldown hold, no error
    router.drain()


def test_spot_backoff_arms_gates_and_doubles(built):
    cfg, params = built
    reps, router = _fleet(cfg, params)
    reps[1].preemptible = True
    asc = Autoscaler(router, config=AutoscalerConfig(
        hot_evals=1, cold_evals=8, cooldown_s=0.0,
        min_replicas=1, max_replicas=2, spot_backoff_s=0.5,
        spot_backoff_mult=2.0,
    ))
    asc.preempt("unified1", grace_steps=0)
    assert not reps[1].alive
    assert asc.timeline[-1]["action"] == "preempt"
    _flood(router, 8)
    # The eviction arms a 0.5 s re-admission backoff; inside it the hot
    # loop finds no standby (and no factory), so it holds.
    assert asc.step(now=0.1) is None
    assert asc.report()["spot_backoffs"]["unified1"]["delay_s"] == 0.5
    assert not reps[1].alive
    grew = asc.step(now=0.7)            # backoff expired: revival
    assert grew is not None and grew["action"] == "grow"
    assert grew["revived"] and grew["preemptible"]
    assert asc.step(now=0.8) is None    # one eval SEES it back alive
    router.drain()
    # A second preemption of the same replica DOUBLES the delay.
    asc.preempt("unified1", grace_steps=0)
    asc.step(now=1.0)
    assert asc.report()["spot_backoffs"]["unified1"]["delay_s"] == 1.0
    backoffs = router.recorder.events("fleet.spot_backoff")
    assert [e["delay_s"] for e in backoffs] == [0.5, 1.0]


def test_canary_probes_fresh_replica_before_adoption(built):
    cfg, params = built
    reps, _ = _fleet(cfg, params)
    router = FleetRouter(reps[:1])
    built_names = []

    def factory(slot, generation):
        built_names.append((slot, generation))
        return reps[1]

    asc = Autoscaler(router, factory, config=AutoscalerConfig(
        hot_evals=1, cold_evals=8, cooldown_s=0.0,
        min_replicas=1, max_replicas=2,
    ))
    _flood(router, 8)
    grew = asc.step(now=0.0)
    assert grew is not None and grew["action"] == "grow"
    assert not grew["revived"]
    assert built_names == [(1, 1)]
    # The canary decision precedes the grow, probed the engine end-to-
    # end, and its compute was reset out of the serving books.
    canary, grow = asc.timeline[-2:]
    assert canary["action"] == "canary" and canary["probe_steps"] > 0
    assert grow["action"] == "grow"
    assert not reps[1].engine.has_work()
    assert reps[1].engine.pop_finished() == {}
    # The stats window reset at adoption: the probe's compute (whole
    # decode steps) is gone; only post-reset bookkeeping slivers remain.
    assert sum(
        dict(reps[1].engine.ledger.window_buckets()).values()
    ) < 1e-3
    assert "unified1" in router.replicas and reps[1].alive
    out = router.drain()
    assert sorted(out) == list(range(8))


# --- drain-and-migrate determinism --------------------------------------


def test_scale_in_mid_flight_bit_identical(built):
    cfg, params = built
    rng = np.random.default_rng(17)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=rng.integers(3, 9))
        .astype(np.int32)
        for _ in range(6)
    ]
    solo_reps, solo = _fleet(cfg, params, count=1)
    for i, p in enumerate(prompts):
        solo.add_request(p, rid=i)
    oracle = solo.drain()

    reps, router = _fleet(cfg, params)
    for i, p in enumerate(prompts):
        router.add_request(p, rid=i)
    router.step()
    assert reps[0].engine.has_work()
    info = router.retire_replica("unified0", reason="scale_in")
    assert info["rerouted"]             # drained mid-flight, visibly
    assert not reps[0].alive and "unified0" in router.replicas
    out = router.drain()
    assert sorted(out) == sorted(oracle)
    for rid in oracle:
        np.testing.assert_array_equal(out[rid], oracle[rid])
    with pytest.raises(ValueError, match="last live"):
        router.retire_replica("unified1")
    with pytest.raises(ValueError, match="not alive"):
        router.retire_replica("unified0")
    with pytest.raises(ValueError, match="already serving"):
        router.adopt_replica(reps[1])


def test_scale_in_mid_replay_conserved_vs_static_oracle(built):
    """The acceptance bar: scale-in DURING the paced canonical-day
    replay (and a later re-adoption) must leave every per-tenant token
    stream byte-identical to a static-fleet oracle, with the economics
    conservation invariant intact — elasticity is invisible in the
    streams and honest in the books."""
    cfg, params = built
    _, events = read_trace(canonical_trace_path())
    seed = 20
    speed = 8.0

    static_reps, static_router = _fleet(cfg, params)
    oracle = replay_trace(
        static_router, events, seed=seed, vocab_size=cfg.vocab_size,
        pace=False,
    )

    reps, router = _fleet(cfg, params)
    state = {"retired": False, "revived": False}

    def on_tick(elapsed):
        # Retire unified1 inside the flash crowd (t=18.5 trace-s) while
        # it still holds in-flight work; re-adopt it two trace-seconds
        # later — a full scale-in + scale-out cycle under live load.
        if (not state["retired"] and elapsed >= 18.6 / speed
                and reps[1].engine.has_work()):
            info = router.retire_replica("unified1", reason="scale_in")
            state["retired"] = True
            state["rerouted"] = len(info["rerouted"])
        elif (state["retired"] and not state["revived"]
                and elapsed >= 20.6 / speed and not reps[1].alive
                and not reps[1].engine.has_work()):
            router.adopt_replica(reps[1])
            state["revived"] = True

    live = replay_trace(
        router, events, seed=seed, vocab_size=cfg.vocab_size,
        speed=speed, on_tick=on_tick,
    )
    assert state["retired"], "the scale-in never fired"
    assert state["rerouted"] >= 1, "nothing was in flight at the drain"
    assert state["revived"], "the re-adoption never fired"
    assert not oracle["shed"] and not live["shed"]
    assert sorted(live["results"]) == sorted(oracle["results"])
    assert live["tenant_of"] == oracle["tenant_of"]
    by_tenant: dict = {}
    for rid, toks in live["results"].items():
        ref = oracle["results"][rid]
        assert not isinstance(toks, RequestFailure)
        assert not isinstance(ref, RequestFailure)
        np.testing.assert_array_equal(toks, ref)
        by_tenant.setdefault(live["tenant_of"][rid], 0)
        by_tenant[live["tenant_of"][rid]] += len(toks)
    assert len(by_tenant) >= 3           # every canonical tenant served
    assert len(router.drain_ms) == 1
    assert len(router.recorder.events("fleet.scale_in")) == 1
    assert len(router.recorder.events("fleet.scale_out")) == 1
    econ = fleet_economics(router, replay=live)
    assert econ["measured"]["conservation"]["ok"], (
        econ["measured"]["conservation"]
    )
    # The rerouted drain legs are billed, not vanished: the elastic
    # fleet's device-seconds conserve with the reroutes inside.
    assert econ["measured"]["conservation"]["residual_s"] == pytest.approx(
        0.0, abs=1e-6
    )
