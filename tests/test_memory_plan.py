"""MemoryPlan regime arithmetic — the closed forms, not just orderings.

``tests/test_memory_tokenizer.py`` pins the planner against the OBSERVED
v5e fit/OOM boundary (calibration); this module pins the ARITHMETIC: the
exact byte formulas per attention regime (dense / remat / flash), the
fused vs unfused loss head, the ``fits()`` headroom boundary, and the
shard divisors — so a planner refactor cannot silently change a term
while staying on the right side of the calibration points.
"""

import dataclasses

import jax.numpy as jnp
import pytest

from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY
from learning_jax_sharding_tpu.utils.memory import (
    HBM_BYTES,
    device_hbm_bytes,
    memory_plan,
)

# A config where every term is hand-computable. fp32 activations AND
# params (itemsize 4 each); no GQA (num_kv_heads None → num_heads).
CFG = dataclasses.replace(
    CONFIG_TINY, dtype=jnp.float32, max_seq_len=256
)
B, S = 4, 256


def _flash(cfg):
    # Any non-None attn_fn marks the flash regime; the planner never calls it.
    return dataclasses.replace(cfg, attn_fn=lambda *a, **k: None)


class TestRegimeArithmetic:
    def test_dense_scores_closed_form(self):
        plan = memory_plan(CFG, B, S)
        # Saved softmax probabilities: B × heads × S² × itemsize.
        assert plan.detail["per_layer_scores"] == B * CFG.num_heads * S * S * 4

    def test_per_layer_residuals_closed_form(self):
        plan = memory_plan(CFG, B, S)
        nh = CFG.num_heads * CFG.head_dim
        expected = B * S * 4 * (
            4 * CFG.features        # block in, 2×LN out, attn out
            + nh + 2 * nh           # q, k, v (no GQA here)
            + 2 * CFG.hidden        # FF up pre/post-GELU
        )
        assert plan.detail["per_layer_residuals"] == expected
        assert plan.saved_activations == CFG.num_layers * (
            expected + plan.detail["per_layer_scores"]
        )

    def test_remat_and_flash_drop_scores_identically(self):
        dense = memory_plan(CFG, B, S)
        remat = memory_plan(
            dataclasses.replace(CFG, remat_attention=True), B, S
        )
        flash = memory_plan(_flash(CFG), B, S)
        assert remat.detail["per_layer_scores"] == 0
        assert flash.detail["per_layer_scores"] == 0
        # Identical except the score term (same residuals, same head).
        assert remat.saved_activations == flash.saved_activations
        assert dense.saved_activations - remat.saved_activations == (
            CFG.num_layers * dense.detail["per_layer_scores"]
        )
        assert remat.total == flash.total < dense.total

    def test_state_terms_closed_form(self):
        plan = memory_plan(CFG, B, S)   # donated, adamw (2 slots)
        p_bytes = CFG.param_count * 4
        assert plan.params == p_bytes
        assert plan.grads == p_bytes
        assert plan.optimizer_state == 2 * p_bytes
        kept = memory_plan(CFG, B, S, donate_state=False)
        assert kept.params == 2 * p_bytes
        assert kept.optimizer_state == 4 * p_bytes
        assert kept.grads == p_bytes      # grads never double
        one_slot = memory_plan(CFG, B, S, optimizer_slots=1)
        assert one_slot.optimizer_state == p_bytes


class TestLossHead:
    def test_unfused_head_closed_form(self):
        plan = memory_plan(CFG, B, S, unfused_loss=True)
        # Full (B,S,V) logits in act dtype + the fp32 softmax upcast.
        assert plan.loss_head == B * S * CFG.vocab_size * (4 + 4)

    def test_fused_head_is_chunk_over_seq(self):
        unfused = memory_plan(CFG, B, S, unfused_loss=True)
        fused = memory_plan(CFG, B, S)
        chunk = min(S, 128)
        assert fused.loss_head == pytest.approx(
            unfused.loss_head * chunk / S
        )

    def test_short_sequences_fuse_to_parity(self):
        # chunk = min(seq, 128): at S <= 128 fusing saves nothing.
        short = dataclasses.replace(CFG, max_seq_len=64)
        assert memory_plan(short, B, 64).loss_head == (
            memory_plan(short, B, 64, unfused_loss=True).loss_head
        )


class TestShardDivisors:
    def test_model_shards_divide_state_and_hidden(self):
        one = memory_plan(CFG, B, S)
        tp2 = memory_plan(CFG, B, S, n_model_shards=2)
        assert tp2.params == one.params / 2
        assert tp2.grads == one.grads / 2
        assert tp2.optimizer_state == one.optimizer_state / 2
        assert tp2.loss_head == one.loss_head / 2
        assert tp2.detail["per_layer_scores"] == (
            one.detail["per_layer_scores"] / 2
        )

    def test_data_shards_divide_activations_not_state(self):
        one = memory_plan(CFG, B, S)
        dp4 = memory_plan(CFG, B, S, n_data_shards=4)
        assert dp4.params == one.params
        assert dp4.saved_activations == one.saved_activations / 4
        assert dp4.loss_head == one.loss_head / 4
        assert dp4.detail["batch_per_shard"] == B / 4


class TestFits:
    def test_headroom_boundary(self):
        plan = memory_plan(CFG, B, S)
        # fits ⇔ total <= headroom × capacity, default headroom 0.8.
        assert plan.fits(plan.total / 0.8 * 1.001)
        assert not plan.fits(plan.total / 0.8 * 0.999)
        assert plan.fits(plan.total, headroom=1.0)
        assert not plan.fits(plan.total * 0.999, headroom=1.0)

    def test_total_is_the_sum_of_parts(self):
        plan = memory_plan(CFG, B, S)
        assert plan.total == (
            plan.params + plan.grads + plan.optimizer_state
            + plan.saved_activations + plan.loss_head
        )


class TestDeviceHBM:
    def test_known_and_unknown_kinds(self):
        class Dev:
            def __init__(self, kind):
                self.device_kind = kind

        assert device_hbm_bytes(Dev("TPU v5 lite")) == HBM_BYTES["TPU v5 lite"]
        assert device_hbm_bytes(Dev("cpu")) is None
        # Default argument path: the emulated CPU devices here are unknown.
        assert device_hbm_bytes() is None
