"""Multi-host bootstrap + per-host data feeding, on the single-process path.

A real pod can't run in CI; what CAN be verified here is the contract the
multi-host path shares with single-process runs: rank helpers, the host
batch-slice arithmetic, and that per-process-local assembly produces arrays
identical (values AND shardings) to a plain global ``device_put`` when there
is one process — which is exactly the invariant that makes the same training
code run unchanged on a pod.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.parallel import multihost


class TestRankHelpers:
    def test_single_process_ranks(self):
        assert multihost.process_count() == 1
        assert multihost.process_index() == 0
        assert multihost.is_primary()

    def test_initialize_is_idempotent_and_single_process_safe(self):
        # No cluster metadata here: both calls must no-op without raising,
        # and the process must still see its devices afterwards.
        multihost.initialize()
        multihost.initialize()
        assert multihost.process_count() == 1
        assert len(jax.devices()) == 8

    def test_initialize_propagates_real_cluster_errors(self):
        with pytest.raises((ValueError, RuntimeError)):
            # A genuinely multi-process request with an unreachable
            # coordinator must raise, not be silently swallowed — and a prior
            # swallowed single-process no-op must not cache it away.
            multihost.initialize(
                coordinator_address="invalid-host:1", num_processes=2,
                process_id=0,
            )


class TestLocalBatchSlice:
    def test_single_process_owns_everything(self, mesh24):
        assert multihost.local_batch_slice(16) == slice(0, 16)

    def test_four_host_slices(self, mesh24, monkeypatch):
        # Simulate a 4-host cluster: host i owns contiguous rows
        # [i*B/4, (i+1)*B/4).
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        for i in range(4):
            monkeypatch.setattr(jax, "process_index", lambda i=i: i)
            assert multihost.local_batch_slice(16) == slice(4 * i, 4 * i + 4)

    def test_divisibility_error(self, mesh24, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        with pytest.raises(ValueError, match="not divisible"):
            multihost.local_batch_slice(17)


class TestRegistryMerge:
    SNAPS = [
        {"engine_tokens_total": 10.0, "engine_queue_depth": 2.0,
         "engine_queue_depth__high_water": 4.0},
        {"engine_tokens_total": 6.0, "engine_queue_depth": 1.0,
         "engine_queue_depth__high_water": 7.0},
    ]

    def test_unlabeled_merge_sums_and_maxes(self):
        m = multihost.merge_registry_snapshots(self.SNAPS)
        assert m["engine_tokens_total"] == 16.0
        assert m["engine_queue_depth"] == 3.0
        assert m["engine_queue_depth__high_water"] == 7.0

    def test_labels_preserve_replica_identity(self):
        """The round-11 satellite: a labeled merge keeps the unlabeled
        fleet sums BIT-COMPATIBLE while adding per-source series a
        dashboard can tell replicas apart by."""
        plain = multihost.merge_registry_snapshots(self.SNAPS)
        labeled = multihost.merge_registry_snapshots(
            self.SNAPS, labels=["r0", "r1"]
        )
        assert {k: labeled[k] for k in plain} == plain
        assert labeled['engine_tokens_total{replica="r0"}'] == 10.0
        assert labeled['engine_tokens_total{replica="r1"}'] == 6.0


class TestHostLocalBatch:
    def test_matches_global_device_put(self, mesh24, rng):
        batch = {
            "inputs": rng.integers(0, 100, size=(16, 8)).astype(np.int32),
            "targets": rng.integers(0, 100, size=(16, 8)).astype(np.int32),
        }
        local = {k: v[multihost.local_batch_slice(16)]
                 for k, v in batch.items()}
        got = multihost.host_local_batch(local, mesh24, P("x"))
        want_sh = NamedSharding(mesh24, P("x"))
        for k in batch:
            assert got[k].sharding == want_sh
            np.testing.assert_array_equal(np.asarray(got[k]), batch[k])

    def test_spec_as_sequence(self, mesh24, rng):
        x = rng.standard_normal((8, 4)).astype(np.float32)
        got = multihost.host_local_batch(x, mesh24, ("x", "y"))
        assert got.sharding.spec == P("x", "y")
        np.testing.assert_allclose(np.asarray(got), x)

    def test_sharded_batches_iterator(self, mesh24, rng):
        data = [rng.standard_normal((8, 4)).astype(np.float32)
                for _ in range(3)]
        out = list(multihost.sharded_batches(iter(data), mesh24, P("x")))
        assert len(out) == 3
        for want, got in zip(data, out):
            assert isinstance(got, jax.Array)
            np.testing.assert_allclose(np.asarray(got), want)
