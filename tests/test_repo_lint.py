"""The repo-wide AST lint as a tier-1 gate (shardcheck level 3 in CI).

``analysis.source_lint`` over the source surfaces under the checked-in
``analysis/baseline.json`` budget: NEW findings fail here, pre-existing
ones ride their reasoned suppressions. This is the generalization of
``test_timing_audit``'s cases/-only tripwire to the whole repo — that
test stays as the stricter cases/ pin (no baseline there), this one
keeps the framework/scripts surfaces from growing new footguns.

Pure source analysis: no devices, no compiles — milliseconds, so it can
sit in tier-1 unconditionally.
"""

import pathlib

from learning_jax_sharding_tpu.analysis import (
    BASELINE_PATH,
    load_baseline,
    run_ast_pass,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_repo_source_lint_clean_under_baseline():
    findings = run_ast_pass(REPO)
    assert not findings, (
        "new static-lint findings (fix them, or — for a reviewed false "
        "positive — add a reasoned entry to analysis/baseline.json):\n"
        + "\n".join(str(f) for f in findings)
    )


def test_baseline_entries_carry_reasons():
    import json

    doc = json.loads(BASELINE_PATH.read_text())
    for s in doc["suppressions"]:
        assert s.get("reason"), f"baseline entry without a reason: {s}"


def test_baseline_has_no_dead_budget():
    """Every suppression must still match at least one finding — a stale
    entry means the debt was paid and the budget should be deleted (or
    tightened), not silently carried."""
    from collections import Counter

    from learning_jax_sharding_tpu.analysis import lint_tree

    live = Counter(
        (f.where.rsplit(":", 1)[0], f.rule) for f in lint_tree(REPO)
    )
    budget = load_baseline(BASELINE_PATH)
    stale = {k: v for k, v in budget.items() if live.get(k, 0) == 0}
    assert not stale, f"baseline entries with no remaining findings: {stale}"
    loose = {
        k: (live[k], v) for k, v in budget.items() if 0 < live[k] < v
    }
    assert not loose, (
        f"baseline budgets looser than reality (tighten counts): {loose}"
    )


def test_unbounded_host_buffer_rule_is_live():
    """The round-18 rule fires on its target pattern. The repo-wide
    clean gate above passes VACUOUSLY if a rule is dropped from the
    visitor — this pins that ``unbounded-host-buffer`` is actually
    wired in (it has zero live repo hits, so no baseline entry keeps
    it honest the way the suppressions audit does for the others)."""
    import textwrap

    from learning_jax_sharding_tpu.analysis.source_lint import lint_source

    src = textwrap.dedent(
        """
        import jax.numpy as jnp

        class ContinuousEngine:
            def _admit(self, req):
                for tok in req.tokens:
                    self._trace.append(jnp.asarray(tok))
        """
    )
    assert [f.rule for f in lint_source("demo.py", src)] == [
        "unbounded-host-buffer"
    ]


def test_unguarded_scale_decision_rule_is_live():
    """The round-23 rule fires on its target pattern: a fleet scale
    action called from inside an ``*Autoscaler`` class outside a
    ``with ..._decision(...)`` frame. It carries ZERO suppressions by
    design (the autoscaler's decision log is complete by construction),
    so like ``unbounded-host-buffer`` the repo-wide clean gate passes
    vacuously if the rule is unwired — this pins that it is live, that
    the decision frame actually guards, and that the same calls OUTSIDE
    an autoscaler class (the router's own methods, test drivers) stay
    out of scope."""
    import textwrap

    from learning_jax_sharding_tpu.analysis.source_lint import lint_source

    unframed = textwrap.dedent(
        """
        class Autoscaler:
            def _shrink(self, victim):
                info = self.router.retire_replica(victim)
                return info

            def panic(self):
                self.router.kill_replica("unified0")
        """
    )
    found = lint_source("demo.py", unframed)
    assert [f.rule for f in found] == ["unguarded-scale-decision"] * 2
    lines = sorted(int(f.where.rsplit(":", 1)[1]) for f in found)
    assert lines == [4, 8]

    framed = textwrap.dedent(
        """
        class SpotAutoscaler:
            def _shrink(self, victim):
                with self._decision("shrink", replica=victim) as entry:
                    entry["info"] = self.router.retire_replica(victim)

            def _grow(self, rep):
                with self._decision("grow"):
                    self.router.adopt_replica(rep)
        """
    )
    assert not lint_source("demo.py", framed)

    out_of_scope = textwrap.dedent(
        """
        class FleetRouter:
            def _tick_preemptions(self):
                self.retire_replica("unified1", force=True)

        def drive(router):
            router.preempt_replica("unified0", grace_steps=2)
        """
    )
    assert not lint_source("demo.py", out_of_scope)


def test_axis_literal_rule_fires_in_scoped_dirs():
    """The round-21 rule on its target pattern: a bare mesh-axis name
    in a fleet/ (or analysis/) source file — one finding per literal,
    line-attributed."""
    import textwrap

    from learning_jax_sharding_tpu.analysis.source_lint import lint_source

    src = textwrap.dedent(
        """
        def carve(shape=(1, 2), axis_names=("data", "model")):
            return axis_names

        SPEC = {"pipe": 4}
        """
    )
    found = lint_source("learning_jax_sharding_tpu/fleet/demo.py", src)
    assert [f.rule for f in found] == ["axis-literal"] * 3
    lines = sorted(int(f.where.rsplit(":", 1)[1]) for f in found)
    assert lines == [2, 2, 5]


def test_axis_literal_rule_is_scoped_and_exact():
    """No findings outside fleet//analysis/ (the model and parallel
    layers legitimately DEFINE the names), and equality — not substring
    — matching keeps docstrings, prose, and near-miss strings out."""
    import textwrap

    from learning_jax_sharding_tpu.analysis.source_lint import lint_source

    axisy = 'AXES = ("data", "model", "pipe")\n'
    assert not lint_source(
        "learning_jax_sharding_tpu/parallel/demo.py", axisy
    )
    assert not lint_source("scripts/demo.py", axisy)

    benign = textwrap.dedent(
        '''
        def plan():
            """Shards the batch over the "data" axis."""
            return ("dataset", "modeling", "pipeline", "DATA")
        '''
    )
    assert not lint_source(
        "learning_jax_sharding_tpu/fleet/demo.py", benign
    )


def test_jaxpr_budgets_reference_live_entry_points_and_rules():
    """The symmetric audit for the OTHER budget section (round 13):
    ``jaxpr_budgets`` keys on (entry-point name → rule → count), and a
    renamed entry point or a retired rule would leave its ceiling
    silently dead — the exact staleness class the suppressions audit
    above catches. Trace-free: building the entry-point list is lazy
    (no compiles), and the rule ids are pinned against the lint module's
    published set."""
    import json

    from learning_jax_sharding_tpu.analysis.entrypoints import (
        build_entry_programs,
    )

    known_rules = {"dead-eqn", "f32-promotion", "f32-dot-in-bf16-graph"}
    programs = {p.name: p for p in build_entry_programs()}
    doc = json.loads(BASELINE_PATH.read_text())
    budgets = doc.get("jaxpr_budgets", {})
    for name, rules in budgets.items():
        if name.startswith("_"):
            continue  # the section's _comment
        assert name in programs, (
            f"jaxpr_budgets entry {name!r} matches no entry point — "
            "prune it or fix the name"
        )
        assert programs[name].jaxpr is not None, (
            f"jaxpr_budgets entry {name!r} budgets an entry point that "
            "runs no jaxpr pass (audit=False) — the ceiling is dead"
        )
        for rule, count in rules.items():
            assert rule in known_rules, (
                f"jaxpr_budgets[{name!r}] budgets unknown rule {rule!r}"
            )
            assert int(count) > 0, (
                f"jaxpr_budgets[{name!r}][{rule!r}] is {count} — a zero "
                "budget is the default; delete the entry"
            )
