"""KV-cached decoding: incremental == full forward, sharded generation.

The decisive oracle: teacher-forcing tokens one at a time through the
decode-mode model must reproduce the training-mode (full-sequence) logits at
every position — cache writes, masking, and position handling all have to be
right for that to hold.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, activate
from learning_jax_sharding_tpu.training.pipeline import sharded_train_state


@pytest.fixture(scope="module")
def trained(mesh22):
    """Params born sharded on the (data, model) mesh."""
    cfg = CONFIG_TINY
    rng = np.random.default_rng(0)
    x = put(
        rng.integers(0, cfg.vocab_size, size=(4, 16)).astype(np.int32),
        mesh_sharding(mesh22, "data", None),
    )
    state, _ = sharded_train_state(
        Transformer(cfg), optax.adamw(3e-4), x, {"params": jax.random.key(0)},
        mesh22, RULES_DP_TP,
    )
    return cfg, state.params


def _tokens(cfg, b=4, s=16, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, size=(b, s)), jnp.int32)


class TestIncrementalDecode:
    def test_teacher_forcing_matches_full_forward(self, mesh22, trained):
        cfg, params = trained
        tokens = _tokens(cfg)
        model_full = Transformer(cfg)
        model_dec = Transformer(dataclasses.replace(cfg, decode=True))
        with activate(mesh22, RULES_DP_TP):
            want = jax.jit(
                lambda p, t: model_full.apply({"params": p}, t)
            )(params, tokens).astype(jnp.float32)

            @jax.jit
            def one_step(params, cache, tok):
                variables = {"params": params}
                if cache is not None:
                    variables["cache"] = cache
                logits, mut = model_dec.apply(
                    variables, tok, mutable=("cache",)
                )
                return logits.astype(jnp.float32), mut["cache"]

            cache = None
            got = []
            for i in range(tokens.shape[1]):
                logits, cache = one_step(params, cache, tokens[:, i : i + 1])
                got.append(logits[:, 0])
        got = jnp.stack(got, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_prefill_then_steps_matches_full_forward(self, mesh22, trained):
        """Mixed chunk sizes: prompt prefill in one call, then single steps."""
        cfg, params = trained
        tokens = _tokens(cfg)
        split = 10
        model_full = Transformer(cfg)
        model_dec = Transformer(dataclasses.replace(cfg, decode=True))
        with activate(mesh22, RULES_DP_TP):
            want = jax.jit(
                lambda p, t: model_full.apply({"params": p}, t)
            )(params, tokens).astype(jnp.float32)
            logits_pre, mut = model_dec.apply(
                {"params": params}, tokens[:, :split], mutable=("cache",)
            )
            got = [logits_pre.astype(jnp.float32)]
            cache = mut["cache"]
            for i in range(split, tokens.shape[1]):
                logits, mut = model_dec.apply(
                    {"params": params, "cache": cache},
                    tokens[:, i : i + 1],
                    mutable=("cache",),
                )
                cache = mut["cache"]
                got.append(logits.astype(jnp.float32))
        got = jnp.concatenate(got, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )


class TestGenerate:
    def test_greedy_matches_manual_argmax_rollout(self, mesh22, trained):
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=6, temperature=0.0
        )
        out = gen(params, prompt)
        assert out.shape == (2, 10)
        np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
        # Manual rollout with the full-sequence model must agree (greedy).
        model = Transformer(cfg)
        cur = np.asarray(prompt)
        with activate(mesh22, RULES_DP_TP):
            for _ in range(6):
                logits = model.apply({"params": params}, jnp.asarray(cur))
                nxt = np.asarray(
                    jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
                ).astype(np.int32)
                cur = np.concatenate([cur, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), cur)

    def test_greedy_deterministic(self, mesh22, trained):
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4, seed=5)
        gen = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=4)
        np.testing.assert_array_equal(
            np.asarray(gen(params, prompt)), np.asarray(gen(params, prompt))
        )

    def test_temperature_sampling_varies_with_rng(self, mesh22, trained):
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4, seed=5)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=8, temperature=5.0
        )
        a = gen(params, prompt, jax.random.key(1))
        b = gen(params, prompt, jax.random.key(2))
        assert (np.asarray(a) != np.asarray(b)).any()

    def test_repetition_penalty_greedy_never_repeats(self, mesh22, trained):
        """With an overwhelming penalty, greedy decode must avoid every
        token already in the row — prompt included — so all tokens of each
        output row are distinct (vocab 256 >> 4+10 tokens)."""
        cfg, params = trained
        prompt = jnp.asarray([[1, 2, 3, 4], [9, 8, 7, 6]], jnp.int32)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=10,
            repetition_penalty=1e9,
        )
        out = np.asarray(gen(params, prompt))
        for row in out:
            assert len(set(row.tolist())) == len(row), row

    def test_repetition_penalty_one_is_noop(self, mesh22, trained):
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4, seed=5)
        plain = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=6)
        pen1 = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=6, repetition_penalty=1.0
        )
        np.testing.assert_array_equal(
            np.asarray(plain(params, prompt)), np.asarray(pen1(params, prompt))
        )

    def test_min_p_sampling_runs(self, mesh22, trained):
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4, seed=5)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=6,
            temperature=1.0, min_p=0.2,
        )
        out = np.asarray(gen(params, prompt, jax.random.key(3)))
        assert out.shape == (2, 10)
        assert ((0 <= out) & (out < cfg.vocab_size)).all()

    def test_eos_path_matches_scan_path_when_eos_never_fires(
        self, mesh22, trained
    ):
        """The while_loop (eos) and scan (no eos) decoders must produce the
        same greedy tokens when the EOS token never appears."""
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4, seed=5)
        plain = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=8)
        out_plain = np.asarray(plain(params, prompt))
        unused = [
            t for t in range(cfg.vocab_size)
            if t not in set(out_plain[:, 4:].reshape(-1).tolist())
        ][0]
        with_eos = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=8, eos_id=unused
        )
        np.testing.assert_array_equal(
            np.asarray(with_eos(params, prompt)), out_plain
        )

    def test_eos_freezes_rows_and_pads(self, mesh22, trained):
        """Set EOS = the first greedy token of row 0: that row must be all
        EOS after the prompt while other rows keep decoding normally until
        their own (possibly absent) EOS."""
        cfg, params = trained
        prompt = _tokens(cfg, b=4, s=4, seed=7)
        plain = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=8)
        out_plain = np.asarray(plain(params, prompt))
        eos = int(out_plain[0, 4])  # row 0 finishes immediately
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=8, eos_id=eos
        )
        out = np.asarray(gen(params, prompt))
        np.testing.assert_array_equal(out[0, 4:], np.full(8, eos))
        for r in range(4):
            gen_r = out[r, 4:]
            hits = np.nonzero(gen_r == eos)[0]
            if hits.size:  # everything after the first EOS is EOS padding
                np.testing.assert_array_equal(
                    gen_r[hits[0]:], np.full(8 - hits[0], eos)
                )
            # before the first EOS, tokens match the plain decoder
            end = hits[0] if hits.size else 8
            np.testing.assert_array_equal(gen_r[:end], out_plain[r, 4:4 + end])

    @pytest.mark.parametrize("chunk", [3, 5, 10, 64])
    def test_chunked_prefill_matches_whole_prompt(self, mesh22, trained, chunk):
        """Chunked prefill is bit-identical to one-apply prefill: dividing,
        non-dividing, and larger-than-prompt chunk sizes all hit the same
        cache contents (greedy rollout is the observable)."""
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=10, seed=6)
        whole = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=6)
        chunked = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=6,
            prefill_chunk_size=chunk,
        )
        np.testing.assert_array_equal(
            np.asarray(chunked(params, prompt)), np.asarray(whole(params, prompt))
        )

    def test_length_guard(self, mesh22, trained):
        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=60)
        gen = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=10)
        with pytest.raises(ValueError, match="max_seq_len"):
            gen(params, prompt)

    def test_inference_dtype_bf16(self, mesh22, trained):
        """Params cast eagerly to bf16: valid tokens, same greedy path shape;
        pre-cast params give identical results (the cast is a no-op then)."""
        import jax.numpy as jnp

        cfg, params = trained
        prompt = _tokens(cfg, b=2, s=4, seed=5)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=4,
            inference_dtype=jnp.bfloat16,
        )
        out = np.asarray(gen(params, prompt))
        assert out.shape == (2, 8)
        assert (out >= 0).all() and (out < cfg.vocab_size).all()
        p16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params,
        )
        np.testing.assert_array_equal(out, np.asarray(gen(p16, prompt)))


class TestVocabLimit:
    """vocab_limit masks the padded tail of the model vocab so undecodable
    ids can never be emitted (model vocabs are lane-padded past tokenizer
    vocabs; BPETokenizer.decode raises on out-of-range ids)."""

    def test_filter(self):
        from learning_jax_sharding_tpu.models.generate import vocab_limit_filter

        logits = jnp.zeros((2, 8)).at[:, 6].set(9.0)
        out = vocab_limit_filter(logits, 5)
        assert np.all(np.isneginf(np.asarray(out)[:, 5:]))
        np.testing.assert_array_equal(np.asarray(out)[:, :5], 0.0)
        with pytest.raises(ValueError, match="vocab_limit"):
            vocab_limit_filter(logits, 0)

    @pytest.mark.parametrize("temperature", [0.0, 0.9])
    def test_generate_never_emits_past_limit(self, mesh22, trained, temperature):
        cfg, params = trained
        limit = 7  # tiny: unconstrained argmax/sampling would exceed it
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=12,
            temperature=temperature, vocab_limit=limit,
        )
        out = np.asarray(gen(params, _tokens(cfg, b=2, s=8), jax.random.key(5)))
        assert out[:, 8:].max() < limit

    def test_beam_respects_limit(self, mesh22, trained):
        from learning_jax_sharding_tpu.models.beam import make_beam_search_fn

        cfg, params = trained
        limit = 7
        beam = make_beam_search_fn(
            cfg, mesh22, RULES_DP_TP, beam_size=2, max_new_tokens=8,
            vocab_limit=limit,
        )
        tokens, _ = beam(params, _tokens(cfg, b=2, s=6))
        assert np.asarray(tokens)[:, 6:].max() < limit

    def test_speculative_respects_limit(self, mesh22, trained):
        from learning_jax_sharding_tpu.models.speculative import (
            make_speculative_generate_fn,
        )

        cfg, params = trained
        limit = 7
        gen = make_speculative_generate_fn(
            cfg, cfg, mesh22, RULES_DP_TP, max_new_tokens=8, num_draft=2,
            temperature=0.8, vocab_limit=limit,
        )
        out = np.asarray(gen(params, params, _tokens(cfg, b=2, s=6), jax.random.key(2)))
        assert out[:, 6:].max() < limit
