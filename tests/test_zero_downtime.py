"""Fault injection + recovery policies (robustness/, round 10).

Named to sort LAST in the suite: the end-to-end fault matrix builds
several engines and training runs, and the tier-1 window should spend
its budget on the faster oracles first.

Three layers:

* pure units — the chaos injector's determinism, the degradation
  ladder's hysteresis, config validation (milliseconds);
* engine policy integration — deadlines, shedding, quarantine
  probation, close() drain, the degraded-spec program bookkeeping
  (one tiny engine each);
* THE FAULT MATRIX — ``robustness.matrix.run_matrix`` drives every
  (fault × policy) cell end to end; every cell must recover, with
  survivors bit-identical to the fault-free run (the acceptance bar;
  ``scripts/chaos_matrix.py`` is the CLI form of the same check).
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.serving import (
    AdmissionError,
    ContinuousEngine,
    RequestFailure,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.robustness import (
    ChaosInjector,
    DegradationLadder,
    Fault,
    ResilienceConfig,
    chaos_hook,
)
from learning_jax_sharding_tpu.telemetry.flight_recorder import FlightRecorder


# --- pure units -----------------------------------------------------------


class TestChaosInjector:
    def test_no_injector_is_identity(self):
        assert chaos_hook("any.site", value=41) == 41
        assert chaos_hook("any.site") is None

    def test_fires_at_exact_invocations(self):
        f = Fault("s", "mutate", at=1, count=2, mutate=lambda x: x + 100)
        with ChaosInjector(f, recorder=FlightRecorder()) as inj:
            got = [chaos_hook("s", value=i) for i in range(5)]
        assert got == [0, 101, 102, 3, 4]
        assert f.seen == 5 and f.fired == 2
        assert [r["invocation"] for r in inj.injections] == [1, 2]

    def test_rid_matcher_gates_eligibility(self):
        f = Fault("s", "mutate", at=0, count=-1, rid=7, mutate=lambda x: -1)
        with ChaosInjector(f, recorder=FlightRecorder()):
            assert chaos_hook("s", value=1, rids=[1, 2]) == 1
            assert chaos_hook("s", value=1, rids=[7]) == -1
        assert f.seen == 1   # non-matching dispatches don't consume the index

    def test_sites_are_independent_and_nesting_restores(self):
        rec = FlightRecorder()
        outer = ChaosInjector(
            Fault("a", "mutate", mutate=lambda x: "outer"), recorder=rec,
        )
        inner = ChaosInjector(
            Fault("a", "mutate", mutate=lambda x: "inner"), recorder=rec,
        )
        with outer:
            with inner:
                assert chaos_hook("a", value=0) == "inner"
                assert chaos_hook("b", value=0) == 0   # other site untouched
            assert chaos_hook("a", value=0) == "outer"
        assert chaos_hook("a", value=0) == 0

    def test_injections_land_in_the_flight_recorder(self):
        rec = FlightRecorder()
        with ChaosInjector(Fault("s", "slow", delay_s=0.0), recorder=rec):
            chaos_hook("s")
        (ev,) = rec.events("chaos.inject")
        assert ev["site"] == "s" and ev["fault"] == "slow"

    def test_validation(self):
        with pytest.raises(ValueError, match="mutate"):
            Fault("s", "mutate")
        with pytest.raises(ValueError, match="at"):
            Fault("s", "slow", at=-1)
        with pytest.raises(ValueError, match="unknown fault kind"):
            with ChaosInjector(Fault("s", "nope"), recorder=FlightRecorder()):
                chaos_hook("s")


class TestDegradationLadder:
    def test_escalates_after_patience(self):
        lad = DegradationLadder(patience=3)
        assert [lad.update(2.0) for _ in range(2)] == [0, 0]
        assert lad.update(2.0) == 1
        assert lad.name == "no_speculation"

    def test_deescalates_and_clamps(self):
        lad = DegradationLadder(patience=1, max_level=2)
        for _ in range(5):
            lad.update(9.0)
        assert lad.level == 2            # clamped at max_level
        lad.update(0.1)
        assert lad.level == 1
        lad.update(0.1)
        assert lad.level == 0
        lad.update(0.1)
        assert lad.level == 0            # floor

    def test_hysteresis_band_holds_and_resets_streaks(self):
        lad = DegradationLadder(trip=1.0, clear=0.5, patience=2)
        lad.update(2.0)                  # hot streak 1
        lad.update(0.7)                  # inside the band: streaks reset
        assert lad.update(2.0) == 0      # hot streak restarts at 1
        assert lad.update(2.0) == 1

    def test_transitions_are_recorded(self):
        lad = DegradationLadder(patience=1)
        lad.update(5.0)
        assert lad.transitions == [
            {"to": 1, "name": "no_speculation", "burn": 5.0}
        ]

    def test_validation(self):
        with pytest.raises(ValueError, match="clear < trip"):
            DegradationLadder(trip=0.5, clear=0.5)
        with pytest.raises(ValueError, match="patience"):
            DegradationLadder(patience=0)
        with pytest.raises(ValueError, match="max_level"):
            DegradationLadder(max_level=4)


class TestResilienceConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="max_skips"):
            ResilienceConfig(max_skips=-1)
        with pytest.raises(ValueError, match="spike_factor"):
            ResilienceConfig(spike_factor=1.0)
        with pytest.raises(ValueError, match="max_rollbacks"):
            ResilienceConfig(max_rollbacks=-2)


# --- engine policy integration -------------------------------------------


@pytest.fixture(scope="module")
def served(mesh22):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    rng = np.random.default_rng(11)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), np.zeros((2, 8), np.int32)
        )["params"]
    )
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in (3, 9, 5, 4)
    ]
    return cfg, params, prompts


def _drain(eng, params):
    out = {}
    while eng.has_work():
        eng.step(params)
        out.update(eng.pop_finished())
    out.update(eng.pop_finished())
    return out


class TestEnginePolicies:
    def test_close_drains_in_flight_to_terminal_status(self, served, mesh22):
        """The satellite bugfix: close() on a BUSY engine fails every
        in-flight/queued request with status "shutdown" (partial tokens
        attached for admitted ones) instead of raising — a frontend
        polling pop_finished always terminates. Idempotent; engine
        reusable after."""
        cfg, params, prompts = served
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4,
        )
        for p in prompts[:3]:
            eng.add_request(p)
        eng.step(params)              # two admitted + mid-flight, one queued
        eng.close()
        assert not eng.has_work()
        fin = eng.pop_finished()
        assert set(fin) == {0, 1, 2}
        for rid, r in fin.items():
            assert isinstance(r, RequestFailure) and r.status == "shutdown"
        # rid 0/1 were admitted: their partial output carries the prompt;
        # rid 2 never left the queue, so it has no tokens at all.
        assert fin[0].tokens is not None and fin[0].tokens.size >= 1
        assert fin[2].tokens is None
        eng.close()                   # idempotent: no work, no raise
        out = eng.serve(params, [prompts[0]])   # reusable; cache re-created
        assert eng.cache_creations == 2
        assert len(out[0]) == len(prompts[0]) + 4

    def test_deadline_ttl_eviction_and_error_status(self, served, mesh22):
        """Per-request deadlines: an expired request is failed with a
        terminal "deadline" status through pop_finished — queued or
        in-flight — while roomy-deadline requests complete untouched."""
        cfg, params, prompts = served
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4,
        )
        eng.add_request(prompts[0])
        ref = _drain(eng, params)[0]
        eng.add_request(prompts[0], deadline_s=60.0)
        eng.add_request(prompts[1], deadline_s=1e-6)
        out = _drain(eng, params)
        assert isinstance(out[2], RequestFailure)
        assert out[2].status == "deadline"
        np.testing.assert_array_equal(out[1], ref)
        assert eng.registry.counter(
            "engine_deadline_evictions_total"
        ).value == 1
        lat = eng.latency_stats()
        assert lat["deadline_miss_rate"] > 0

    def test_engine_level_deadline_applies_to_all(self, served, mesh22):
        cfg, params, prompts = served
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4, deadline_s=1e-6,
        )
        eng.add_request(prompts[0])
        eng.step(params)
        out = eng.pop_finished()
        assert out[0].status == "deadline"
        assert not eng.has_work()

    def test_bounded_queue_sheds_with_admission_error(self, served, mesh22):
        cfg, params, prompts = served
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4, max_queue=2,
        )
        eng.add_request(prompts[0])
        eng.add_request(prompts[1])
        with pytest.raises(AdmissionError, match="queue full"):
            eng.add_request(prompts[2])
        assert eng.registry.counter("engine_shed_total").value == 1
        out = _drain(eng, params)
        assert set(out) == {0, 1}
        assert eng.latency_stats()["shed_rate"] > 0

    def test_quarantine_strikes_and_probation(self, served, mesh22):
        """A sticky per-request fault: the poison request is failed at
        max_dispatch_strikes, its batchmates are requeued and recomputed
        (solo probation) to bit-identical outputs."""
        cfg, params, prompts = served
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4,
        )
        clean = {}
        for p in prompts:
            clean[eng.add_request(p)] = None
        clean = _drain(eng, params)
        rec = FlightRecorder()
        for p in prompts:
            eng.add_request(p)   # rids 4..7 now
        with ChaosInjector(
            Fault("engine.dispatch", "hang", rid=5, count=-1), recorder=rec,
        ):
            out = _drain(eng, params)
        assert out[5].status == "poisoned"
        for rid, want in ((4, 0), (6, 2), (7, 3)):
            np.testing.assert_array_equal(out[rid], clean[want])
        assert eng.registry.counter("engine_quarantined_total").value == 1
        assert rec.events("chaos.inject")
        # The engine logs its side of the incident to ITS recorder (the
        # process ring by default) — injection and recovery both land.
        assert eng.recorder.events("engine.quarantine")
        assert eng.recorder.events("engine.dispatch_fault")

    def test_drain_requests_rerouted_visible_and_recomputes(
        self, served, mesh22
    ):
        """The round-11 failover drain: queued AND in-flight requests
        retire with a VISIBLE "rerouted" terminal status (counter +
        latency_stats field — never disguised as fresh admissions), and
        the returned records re-admit (original arrival clock kept) to
        BIT-IDENTICAL outputs."""
        cfg, params, prompts = served
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4,
        )
        for p in prompts[:3]:
            eng.add_request(p)
        ref = _drain(eng, params)
        for p in prompts[:3]:
            eng.add_request(p)
        eng.step(params)          # two admitted mid-flight, one queued
        recs = eng.drain_requests()
        assert [r["rid"] for r in recs] == [3, 4, 5]
        fin = eng.pop_finished()
        for rid, r in fin.items():
            assert isinstance(r, RequestFailure)
            assert r.status == "rerouted"
        assert fin[3].tokens is not None    # admitted: partial kept
        assert fin[5].tokens is None        # never left the queue
        assert eng.registry.counter("engine_rerouted_total").value == 3
        # Re-admission (what the fleet router does on a survivor):
        # same rids, original arrival stamps — outputs bit-identical.
        for r in recs:
            eng.add_request(
                r["prompt"], rid=r["rid"], arrival_t=r["arrival_t"],
            )
        out = _drain(eng, params)
        for rid, want in ((3, 0), (4, 1), (5, 2)):
            np.testing.assert_array_equal(out[rid], ref[want])
        lat = eng.latency_stats()
        assert lat["rerouted"] == 3
        assert lat["failed"] >= 3

    def test_validation(self, served, mesh22):
        cfg, *_ = served
        kw = dict(batch_size=2, max_new_tokens=4)
        with pytest.raises(ValueError, match="deadline_s"):
            ContinuousEngine(cfg, mesh22, RULES_DP_TP, **kw, deadline_s=0)
        with pytest.raises(ValueError, match="max_queue"):
            ContinuousEngine(cfg, mesh22, RULES_DP_TP, **kw, max_queue=0)
        with pytest.raises(ValueError, match="max_dispatch_strikes"):
            ContinuousEngine(
                cfg, mesh22, RULES_DP_TP, **kw, max_dispatch_strikes=0
            )
        with pytest.raises(ValueError, match="slo"):
            ContinuousEngine(
                cfg, mesh22, RULES_DP_TP, **kw,
                degradation=DegradationLadder(),
            )
        eng = ContinuousEngine(cfg, mesh22, RULES_DP_TP, **kw)
        with pytest.raises(ValueError, match="deadline_s"):
            eng.add_request(np.ones(3, np.int32), deadline_s=-1.0)


class TestDegradedSpeculation:
    def test_spec_disable_keeps_outputs_and_maps_contracts(self, served, mesh22):
        """Degradation level 1 on a speculative engine: the plain
        decode_block takes over — greedy outputs stay bit-identical
        (the verifier defined them all along), the program lands in
        compile_counts/_dispatched_programs, and contract_name maps it
        to the PLAIN decode_step golden (no new steady-state program —
        the shardcheck satellite)."""
        from learning_jax_sharding_tpu.telemetry.slo import (
            SLOMonitor,
            SLOTarget,
        )

        cfg, params, prompts = served
        dcfg = dataclasses.replace(cfg, num_layers=1)
        kw = dict(
            batch_size=2, max_new_tokens=4, refill_chunk=4,
            draft_config=dcfg, num_draft=2,
        )
        d_params = nn.meta.unbox(
            jax.jit(
                lambda r, t: Transformer(dcfg).init({"params": r}, t)
            )(jax.random.key(5), np.zeros((2, 8), np.int32))["params"]
        )
        ref_eng = ContinuousEngine(cfg, mesh22, RULES_DP_TP, **kw)
        ref = ref_eng.serve(params, prompts, draft_params=d_params)
        # An unreachable SLO escalates the ladder past level 1 while the
        # queue is mid-flight: speculation turns off for the decode tail.
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, **kw,
            slo=SLOMonitor([SLOTarget("ttft", 1e-9, objective=0.5)]),
            degradation=DegradationLadder(patience=1),
        )
        for p in prompts + prompts:   # two waves so degradation bites wave 2
            eng.add_request(p)
        # drive manually — the speculative step needs draft params
        out = {}
        while eng.has_work():
            eng.step(params, d_params)
            out.update(eng.pop_finished())
        out.update(eng.pop_finished())
        assert eng.degradation_level >= 1
        assert eng._spec_disabled
        for i in range(len(prompts)):
            np.testing.assert_array_equal(out[i], ref[i])
            np.testing.assert_array_equal(out[i + len(prompts)], ref[i])
        counts = eng.compile_counts()
        assert counts.get("decode_block") == 1    # the degraded program
        progs = [name for name, *_ in eng._dispatched_programs()]
        assert "decode_block" in progs
        assert eng.contract_name("decode_block") == "decode_step"
        assert eng.contract_name("decode_block_spec") == "spec_decode_step"
        assert eng.contract_name("refill_step") == "spec_prefill"


# --- training policy integration -----------------------------------------


class TestSkipGuard:
    def test_guarded_step_refuses_nonfinite_update(self, mesh22):
        """The on-device guard: a poisoned batch (NaN loss + NaN grads
        inside the jitted step) leaves params and optimizer state
        BIT-IDENTICAL; a clean batch updates exactly like the unguarded
        grad-norm step."""
        import optax

        from learning_jax_sharding_tpu.models.transformer import (
            next_token_loss,
        )
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.training.pipeline import (
            make_train_step,
            sharded_train_state,
        )

        cfg = CONFIG_TINY
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(
            0, cfg.vocab_size, size=(8, 17)
        ).astype(np.int32)
        sh = mesh_sharding(mesh22, "data", None)
        batch = {
            "inputs": put(tokens[:, :-1], sh),
            "targets": put(tokens[:, 1:], sh),
            "poison": put(np.zeros((8, 1), np.float32), sh),
        }
        state, state_sh = sharded_train_state(
            model, optax.adamw(3e-4), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )

        def loss_fn(y, b):
            # Poisoned batches multiply the loss by NaN — loss AND grads
            # go non-finite inside the step (clean batches: × 1.0, bit-
            # identical to the plain loss).
            poisoned = jnp.sum(b["poison"]) > 0
            return next_token_loss(y, b) * jnp.where(
                poisoned, jnp.float32(jnp.nan), jnp.float32(1.0)
            )

        x_sh = {k: v.sharding for k, v in batch.items()}
        guarded = make_train_step(
            state_sh, x_sh, mesh22, RULES_DP_TP, loss_fn=loss_fn,
            donate_state=False, skip_nonfinite=True,
        )
        plain = make_train_step(
            state_sh, x_sh, mesh22, RULES_DP_TP, loss_fn=loss_fn,
            donate_state=False, with_grad_norm=True,
        )
        poisoned = {**batch, "poison": put(np.ones((8, 1), np.float32), sh)}
        skipped, out = guarded(state, poisoned)
        assert not np.isfinite(float(out["loss"]))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            state.params, skipped.params,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            state.opt_state, skipped.opt_state,
        )
        assert int(skipped.step) == int(state.step) + 1   # step still counts
        stepped_g, outg = guarded(state, batch)
        stepped_p, outp = plain(state, batch)
        assert float(outg["loss"]) == float(outp["loss"])
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            stepped_g.params, stepped_p.params,
        )

    def test_guarded_step_satisfies_its_golden(self):
        """The guarded step has its OWN golden (train_step_skip —
        analysis/entrypoints.py mirrors fit()'s construction): the
        selects add no collectives but shift XLA's layout enough that
        the gn golden no longer matches exactly, so
        fit(contract=, resilience=) launches against the program it
        really runs. This recompiles the entry point and diffs it
        against the checked-in golden — the same gate
        scripts/shardcheck.py applies."""
        from learning_jax_sharding_tpu.analysis import run_contract_pass

        findings = run_contract_pass(names=["train_step_skip"])
        assert not findings, [str(f) for f in findings]


# --- the fault x policy matrix -------------------------------------------


class TestFaultMatrix:
    def test_every_cell_recovers(self):
        """THE acceptance gate: every injected fault is detected,
        recovered, and logged, with surviving work bit-identical to a
        fault-free run where the cell promises it."""
        from learning_jax_sharding_tpu.robustness.matrix import run_matrix

        results = run_matrix()
        bad = [r for r in results if not r["recovered"]]
        assert not bad, "unrecovered cells:\n" + "\n".join(
            f"  {r['cell']}: {r['error']}" for r in bad
        )
        assert len(results) == 21
        # Every cell that injects through a chaos seam recorded it
        # (ckpt_corruption corrupts the filesystem directly; the
        # overload cells' fault IS the offered load — none cross a seam).
        for r in results:
            if r["cell"] not in (
                "ckpt_corruption", "overload_shed", "overload_h4",
            ):
                assert r["detail"]["injections"] >= 1, r
