"""Byte tokenizer round-trips and memory-planner calibration.

The planner's oracle values are the OBSERVED fit/OOM boundary on the 16 GB
v5e (this repo's bench experiments, PERF.md): the 125M model at s=1024,
donate_state=False —

* b=8,  dense attention, unfused loss → ran (102 ms baseline);
* b=16, dense attention              → ResourceExhausted;
* b=16, flash + fused loss           → ran;
* b=32, flash + fused loss           → ResourceExhausted.
"""

import dataclasses

import numpy as np
import pytest

from learning_jax_sharding_tpu.data.tokenizer import (
    BOS_ID,
    EOS_ID,
    ByteTokenizer,
)
from learning_jax_sharding_tpu.models.transformer import CONFIG_125M
from learning_jax_sharding_tpu.utils.memory import HBM_BYTES, memory_plan

V5E = HBM_BYTES["TPU v5 lite"]


def _flash_cfg():
    # Any non-None attn_fn marks the flash regime; the planner never calls it.
    return dataclasses.replace(CONFIG_125M, attn_fn=lambda *a, **k: None)


class TestMemoryPlan:
    def test_b8_dense_unfused_fits_v5e(self):
        plan = memory_plan(
            CONFIG_125M, 8, 1024, donate_state=False, unfused_loss=True
        )
        assert plan.fits(V5E)

    def test_b16_dense_ooms_v5e(self):
        plan = memory_plan(
            CONFIG_125M, 16, 1024, donate_state=False, unfused_loss=True
        )
        assert not plan.fits(V5E)

    def test_b16_flash_fused_fits_v5e(self):
        plan = memory_plan(_flash_cfg(), 16, 1024, donate_state=False)
        assert plan.fits(V5E)

    def test_b32_flash_fused_ooms_v5e(self):
        plan = memory_plan(_flash_cfg(), 32, 1024, donate_state=False)
        assert not plan.fits(V5E)

    def test_remat_attention_drops_score_term(self):
        dense = memory_plan(CONFIG_125M, 8, 1024)
        remat = memory_plan(
            dataclasses.replace(CONFIG_125M, remat_attention=True), 8, 1024
        )
        assert dense.detail["per_layer_scores"] > 0
        assert remat.detail["per_layer_scores"] == 0
        assert remat.total < dense.total

    def test_sharding_divides_the_big_terms(self):
        one = memory_plan(CONFIG_125M, 8, 1024)
        tp4 = memory_plan(CONFIG_125M, 8, 1024, n_model_shards=4)
        dp4 = memory_plan(CONFIG_125M, 8, 1024, n_data_shards=4)
        assert tp4.optimizer_state == pytest.approx(one.optimizer_state / 4)
        assert dp4.saved_activations == pytest.approx(one.saved_activations / 4)

    def test_donation_halves_state_residency(self):
        kept = memory_plan(CONFIG_125M, 8, 1024, donate_state=False)
        donated = memory_plan(CONFIG_125M, 8, 1024, donate_state=True)
        assert donated.params == pytest.approx(kept.params / 2)
        assert donated.optimizer_state == pytest.approx(kept.optimizer_state / 2)


class TestByteTokenizer:
    def test_ascii_round_trip(self):
        tok = ByteTokenizer()
        text = "hello, TPU world!"
        assert tok.decode(tok.encode(text)) == text

    def test_utf8_round_trip(self):
        tok = ByteTokenizer()
        text = "résumé — 日本語 🚀"
        assert tok.decode(tok.encode(text)) == text

    def test_bos_eos_framing(self):
        tok = ByteTokenizer(add_bos=True, add_eos=True)
        ids = tok.encode("ab")
        assert ids[0] == BOS_ID and ids[-1] == EOS_ID
        assert tok.decode(ids) == "ab"  # specials dropped on decode

    def test_array_encoding_dtype(self):
        arr = ByteTokenizer().encode_to_array("abc")
        assert arr.dtype == np.uint16
        np.testing.assert_array_equal(arr, [97, 98, 99])

    def test_truncated_utf8_replaces_not_raises(self):
        tok = ByteTokenizer()
        ids = tok.encode("🚀")[:2]  # mid-codepoint cut
        assert "�" in tok.decode(ids)

    def test_vocab_size_covers_specials(self):
        assert ByteTokenizer().vocab_size == 259 > EOS_ID


class TestEndToEnd:
    def test_text_to_training_batches(self, tmp_path):
        """Raw text → packed token file → sharded batches, no externals."""
        from learning_jax_sharding_tpu.data.datasets import (
            MemmapTokenDataset,
            write_token_file,
        )

        tok = ByteTokenizer(add_eos=True)
        corpus = "the quick brown fox jumps over the lazy dog. " * 40
        path = write_token_file(tmp_path / "corpus.bin", tok.encode_to_array(corpus))
        ds = MemmapTokenDataset(path, seq_len=16)
        batch = ds.batch(0, batch_size=4)
        assert batch["inputs"].shape == (4, 16)
        np.testing.assert_array_equal(
            batch["inputs"][:, 1:], batch["targets"][:, :-1]
        )
        # Decoded inputs are substrings of the corpus (plus possible EOS).
        row = tok.decode(batch["inputs"][0])
        assert row.strip("�") and all(
            piece in corpus for piece in row.split("�") if piece
        )
