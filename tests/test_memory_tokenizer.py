"""Byte tokenizer round-trips and memory-planner calibration.

The planner's oracle values are the OBSERVED fit/OOM boundary on the 16 GB
v5e (this repo's bench experiments, PERF.md): the 125M model at s=1024,
donate_state=False —

* b=8,  dense attention, unfused loss → ran (102 ms baseline);
* b=16, dense attention              → ResourceExhausted;
* b=16, flash + fused loss           → ran;
* b=32, flash + fused loss           → ResourceExhausted.
"""

import dataclasses

import numpy as np
import pytest

from learning_jax_sharding_tpu.data.tokenizer import (
    BOS_ID,
    EOS_ID,
    ByteTokenizer,
)
from learning_jax_sharding_tpu.models.transformer import CONFIG_125M
from learning_jax_sharding_tpu.utils.memory import HBM_BYTES, memory_plan

V5E = HBM_BYTES["TPU v5 lite"]


def _flash_cfg():
    # Any non-None attn_fn marks the flash regime; the planner never calls it.
    return dataclasses.replace(CONFIG_125M, attn_fn=lambda *a, **k: None)


class TestMemoryPlan:
    def test_b8_dense_unfused_fits_v5e(self):
        plan = memory_plan(
            CONFIG_125M, 8, 1024, donate_state=False, unfused_loss=True
        )
        assert plan.fits(V5E)

    def test_b16_dense_ooms_v5e(self):
        plan = memory_plan(
            CONFIG_125M, 16, 1024, donate_state=False, unfused_loss=True
        )
        assert not plan.fits(V5E)

    def test_b16_flash_fused_fits_v5e(self):
        plan = memory_plan(_flash_cfg(), 16, 1024, donate_state=False)
        assert plan.fits(V5E)

    def test_b32_flash_fused_ooms_v5e(self):
        plan = memory_plan(_flash_cfg(), 32, 1024, donate_state=False)
        assert not plan.fits(V5E)

    def test_remat_attention_drops_score_term(self):
        dense = memory_plan(CONFIG_125M, 8, 1024)
        remat = memory_plan(
            dataclasses.replace(CONFIG_125M, remat_attention=True), 8, 1024
        )
        assert dense.detail["per_layer_scores"] > 0
        assert remat.detail["per_layer_scores"] == 0
        assert remat.total < dense.total

    def test_sharding_divides_the_big_terms(self):
        one = memory_plan(CONFIG_125M, 8, 1024)
        tp4 = memory_plan(CONFIG_125M, 8, 1024, n_model_shards=4)
        dp4 = memory_plan(CONFIG_125M, 8, 1024, n_data_shards=4)
        assert tp4.optimizer_state == pytest.approx(one.optimizer_state / 4)
        assert dp4.saved_activations == pytest.approx(one.saved_activations / 4)

    def test_donation_halves_state_residency(self):
        kept = memory_plan(CONFIG_125M, 8, 1024, donate_state=False)
        donated = memory_plan(CONFIG_125M, 8, 1024, donate_state=True)
        assert donated.params == pytest.approx(kept.params / 2)
        assert donated.optimizer_state == pytest.approx(kept.optimizer_state / 2)


class TestByteTokenizer:
    def test_ascii_round_trip(self):
        tok = ByteTokenizer()
        text = "hello, TPU world!"
        assert tok.decode(tok.encode(text)) == text

    def test_utf8_round_trip(self):
        tok = ByteTokenizer()
        text = "résumé — 日本語 🚀"
        assert tok.decode(tok.encode(text)) == text

    def test_bos_eos_framing(self):
        tok = ByteTokenizer(add_bos=True, add_eos=True)
        ids = tok.encode("ab")
        assert ids[0] == BOS_ID and ids[-1] == EOS_ID
        assert tok.decode(ids) == "ab"  # specials dropped on decode

    def test_array_encoding_dtype(self):
        arr = ByteTokenizer().encode_to_array("abc")
        assert arr.dtype == np.uint16
        np.testing.assert_array_equal(arr, [97, 98, 99])

    def test_truncated_utf8_replaces_not_raises(self):
        tok = ByteTokenizer()
        ids = tok.encode("🚀")[:2]  # mid-codepoint cut
        assert "�" in tok.decode(ids)

    def test_vocab_size_covers_specials(self):
        assert ByteTokenizer().vocab_size == 259 > EOS_ID


class TestBPETokenizer:
    CORPUS = (
        "the quick brown fox jumps over the lazy dog. " * 30
        + "sharding shards the shared shardings across the mesh. " * 30
        + "naïve café — résumé ünïcôde ✓ " * 10
    )

    def _tok(self, **kw):
        from learning_jax_sharding_tpu.data.tokenizer import BPETokenizer

        return BPETokenizer.train(self.CORPUS, vocab_size=512, **kw)

    def test_round_trips_training_and_novel_text(self):
        tok = self._tok()
        assert tok.decode(tok.encode(self.CORPUS)) == self.CORPUS
        # Byte fallback: text with unseen words/codepoints still round-trips.
        novel = "wholly unseen zebra-quartz glyphs ☂ §§ 🚀 across the mesh"
        assert tok.decode(tok.encode(novel)) == novel

    def test_compresses_vs_bytes(self):
        tok = self._tok()
        n_bytes = len(self.CORPUS.encode("utf-8"))
        n_bpe = len(tok.encode(self.CORPUS))
        assert len(tok.merges) > 0
        assert n_bpe < n_bytes / 2  # repeated words must merge substantially

    def test_id_layout_and_specials(self):
        tok = self._tok(add_bos=True, add_eos=True)
        m = len(tok.merges)
        assert (tok.pad_id, tok.bos_id, tok.eos_id) == (256 + m, 257 + m, 258 + m)
        assert tok.vocab_size == 259 + m
        ids = tok.encode("hi")
        assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
        assert tok.decode(ids) == "hi"  # specials dropped

    def test_training_is_deterministic(self):
        assert self._tok().merges == self._tok().merges

    def test_save_load_round_trip(self, tmp_path):
        from learning_jax_sharding_tpu.data.tokenizer import BPETokenizer

        tok = self._tok(add_eos=True)
        path = tmp_path / "bpe.json"
        tok.save(path)
        tok2 = BPETokenizer.load(path)
        assert tok2 == tok
        text = "the shared mesh"
        assert tok2.encode(text) == tok.encode(text)

    def test_one_merge_chain_per_word_across_whitespace_contexts(self):
        # GPT-2-style gluing: at most ONE leading space joins the word, so
        # " the" uses the same learned tokens after a space, a newline, or an
        # indent — deeper whitespace must not fork a second merge chain.
        tok = self._tok()
        word = tok.encode(" the")
        for ctx in ["\n the", "\n\n    the", "  the"]:
            assert tok.encode(ctx)[-len(word):] == word

    def test_merges_never_cross_words(self):
        tok = self._tok()
        # "the" is frequent; encoding " the the" must reuse the same word
        # token(s) for both occurrences, not a cross-word merge.
        a = tok.encode(" the")
        b = tok.encode(" the the")
        assert b[: len(a)] == a

    def test_vocab_floor_rejected(self):
        from learning_jax_sharding_tpu.data.tokenizer import BPETokenizer

        with pytest.raises(ValueError, match="vocab_size"):
            BPETokenizer.train("abc", vocab_size=100)


class TestEndToEnd:
    def test_text_to_training_batches(self, tmp_path):
        """Raw text → packed token file → sharded batches, no externals."""
        from learning_jax_sharding_tpu.data.datasets import (
            MemmapTokenDataset,
            write_token_file,
        )

        tok = ByteTokenizer(add_eos=True)
        corpus = "the quick brown fox jumps over the lazy dog. " * 40
        path = write_token_file(tmp_path / "corpus.bin", tok.encode_to_array(corpus))
        ds = MemmapTokenDataset(path, seq_len=16)
        batch = ds.batch(0, batch_size=4)
        assert batch["inputs"].shape == (4, 16)
        np.testing.assert_array_equal(
            batch["inputs"][:, 1:], batch["targets"][:, :-1]
        )
        # Decoded inputs are substrings of the corpus (plus possible EOS).
        row = tok.decode(batch["inputs"][0])
        assert row.strip("�") and all(
            piece in corpus for piece in row.split("�") if piece
        )
