"""EMA weights + configurable loss (label smoothing, z-loss).

Oracles: with_ema leaves training dynamics bitwise unchanged while the EMA
follows the analytic geometric average; make_next_token_loss defaults equal
next_token_loss exactly; the smoothing shortcut equals the explicit
smoothed-one-hot cross-entropy; z-loss shrinks logsumexp over training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    make_next_token_loss,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.ema import EmaState, ema_params, with_ema
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


class TestLossFactory:
    def _logits_batch(self, rng):
        logits = jnp.asarray(rng.standard_normal((4, 16, 32)).astype(np.float32))
        targets = jnp.asarray(rng.integers(0, 32, size=(4, 16)).astype(np.int32))
        return logits, {"targets": targets}

    def test_defaults_equal_next_token_loss(self, rng):
        logits, batch = self._logits_batch(rng)
        a = float(next_token_loss(logits, batch))
        b = float(make_next_token_loss()(logits, batch))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_smoothing_matches_explicit_one_hot(self, rng):
        logits, batch = self._logits_batch(rng)
        eps = 0.1
        ours = float(make_next_token_loss(label_smoothing=eps)(logits, batch))
        v = logits.shape[-1]
        one_hot = jax.nn.one_hot(batch["targets"], v)
        smoothed = (1 - eps) * one_hot + eps / v
        explicit = float(optax.softmax_cross_entropy(logits, smoothed).mean())
        np.testing.assert_allclose(ours, explicit, rtol=1e-5)

    def test_z_loss_adds_squared_logsumexp(self, rng):
        logits, batch = self._logits_batch(rng)
        base = float(make_next_token_loss()(logits, batch))
        with_z = float(make_next_token_loss(z_loss=1e-2)(logits, batch))
        lse2 = float(jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1))))
        np.testing.assert_allclose(with_z, base + 1e-2 * lse2, rtol=1e-5)

    def test_z_loss_shrinks_partition_function(self, mesh22, rng):
        """Training with z-loss drives mean logsumexp² down vs without."""
        tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
        sh = mesh_sharding(mesh22, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        model = Transformer(CONFIG_TINY)

        def lse2_after(loss_fn, steps=12):
            state, state_sh = sharded_train_state(
                model, optax.adamw(3e-3), batch["inputs"],
                {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
            )
            step = make_train_step(
                state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
                RULES_DP_TP, loss_fn=loss_fn, donate_state=False,
            )
            for _ in range(steps):
                state, _ = step(state, batch)
            logits = model.apply({"params": state.params}, batch["inputs"])
            return float(
                jnp.mean(jnp.square(jax.nn.logsumexp(
                    logits.astype(jnp.float32), axis=-1
                )))
            )

        assert lse2_after(make_next_token_loss(z_loss=1e-1)) < lse2_after(
            next_token_loss
        )


class TestEma:
    def test_training_dynamics_unchanged(self):
        p = {"w": jnp.ones((4,), jnp.float32)}
        g = {"w": jnp.full((4,), 0.5, jnp.float32)}
        plain, wrapped = optax.adam(1e-2), with_ema(optax.adam(1e-2), 0.9)
        sp, sw = plain.init(p), wrapped.init(p)
        pp, pw = p, p
        for _ in range(5):
            up, sp = plain.update(g, sp, pp)
            pp = optax.apply_updates(pp, up)
            uw, sw = wrapped.update(g, sw, pw)
            pw = optax.apply_updates(pw, uw)
        np.testing.assert_array_equal(np.asarray(pp["w"]), np.asarray(pw["w"]))

    def test_ema_is_geometric_average(self):
        decay = 0.8
        p = {"w": jnp.zeros((), jnp.float32)}
        tx = with_ema(optax.sgd(1.0), decay)
        state = tx.init(p)
        expected_ema = 0.0
        for _ in range(6):
            up, state = tx.update({"w": jnp.asarray(-1.0)}, state, p)
            p = optax.apply_updates(p, up)  # w increases by 1 each step
            expected_ema = decay * expected_ema + (1 - decay) * float(p["w"])
        np.testing.assert_allclose(
            float(ema_params(state)["w"]), expected_ema, rtol=1e-6
        )

    def test_bf16_params_ema_does_not_freeze(self):
        """with_ema(master_weights(...)) on bf16 params: the fp32 accumulator
        keeps moving where a bf16 one would round 0.001·(p-e) to zero."""
        from learning_jax_sharding_tpu.training.precision import master_weights

        decay = 0.999
        tx = with_ema(master_weights(optax.sgd(1e-3)), decay)
        p = {"w": jnp.ones((), jnp.bfloat16)}
        state = tx.init(p)
        assert ema_params(state)["w"].dtype == jnp.float32
        first = None
        for i in range(20):
            up, state = tx.update({"w": jnp.ones((), jnp.bfloat16)}, state, p)
            p = optax.apply_updates(p, up)
            if first is None:
                first = float(ema_params(state)["w"])
        last = float(ema_params(state)["w"])
        # Tracks the decreasing trajectory (a bf16 accumulator would freeze
        # at 1.0 forever: every 0.001·(p-e) increment rounds away near 1.0).
        assert last < 1.0 and last <= first

    def test_requires_params(self):
        tx = with_ema(optax.sgd(1e-2))
        state = tx.init({"w": jnp.ones(())})
        try:
            tx.update({"w": jnp.ones(())}, state)
        except ValueError as e:
            assert "params" in str(e)
        else:
            raise AssertionError("expected ValueError")

    def test_lookup_raises_without_ema(self):
        state = optax.adam(1e-2).init({"w": jnp.ones(())})
        try:
            ema_params(state)
        except LookupError:
            pass
        else:
            raise AssertionError("expected LookupError")

    def test_sharded_integration(self, mesh22, rng):
        """EMA tree born sharded like the params; serving from the EMA works;
        ema_params finds the tree through TrainState.opt_state."""
        tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
        sh = mesh_sharding(mesh22, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        model = Transformer(CONFIG_TINY)
        state, state_sh = sharded_train_state(
            model, with_ema(optax.adamw(3e-3), 0.99), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        assert isinstance(state.opt_state, EmaState)
        kernel = state.params["block_0"]["attn"]["query"]["kernel"]
        ema_kernel = state.opt_state.ema["block_0"]["attn"]["query"]["kernel"]
        assert kernel.sharding.spec == ema_kernel.sharding.spec

        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        losses = []
        for _ in range(8):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # EMA lags the iterate but is a usable param tree.
        ema = ema_params(state.opt_state)
        y = model.apply({"params": ema}, batch["inputs"])
        assert np.isfinite(np.asarray(y, np.float32)).all()
        d = jax.tree.map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            ema, state.params,
        )
        assert max(jax.tree.leaves(d)) > 0  # lags, not equal
