"""Fused residual+norm kernel (ops/fused_norm.py) and its model wiring.

Oracles: bit-level param-tree compatibility across the ``fused_norm``
flag (checkpoints transfer verbatim); forward/grad parity against the
plain JAX implementation at fp32 tolerance — kernel-level AND through a
full Transformer train-loss gradient."""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.ops.fused_norm import fused_residual_norm


def _ref_ln(x, res, g, b, eps=1e-6):
    r = x if res is None else x + res
    mu = jnp.mean(r, -1, keepdims=True)
    var = jnp.mean((r - mu) ** 2, -1, keepdims=True)
    y = (r - mu) * jax.lax.rsqrt(var + eps) * g
    if b is not None:
        y = y + b
    return y, r


def _ref_rms(x, res, g, eps=1e-6):
    r = x if res is None else x + res
    ms = jnp.mean(r * r, -1, keepdims=True)
    return r * jax.lax.rsqrt(ms + eps) * g, r


class TestKernel:
    @pytest.mark.parametrize("kind,resid,beta", [
        ("layernorm", True, True),
        ("layernorm", False, True),
        ("layernorm", True, False),
        ("rmsnorm", True, None),
        ("rmsnorm", False, None),
    ])
    def test_fwd_and_grad_parity(self, kind, resid, beta):
        rng = np.random.default_rng(0)
        B, S, M = 2, 32, 128
        x = jnp.asarray(rng.normal(size=(B, S, M)), jnp.float32)
        res = jnp.asarray(rng.normal(size=(B, S, M)), jnp.float32) if resid else None
        g = jnp.asarray(rng.normal(size=(M,)), jnp.float32)
        b = (
            jnp.asarray(rng.normal(size=(M,)), jnp.float32)
            if (kind == "layernorm" and beta) else None
        )

        def fused_loss(x, res, g, b):
            y, r = fused_residual_norm(x, res, g, b, kind=kind)
            return jnp.sum(jnp.sin(y) * 1.3 + 0.7 * jnp.cos(r))

        def ref_loss(x, res, g, b):
            ref = _ref_ln if kind == "layernorm" else (
                lambda x, res, g, b: _ref_rms(x, res, g)
            )
            y, r = ref(x, res, g, b)
            return jnp.sum(jnp.sin(y) * 1.3 + 0.7 * jnp.cos(r))

        np.testing.assert_allclose(
            float(fused_loss(x, res, g, b)), float(ref_loss(x, res, g, b)),
            rtol=1e-5,
        )
        argnums = (0, 2) if res is None else (0, 1, 2)
        gf = jax.grad(fused_loss, argnums=argnums)(x, res, g, b)
        gr = jax.grad(ref_loss, argnums=argnums)(x, res, g, b)
        for a, e in zip(gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=3e-4, atol=3e-4
            )

    def test_validation(self):
        x = jnp.zeros((2, 8, 16))
        g = jnp.ones((16,))
        with pytest.raises(ValueError, match="beta"):
            fused_residual_norm(x, None, g, jnp.zeros((16,)), kind="rmsnorm")
        with pytest.raises(ValueError, match="kind"):
            fused_residual_norm(x, None, g, kind="batchnorm")
        # Non-dividing block_r must raise, not silently truncate the grid.
        with pytest.raises(ValueError, match="divisible"):
            fused_residual_norm(
                jnp.zeros((2, 10, 16)), None, g, kind="rmsnorm", block_r=8
            )

    def test_odd_rows_still_correct(self):
        """Rows with no power-of-two factor fall back to one whole tile
        (guarded) — results must still match the reference."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(2, 9, 128)), jnp.float32)  # 18 rows
        g = jnp.asarray(rng.normal(size=(128,)), jnp.float32)
        y, _ = fused_residual_norm(x, None, g, kind="rmsnorm")
        ref, _ = _ref_rms(x, None, g)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5
        )


class TestModelWiring:
    @pytest.mark.parametrize("norm", ["layernorm", "rmsnorm"])
    def test_param_tree_identical_and_loss_matches(self, norm):
        cfg = dataclasses.replace(CONFIG_TINY, norm=norm, dtype=jnp.float32)
        cfg_f = dataclasses.replace(cfg, fused_norm=True)
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, cfg.vocab_size, size=(2, 17)).astype(np.int32)
        batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}

        plain, fused = Transformer(cfg), Transformer(cfg_f)
        params = nn.meta.unbox(
            plain.init({"params": jax.random.key(0)}, batch["inputs"])["params"]
        )
        # The fused model must accept the plain model's params VERBATIM.
        shapes_p = jax.tree.map(lambda x: (x.shape, str(x.dtype)), params)
        shapes_f = jax.tree.map(
            lambda x: (x.shape, str(x.dtype)),
            nn.meta.unbox(
                fused.init({"params": jax.random.key(0)}, batch["inputs"])[
                    "params"
                ]
            ),
        )
        assert jax.tree.structure(shapes_p) == jax.tree.structure(shapes_f)
        assert jax.tree.leaves(shapes_p) == jax.tree.leaves(shapes_f)

        def loss(model, p):
            return next_token_loss(
                model.apply({"params": p}, batch["inputs"]), batch
            )

        lp = float(loss(plain, params))
        lf = float(loss(fused, params))
        np.testing.assert_allclose(lf, lp, rtol=1e-5)

        gp = jax.grad(lambda p: loss(plain, p))(params)
        gf = jax.grad(lambda p: loss(fused, p))(params)
        for (kp, a), (_, e) in zip(
            jax.tree_util.tree_leaves_with_path(gf),
            jax.tree_util.tree_leaves_with_path(gp),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=5e-4, atol=5e-4,
                err_msg=str(kp),
            )
