"""Worker process for the real 2-process cluster test (not a test module).

Launched by ``test_distributed_cluster.py`` as ``python _distributed_worker.py
<rank> <nproc> <port>``. Each process owns 2 emulated CPU devices; together
they form one 4-device system over the JAX distributed runtime (Gloo-backed
cross-process collectives — the CPU stand-in for DCN). The worker runs the
FRAMEWORK path end to end: ``multihost.initialize`` → ``build_mesh`` over the
global devices → per-host batch assembly via ``ShardedBatchLoader`` →
one sharded train step; prints the loss for the launcher to compare across
ranks.
"""

import os
import sys

rank, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from learning_jax_sharding_tpu.parallel import multihost  # noqa: E402

multihost.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc, process_id=rank
)

import numpy as np  # noqa: E402
import optax  # noqa: E402

from learning_jax_sharding_tpu.data import (  # noqa: E402
    ShardedBatchLoader,
    SyntheticLMDataset,
)
from learning_jax_sharding_tpu.models.transformer import (  # noqa: E402
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import build_mesh  # noqa: E402
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP  # noqa: E402
from learning_jax_sharding_tpu.training.pipeline import (  # noqa: E402
    make_train_step,
    sharded_train_state,
)

assert multihost.process_count() == nproc, multihost.process_count()
assert len(jax.devices()) == 2 * nproc, jax.devices()
assert len(jax.local_devices()) == 2

# data axis spans PROCESSES (the DCN direction), model axis stays host-local.
mesh = build_mesh((nproc, 2), ("data", "model"))

cfg = CONFIG_TINY
model = Transformer(cfg)
loader = ShardedBatchLoader(
    SyntheticLMDataset(vocab_size=cfg.vocab_size, seq_len=32, seed=0),
    mesh, batch_size=4, spec=("data",),
)
batch = loader.batch_at(0)  # this host materializes only ITS rows

state, state_sh = sharded_train_state(
    model, optax.adamw(1e-3), batch["inputs"],
    {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
)
step = make_train_step(
    state_sh, {k: v.sharding for k, v in batch.items()}, mesh, RULES_DP_TP,
    loss_fn=next_token_loss,
)
state, loss = step(state, batch)
loss = float(loss)  # cross-process replicated scalar: readback syncs all
assert np.isfinite(loss)
print(f"RANK{rank} LOSS {loss:.6f}", flush=True)
