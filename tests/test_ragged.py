"""Ragged serving: per-row cache lengths end-to-end.

The serving case the rectangular stack could not express: a batch of
MIXED-length prompts, each row generating from its own length. Oracles:

* kernel level — ``decode_attention`` with a per-row ``(B,)`` index equals
  running each row separately at its scalar index (the per-row clamp maps
  cannot leak across rows);
* model level — ``make_generate_fn(ragged=True)`` on a right-padded
  mixed-length batch produces EXACTLY what per-row single (rectangular)
  runs produce, dense AND blocked backends, greedy fp32 (bit-identical on
  the CPU backend);
* EOS rows stop consuming cache — a ``chunk_lengths=0`` step leaves
  ``cache_index``/``position`` untouched (the mechanism behind "finished
  rows stop paying attention traffic").

The throughput claim (short rows fetch fewer cache blocks than pad-to-max)
is a real-TPU measurement — PERF.md "Ragged serving".
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.ops.decode_attention import decode_attention
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.pipeline import sharded_train_state

LENGTHS = [3, 8, 5, 1]  # includes the batch max (8) and a length-1 row
PROMPT_MAX = 8
NEW = 6


@pytest.fixture(scope="module")
def tiny_setup(mesh22):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    rng = np.random.default_rng(7)
    prompt = rng.integers(1, cfg.vocab_size, size=(4, PROMPT_MAX)).astype(np.int32)
    for b, l in enumerate(LENGTHS):
        prompt[b, l:] = 0  # right-pad with an arbitrary id
    x = put(prompt, mesh_sharding(mesh22, "data", None))
    state, _ = sharded_train_state(
        Transformer(cfg), optax.sgd(1e-2), x,
        {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
    )
    return cfg, nn.meta.unbox(state.params), prompt


class TestKernelPerRowIndex:
    def test_matches_per_row_scalar_runs(self, rng):
        b, n_kv, length, h, group = 4, 2, 64, 16, 2
        n = n_kv * group
        q = jnp.asarray(rng.normal(size=(b, 1, n, h)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(b, n_kv, length, h)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(b, n_kv, length, h)), jnp.float32)
        idx = jnp.asarray([5, 40, 17, 0], jnp.int32)
        with jax.default_matmul_precision("float32"):
            batched = decode_attention(q, kc, vc, idx, block_k=16, interpret=True)
            for row in range(b):
                single = decode_attention(
                    q[row : row + 1], kc[row : row + 1], vc[row : row + 1],
                    int(idx[row]), block_k=16, interpret=True,
                )
                np.testing.assert_allclose(
                    np.asarray(batched[row]), np.asarray(single[0]), atol=1e-6
                )

    def test_per_row_window(self, rng):
        """Sliding windows compose with per-row indexes (each row's band
        starts at ITS index)."""
        b, n_kv, length, h = 3, 1, 64, 16
        q = jnp.asarray(rng.normal(size=(b, 1, n_kv, h)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(b, n_kv, length, h)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(b, n_kv, length, h)), jnp.float32)
        idx = jnp.asarray([50, 9, 23], jnp.int32)
        with jax.default_matmul_precision("float32"):
            batched = decode_attention(
                q, kc, vc, idx, window=16, block_k=8, interpret=True
            )
            for row in range(b):
                single = decode_attention(
                    q[row : row + 1], kc[row : row + 1], vc[row : row + 1],
                    int(idx[row]), window=16, block_k=8, interpret=True,
                )
                np.testing.assert_allclose(
                    np.asarray(batched[row]), np.asarray(single[0]), atol=1e-6
                )


class TestRaggedGenerate:
    @pytest.mark.parametrize("backend", ["dense", "blocked"])
    def test_matches_per_row_single_runs(self, tiny_setup, mesh22, backend):
        """THE ragged oracle: every row of the mixed-length batch generates
        exactly what a rectangular run of that row alone produces."""
        cfg, params, prompt = tiny_setup
        cfg = dataclasses.replace(cfg, decode_attention=backend)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW, ragged=True
        )
        out = np.asarray(
            gen(params, prompt, jax.random.key(1), lengths=np.asarray(LENGTHS))
        )
        single_gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW
        )
        for row, l in enumerate(LENGTHS):
            # Rectangular run on the row's exact prompt (duplicated to b=2:
            # the mesh's data axis must divide the batch).
            ref = np.asarray(
                single_gen(
                    params,
                    np.repeat(prompt[row : row + 1, :l], 2, axis=0),
                    jax.random.key(1),
                )
            )
            np.testing.assert_array_equal(
                out[row, : l + NEW], ref[0],
                err_msg=f"row {row} (length {l}, backend {backend})",
            )

    @pytest.mark.parametrize("backend", ["dense", "blocked"])
    def test_int8_cache_ragged(self, tiny_setup, mesh22, backend):
        """Per-row scale writes land at per-row offsets too — including the
        blocked backend's FOLDED in-kernel write of values AND scales."""
        cfg, params, prompt = tiny_setup
        cfg = dataclasses.replace(
            cfg, kv_cache_dtype=jnp.int8, decode_attention=backend
        )
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW, ragged=True
        )
        out = np.asarray(
            gen(params, prompt, jax.random.key(1), lengths=np.asarray(LENGTHS))
        )
        single_gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW
        )
        for row, l in enumerate(LENGTHS):
            ref = np.asarray(
                single_gen(
                    params,
                    np.repeat(prompt[row : row + 1, :l], 2, axis=0),
                    jax.random.key(1),
                )
            )
            np.testing.assert_array_equal(out[row, : l + NEW], ref[0])

    def test_eos_rows_and_output_layout(self, tiny_setup, mesh22):
        """With eos_id set: output rows read [prompt_b, generated..., eos
        fill] and the result still matches per-row single runs."""
        cfg, params, prompt = tiny_setup
        # Use greedy output of the plain run to find a token the row WILL
        # emit, then rerun with that as eos — deterministic early stop.
        gen_plain = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW, ragged=True
        )
        out_plain = np.asarray(
            gen_plain(params, prompt, jax.random.key(1), lengths=np.asarray(LENGTHS))
        )
        eos = int(out_plain[0, LENGTHS[0] + 1])  # row 0's second new token
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW, ragged=True,
            eos_id=eos,
        )
        out = np.asarray(
            gen(params, prompt, jax.random.key(1), lengths=np.asarray(LENGTHS))
        )
        single_gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW, eos_id=eos
        )
        for row, l in enumerate(LENGTHS):
            ref = np.asarray(
                single_gen(
                    params,
                    np.repeat(prompt[row : row + 1, :l], 2, axis=0),
                    jax.random.key(1),
                )
            )
            np.testing.assert_array_equal(out[row, : l + NEW], ref[0])
            # EVERYTHING past the generated span is the eos fill — including
            # where the caller's prompt padding used to sit. A consumer
            # scanning for the terminator can never read stale pad ids.
            assert (out[row, l + NEW :] == eos).all(), out[row]

    def test_validation(self, tiny_setup, mesh22):
        cfg, params, prompt = tiny_setup
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=2, ragged=True
        )
        with pytest.raises(ValueError, match="lengths"):
            gen(params, prompt, jax.random.key(0))
        plain = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=2)
        with pytest.raises(ValueError, match="ragged"):
            plain(params, prompt, jax.random.key(0), lengths=np.asarray(LENGTHS))
        with pytest.raises(ValueError, match="prefill_chunk_size"):
            make_generate_fn(
                cfg, mesh22, RULES_DP_TP, max_new_tokens=2, ragged=True,
                prefill_chunk_size=4,
            )


class TestFrozenRowsStopConsumingCache:
    def test_chunk_lengths_zero_freezes_index(self, tiny_setup, mesh22):
        """A step with chunk_lengths=0 must leave every cache_index AND the
        position counter untouched — how EOS-finished rows stop consuming
        cache slots (their writes land on the same dead slot forever)."""
        from learning_jax_sharding_tpu.models.decoding import (
            derive_decode_config,
            make_cached_apply,
        )
        from learning_jax_sharding_tpu.parallel.logical import activate

        cfg, params, prompt = tiny_setup
        dcfg = derive_decode_config(dataclasses.replace(cfg, decode_ragged=True))
        apply = make_cached_apply(Transformer(dcfg))
        lengths = jnp.asarray(LENGTHS, jnp.int32)
        with activate(mesh22, RULES_DP_TP):
            _, cache = apply(params, None, jnp.asarray(prompt), lengths)
            tok = jnp.zeros((4, 1), jnp.int32)
            active = jnp.asarray([1, 0, 1, 0], jnp.int32)
            _, cache2 = apply(params, cache, tok, active)

        def indexes(c):
            vals = []
            for path, leaf in jax.tree_util.tree_leaves_with_path(c):
                if getattr(path[-1], "key", None) in ("cache_index", "position"):
                    vals.append(np.asarray(leaf))
            return vals

        before, after = indexes(cache), indexes(cache2)
        assert before and len(before) == len(after)
        for bf, af in zip(before, after):
            np.testing.assert_array_equal(bf, np.asarray(LENGTHS))
            np.testing.assert_array_equal(af, np.asarray(LENGTHS) + [1, 0, 1, 0])
