"""Goodput ledger + request tracing (telemetry/, round 14).

Three layers:

* pure units — the ledger's exclusive-frame accounting identity under a
  fake clock (nesting, retrospective booking, compile re-bucketing,
  windows, the reconcile invariant and its failure modes) and the
  TraceStore's critical-path algebra (stall remainder, wasted legs,
  TTFT, reroute/swap-pin events, Perfetto export, merge rebase);
* engine/loop integration — a real ContinuousEngine drain and a real
  ``fit()`` run must RECONCILE (Σ buckets == wall within ε) with traced
  requests carrying complete critical paths;
* chaos attribution — injected faults (slow dispatch, NaN-trap raise)
  book into ``recovery``, never ``device``: the ledger cannot blame the
  hardware for the failure machinery.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.parallel.multihost import (
    merge_registry_snapshots,
)
from learning_jax_sharding_tpu.robustness import ChaosInjector, Fault
from learning_jax_sharding_tpu.telemetry import (
    BUCKETS,
    GoodputLedger,
    STAGES,
    TraceStore,
    merge_tracers,
)
from learning_jax_sharding_tpu.telemetry.flight_recorder import FlightRecorder
from learning_jax_sharding_tpu.telemetry.registry import (
    MetricsRegistry,
    snapshot_prometheus_text,
)


class _Clock:
    """Deterministic manual clock for the pure units."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, s):
        self.t += s


# --- ledger units ---------------------------------------------------------


class TestLedger:
    def test_nested_frames_book_exclusive_time(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("sched"):
            clk.tick(1.0)
            with led.measure("device"):
                clk.tick(2.0)
            clk.tick(0.5)
        clk.tick(0.5)                       # idle tail
        b = led.window_buckets()
        assert b["device"] == pytest.approx(2.0)
        assert b["sched"] == pytest.approx(1.5)      # 3.5 total − 2.0 child
        assert b["idle"] == pytest.approx(0.5)
        rec = led.reconcile()
        assert rec["ok"], rec
        assert rec["wall_s"] == pytest.approx(4.0)
        assert rec["residual_s"] == pytest.approx(0.0)

    def test_account_steals_from_the_enclosing_frame(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("sched"):
            clk.tick(1.0)
            led.account("telemetry", 0.25)   # part of the elapsed second
        b = led.window_buckets()
        assert b["telemetry"] == pytest.approx(0.25)
        assert b["sched"] == pytest.approx(0.75)
        assert led.reconcile()["ok"]
        with pytest.raises(ValueError):
            led.account("telemetry", -1.0)

    def test_rebucket_moves_a_compile_stolen_dispatch(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("device") as frame:
            clk.tick(3.0)
            frame.rebucket("compile")        # executable cache grew
        b = led.window_buckets()
        assert b["compile"] == pytest.approx(3.0)
        assert b["device"] == pytest.approx(0.0)

    def test_windows_are_deltas(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("device"):
            clk.tick(5.0)
        led.begin_window()
        with led.measure("device"):
            clk.tick(1.0)
        assert led.window_buckets()["device"] == pytest.approx(1.0)
        assert led.totals()["device"] == pytest.approx(6.0)
        assert led.reconcile()["ok"]

    def test_window_report_names_the_top_gap(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("sched"):
            clk.tick(1.5)
            with led.measure("device"):
                clk.tick(2.0)
        clk.tick(0.5)                        # idle
        rep = led.window_report()
        assert rep["wall_s"] == pytest.approx(4.0)
        assert rep["busy_s"] == pytest.approx(3.5)
        assert rep["host_share"] == pytest.approx(1.0 - 2.0 / 3.5)
        assert rep["top_contributor"] == "sched"
        assert rep["top_contributor_s"] == pytest.approx(1.5)
        # Measured ratio without a roofline; the roofline overrides.
        assert rep["goodput_ratio"] == pytest.approx(2.0 / 4.0)
        rep2 = led.window_report(roofline_device_s=1.0)
        assert rep2["goodput_ratio"] == pytest.approx(0.25)

    def test_reconcile_catches_leaks_and_open_frames(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("device"):
            clk.tick(1.0)
        # A booking that never happened on this clock breaks the
        # identity — exactly what reconcile() exists to catch.
        led._totals["sched"] = led._totals.get("sched", 0.0) + 5.0
        assert not led.reconcile()["ok"]
        led2 = GoodputLedger(clock=clk)
        cm = led2.measure("device")
        cm.__enter__()
        assert not led2.reconcile()["ok"]    # open frame → not reconciled
        cm.__exit__(None, None, None)

    def test_meters_into_the_registry(self):
        clk = _Clock()
        reg = MetricsRegistry()
        led = GoodputLedger(registry=reg, clock=clk)
        with led.measure("device"):
            clk.tick(2.0)
        c = reg.get('ledger_seconds_total{bucket="device"}')
        assert c is not None and c.value == pytest.approx(2.0)
        assert 'ledger_seconds_total{bucket="device"} 2' in (
            reg.prometheus_text()
        )

    def test_canonical_buckets_always_report(self):
        led = GoodputLedger(clock=_Clock())
        b = led.window_buckets()
        assert list(b) == list(BUCKETS)


# --- trace-store units ----------------------------------------------------


class TestTraceStore:
    def test_mint_is_idempotent_and_ordered(self):
        ts = TraceStore()
        assert ts.mint(7, arrival_t=1.0) == "trace-00001"
        assert ts.mint(7) == "trace-00001"
        assert ts.mint(9) == "trace-00002"
        assert ts.trace_of(7) == "trace-00001"
        assert ts.trace_of(404) is None

    def test_critical_path_decomposition(self):
        ts = TraceStore(registry=MetricsRegistry())
        ts.mint(1, arrival_t=10.0)
        ts.leg(1, "queue", 10.0, 11.0, replica="p0")
        ts.leg(1, "prefill", 11.0, 12.5, replica="p0", first_token_t=12.5)
        ts.leg(1, "handoff", 12.5, 12.7)
        ts.leg(1, "decode", 12.8, 14.0, replica="d0")
        ts.complete(1, finish_t=14.2)
        cp = ts.critical_path(1)
        assert cp["e2e_s"] == pytest.approx(4.2)
        assert cp["ttft_s"] == pytest.approx(2.5)
        assert cp["stages"]["queue"] == pytest.approx(1.0)
        assert cp["stages"]["prefill"] == pytest.approx(1.5)
        assert cp["stages"]["handoff"] == pytest.approx(0.2)
        assert cp["stages"]["decode"] == pytest.approx(1.2)
        # stall = e2e − named stages: the 0.1 gap before decode plus the
        # 0.2 tail after it.
        assert cp["stages"]["stall"] == pytest.approx(0.3)

    def test_wasted_legs_sum_separately(self):
        ts = TraceStore()
        ts.mint(1, arrival_t=0.0)
        ts.leg(1, "prefill", 0.0, 1.0, wasted=True)    # failover threw it
        ts.leg(1, "prefill", 1.0, 1.5, first_token_t=1.5)
        ts.complete(1, finish_t=2.0)
        cp = ts.critical_path(1)
        assert cp["wasted_s"] == pytest.approx(1.0)
        assert cp["stages"]["prefill"] == pytest.approx(0.5)
        assert cp["stages"]["stall"] == pytest.approx(1.5)
        assert cp["legs"] == 2

    def test_events_count_reroutes_and_pin_versions(self):
        ts = TraceStore()
        ts.mint(1)
        ts.instant(1, "reroute", replica="d1", error="killed")
        ts.instant(1, "reroute", replica="d0")
        ts.instant(1, "swap_pin", version=3)
        ts.complete(1, finish_t=1.0)
        cp = ts.critical_path(1)
        assert cp["reroutes"] == 2
        assert cp["swap_pins"] == [3]

    def test_complete_is_idempotent_and_observes_histograms(self):
        reg = MetricsRegistry()
        ts = TraceStore(registry=reg)
        ts.mint(1, arrival_t=0.0)
        ts.leg(1, "prefill", 0.0, 1.0, first_token_t=1.0)
        ts.complete(1, status="ok", finish_t=2.0)
        ts.complete(1, status="late-duplicate", finish_t=99.0)
        assert ts.record(1)["status"] == "ok"
        h = reg.get('trace_stage_seconds{stage="prefill"}')
        assert h.count == 1 and h.sum == pytest.approx(1.0)
        assert reg.get("trace_ttft_seconds").count == 1
        assert reg.get("trace_e2e_seconds").sum == pytest.approx(2.0)
        assert len(ts.completed()) == 1

    def test_done_traces_age_out_live_ones_never(self):
        ts = TraceStore(max_done=2)
        for rid in (1, 2, 3):
            ts.mint(rid)
            ts.complete(rid, finish_t=1.0)
        ts.mint(77)                          # live
        assert ts.record(1) is None          # oldest done aged out
        assert ts.record(3) is not None
        assert ts.record(77) is not None

    def test_chrome_trace_has_per_replica_process_tracks(self):
        ts = TraceStore()
        ts.mint(1, arrival_t=0.0)
        ts.leg(1, "prefill", 0.0, 1.0, replica="p0")
        ts.leg(1, "decode", 1.0, 2.0, replica="d0")
        ts.instant(1, "reroute")             # replica-less → "fleet"
        doc = ts.chrome_trace()
        meta = {
            e["args"]["name"]: e["pid"]
            for e in doc["traceEvents"] if e["ph"] == "M"
        }
        assert set(meta) == {"replica d0", "replica p0", "fleet"}
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {s["name"] for s in spans} == {"prefill", "decode"}
        assert all(s["tid"] == 1 for s in spans)
        (inst,) = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        assert inst["pid"] == meta["fleet"]

    def test_merge_tracers_rebases_rings_onto_one_epoch(self):
        class _Ring:
            def __init__(self, t0, events):
                self._t0 = t0
                self.events = events

        merged = merge_tracers(
            {
                "b": _Ring(10.0, [{"name": "x", "ph": "X", "ts": 5.0,
                                   "dur": 1.0, "tid": 0}]),
                "a": _Ring(12.0, [{"name": "y", "ph": "X", "ts": 5.0,
                                   "dur": 1.0, "tid": 0}]),
            },
            extra_events=[{"name": "marker", "ph": "i", "ts": 0.0}],
        )
        ev = merged["traceEvents"]
        names = {
            e["args"]["name"]: e["pid"]
            for e in ev
            if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert names == {"replica a": 1, "replica b": 2}
        # Deterministic Perfetto ordering: each replica pid also carries
        # a process_sort_index row matching its sorted-name rank.
        sorts = {
            e["pid"]: e["args"]["sort_index"]
            for e in ev
            if e["ph"] == "M" and e["name"] == "process_sort_index"
        }
        assert sorts == {1: 0, 2: 1}
        by_name = {e["name"]: e for e in ev if e["ph"] == "X"}
        # a's epoch is 2 s after b's: same local ts lands 2e6 µs later.
        assert by_name["y"]["ts"] == pytest.approx(
            by_name["x"]["ts"] + 2e6
        )
        assert ev[-1]["name"] == "marker"    # extras appended verbatim
        assert merged["otherData"]["epoch_perf_t0"] == 10.0


# --- engine + fit integration --------------------------------------------


def _params(cfg):
    return nn.meta.unbox(
        jax.jit(lambda r, t: Transformer(cfg).init({"params": r}, t))(
            jax.random.key(3), np.zeros((2, 8), np.int32)
        )["params"]
    )


@pytest.fixture(scope="module")
def served():
    """One traced engine drain, shared by the integration asserts."""
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    mesh = build_mesh((1, 2), ("data", "model"), devices=jax.devices()[:2])
    params = _params(cfg)
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=4,
        refill_chunk=8,
    )
    eng.trace_sink = TraceStore(registry=eng.registry)
    rng = np.random.default_rng(14)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in rng.integers(5, 12, size=6)
    ]
    for p in prompts:
        eng.add_request(p)
    while eng.has_work():
        eng.step(params)
    outs = eng.pop_finished()
    return eng, outs


class TestEngineLedger:
    def test_engine_wall_reconciles(self, served):
        eng, outs = served
        assert len(outs) == 6
        rec = eng.ledger.reconcile()
        assert rec["ok"], rec
        b = rec["buckets"]
        assert b["device"] > 0.0
        assert b["compile"] > 0.0            # first dispatches compiled
        assert b["sched"] > 0.0

    def test_solo_engine_traces_complete_critical_paths(self, served):
        eng, outs = served
        cps = eng.trace_sink.completed()
        assert len(cps) == 6
        for cp in cps:
            assert cp["status"] == "ok"
            assert cp["stages"]["queue"] >= 0.0
            assert cp["stages"]["prefill"] > 0.0
            assert cp["stages"]["decode"] > 0.0
            assert cp["ttft_s"] is not None and cp["ttft_s"] > 0.0
            assert cp["e2e_s"] >= cp["ttft_s"]

    def test_ledger_series_reach_prometheus(self, served):
        eng, _ = served
        text = eng.registry.prometheus_text()
        assert 'ledger_seconds_total{bucket="device"}' in text
        assert 'trace_stage_seconds_bucket{stage="queue",le=' in text

    def test_report_names_top_contributor(self, served):
        eng, _ = served
        rep = eng.ledger.window_report()
        assert rep["host_share"] is not None and 0.0 < rep["host_share"] < 1.0
        assert rep["top_contributor"] in set(BUCKETS) - {"device"}
        assert rep["telemetry_share"] < 0.05

    def test_exposed_comm_is_a_view_over_device_never_telemetry(
        self, served
    ):
        """The round-19 overlap decomposition must be a pure VIEW: per
        family it sums back to that family's measured device seconds,
        the family totals cover the device bucket, and arming the view
        moves nothing into ``telemetry`` (so ``reconcile()`` is
        untouched by construction)."""
        eng, _ = served
        before = eng.ledger.window_buckets()
        rep = eng.overlap_report()
        assert rep["families"], "device seconds lost their family tags"
        for fam, row in rep["families"].items():
            total = (row["compute_s"] + row["exposed_comm_s"]
                     + row["overlapped_comm_s"])
            assert total == pytest.approx(row["device_s"]), (fam, row)
        assert rep["attributed_s"] + rep["residual_s"] == pytest.approx(
            rep["device_s"])
        after = eng.ledger.window_buckets()
        assert after["device"] == pytest.approx(before["device"])
        assert after.get("telemetry", 0.0) == pytest.approx(
            before.get("telemetry", 0.0))
        assert eng.ledger.reconcile()["ok"]


class TestChaosAttribution:
    """Injected faults must land in ``recovery``, never ``device`` —
    the attribution contract that keeps the goodput verdict honest under
    failure (an injected hang blamed on the device bucket would read as
    a hardware slowdown)."""

    def _drain(self, eng, params, prompts):
        for p in prompts:
            eng.add_request(p)
        while eng.has_work():
            eng.step(params)
        return eng.pop_finished()

    @pytest.fixture(scope="class")
    def chaos_run(self):
        cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
        mesh = build_mesh(
            (1, 2), ("data", "model"), devices=jax.devices()[:2]
        )
        params = _params(cfg)
        eng = ContinuousEngine(
            cfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=8,
        )
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
            for n in rng.integers(5, 12, size=4)
        ]
        self._drain(eng, params, prompts)          # warm: compiles out
        eng.ledger.begin_window()
        base_device = eng.ledger.totals().get("device", 0.0)
        with ChaosInjector(
            Fault("engine.dispatch", "slow", at=1, count=2, delay_s=0.05),
            Fault("engine.dispatch", "raise", at=3, count=1,
                  error=FloatingPointError),
            recorder=FlightRecorder(),
        ) as inj:
            outs = self._drain(eng, params, prompts)
        return eng, inj, outs, base_device

    def test_injected_delay_books_to_recovery(self, chaos_run):
        eng, inj, outs, _ = chaos_run
        assert len([f for f in inj.injections if f["fault"] == "slow"]) == 2
        assert eng.ledger.window_buckets()["recovery"] >= 0.1

    def test_nan_trap_recovers_and_reconciles(self, chaos_run):
        eng, inj, outs, _ = chaos_run
        assert any(f["fault"] == "raise" for f in inj.injections)
        assert len(outs) == 4                # strikes requeue, none lost
        rec = eng.ledger.reconcile()
        assert rec["ok"], rec

    def test_device_bucket_stays_clean_of_chaos(self, chaos_run):
        eng, inj, outs, base_device = chaos_run
        # The device bucket may only hold real dispatch wall — it must
        # not have absorbed the 2×50 ms injected sleeps.
        device = eng.ledger.totals()["device"] - base_device
        assert device < 0.1 or (
            device < eng.ledger.window_buckets()["recovery"]
        )


class TestFitLedger:
    def test_fit_reconciles_and_books_compile(self, tmp_path):
        from learning_jax_sharding_tpu.data import SyntheticLMDataset
        from learning_jax_sharding_tpu.training.loop import (
            TrainLoopConfig,
            fit,
        )

        mesh = build_mesh(
            (2, 2), ("data", "model"), devices=jax.devices()[:4]
        )
        led = GoodputLedger(registry=MetricsRegistry())
        cfg = TrainLoopConfig(
            steps=3, global_batch_size=8, learning_rate=1e-3,
            metrics_path=str(tmp_path / "m.jsonl"),
        )
        ds = SyntheticLMDataset(
            vocab_size=CONFIG_TINY.vocab_size, seq_len=16, seed=7
        )
        state, hist = fit(
            Transformer(CONFIG_TINY), ds, mesh, RULES_DP_TP, cfg,
            ledger=led,
        )
        assert len(hist) == 3
        rec = led.reconcile()
        assert rec["ok"], rec
        b = rec["buckets"]
        assert b["compile"] > 0.0            # setup + first-step traces
        assert b["device"] > 0.0             # the steady steps
        assert b["sched"] >= 0.0


# --- labeled fleet export -------------------------------------------------


class TestLabeledExport:
    def test_fleet_merge_splices_replica_into_ledger_labels(self):
        regs = {}
        for name in ("p0", "d0"):
            clk = _Clock()
            reg = MetricsRegistry()
            led = GoodputLedger(registry=reg, clock=clk)
            with led.measure("device"):
                clk.tick(1.0 if name == "p0" else 2.0)
            regs[name] = reg
        merged = merge_registry_snapshots(
            [regs["p0"].snapshot(), regs["d0"].snapshot()],
            labels=["p0", "d0"],
        )
        # The fleet sum keeps the bucket-only key; per-replica series
        # carry both labels.
        assert merged['ledger_seconds_total{bucket="device"}'] == (
            pytest.approx(3.0)
        )
        key = 'ledger_seconds_total{bucket="device",replica="d0"}'
        assert merged[key] == pytest.approx(2.0)
        text = snapshot_prometheus_text(merged)
        assert 'ledger_seconds_total{bucket="device",replica="p0"} 1' in text
        # The exposition keeps the family contiguous: fleet sum and
        # per-replica series group together, never interleaved with
        # other families.
        fam = [
            ln for ln in text.splitlines()
            if ln.startswith("ledger_seconds_total")
        ]
        idx = [
            i for i, ln in enumerate(text.splitlines())
            if ln.startswith("ledger_seconds_total")
        ]
        assert len(fam) == 3
        assert idx == list(range(idx[0], idx[0] + 3))
