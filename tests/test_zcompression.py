"""Engine/fleet oracles for the comm compression layer (round 22).

Named to sort LAST alongside ``test_zfleet``/``test_zkv_economy`` (same
rationale: these build real engines and compile real programs, so they
live at the tail of the suite where the tier-1 wall budget can absorb
them). What they pin:

* **drift gate** — the quantized TP all-reduce agrees with the plain
  engine token-for-token under greedy decoding; the forced-trip hook
  (negative budget) fires the degradation ladder, flips
  ``comm_compression_active`` off, and the NEXT serve retraces to the
  plain programs and is bit-identical to an engine that never
  compressed;
* **page boundaries** — compressed spill→fill→re-spill round-trips
  bit-identically (f32 requantization fixed point, pinned at the codec
  level in ``test_compression.py``) and the ``_q8`` contract names land
  on the kv programs;
* **delta-vs-base across version bumps** — the tier store's stale entry
  earns its RAM as the delta codec's base: re-spilling unchanged rows
  against it ships near-zero wire bytes and decodes bit-identically;
* **priced and searchable** — the costmodel's quantized event variants
  and codec-overhead charge, plus the seeded layout-search case: flat
  pricing DECLINES quantization (codec passes cost more than the wire
  they save when link ≈ HBM), two-tier pricing flips the DCN grad-sync
  axis to int8;
* the ``uncounted-compression`` source-lint rule fires on codec calls
  outside the counted seams and stays quiet inside them.
"""

import dataclasses as dc

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.analysis import costmodel
from learning_jax_sharding_tpu.analysis.entrypoints import (
    _sharded_serving_params,
)
from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.compression import CommCompression
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    RULES_TP_SERVING,
)

CFG = dc.replace(CONFIG_TINY, dtype=jnp.float32)


def _same_tokens(a, b):
    return all(
        (np.asarray(x) == np.asarray(y)).all() for x, y in zip(a, b)
    )


@pytest.fixture(scope="module")
def tp_mesh():
    return build_mesh((2, 4), ("data", "model"))


@pytest.fixture(scope="module")
def served(tp_mesh):
    params = _sharded_serving_params(
        Transformer(CFG), tp_mesh, RULES_TP_SERVING
    )
    prng = np.random.default_rng(0)
    prompts = [
        prng.integers(1, CFG.vocab_size, size=(n,)).astype(np.int32)
        for n in (20, 5)
    ]
    return params, prompts


def _mixed_engine(mesh, comm=None):
    return ContinuousEngine(
        CFG, mesh, RULES_TP_SERVING, batch_size=2, max_new_tokens=8,
        refill_chunk=16, decode_block_steps=4, mixed=True,
        comm_compression=comm,
    )


@pytest.fixture(scope="module")
def plain_tokens(tp_mesh, served):
    params, prompts = served
    return _mixed_engine(tp_mesh).serve(params, prompts)


class TestQuantizedCollectives:
    def test_greedy_agreement_with_plain_engine(
        self, tp_mesh, served, plain_tokens
    ):
        params, prompts = served
        # probe every maintain tick so even this short serve exercises
        # the drift oracle (the default cadence is every 8th tick)
        eng = _mixed_engine(tp_mesh, CommCompression(drift_check_every=1))
        out = eng.serve(params, prompts)
        assert _same_tokens(plain_tokens, out)
        assert eng._c_comp_probes.value >= 1
        assert eng._c_comp_disagree.value == 0
        assert eng._c_comp_trips.value == 0
        names = {
            k: eng.contract_name(k)
            for k, _, _ in eng._dispatched_programs()
        }
        assert any(v.endswith("_q8") for v in names.values())

    def test_forced_trip_disables_then_matches_plain(
        self, tp_mesh, served, plain_tokens
    ):
        # Negative drift budget = the deterministic trip hook: the first
        # probe burns infinite budget, the ladder disables compression.
        params, prompts = served
        eng = _mixed_engine(
            tp_mesh,
            CommCompression(drift_budget=-1.0, drift_check_every=1),
        )
        eng.serve(params, prompts)
        assert eng._c_comp_trips.value == 1
        assert not eng.comm_compression_active
        # next serve retraces to the plain programs: bit-identical
        # fallback, contract names revert
        out = eng.serve(params, prompts)
        assert _same_tokens(plain_tokens, out)
        names = {
            k: eng.contract_name(k)
            for k, _, _ in eng._dispatched_programs()
        }
        assert not any(v.endswith("_q8") for v in names.values())

    def test_collectives_require_mixed_steps(self, tp_mesh):
        with pytest.raises(ValueError):
            ContinuousEngine(
                CFG, tp_mesh, RULES_TP_SERVING, batch_size=2,
                max_new_tokens=8, comm_compression=CommCompression(),
            )


# --------------------------------------------------------------------- #
# compressed KV pages — 1-device engine, cheap (test_zkv_economy idiom)
# --------------------------------------------------------------------- #

CFG_PAGED = dc.replace(CFG, decode_attention="blocked")


@pytest.fixture(scope="module")
def paged_params():
    model = Transformer(CFG_PAGED)
    return nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), np.zeros((2, 8), np.int32)
        )["params"]
    )


@pytest.fixture(scope="module")
def paged_engine(paged_params):
    mesh = build_mesh(
        (1, 1), ("data", "model"), devices=jax.devices()[:1]
    )
    eng = ContinuousEngine(
        CFG_PAGED, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=4,
        refill_chunk=8, paged_pages=12, page_size=4, prefix_cache=True,
        comm_compression=CommCompression(collectives=False),
    )
    prng = np.random.default_rng(23)
    prompt = prng.integers(
        1, CFG_PAGED.vocab_size, size=(9,)
    ).astype(np.int32)
    eng.serve(paged_params, [prompt])
    return eng


class TestCompressedKvPages:
    def test_spill_fill_respill_bit_identical(self, paged_engine):
        eng = paged_engine
        key = next(iter(eng.retained_prefixes()))
        rows, st = eng.spill_page(key, drop=True)
        assert st["raw_bytes"] > st["bytes"] > 0
        assert st["raw_bytes"] / st["bytes"] > 3  # f32 → ≈ 3.6× wire
        eng.fill_page(key, rows)
        rows2, _ = eng.spill_page(key, drop=True)
        for a, b in zip(rows, rows2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        eng.fill_page(key, rows2)

    def test_kv_programs_carry_q8_contracts(self, paged_engine):
        names = {
            paged_engine.contract_name(k)
            for k, _, _ in paged_engine._dispatched_programs()
        }
        assert "kv_page_spill_q8" in names
        assert "kv_page_fill_q8" in names


# --------------------------------------------------------------------- #
# tiered economy: delta codec against the version-stamped base
# --------------------------------------------------------------------- #


class TestTieredDeltaEconomy:
    def test_demote_delta_promote_cycle(self, paged_params):
        from learning_jax_sharding_tpu.fleet import (
            FleetPolicy,
            FleetRouter,
            KvEconomy,
            make_replicas,
        )
        from learning_jax_sharding_tpu.telemetry.flight_recorder import (
            FlightRecorder,
        )

        prng = np.random.default_rng(23)
        base = prng.integers(
            1, CFG_PAGED.vocab_size, size=(9,)
        ).astype(np.int32)
        reps = make_replicas(
            CFG_PAGED, RULES_DP_TP, paged_params, count=2,
            mesh_shape=(1, 1), batch_size=2, max_new_tokens=4,
            refill_chunk=8, paged_pages=12, page_size=4,
            prefix_cache=True,
            comm_compression=CommCompression(
                collectives=False, kv_codec="int8_delta"
            ),
        )
        econ = KvEconomy(hbm_retained_target=0, burn_threshold=1e9)
        router = FleetRouter(
            reps, policy=FleetPolicy(prefix_weight=0.5),
            kv_economy=econ, recorder=FlightRecorder(),
        )
        router.add_request(base)
        router.drain()  # drain() runs maintain(): pages tier eagerly
        rep = econ.tier_report()
        assert rep["demotions"] >= 2
        assert rep["raw_bytes"] > rep["spill_bytes"] > 0
        assert rep["compression_ratio"] > 1.5
        # already tiered at the live version → nothing left to demote
        assert econ.maintain() == 0

        # the stale entry is the delta base: unchanged rows re-spilled
        # against it ship (near) zero wire bytes and decode bit-identical
        hits = econ.predicted_hits(base)
        home = max(hits, key=hits.get)
        eng = router.replicas[home].engine
        tier = econ.tier_of(home)
        key = base[:4].tobytes()
        held = tier.base_rows(key)
        assert held is not None
        rows2, st = eng.spill_page(key, drop=False, base_rows=held)
        assert st["bytes"] < st["raw_bytes"] / 8
        for a, b in zip(held, rows2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # promotion books raw bytes alongside wire bytes
        for k in (base[:8].tobytes(), base[:4].tobytes()):
            eng.spill_page(k, drop=True)
        assert econ.promote(router.replicas[home], base) == 2
        rep2 = econ.tier_report()
        assert rep2["fill_bytes"] > 0
        assert rep2["raw_bytes"] > rep["raw_bytes"]
        assert router.goodput_report()["reconcile_ok"]

    def test_prefill_decode_handoff_ships_compressed(self, paged_params):
        from learning_jax_sharding_tpu.fleet import (
            FleetRouter,
            make_replicas,
        )
        from learning_jax_sharding_tpu.telemetry.flight_recorder import (
            FlightRecorder,
        )

        prng = np.random.default_rng(23)
        base = prng.integers(
            1, CFG_PAGED.vocab_size, size=(9,)
        ).astype(np.int32)
        pre = make_replicas(
            CFG_PAGED, RULES_DP_TP, paged_params, count=1,
            mesh_shape=(1, 1), role="prefill", batch_size=2,
            max_new_tokens=1, refill_chunk=8,
        )
        dec = make_replicas(
            CFG_PAGED, RULES_DP_TP, paged_params, count=1,
            mesh_shape=(1, 1), role="decode", offset=1, batch_size=2,
            max_new_tokens=4, refill_chunk=8,
        )
        router = FleetRouter(
            pre + dec, kv_codec="int8", recorder=FlightRecorder()
        )
        router.add_request(base)
        router.drain()
        snap = router.registry.snapshot()
        wire = snap["fleet_kv_transfer_bytes_total"]
        raw = snap["fleet_kv_raw_bytes_total"]
        assert raw > wire > 0
        # acceptance: wire bytes per request ≥ 1.8× reduced
        assert snap["fleet_kv_compression_ratio"] >= 1.8
        assert router.goodput_report()["reconcile_ok"]


# --------------------------------------------------------------------- #
# priced and searchable
# --------------------------------------------------------------------- #


def _reduce_event(nbytes=1 << 20, axis="model", in_loop=False, trip=None):
    from learning_jax_sharding_tpu.analysis.shardflow import CommEvent

    return CommEvent(
        kind="reduce", axes=(axis,), bytes=nbytes, where="x.py:1",
        primitive="dot_general", reason="pending partial sum",
        realizations=(("all-reduce", axis),), in_loop=in_loop, trip=trip,
    )


class TestPricedCompression:
    def test_quantize_events_reweights_reduces_only(self):
        from learning_jax_sharding_tpu.analysis.shardflow import CommEvent
        from learning_jax_sharding_tpu.parallel.compression import (
            wire_scale,
        )

        red = _reduce_event()
        gather = CommEvent(
            kind="reshard", axes=("model",), bytes=1 << 20,
            where="x.py:2", primitive="dot_general", reason="gather",
            realizations=(("all-gather", "model"),),
        )
        out = costmodel.quantize_events([red, gather], ("model",))
        assert out[0].bytes == int(
            np.ceil(red.bytes * wire_scale(4, 32))
        )
        assert "[int8 block-scaled wire]" in out[0].reason
        assert out[1].bytes == gather.bytes  # pure movement: untouched
        # idempotent — the reason marker guards double quantization
        again = costmodel.quantize_events(out, ("model",))
        assert again[0].bytes == out[0].bytes
        # other axes untouched
        flat = costmodel.quantize_events([red], ("data",))
        assert flat[0].bytes == red.bytes

    def test_codec_overhead_scales_with_trip(self):
        prof = costmodel.table_profile("TPU v5 lite")
        once = costmodel.codec_overhead_s(
            [_reduce_event()], ("model",), prof
        )
        looped = costmodel.codec_overhead_s(
            [_reduce_event(in_loop=True, trip=7)], ("model",), prof
        )
        assert once > 0
        assert looped == pytest.approx(7 * once)

    def test_seeded_case_flat_declines_two_tier_accepts(self):
        # The headline search story: on flat pricing (CPU-calibrated,
        # link ≈ HBM) the codec passes cost more than the 1.08× wire
        # they save, so the move is declined; under two-tier pricing
        # the leading (DCN) axis all-reduce flips to int8.
        from learning_jax_sharding_tpu.analysis.entrypoints import (
            build_search_inputs,
        )
        from learning_jax_sharding_tpu.analysis.layout_search import (
            search_layout,
        )
        from learning_jax_sharding_tpu.analysis.topology import (
            reference_two_tier,
        )

        si = build_search_inputs("train_step")
        mesh = si["mesh"]
        common = dict(
            mesh=mesh, budget=8, max_sweeps=1,
            while_trip_hint=si.get("while_trip_hint"),
        )
        flat = search_layout(
            si["name"], si["fn"], *si["args"], **common, **si["kwargs"]
        )
        assert flat.quantized_axes == ()
        assert flat.quantize_comm_s is None

        topo = reference_two_tier(
            tuple(str(a) for a in mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        )
        tiered = search_layout(
            si["name"], si["fn"], *si["args"], **common,
            topology=topo,
            profile=costmodel.table_profile("TPU v5 lite"),
            **si["kwargs"],
        )
        assert "data" in tiered.quantized_axes  # the DCN grad-sync axis
        qs = tiered.quantize_comm_s
        assert qs["q8_wire_s"] + qs["codec_overhead_s"] < qs["fp_wire_s"]
        assert tiered.to_dict()["quantized_axes"] == list(
            tiered.quantized_axes
        )


class TestUncountedCompressionLint:
    def test_codec_calls_outside_seams_flagged(self):
        from learning_jax_sharding_tpu.analysis.source_lint import (
            lint_source,
        )

        text = (
            "from learning_jax_sharding_tpu.parallel.compression import"
            " quantize_blocks, Int8Codec\n"
            "codec = Int8Codec()\n"
            "q, s = quantize_blocks(x, 32)\n"
            "p = codec.encode(x)\n"
            "y = self._kv_codec.decode(p)\n"
            "b = name.encode('utf-8')\n"          # str.encode: exempt
            "t = tokenizer.decode(ids)\n"         # not a codec: exempt
        )
        hits = [
            f for f in lint_source(
                "learning_jax_sharding_tpu/models/example.py", text=text
            )
            if f.rule == "uncounted-compression"
        ]
        assert len(hits) == 3

    def test_seam_files_exempt(self):
        from learning_jax_sharding_tpu.analysis.source_lint import (
            lint_source,
        )

        hits = [
            f for f in lint_source(
                "learning_jax_sharding_tpu/parallel/compression.py",
                text="q, s = quantize_blocks(x, 32)\n",
            )
            if f.rule == "uncounted-compression"
        ]
        assert hits == []

    def test_current_tree_is_clean(self):
        # the rule ships with ZERO baseline suppressions: every codec
        # call in the repo flows through a counted seam
        import pathlib

        from learning_jax_sharding_tpu.analysis.source_lint import (
            lint_source,
        )

        root = pathlib.Path(
            "learning_jax_sharding_tpu"
        )
        bad = []
        for p in sorted(root.rglob("*.py")):
            bad += [
                f for f in lint_source(p.as_posix())
                if f.rule == "uncounted-compression"
            ]
        assert bad == [], [f.where for f in bad]
