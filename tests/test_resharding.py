"""parallel.resharding: whole-tree redistribution edge cases.

The KV-handoff shape of the core (seq dims, stop clipping, page splits)
is pinned by tests/test_zfleet.py; this module pins the WEIGHT-HOT-SWAP
shape: uneven (non-divisible) shard boundaries, replicated↔sharded in
both directions, dtype preservation for quantized trees, host (numpy)
leaves, and the device fast path's bit-identity + jit-cache reuse —
plus (round 21) the two-tier DOMAIN SPLIT: every plan's wire volume
partitions exactly into intra-ICI-domain vs cross-domain (DCN) bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.analysis.topology import reference_two_tier
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.resharding import (
    device_reshard,
    plan_transfer,
    reshard_tree,
    transfer_tree,
)


def _ns(mesh, *spec):
    return NamedSharding(mesh, P(*spec))


@pytest.fixture(scope="module")
def mesh14():
    return build_mesh((1, 4), ("data", "model"), devices=jax.devices()[:4])


@pytest.fixture(scope="module")
def mesh13():
    # A 3-way model axis: its shard boundaries can NEVER nest inside a
    # 2- or 4-way split of the same dim — the uneven-intersection case.
    return build_mesh((1, 3), ("data", "model"), devices=jax.devices()[:3])


def test_uneven_boundaries_roundtrip(mesh24, mesh13):
    """Misaligned shard boundaries: (6,) split 3 ways (2+2+2) moved to a
    2-way split (3+3) — neither block size divides the other, so the
    plan must emit straddling partial segments; round-trip back and
    every element lands exactly once per destination holder."""
    x = jnp.arange(6, dtype=jnp.float32)
    src = jax.device_put(x, _ns(mesh13, "model"))
    dst_sh = _ns(mesh24, "x")
    out, stats = reshard_tree([src], [dst_sh], mode="host")
    (moved,) = out
    assert moved.sharding.is_equivalent_to(dst_sh, moved.ndim)
    np.testing.assert_array_equal(np.asarray(moved), np.asarray(x))
    # dim0 split by x (2-way); the unused y axis replicates each half
    # across 4 devices — 4 honest copies on the wire.
    assert stats["bytes"] == 4 * x.nbytes
    back, _ = reshard_tree([moved], [_ns(mesh13, "model")], mode="host")
    np.testing.assert_array_equal(np.asarray(back[0]), np.asarray(x))


def test_uneven_2d_cross_axis(mesh24, mesh13):
    """(6, 4) rows 3-way (2+2+2) → fully sharded 2×4 on another mesh:
    the 2-vs-3-way row boundaries straddle, producing partial segments
    on both sides of every destination row split."""
    x = jnp.arange(24, dtype=jnp.float32).reshape(6, 4)
    src = jax.device_put(x, _ns(mesh13, "model", None))
    out, stats = reshard_tree({"w": src}, {"w": _ns(mesh24, "x", "y")},
                              mode="host")
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(x))
    # Row intersections {(0,2),(2,3),(3,4),(4,6)} × 4 col blocks; fully
    # sharded destination → each element crosses the wire exactly once.
    assert stats["segments"] == 4 * 4
    assert stats["bytes"] == x.nbytes


def test_replicated_to_sharded(mesh24):
    x = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
    src = jax.device_put(x, _ns(mesh24))
    out, stats = reshard_tree([src], [_ns(mesh24, "x", "y")], mode="host")
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x))
    # Replicated source dedups to ONE elected owner: exactly the array's
    # bytes cross the wire, not 8 copies.
    assert stats["bytes"] == x.nbytes


def test_sharded_to_replicated(mesh24):
    x = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
    src = jax.device_put(x, _ns(mesh24, "x", "y"))
    out, stats = reshard_tree([src], [_ns(mesh24)], mode="host")
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x))
    # Destination replication is honestly priced: one copy per holder.
    assert stats["bytes"] == 8 * x.nbytes


@pytest.mark.parametrize("dtype", ["int8", "int4", "bfloat16"])
def test_dtype_preserved_quantized_tree(mesh24, dtype):
    """A quantized tree reshards bit-for-bit: dtypes preserved exactly,
    values unchanged — nothing in the path casts."""
    dt = jnp.dtype(dtype)
    vals = np.arange(-8, 8).reshape(4, 4)
    x = jnp.asarray(vals, dt)
    tree = {"q": jax.device_put(x, _ns(mesh24, "x", None)),
            "scale": jax.device_put(jnp.float32(0.5), _ns(mesh24))}
    dst = {"q": _ns(mesh24, None, "y"), "scale": _ns(mesh24)}
    out, _ = reshard_tree(tree, dst, mode="host")
    assert out["q"].dtype == dt
    assert out["scale"].dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(out["q"].astype(jnp.int32)), vals,
    )


def test_host_numpy_leaves_committed(mesh24):
    """Checkpoint-restore shape: plain numpy leaves land directly under
    the destination sharding, no prior device commit required."""
    x = np.arange(32, dtype=np.float32).reshape(4, 8)
    out, stats = reshard_tree(
        {"w": x, "b": np.float32(3.0)},
        {"w": _ns(mesh24, "x", "y"), "b": _ns(mesh24)},
    )
    assert isinstance(out["w"], jax.Array)
    assert out["w"].sharding.is_equivalent_to(_ns(mesh24, "x", "y"), 2)
    np.testing.assert_array_equal(np.asarray(out["w"]), x)
    assert float(out["b"]) == 3.0
    assert stats["mode"] == "host"


def test_auto_picks_device_path_same_mesh(mesh24):
    """Intra-mesh layout change (train → serve layout on one device set)
    takes the single-program device path; the result is bit-identical to
    the host plan."""
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    src = jax.device_put(x, _ns(mesh24, "x", None))
    dst = {"w": _ns(mesh24, None, "y")}
    jit_cache: dict = {}
    out, stats = reshard_tree({"w": src}, dst, jit_cache=jit_cache)
    assert stats["mode"] == "device"
    assert len(jit_cache) == 1
    host_out, _ = reshard_tree({"w": src}, dst, mode="host")
    np.testing.assert_array_equal(
        np.asarray(out["w"]), np.asarray(host_out["w"]),
    )
    # Same (treedef, layout) swap reuses the compiled program.
    out2, _ = reshard_tree({"w": src}, dst, jit_cache=jit_cache)
    assert len(jit_cache) == 1
    np.testing.assert_array_equal(np.asarray(out2["w"]), np.asarray(x))


def test_auto_falls_back_to_host_cross_mesh(mesh24, mesh14):
    """Different device sets (8-device train mesh → 4-device serve mesh)
    can't be one program — auto must take the host plan."""
    x = jnp.arange(8, dtype=jnp.float32)
    src = jax.device_put(x, _ns(mesh24, "x"))
    out, stats = reshard_tree([src], [_ns(mesh14, "model")])
    assert stats["mode"] == "host"
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x))


def test_device_reshard_rejects_foreign_devices(mesh24, mesh14):
    x = jax.device_put(jnp.arange(8, dtype=jnp.float32), _ns(mesh24, "x"))
    with pytest.raises(ValueError):
        device_reshard([x], [_ns(mesh14, "model")])


def test_plan_cache_reused_across_trees(mesh24):
    """Two same-layout leaves share one plan; a third layout adds one."""
    a = jax.device_put(jnp.ones((4, 4)), _ns(mesh24, "x", None))
    b = jax.device_put(jnp.ones((4, 4)), _ns(mesh24, "x", None))
    c = jax.device_put(jnp.ones((2, 4)), _ns(mesh24))
    cache: dict = {}
    dst = [_ns(mesh24, None, "y"), _ns(mesh24, None, "y"), _ns(mesh24, "x", "y")]
    reshard_tree([a, b, c], dst, plan_cache=cache, mode="host")
    assert len(cache) == 2
    reshard_tree([a, b, c], dst, plan_cache=cache, mode="host")
    assert len(cache) == 2


def test_plan_transfer_whole_leaf_matches_nbytes(mesh24):
    """seq_dim=None plans cover the leaf exactly once per destination
    holder — bytes_total is an invariant the swap telemetry reports."""
    sh = _ns(mesh24, "x", "y")
    plan = plan_transfer((8, 8), 4, sh, _ns(mesh24, "y", None))
    # Destination leaves x unused → every byte lands on 2 replicas.
    assert plan.bytes_total == 2 * 8 * 8 * 4


# --- two-tier domain split (round 21) -----------------------------------

#: (2,4) 'x','y' with the leading axis crossing hosts: devices 0–3 are
#: ICI domain 0, devices 4–7 domain 1 — build_mesh's row-major carving.
TOPO_24 = reference_two_tier(("x", "y"), (2, 4))


class TestDomainSplit:
    def test_split_sums_to_plan_bytes(self, mesh24, mesh13):
        """Cross-sub-mesh plan (3-device mesh → full 2×4 mesh, uneven
        boundaries): the ICI/DCN partition is exhaustive and exclusive
        — the two buckets sum EXACTLY to bytes_total, segments too."""
        x = jnp.arange(24, dtype=jnp.float32).reshape(6, 4)
        src = jax.device_put(x, _ns(mesh13, "model", None))
        plan = plan_transfer(
            (6, 4), 4, src.sharding, _ns(mesh24, "x", "y"),
        )
        split = plan.domain_split(TOPO_24)
        assert split["ici_bytes"] + split["dcn_bytes"] == plan.bytes_total
        assert (
            split["ici_segments"] + split["dcn_segments"]
            == len(plan.segments)
        )
        # Sources live on devices 0–2 (domain 0); the x=1 half of the
        # destination lives on devices 4–7 (domain 1) — bytes MUST
        # cross, and the intra-domain half must not be billed as DCN.
        assert split["dcn_bytes"] > 0
        assert split["ici_bytes"] > 0

    def test_cross_sub_mesh_handoff_is_all_dcn(self):
        """Two disjoint sub-meshes in different ICI domains (the
        disaggregated prefill→decode shape): every handoff byte is a
        cross-domain hop — and a finer-grained topology that puts both
        sub-meshes in ONE domain prices the same plan at zero DCN."""
        devs = jax.devices()
        a = build_mesh((1, 2), ("data", "model"), devices=devs[:2])
        b = build_mesh((1, 2), ("data", "model"), devices=devs[4:6])
        x = jnp.arange(16, dtype=jnp.float32).reshape(2, 8)
        src = jax.device_put(
            x, NamedSharding(a, P(None, "model")),
        )
        plan = plan_transfer(
            (2, 8), 4, src.sharding, NamedSharding(b, P(None, "model")),
        )
        split = plan.domain_split(TOPO_24)        # grain 4: a vs b cross
        assert split["dcn_bytes"] == plan.bytes_total == x.nbytes
        assert split["ici_bytes"] == 0
        one_domain = reference_two_tier(("x", "y"), (1, 8))   # grain 8
        merged = plan.domain_split(one_domain)
        assert merged["dcn_bytes"] == 0
        assert merged["ici_bytes"] == plan.bytes_total

    def test_replicated_source_dedup_no_dcn_double_charge(self, mesh24):
        """A fully-replicated source elects ONE owner; the cross-domain
        bill is only the bytes that actually land in the OTHER domain —
        not one copy per source replica (which would double-charge DCN
        8×)."""
        x = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
        src = jax.device_put(x, _ns(mesh24))
        plan = plan_transfer(
            (4, 4), 4, src.sharding, _ns(mesh24, "x", "y"),
        )
        assert plan.bytes_total == x.nbytes      # dedup: one copy total
        split = plan.domain_split(TOPO_24)
        # The elected owner sits in one domain; exactly the x=1 half of
        # the destination (half the array) lives in the other.
        assert split["dcn_bytes"] == x.nbytes // 2
        assert split["ici_bytes"] == x.nbytes - x.nbytes // 2

    @pytest.mark.parametrize("dtype", ["int8", "int4", "bfloat16"])
    def test_quantized_tree_preserves_split(self, mesh24, dtype):
        """The domain split is itemsize-exact for quantized leaves, and
        transfer_tree's topology-aware totals agree with the static
        per-plan split (whole-leaf move: actuals == plan)."""
        dt = jnp.dtype(dtype)
        vals = np.arange(-8, 8).reshape(4, 4)
        x = jax.device_put(jnp.asarray(vals, dt), _ns(mesh24, "x", None))
        dst = _ns(mesh24, None, "y")
        plan = plan_transfer(
            (4, 4), dt.itemsize, x.sharding, dst,
        )
        split = plan.domain_split(TOPO_24)
        assert split["ici_bytes"] + split["dcn_bytes"] == plan.bytes_total
        out, stats = transfer_tree(
            [x], [dst], seq_dims=[-1], topology=TOPO_24,
        )
        assert out[0].dtype == dt
        assert stats["bytes"] == plan.bytes_total
        assert stats["dcn_bytes"] == split["dcn_bytes"]

    def test_host_endpoints_stay_intra_domain(self, mesh24):
        """A device→host spill plan has no device pair to cross — the
        host hop is already explicit in the plan's own bytes, so the
        DCN bucket must stay empty (no double count)."""
        from learning_jax_sharding_tpu.parallel.resharding import (
            HostBuffer,
        )

        x = jax.device_put(
            jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
            _ns(mesh24, "x", None),
        )
        plan = plan_transfer((4, 4), 4, x.sharding, HostBuffer())
        split = plan.domain_split(TOPO_24)
        assert split["dcn_bytes"] == 0
        assert split["ici_bytes"] == plan.bytes_total
