"""Metrics logger + profiling/debug contexts (SURVEY.md §5 subsystems)."""

import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.utils import MetricsLogger, checking, trace
from learning_jax_sharding_tpu.utils.profiling import annotate


class TestMetricsLogger:
    def test_records_loss_throughput_and_jsonl(self, tmp_path):
        path = tmp_path / "m" / "metrics.jsonl"
        stream = io.StringIO()
        with MetricsLogger(
            path, stream=stream, flops_per_step=1e9, tokens_per_step=1024,
            n_devices=2,
        ) as m:
            for step in range(3):
                rec = m.log(step, loss=jnp.float32(2.5 - step))
                assert rec is not None

        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert [r["step"] for r in lines] == [0, 1, 2]
        assert lines[0]["loss"] == 2.5
        # First step has no predecessor → no rate fields.
        assert "seconds_per_step" not in lines[0]
        for r in lines[1:]:
            assert r["seconds_per_step"] > 0
            assert r["tokens_per_second"] == pytest.approx(
                1024 / r["seconds_per_step"]
            )
            assert r["tflops_per_chip"] == pytest.approx(
                1e9 / r["seconds_per_step"] / 2 / 1e12
            )
        out = stream.getvalue()
        assert "loss 2.5000" in out and "ms/step" in out and "tok/s" in out

    def test_log_every_skips_but_still_syncs(self):
        with MetricsLogger(stream=None, log_every=5) as m:
            recs = [m.log(s, loss=float(s)) for s in range(11)]
        assert [r["step"] for r in recs if r is not None] == [0, 5, 10]
        assert len(m.history) == 3

    def test_extra_scalars(self):
        with MetricsLogger(stream=None) as m:
            rec = m.log(0, loss=1.0, grad_norm=jnp.float32(0.25), lr=3e-4)
        assert rec["grad_norm"] == 0.25 and rec["lr"] == 3e-4


class TestProfiling:
    def test_trace_writes_profile(self, tmp_path):
        logdir = tmp_path / "profile"
        with trace(logdir):
            with annotate("bench_block"):
                np.asarray(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
        # A capture landed: jax.profiler writes plugins/profile/<run>/...
        dumped = list(logdir.rglob("*.xplane.pb"))
        assert dumped, f"no xplane capture under {logdir}"

    def test_checking_traps_nan_and_restores(self):
        prev = jax.config.jax_debug_nans
        with pytest.raises(FloatingPointError):
            with checking():
                jnp.divide(jnp.zeros(()), jnp.zeros(()))  # 0/0 → NaN
        assert jax.config.jax_debug_nans == prev

    def test_checking_no_leak_after_raise_mid_dispatch(self):
        """Regression (round 6): a check-laden executable compiled INSIDE
        the context — on the very dispatch that raises — must not serve
        post-context calls. The restore path runs while unwinding that
        exception, so it must clear caches before (and regardless of)
        the flag restores; afterwards the same jitted fn must produce its
        NaN silently."""
        f = jax.jit(lambda x: x / x)
        prev_nans = jax.config.jax_debug_nans
        prev_checks = jax.config.jax_enable_checks
        with pytest.raises(FloatingPointError):
            with checking():
                f(jnp.zeros(()))      # compiles under checks, raises
        assert jax.config.jax_debug_nans == prev_nans
        assert jax.config.jax_enable_checks == prev_checks
        out = np.asarray(f(jnp.zeros(())))   # re-dispatch: NO trap
        assert np.isnan(out)

    def test_checking_restores_when_block_raises_mid_compile(self):
        """An error raised while TRACING inside the block (before any
        executable exists) must restore both flags too."""
        prev_nans = jax.config.jax_debug_nans
        prev_checks = jax.config.jax_enable_checks
        with pytest.raises(TypeError):
            with checking():
                jax.jit(lambda x: jnp.reshape(x, (3,)))(jnp.zeros((4,)))
        assert jax.config.jax_debug_nans == prev_nans
        assert jax.config.jax_enable_checks == prev_checks
        # And a fresh compile afterwards is check-free.
        assert np.isnan(
            np.asarray(jax.jit(lambda x: x / x)(jnp.zeros(())))
        )


class TestBenchUtils:
    def test_time_fn_measures_per_iteration_cost(self):
        """The k/2k differencing recovers per-call cost with fixed overhead
        cancelled: a fn that sleeps 2 ms measures ≈2 ms, not 2 ms + L."""
        import time

        import numpy as np

        def fn():
            time.sleep(0.002)
            return np.zeros(())

        from learning_jax_sharding_tpu.utils.bench import time_fn

        per = time_fn(fn, warmup=1, min_time=0.05, repeats=2)
        # Sleep overshoot isn't a fixed latency the k/2k diff can cancel, so
        # only bound loosely: clearly the sleep, not sleep + a ~100 ms L.
        assert 0.0015 < per < 0.01, per

    def test_compiled_flops_counts_matmul(self):
        import jax
        import jax.numpy as jnp

        from learning_jax_sharding_tpu.utils.bench import compiled_flops

        a = jnp.ones((64, 128), jnp.float32)
        b = jnp.ones((128, 32), jnp.float32)
        flops = compiled_flops(lambda a, b: a @ b, a, b)
        # 2*M*N*K, allow XLA accounting slack either way.
        assert flops is not None
        assert 0.5 * 2 * 64 * 128 * 32 <= flops <= 2 * 2 * 64 * 128 * 32
