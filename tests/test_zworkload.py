"""Workload observatory (round 20): loadgen traces + per-tenant economics.

Named to sort LAST alongside ``test_zfleet`` / ``test_zero_downtime``
(same rationale: the end-to-end oracles build multi-replica fleets, and
the tier-1 window should spend its budget on the fast oracles first).

Four layers, cheapest first:

* the TRACE FORMAT as a contract — generation is deterministic, the
  JSONL bytes regenerate identically (including the checked-in canonical
  day), prompt content resynthesizes from ``(seed, rid)`` alone, and the
  reader refuses versions/counts it cannot honor;
* the tenant-labeled SLO extension — an UNLABELED monitor stays
  bit-compatible with the pre-tenant one, hostile tenant names cannot
  corrupt the Prometheus exposition (the escaping satellite);
* the CONSERVATION INVARIANT on a replayed K=2 fleet — Σ per-tenant
  attributed device-seconds equals the fleet ledger's device bucket,
  every admitted request lands in exactly ONE tenant roll-up (ok, shed,
  rerouted — none double-billed, none vanish), and a mid-replay replica
  kill books the wasted reroute legs to the ORIGINATING tenant;
* REPLAY DETERMINISM — same seed + same trace through a fresh fleet
  reproduces the admission order and the byte-identical
  ``deterministic`` subtree of economics.json.
"""

import dataclasses
import json
import re

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.fleet import (
    FleetPolicy,
    FleetRouter,
    FlashCrowd,
    TenantSpec,
    TraceSpec,
    canonical_day_spec,
    canonical_trace_path,
    generate_trace,
    make_replicas,
    read_trace,
    replay_trace,
    synth_prompt,
    write_trace,
)
from learning_jax_sharding_tpu.models.serving import RequestFailure
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.robustness import ChaosInjector, Fault
from learning_jax_sharding_tpu.telemetry import (
    MetricsRegistry,
    OVERHEAD_TENANT,
    SLOMonitor,
    SLOTarget,
    deterministic_view,
    fleet_economics,
)
from learning_jax_sharding_tpu.telemetry.registry import (
    escape_label_value,
    labeled_name,
)

#: A tenant name crafted to break Prometheus text exposition unless label
#: values are escaped (terminates the label set early, smuggles a fake
#: sample) — threaded through the FULL path: trace → fleet → SLO series
#: → economics gauges.
HOSTILE = 'evil"} 1'

#: One sample per physical line, label set intact. Family names may
#: carry dots (SLO target names embed thresholds: ``slo_e2e_le_0.2_…``);
#: what must NEVER appear is a raw quote/newline escaping a label value.
_EXPO_LINE = re.compile(r"^[A-Za-z_][\w:.]*(\{.*\})? [^ ]+$")


def _assert_exposition_parses(text: str) -> None:
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _EXPO_LINE.match(line), (
            f"corrupt exposition line: {line!r}"
        )


def _spec() -> TraceSpec:
    return TraceSpec(
        duration_s=2.0,
        seed=9,
        tenants=(
            TenantSpec(
                "alpha", rate_rps=3.0, prompt_len_min=3,
                prompt_len_tail=2.0, prompt_len_max=10,
            ),
            TenantSpec(
                "beta", rate_rps=2.0, burstiness=2.0, prompt_len_min=4,
                prompt_len_tail=3.0, prompt_len_max=12,
            ),
            TenantSpec(
                HOSTILE, rate_rps=1.5, prompt_len_min=3,
                prompt_len_tail=2.0, prompt_len_max=8,
            ),
        ),
    )


@pytest.fixture(scope="module")
def built():
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(5), np.zeros((2, 8), np.int32)
        )["params"]
    )
    return cfg, params


def _fleet(cfg, params, *, slo=None, max_inflight=None):
    kw = dict(batch_size=2, max_new_tokens=6, refill_chunk=8)
    if slo is not None:
        kw["slo"] = slo
    reps = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 1), **kw,
    )
    policy = (
        FleetPolicy(max_inflight=max_inflight)
        if max_inflight is not None else None
    )
    return reps, FleetRouter(reps, policy=policy)


def _cap(events) -> int:
    """Admission cap sized to force a few fleet-level sheds: unpaced
    replay admits the whole trace up front, so exactly the trailing
    ``len(events) - cap`` arrivals shed."""
    return max(4, len(events) - 3)


@pytest.fixture(scope="module")
def replayed(built):
    """ONE replayed K=2 fleet shared by the conservation-side tests:
    trace in, economics out, with per-tenant SLO burn (threshold pinned
    below any real e2e, so every retirement breaches — burn rates are
    exactly budget⁻¹ = 2.0) and a shed-forcing admission cap."""
    cfg, params = built
    spec = _spec()
    events = generate_trace(spec)
    slo = SLOMonitor([SLOTarget("e2e", 1e-6, objective=0.5)])
    reps, router = _fleet(
        cfg, params, slo=slo, max_inflight=_cap(events),
    )
    rep = replay_trace(
        router, events, seed=spec.seed, vocab_size=cfg.vocab_size,
        pace=False,
    )
    econ = fleet_economics(router, replay=rep, slo=slo)
    return spec, events, router, rep, econ


class TestTraceFormat:
    def test_generation_is_deterministic_and_sorted(self):
        a, b = generate_trace(_spec()), generate_trace(_spec())
        assert a == b
        assert len(a) >= 8
        assert [e["rid"] for e in a] == list(range(len(a)))
        assert all(
            a[i]["t"] <= a[i + 1]["t"] for i in range(len(a) - 1)
        )
        assert {e["tenant"] for e in a} == {"alpha", "beta", HOSTILE}

    def test_write_trace_is_byte_identical(self, tmp_path):
        p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        ev1 = write_trace(p1, _spec())
        ev2 = write_trace(p2, _spec())
        assert ev1 == ev2
        assert p1.read_bytes() == p2.read_bytes()
        header, events = read_trace(p1)
        assert events == ev1
        assert header["seed"] == 9 and header["events"] == len(ev1)

    def test_canonical_trace_regenerates_byte_identical(self, tmp_path):
        """The checked-in canonical day IS its spec's output — a drifted
        generator (or a hand-edited trace) fails here, which is the
        replayability guarantee bench_economics leans on."""
        regen = tmp_path / "canonical.jsonl"
        write_trace(regen, canonical_day_spec())
        assert regen.read_bytes() == canonical_trace_path().read_bytes()

    def test_reader_refuses_wrong_version_and_count(self, tmp_path):
        p = tmp_path / "t.jsonl"
        write_trace(p, _spec())
        lines = p.read_text().splitlines()
        header = json.loads(lines[0])
        header["trace_version"] = 99
        (tmp_path / "v.jsonl").write_text(
            "\n".join([json.dumps(header)] + lines[1:]) + "\n"
        )
        with pytest.raises(ValueError, match="version"):
            read_trace(tmp_path / "v.jsonl")
        (tmp_path / "c.jsonl").write_text("\n".join(lines[:-1]) + "\n")
        with pytest.raises(ValueError, match="promises"):
            read_trace(tmp_path / "c.jsonl")

    def test_synth_prompt_deterministic_keyed_by_rid(self):
        a = synth_prompt(9, 3, 12, 256)
        b = synth_prompt(9, 3, 12, 256)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == np.int32 and a.shape == (12,)
        assert a.min() >= 1 and a.max() < 256
        assert not np.array_equal(a, synth_prompt(9, 4, 12, 256))
        assert not np.array_equal(a, synth_prompt(10, 3, 12, 256))

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="rate_rps"):
            TenantSpec("x", rate_rps=0.0)
        with pytest.raises(ValueError, match="unique"):
            TraceSpec(
                duration_s=1.0,
                tenants=(
                    TenantSpec("x", rate_rps=1.0),
                    TenantSpec("x", rate_rps=2.0),
                ),
            )
        with pytest.raises(ValueError, match="unknown tenant"):
            TraceSpec(
                duration_s=1.0,
                tenants=(TenantSpec("x", rate_rps=1.0),),
                flash_crowds=(FlashCrowd("y", t_s=0.0, duration_s=1.0),),
            )
        with pytest.raises(ValueError, match="alpha"):
            TenantSpec("x", rate_rps=1.0, prompt_len_alpha=1.0)

    def test_flash_crowd_adds_arrivals_inside_window(self):
        base = _spec()
        crowd = dataclasses.replace(
            base,
            flash_crowds=(
                FlashCrowd(
                    "alpha", t_s=0.5, duration_s=1.0, multiplier=10.0
                ),
            ),
        )
        ev_base, ev_crowd = generate_trace(base), generate_trace(crowd)
        extra = len(ev_crowd) - len(ev_base)
        assert extra > 0
        # The added arrivals all live inside the crowd's window, and the
        # base process is untouched (additive, not reshaping).
        base_times = [e["t"] for e in ev_base]
        added = [e["t"] for e in ev_crowd if e["t"] not in base_times]
        assert len(added) == extra
        assert all(0.5 <= t < 1.5 for t in added)


class TestTenantSLO:
    def _feed(self, mon, tenants):
        for i in range(20):
            mon.observe(
                "e2e", 0.1 + 0.2 * (i % 2),
                tenant=tenants[i % len(tenants)] if tenants else None,
            )

    def test_unlabeled_monitor_bit_compatible(self):
        t = [SLOTarget("e2e", 0.2, objective=0.5)]
        plain, labeled = SLOMonitor(t), SLOMonitor(t)
        self._feed(plain, [])
        self._feed(labeled, ["a", "b"])
        sp, sl = plain.snapshot(), labeled.snapshot()
        # The aggregate (unlabeled) view is IDENTICAL — tenants only add.
        assert sp["targets"] == sl["targets"]
        assert sp["metrics"] == sl["metrics"]
        assert "tenants" not in sp and "tenants" in sl
        assert sl["tenants"]["a"]["e2e_le_0.2"]["events"] == 10

    def test_tenant_burn_isolated(self):
        mon = SLOMonitor([SLOTarget("e2e", 0.5, objective=0.5)])
        for _ in range(8):
            mon.observe("e2e", 1.0, tenant="hot")    # all breach
            mon.observe("e2e", 0.1, tenant="cold")   # none breach
        assert mon.tenant_burn_rate("e2e_le_0.5", "hot") == 2.0
        assert mon.tenant_burn_rate("e2e_le_0.5", "cold") == 0.0
        assert mon.tenant_burn_rate("e2e_le_0.5", "never-seen") == 0.0
        assert mon.burn_rate("e2e_le_0.5") == 1.0   # aggregate: half bad
        assert mon.tenant_burn_rates() == {
            "hot": {"e2e_le_0.5": 2.0}, "cold": {"e2e_le_0.5": 0.0},
        }

    def test_escape_label_value_exact(self):
        assert escape_label_value('evil"} 1') == 'evil\\"} 1'
        assert escape_label_value("back\\slash") == "back\\\\slash"
        assert escape_label_value("new\nline") == "new\\nline"
        assert (
            labeled_name("x_total", tenant='a"b\\c\nd')
            == 'x_total{tenant="a\\"b\\\\c\\nd"}'
        )

    def test_hostile_tenant_cannot_corrupt_exposition(self):
        reg = MetricsRegistry()
        mon = SLOMonitor(
            [SLOTarget("e2e", 0.2, objective=0.5)], registry=reg,
        )
        nasty = 'evil"} 1\n\\'
        for _ in range(4):
            mon.observe("e2e", 1.0, tenant=nasty)
        text = reg.prometheus_text()
        assert 'tenant="evil\\"} 1\\n\\\\"' in text
        _assert_exposition_parses(text)


class TestConservation:
    def test_conservation_gate(self, replayed):
        *_, econ = replayed
        cons = econ["measured"]["conservation"]
        assert cons["ok"], cons
        assert cons["residual_s"] <= cons["eps"]
        assert cons["device_total_s"] > 0
        assert econ["measured"]["fleet"]["reconcile_ok"]

    def test_every_request_in_exactly_one_rollup(self, replayed):
        spec, events, router, rep, econ = replayed
        rolls = econ["deterministic"]["tenants"]
        assert sum(
            r["requests"] for r in rolls.values()
        ) == len(rep["admission_order"])
        assert sum(r["shed"] for r in rolls.values()) == len(rep["shed"])
        assert len(rep["shed"]) == len(events) - _cap(events) > 0
        assert len(rep["admission_order"]) + len(rep["shed"]) == len(
            events
        ) == rep["offered"]
        assert set(rolls) <= {"alpha", "beta", HOSTILE}
        for ten, r in rolls.items():
            assert r["ok"] + sum(r["failed"].values()) == r["requests"]
            if r["ok"]:
                assert r["generated_tokens"] > 0
                assert r["prompt_tokens"] > 0

    def test_attributed_seconds_and_burn_per_tenant(self, replayed):
        *_, econ = replayed
        m = econ["measured"]
        served = {
            t for t, r in econ["deterministic"]["tenants"].items()
            if r["ok"]
        }
        for ten in served:
            mt = m["tenants"][ten]
            assert mt["device_seconds"] > 0
            assert mt["cost_usd"] > 0
            assert mt["cost_per_token_usd"] > 0
            # Threshold pinned below any real e2e: every retirement
            # breaches, so each served tenant burns exactly 1/budget.
            assert mt["worst_burn_rate"] == pytest.approx(2.0)
        assert m["worst_tenant"] in served
        assert m["worst_tenant_burn_rate"] == pytest.approx(2.0)
        assert m["worst_tenant"] != OVERHEAD_TENANT

    def test_hostile_tenant_survives_full_path(self, replayed):
        """The hostile name rode the trace → fleet → SLO → economics
        path; the router registry's exposition must still parse."""
        *_, router, rep, econ = replayed
        assert HOSTILE in econ["deterministic"]["tenants"]
        text = router.registry.prometheus_text()
        assert 'tenant="evil\\"} 1"' in text
        _assert_exposition_parses(text)


class TestKillAttribution:
    def test_mid_replay_kill_books_waste_to_originating_tenant(
        self, built
    ):
        """A replica dies mid-replay: its partial work reroutes and
        recomputes on the survivor; the thrown-away legs surface as
        per-tenant ``wasted_seconds`` on the tenants whose requests
        rerouted — and conservation still holds (the wasted seconds are
        real ledger seconds, attributed, not invented or dropped)."""
        cfg, params = built
        spec = _spec()
        events = generate_trace(spec)
        reps, router = _fleet(cfg, params)
        with ChaosInjector(
            Fault("fleet.step", "raise", at=2, count=1),
        ):
            rep = replay_trace(
                router, events, seed=spec.seed,
                vocab_size=cfg.vocab_size, pace=False,
            )
        assert sum(not r.alive for r in reps) == 1
        for rid, v in rep["results"].items():
            assert not isinstance(v, RequestFailure), (rid, v)
        assert set(rep["results"]) == set(rep["admission_order"])

        econ = fleet_economics(router, replay=rep, register=False)
        cons = econ["measured"]["conservation"]
        assert cons["ok"], cons
        rolls = econ["deterministic"]["tenants"]
        assert sum(
            r["requests"] for r in rolls.values()
        ) == len(rep["admission_order"])
        assert sum(r["reroutes"] for r in rolls.values()) >= 1
        wasted = {
            t: m["wasted_seconds"]
            for t, m in econ["measured"]["tenants"].items()
            if m["wasted_seconds"] > 0
        }
        assert wasted, "the kill must surface wasted reroute legs"
        # Waste books to the ORIGINATING tenant: only tenants whose own
        # requests rerouted may carry wasted seconds.
        for ten in wasted:
            assert rolls[ten]["reroutes"] >= 1, (ten, wasted, rolls)


class TestReplayDeterminism:
    def test_same_seed_same_trace_same_economics(self, built, replayed):
        """A FRESH fleet replaying the same trace reproduces the
        admission order, the shed set, and the byte-identical
        ``deterministic`` subtree of economics.json."""
        cfg, params = built
        spec, events, _, rep_a, econ_a = replayed
        slo = SLOMonitor([SLOTarget("e2e", 1e-6, objective=0.5)])
        reps, router = _fleet(
            cfg, params, slo=slo, max_inflight=_cap(events),
        )
        rep_b = replay_trace(
            router, events, seed=spec.seed, vocab_size=cfg.vocab_size,
            pace=False,
        )
        assert rep_b["admission_order"] == rep_a["admission_order"]
        assert rep_b["shed"] == rep_a["shed"]
        assert rep_b["tenant_of"] == rep_a["tenant_of"]
        econ_b = fleet_economics(router, replay=rep_b, register=False)
        assert json.dumps(
            deterministic_view(econ_b), sort_keys=True
        ) == json.dumps(deterministic_view(econ_a), sort_keys=True)
        # ... while the measured subtree is honest wall-clock (present,
        # reconciled, never asserted identical).
        assert econ_b["measured"]["conservation"]["ok"]
