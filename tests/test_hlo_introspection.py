"""parallel/hlo.py instruction-regex edge cases, pinned on HLO text.

``_INSTR_RE`` is the foundation the collective assertions (and now the
telemetry layer's per-step collective inventory) stand on. Its corner
cases are text-level, so they are pinned on realistic HLO snippets:
async ``-start``/``-done`` pairs count once, tuple-typed results match,
and op substrings inside fusion/computation NAMES are never counted.
"""

import pytest

from learning_jax_sharding_tpu.parallel.hlo import (
    COLLECTIVE_OPS,
    collective_counts,
    collective_instructions,
    constant_instructions,
    hlo_computations,
    while_scoped_computations,
)


class TestInstrRegexEdgeCases:
    def test_async_start_done_pair_counts_once(self):
        hlo = """
ENTRY %main {
  %p0 = f32[4,8]{1,0} parameter(0)
  %ag-start = (f32[4,8]{1,0}, f32[4,16]{1,0}) all-gather-start(f32[4,8]{1,0} %p0), replica_groups={{0,1}}, dimensions={1}
  %ag-done = f32[4,16]{1,0} all-gather-done((f32[4,8]{1,0}, f32[4,16]{1,0}) %ag-start)
}
"""
        counts = collective_counts(hlo)
        assert counts["all-gather"] == 1
        assert sum(counts.values()) == 1

    def test_async_all_reduce_pair_counts_once(self):
        hlo = """
  %ar-start = f32[64]{0} all-reduce-start(f32[64]{0} %x), to_apply=%add
  %ar-done = f32[64]{0} all-reduce-done(f32[64]{0} %ar-start)
"""
        assert collective_counts(hlo)["all-reduce"] == 1

    def test_tuple_typed_result_matches(self):
        # A sync collective whose RESULT is a tuple (spaces inside the
        # type) must still match the `= <type> <op>(` form.
        hlo = """
  %rs = (bf16[8,4]{1,0}, bf16[8,4]{1,0}) reduce-scatter(bf16[16,4]{1,0} %a, bf16[16,4]{1,0} %b), dimensions={0}, to_apply=%add
"""
        assert collective_counts(hlo)["reduce-scatter"] == 1

    def test_op_names_inside_fusion_names_not_counted(self):
        # "all-reduce" appears in the fusion NAME, the computation NAME,
        # and an operand name — none of those are instructions.
        hlo = """
%fused_all-reduce.clone (param_0: f32[4]) -> f32[4] {
  %param_0 = f32[4]{0} parameter(0)
  ROOT %m = f32[4]{0} multiply(f32[4]{0} %param_0, f32[4]{0} %param_0)
}

ENTRY %all-reduce_main {
  %x = f32[4]{0} parameter(0)
  %fusion.all-reduce.1 = f32[4]{0} fusion(f32[4]{0} %x), kind=kLoop, calls=%fused_all-reduce.clone
  ROOT %out = f32[4]{0} add(f32[4]{0} %fusion.all-reduce.1, f32[4]{0} %x)
}
"""
        counts = collective_counts(hlo)
        assert sum(counts.values()) == 0, counts

    def test_real_instruction_next_to_decoy_names(self):
        hlo = """
  %fusion.all-gather.7 = f32[8]{0} fusion(f32[8]{0} %p), kind=kLoop, calls=%c
  %real = f32[16]{0} all-gather(f32[8]{0} %fusion.all-gather.7), replica_groups={{0,1}}, dimensions={0}
"""
        counts = collective_counts(hlo)
        assert counts["all-gather"] == 1
        assert sum(counts.values()) == 1

    def test_every_op_kind_keyed_even_when_absent(self):
        counts = collective_counts("ENTRY %e { ROOT %r = f32[] constant(0) }")
        assert set(counts) == set(COLLECTIVE_OPS)
        assert all(v == 0 for v in counts.values())

    def test_collective_permute_and_all_to_all(self):
        hlo = """
  %cp = u32[2]{0} collective-permute(u32[2]{0} %x), source_target_pairs={{0,1},{1,0}}
  %a2a-start = (f32[4]{0}, f32[4]{0}) all-to-all-start(f32[4]{0} %y), replica_groups={{0,1}}
  %a2a-done = f32[4]{0} all-to-all-done((f32[4]{0}, f32[4]{0}) %a2a-start)
"""
        counts = collective_counts(hlo)
        assert counts["collective-permute"] == 1
        assert counts["all-to-all"] == 1

    def test_headerless_snippets_still_scan(self):
        # Instruction-only snippets (no computation headers) must keep
        # working: computation=None, never in_while.
        hlo = """
  %ar = f32[64]{0} all-reduce(f32[64]{0} %x), replica_groups={{0,1}}, to_apply=%add
"""
        [ins] = collective_instructions(hlo)
        assert ins["computation"] is None
        assert ins["in_while"] is False

    def test_compiled_function_counts_match_text_counts(self, mesh24, rng):
        """The regex against REAL compiler output: a psum matmul's
        optimized HLO must contain exactly the all-reduce the explicit
        collective promises (sync or async-pair spelled)."""
        from functools import partial

        from learning_jax_sharding_tpu.parallel.collectives import (
            psum_matmul,
        )
        from learning_jax_sharding_tpu.parallel.hlo import compiled_hlo
        from tests.conftest import matmul_operands

        a, b = matmul_operands(rng)
        text = compiled_hlo(partial(psum_matmul, mesh=mesh24, axis="y"), a, b)
        counts = collective_counts(text)
        assert counts["all-reduce"] >= 1
        # -done must never double an async pair: the done-op count is
        # bounded by (in fact equal to) the start/sync count.
        dones = text.count("all-reduce-done(")
        starts = text.count("all-reduce-start(")
        assert counts["all-reduce"] >= dones == starts


#: A realistic two-computation module: one collective in the ENTRY body,
#: one inside the while's body computation (whose params are tuple-typed —
#: nested parens the header parser must survive).
_WHILE_HLO = """
HloModule jit_f, is_scheduled=true, entry_computation_layout={(f32[4,4]{1,0})->f32[4,4]{1,0}}

%region_body (param: (s32[], f32[4,4])) -> (s32[], f32[4,4]) {
  %param = (s32[], f32[4,4]{1,0}) parameter(0)
  %gte = f32[4,4]{1,0} get-tuple-element((s32[], f32[4,4]{1,0}) %param), index=1
  %ar.body = f32[4,4]{1,0} all-reduce(f32[4,4]{1,0} %gte), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%add
}

%region_cond (param.1: (s32[], f32[4,4])) -> pred[] {
  %param.1 = (s32[], f32[4,4]{1,0}) parameter(0)
  ROOT %lt = pred[] compare(s32[] %c, s32[] %n), direction=LT
}

ENTRY %main_spmd (p0: f32[4,4]) -> f32[4,4] {
  %p0 = f32[4,4]{1,0} parameter(0)
  %ag.entry = f32[8,4]{1,0} all-gather(f32[4,4]{1,0} %p0), replica_groups={{0,4},{1,5},{2,6},{3,7}}, dimensions={0}
  %while.1 = (s32[], f32[4,4]{1,0}) while((s32[], f32[4,4]{1,0}) %tuple.0), condition=%region_cond, body=%region_body
}
"""


class TestWhileBodyScoping:
    """`_INSTR_RE` matches scoped to their computation: collectives inside
    while bodies are per-iteration cost and must be distinguishable from
    entry-body ones (the contract pass's ``while-loop-collective`` rule
    stands on this)."""

    def test_computations_split_with_tuple_typed_params(self):
        comps = hlo_computations(_WHILE_HLO)
        assert set(comps) == {"region_body", "region_cond", "main_spmd"}
        assert "all-reduce(" in comps["region_body"]
        assert "while(" in comps["main_spmd"]

    def test_while_scope_closure(self):
        assert while_scoped_computations(_WHILE_HLO) == {
            "region_body", "region_cond",
        }

    def test_instructions_carry_scope(self):
        by_op = {
            i["op"]: i for i in collective_instructions(_WHILE_HLO)
        }
        assert by_op["all-reduce"]["in_while"] is True
        assert by_op["all-reduce"]["computation"] == "region_body"
        assert by_op["all-gather"]["in_while"] is False
        assert by_op["all-gather"]["computation"] == "main_spmd"

    def test_counts_unaffected_by_scoping(self):
        counts = collective_counts(_WHILE_HLO)
        assert counts["all-reduce"] == 1
        assert counts["all-gather"] == 1

    def test_nested_call_from_while_body_is_scoped(self):
        hlo = """
%inner (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  %ar = f32[4]{0} all-reduce(f32[4]{0} %p), replica_groups={{0,1}}, to_apply=%add
}

%body (param: (s32[], f32[4])) -> (s32[], f32[4]) {
  %param = (s32[], f32[4]{0}) parameter(0)
  %fus = f32[4]{0} fusion(f32[4]{0} %x), kind=kLoop, calls=%inner
}

ENTRY %main (p0: f32[4]) -> f32[4] {
  %w = (s32[], f32[4]{0}) while((s32[], f32[4]{0}) %t), condition=%cond, body=%body
}
"""
        assert "inner" in while_scoped_computations(hlo)
        [ins] = collective_instructions(hlo)
        assert ins["in_while"] is True

    def test_pred_conditional_branch_in_while_body_is_scoped(self):
        # XLA prints two-branch conditionals as true_computation=/
        # false_computation= (not branch_computations) — a collective
        # hiding in such a branch inside a while body is per-iteration
        # cost and must be scoped.
        hlo = """
%branch_t (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  %ag = f32[8]{0} all-gather(f32[4]{0} %p), replica_groups={{0,1}}, dimensions={0}
}

%branch_f (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
}

%body (param: (s32[], f32[4])) -> (s32[], f32[4]) {
  %param = (s32[], f32[4]{0}) parameter(0)
  %c = f32[4]{0} conditional(pred[] %pr, f32[4]{0} %x, f32[4]{0} %x), true_computation=%branch_t, false_computation=%branch_f
}

ENTRY %main (p0: f32[4]) -> f32[4] {
  %w = (s32[], f32[4]{0}) while((s32[], f32[4]{0}) %t), condition=%cond, body=%body
}
"""
        assert {"branch_t", "branch_f"} <= while_scoped_computations(hlo)
        [ins] = collective_instructions(hlo)
        assert ins["in_while"] is True

    def test_reduction_to_apply_is_not_an_edge(self):
        # `to_apply=%add` names a scalar reducer, not executed-inside-loop
        # user code; following it would misfile computations named there.
        hlo = """
%body (param: (s32[], f32[4])) -> (s32[], f32[4]) {
  %param = (s32[], f32[4]{0}) parameter(0)
}

ENTRY %main (p0: f32[4]) -> f32[4] {
  %r = f32[] reduce(f32[4]{0} %p0, f32[] %z), dimensions={0}, to_apply=%add
  %w = (s32[], f32[4]{0}) while((s32[], f32[4]{0}) %t), condition=%cond, body=%body
}
"""
        assert "add" not in while_scoped_computations(hlo)


class TestRoutingAttributes:
    """``source_target_pairs`` / ``channel_id`` as record fields (round
    13): a collective-permute prints NO replica_groups — its routing
    lives entirely in the pair list — and channel-lowered collectives
    print an EMPTY ``replica_groups={}`` with the grouping carried by
    the channel. Both used to parse as bare ``replica_groups=None``
    records, indistinguishable from a groups-less snippet."""

    def test_permute_source_target_pairs_parse(self):
        hlo = """
  %cp = bf16[4,8]{1,0} collective-permute(bf16[4,8]{1,0} %x), channel_id=3, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
"""
        [ins] = collective_instructions(hlo)
        assert ins["op"] == "collective-permute"
        assert ins["source_target_pairs"] == [[0, 1], [1, 2], [2, 3], [3, 0]]
        assert ins["channel_id"] == 3
        assert ins["replica_groups"] is None

    def test_async_permute_pair_counts_once_with_pairs(self):
        hlo = """
  %cp-start = (u32[2]{0}, u32[2]{0}) collective-permute-start(u32[2]{0} %x), channel_id=1, source_target_pairs={{0,1},{1,0}}
  %cp-done = u32[2]{0} collective-permute-done((u32[2]{0}, u32[2]{0}) %cp-start)
"""
        [ins] = collective_instructions(hlo)
        assert ins["source_target_pairs"] == [[0, 1], [1, 0]]
        assert collective_counts(hlo)["collective-permute"] == 1

    def test_channel_only_empty_replica_groups(self):
        # Channel-lowered spelling: `replica_groups={}` is empty on the
        # instruction — the grouping is the channel's. The empty form
        # must neither crash the groups parser nor masquerade as
        # explicit groups; the channel id is the surviving routing fact.
        hlo = """
  %ar = f32[64]{0} all-reduce(f32[64]{0} %x), channel_id=7, replica_groups={}, use_global_device_ids=true, to_apply=%add
"""
        [ins] = collective_instructions(hlo)
        assert ins["channel_id"] == 7
        assert ins["replica_groups"] is None
        assert ins["source_target_pairs"] is None

    def test_variadic_all_gather_tuple_result(self):
        # Variadic all-gather: several operands gathered in one
        # instruction, tuple-typed result holding EVERY output buffer.
        # The instruction moves ALL of them, so bytes is the SUM
        # (f32[8,4] = 128 plus bf16[8,16] = 256) — taking only the
        # largest undercounted multi-operand gathers, and commscope's
        # per-line attribution keys on this volume.
        hlo = """
  %ag = (f32[8,4]{1,0}, bf16[8,16]{1,0}) all-gather(f32[4,4]{1,0} %a, bf16[4,16]{1,0} %b), channel_id=2, replica_groups={{0,1}}, dimensions={0}
"""
        [ins] = collective_instructions(hlo)
        assert ins["op"] == "all-gather"
        assert ins["bytes"] == 8 * 4 * 4 + 8 * 16 * 2
        assert ins["replica_groups"] == [[0, 1]]
        assert ins["channel_id"] == 2
        assert ins["source_target_pairs"] is None

    def test_variadic_reduce_scatter_bytes_sum(self):
        # Variadic reduce-scatter: both tuple elements are scattered
        # outputs; the volume is their sum, not the max.
        hlo = """
  %rs = (bf16[8,4]{1,0}, f32[8,4]{1,0}) reduce-scatter(bf16[16,4]{1,0} %a, f32[16,4]{1,0} %b), replica_groups={{0,1}}, dimensions={0}, to_apply=%add
"""
        [ins] = collective_instructions(hlo)
        assert ins["op"] == "reduce-scatter"
        assert ins["bytes"] == 8 * 4 * 2 + 8 * 4 * 4

    def test_async_start_bytes_are_post_collective_side(self):
        # Async single-operand pair: the 2-tuple is (operand, result) of
        # ONE transfer — bytes is the larger (post-gather) side, not the
        # sum of both halves.
        hlo = """
  %ag-start = (f32[4,8]{1,0}, f32[4,16]{1,0}) all-gather-start(f32[4,8]{1,0} %p0), replica_groups={{0,1}}, dimensions={1}
  %ag-done = f32[4,16]{1,0} all-gather-done((f32[4,8]{1,0}, f32[4,16]{1,0}) %ag-start)
"""
        [ins] = collective_instructions(hlo)
        assert ins["bytes"] == 4 * 16 * 4

    def test_variadic_async_start_sums_pair_maxima(self):
        # Variadic async all-gather: 2k-tuple interleaves k operands
        # with k results (operands first). Each operand/result pair
        # counts once at its larger side, summed across operands:
        # max(f32[4,8], f32[4,16]) + max(bf16[4,4], bf16[4,8]).
        hlo = """
  %ag-start = (f32[4,8]{1,0}, bf16[4,4]{1,0}, f32[4,16]{1,0}, bf16[4,8]{1,0}) all-gather-start(f32[4,8]{1,0} %a, bf16[4,4]{1,0} %b), replica_groups={{0,1}}, dimensions={1}
"""
        [ins] = collective_instructions(hlo)
        assert ins["bytes"] == 4 * 16 * 4 + 4 * 8 * 2

    def test_odd_async_tuple_falls_back_to_max(self):
        # An async tuple whose arity is not 2k (context/extra scratch
        # element) cannot be paired up — the largest buffer is the
        # conservative fallback rather than double-counting.
        hlo = """
  %ar-start = (f32[64]{0}, f32[64]{0}, u32[]) all-reduce-start(f32[64]{0} %x), replica_groups={{0,1}}, to_apply=%add
"""
        [ins] = collective_instructions(hlo)
        assert ins["bytes"] == 64 * 4

    def test_fields_default_none_for_plain_collectives(self):
        hlo = """
  %ar = f32[64]{0} all-reduce(f32[64]{0} %x), replica_groups={{0,1}}, to_apply=%add
"""
        [ins] = collective_instructions(hlo)
        assert ins["channel_id"] is None
        assert ins["source_target_pairs"] is None
        assert ins["replica_groups"] == [[0, 1]]

    def test_compiled_ppermute_pairs_are_a_permutation(self, mesh24):
        """Against REAL compiler output: a shard_map ppermute ring over
        'y' must lower to collective-permutes whose parsed pairs form a
        permutation of the 8 flattened partition ids (each x-row its own
        y-ring)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from learning_jax_sharding_tpu.parallel.hlo import compiled_hlo

        n = mesh24.shape["y"]

        def shift(x):
            return lax.ppermute(
                x, "y", [(i, (i + 1) % n) for i in range(n)]
            )

        f = jax.shard_map(
            shift, mesh=mesh24, in_specs=P(None, "y"), out_specs=P(None, "y")
        )
        text = compiled_hlo(f, jnp.arange(32, dtype=jnp.float32).reshape(4, 8))
        recs = [
            r for r in collective_instructions(text)
            if r["op"] == "collective-permute"
        ]
        assert recs, "no collective-permute in compiled ring"
        ndev = mesh24.devices.size
        pairs = [p for r in recs for p in r["source_target_pairs"]]
        srcs = [p[0] for p in pairs]
        tgts = [p[1] for p in pairs]
        assert sorted(srcs) == list(range(ndev))
        assert sorted(tgts) == list(range(ndev))
        # Every hop stays inside its x-row's y-ring.
        for s, t in pairs:
            assert s // n == t // n and t % n == (s + 1) % n, (s, t)

    def test_record_schema_stable_on_real_compiler_output(self, mesh24, rng):
        """Schema pin over live lowered output: every record — whatever
        the op — carries exactly the published keys, so downstream
        consumers can index without guards."""
        from functools import partial

        from learning_jax_sharding_tpu.parallel.collectives import (
            psum_matmul,
        )
        from learning_jax_sharding_tpu.parallel.hlo import compiled_hlo
        from tests.conftest import matmul_operands

        a, b = matmul_operands(rng)
        text = compiled_hlo(partial(psum_matmul, mesh=mesh24, axis="y"), a, b)
        recs = collective_instructions(text)
        assert recs
        keys = {
            "op", "bytes", "replica_groups", "computation", "in_while",
            "source_target_pairs", "channel_id",
        }
        for r in recs:
            assert set(r) == keys
            if r["channel_id"] is not None:
                assert r["channel_id"] > 0


class TestConstantInstructions:
    def test_sizes_and_threshold(self):
        hlo = """
ENTRY %main (p0: f32[4]) -> f32[4] {
  %small = s32[] constant(3)
  %big = f32[512,512]{1,0} constant({...})
}
"""
        all_ = constant_instructions(hlo)
        assert {c["bytes"] for c in all_} == {4, 512 * 512 * 4}
        only_big = constant_instructions(hlo, min_bytes=1024)
        assert [c["bytes"] for c in only_big] == [512 * 512 * 4]
        assert only_big[0]["computation"] == "main"
