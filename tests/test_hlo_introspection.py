"""parallel/hlo.py instruction-regex edge cases, pinned on HLO text.

``_INSTR_RE`` is the foundation the collective assertions (and now the
telemetry layer's per-step collective inventory) stand on. Its corner
cases are text-level, so they are pinned on realistic HLO snippets:
async ``-start``/``-done`` pairs count once, tuple-typed results match,
and op substrings inside fusion/computation NAMES are never counted.
"""

import pytest

from learning_jax_sharding_tpu.parallel.hlo import (
    COLLECTIVE_OPS,
    collective_counts,
)


class TestInstrRegexEdgeCases:
    def test_async_start_done_pair_counts_once(self):
        hlo = """
ENTRY %main {
  %p0 = f32[4,8]{1,0} parameter(0)
  %ag-start = (f32[4,8]{1,0}, f32[4,16]{1,0}) all-gather-start(f32[4,8]{1,0} %p0), replica_groups={{0,1}}, dimensions={1}
  %ag-done = f32[4,16]{1,0} all-gather-done((f32[4,8]{1,0}, f32[4,16]{1,0}) %ag-start)
}
"""
        counts = collective_counts(hlo)
        assert counts["all-gather"] == 1
        assert sum(counts.values()) == 1

    def test_async_all_reduce_pair_counts_once(self):
        hlo = """
  %ar-start = f32[64]{0} all-reduce-start(f32[64]{0} %x), to_apply=%add
  %ar-done = f32[64]{0} all-reduce-done(f32[64]{0} %ar-start)
"""
        assert collective_counts(hlo)["all-reduce"] == 1

    def test_tuple_typed_result_matches(self):
        # A sync collective whose RESULT is a tuple (spaces inside the
        # type) must still match the `= <type> <op>(` form.
        hlo = """
  %rs = (bf16[8,4]{1,0}, bf16[8,4]{1,0}) reduce-scatter(bf16[16,4]{1,0} %a, bf16[16,4]{1,0} %b), dimensions={0}, to_apply=%add
"""
        assert collective_counts(hlo)["reduce-scatter"] == 1

    def test_op_names_inside_fusion_names_not_counted(self):
        # "all-reduce" appears in the fusion NAME, the computation NAME,
        # and an operand name — none of those are instructions.
        hlo = """
%fused_all-reduce.clone (param_0: f32[4]) -> f32[4] {
  %param_0 = f32[4]{0} parameter(0)
  ROOT %m = f32[4]{0} multiply(f32[4]{0} %param_0, f32[4]{0} %param_0)
}

ENTRY %all-reduce_main {
  %x = f32[4]{0} parameter(0)
  %fusion.all-reduce.1 = f32[4]{0} fusion(f32[4]{0} %x), kind=kLoop, calls=%fused_all-reduce.clone
  ROOT %out = f32[4]{0} add(f32[4]{0} %fusion.all-reduce.1, f32[4]{0} %x)
}
"""
        counts = collective_counts(hlo)
        assert sum(counts.values()) == 0, counts

    def test_real_instruction_next_to_decoy_names(self):
        hlo = """
  %fusion.all-gather.7 = f32[8]{0} fusion(f32[8]{0} %p), kind=kLoop, calls=%c
  %real = f32[16]{0} all-gather(f32[8]{0} %fusion.all-gather.7), replica_groups={{0,1}}, dimensions={0}
"""
        counts = collective_counts(hlo)
        assert counts["all-gather"] == 1
        assert sum(counts.values()) == 1

    def test_every_op_kind_keyed_even_when_absent(self):
        counts = collective_counts("ENTRY %e { ROOT %r = f32[] constant(0) }")
        assert set(counts) == set(COLLECTIVE_OPS)
        assert all(v == 0 for v in counts.values())

    def test_collective_permute_and_all_to_all(self):
        hlo = """
  %cp = u32[2]{0} collective-permute(u32[2]{0} %x), source_target_pairs={{0,1},{1,0}}
  %a2a-start = (f32[4]{0}, f32[4]{0}) all-to-all-start(f32[4]{0} %y), replica_groups={{0,1}}
  %a2a-done = f32[4]{0} all-to-all-done((f32[4]{0}, f32[4]{0}) %a2a-start)
"""
        counts = collective_counts(hlo)
        assert counts["collective-permute"] == 1
        assert counts["all-to-all"] == 1

    def test_compiled_function_counts_match_text_counts(self, mesh24, rng):
        """The regex against REAL compiler output: a psum matmul's
        optimized HLO must contain exactly the all-reduce the explicit
        collective promises (sync or async-pair spelled)."""
        from functools import partial

        from learning_jax_sharding_tpu.parallel.collectives import (
            psum_matmul,
        )
        from learning_jax_sharding_tpu.parallel.hlo import compiled_hlo
        from tests.conftest import matmul_operands

        a, b = matmul_operands(rng)
        text = compiled_hlo(partial(psum_matmul, mesh=mesh24, axis="y"), a, b)
        counts = collective_counts(text)
        assert counts["all-reduce"] >= 1
        # -done must never double an async pair: the done-op count is
        # bounded by (in fact equal to) the start/sync count.
        dones = text.count("all-reduce-done(")
        starts = text.count("all-reduce-start(")
        assert counts["all-reduce"] >= dones == starts
