"""Beam search: beats-or-equals greedy, exact at beam_size=1, EOS handling.

Oracles: beam_size=1 reproduces the greedy decode token for token; wider
beams never score WORSE than greedy under the model's own sequence logprob
(the defining property); EOS freezes beams (suffix padded with EOS) and
length normalization uses the pre-EOS length.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.beam import make_beam_search_fn
from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


def _trained(mesh, rng, steps=5):
    model = Transformer(CONFIG_TINY)
    tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    for _ in range(steps):
        state, _ = step(state, batch)
    return model, state.params, tokens


def _seq_logprob(model, params, full, prompt_len):
    """Sum of next-token logprobs of full[:, prompt_len:] under the model."""
    logits = model.apply({"params": params}, full[:, :-1]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tgt = full[:, 1:]
    picked = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return np.asarray(picked[:, prompt_len - 1 :].sum(axis=1))


class TestBeamSearch:
    def test_beam1_equals_greedy(self, mesh22, rng):
        model, params, tokens = _trained(mesh22, rng)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        greedy = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=10
        )
        beam = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=1, max_new_tokens=10
        )
        out_g = np.asarray(greedy(params, prompt, jax.random.key(0)))
        out_b, _ = beam(params, prompt)
        np.testing.assert_array_equal(np.asarray(out_b), out_g)

    def test_beam1_equals_greedy_blocked_backend(self, mesh22, rng):
        """The production TPU decode path (blocked cache kernel, interpret
        on CPU) under beam search: beam reordering gathers the sequence-
        major (B·K, N_kv, L, H) caches on their batch dim, and on the
        4-device mesh the kernel runs through the shard_map wrapper. The
        beam-1 ≡ greedy identity must survive both."""
        import dataclasses

        cfg = dataclasses.replace(CONFIG_TINY, decode_attention="blocked")
        model, params, tokens = _trained(mesh22, rng)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        greedy = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=10)
        beam = make_beam_search_fn(
            cfg, mesh22, RULES_DP_TP, beam_size=1, max_new_tokens=10
        )
        out_g = np.asarray(greedy(params, prompt, jax.random.key(0)))
        out_b, _ = beam(params, prompt)
        np.testing.assert_array_equal(np.asarray(out_b), out_g)

    def test_beam3_blocked_matches_dense_backend(self, mesh22, rng):
        """Beam-3 search end to end: the blocked kernel and the dense cached
        path must pick the same beams (fp32 matmuls — the two backends are
        numerically aligned on CPU)."""
        import dataclasses

        model, params, tokens = _trained(mesh22, rng)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        outs = {}
        for backend in ("dense", "blocked"):
            cfg = dataclasses.replace(CONFIG_TINY, decode_attention=backend)
            beam = make_beam_search_fn(
                cfg, mesh22, RULES_DP_TP, beam_size=3, max_new_tokens=8
            )
            toks, scores = beam(params, prompt)
            outs[backend] = (np.asarray(toks), np.asarray(scores))
        np.testing.assert_array_equal(outs["dense"][0], outs["blocked"][0])
        np.testing.assert_allclose(
            outs["dense"][1], outs["blocked"][1], atol=1e-4
        )

    @pytest.mark.parametrize("beam_size", [2, 4])
    def test_beats_or_equals_greedy_logprob(self, mesh22, rng, beam_size):
        model, params, tokens = _trained(mesh22, rng)
        prompt_np = tokens[:4, :8]
        prompt = put(prompt_np, mesh_sharding(mesh22, "data", None))
        greedy = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=10
        )
        beam = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP,
            beam_size=beam_size, max_new_tokens=10,
        )
        out_g = np.asarray(greedy(params, prompt, jax.random.key(0)))
        out_b, scores = beam(params, prompt)
        out_b = np.asarray(out_b)
        lp_g = _seq_logprob(model, params, jnp.asarray(out_g), 8)
        lp_b = _seq_logprob(model, params, jnp.asarray(out_b), 8)
        assert (lp_b >= lp_g - 1e-3).all(), (lp_b, lp_g)
        # Returned scores are the same quantity (length_penalty=1, no EOS →
        # normalized by the common length).
        np.testing.assert_allclose(
            np.asarray(scores) * 10.0, lp_b, rtol=1e-3, atol=1e-3
        )

    def test_eos_freezes_beams(self, mesh22, rng):
        """Deterministic EOS exercise: train the cyclic +1 pattern until the
        continuation is certain, set EOS = the 3rd continuation token of
        EVERY row — all beams must emit it at step 3 and the suffix must be
        frozen to EOS from there on. No vacuous branch: the assertion fires
        on every row."""
        model = Transformer(CONFIG_TINY)
        v = CONFIG_TINY.vocab_size
        sh = mesh_sharding(mesh22, "data", None)

        def cyc_batch(i):
            starts = np.random.default_rng((3, i)).integers(0, v, size=8)
            toks = ((starts[:, None] + np.arange(33)[None]) % v).astype(np.int32)
            return {"inputs": put(toks[:, :-1], sh), "targets": put(toks[:, 1:], sh)}

        b0 = cyc_batch(0)
        state, state_sh = sharded_train_state(
            model, optax.adamw(3e-3), b0["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        step = make_train_step(
            state_sh, {k: vv.sharding for k, vv in b0.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        for i in range(60):
            state, _ = step(state, cyc_batch(i))
        # EOS = the model's own first greedy token. With length_penalty=0
        # scores are RAW total logprobs, so the beam frozen at step 1 (its
        # only continuation: repeated EOS at zero added logprob) strictly
        # beats every longer path (each extra real token adds a negative
        # term) — the winner is fully determined: all-EOS rows. Exercises
        # the freeze mask, zero-cost continuation, and length freezing with
        # no vacuous branch.
        starts = np.asarray([10, 10]) % v
        prompt_np = ((starts[:, None] + np.arange(8)[None]) % v).astype(np.int32)
        prompt = put(prompt_np, sh)
        greedy = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=1
        )
        cont = np.asarray(greedy(state.params, prompt, jax.random.key(0)))[:, 8:]
        assert (cont[0] == cont[1]).all()  # identical rows, identical greedy
        eos = int(cont[0, 0])
        beam = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=3,
            max_new_tokens=8, eos_id=eos, length_penalty=0.0,
        )
        out, scores = beam(state.params, prompt)
        out = np.asarray(out)
        for row in out[:, 8:]:
            np.testing.assert_array_equal(row, np.full(8, eos, np.int32))
        # Raw score of the frozen beam = logprob of its single real token.
        assert np.isfinite(np.asarray(scores)).all()
        assert (np.asarray(scores) < 0).all()

    def test_bad_beam_size_rejected(self, mesh22):
        with pytest.raises(ValueError, match="beam_size"):
            make_beam_search_fn(
                CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=0, max_new_tokens=4
            )

    def test_returned_score_is_normalized_seq_logprob_with_eos(self, mesh22, rng):
        """Self-consistency of the finished pool: whatever hypothesis wins,
        its returned score must equal the model's own logprob of that
        sequence up to (and including) the first EOS, normalized by that
        length — scores brought forward from the pool can't be stale."""
        model, params, tokens = _trained(mesh22, rng)
        prompt_np = tokens[:4, :8]
        prompt = put(prompt_np, mesh_sharding(mesh22, "data", None))
        # Pick EOS = the greedy continuation token of row 0 at step 2 so at
        # least one row finishes mid-search on a real hypothesis.
        greedy = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=10
        )
        out_g = np.asarray(greedy(params, prompt, jax.random.key(0)))
        eos = int(out_g[0, 8 + 2])
        beam = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=3,
            max_new_tokens=10, eos_id=eos, length_penalty=1.0,
        )
        out, scores = beam(params, prompt)
        out, scores = np.asarray(out), np.asarray(scores)
        logits = model.apply({"params": params}, jnp.asarray(out[:, :-1]))
        logp = np.asarray(
            jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        )
        for r in range(out.shape[0]):
            gen = out[r, 8:]
            end = np.argmax(gen == eos) + 1 if (gen == eos).any() else len(gen)
            # everything after the first EOS must be EOS padding
            assert (gen[end:] == eos).all() or end == len(gen)
            total = sum(
                logp[r, 8 - 1 + t, gen[t]] for t in range(end)
            )
            assert scores[r] == pytest.approx(total / end, rel=1e-3, abs=1e-3)

    def test_beam1_dequantized_equals_int8_greedy(self, mesh22, rng):
        """int8 trees are beam-searchable: beam_size=1 with dequantize must
        reproduce the int8 greedy decode token for token (the same oracle
        that ties beam-1 to greedy in fp32)."""
        from learning_jax_sharding_tpu.models.quantize import quantize_tree

        _, params, tokens = _trained(mesh22, rng)
        qparams = quantize_tree(params)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        greedy = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=10,
            inference_dtype=jnp.bfloat16, dequantize=True,
        )
        beam = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=1, max_new_tokens=10,
            inference_dtype=jnp.bfloat16, dequantize=True,
        )
        out_g = np.asarray(greedy(qparams, prompt, jax.random.key(0)))
        out_b, _ = beam(qparams, prompt)
        np.testing.assert_array_equal(np.asarray(out_b), out_g)


class TestRaggedBeam:
    """``ragged=True``: mixed-length prompt batches through the beam fold.
    Oracles: beam-1 ≡ ragged greedy per row (dense AND blocked); beam-k
    rows bit-identical to a rectangular search of each row alone at its
    true length."""

    LENGTHS = np.array([8, 5, 3, 7], np.int32)

    def _ragged_prompt(self, tokens):
        prompt = tokens[:4, :8].copy()
        for b, n in enumerate(self.LENGTHS):
            prompt[b, n:] = 0
        return prompt

    @pytest.mark.parametrize("backend", ["dense", "blocked"])
    def test_beam1_equals_ragged_greedy(self, mesh22, rng, backend):
        import dataclasses

        cfg = dataclasses.replace(CONFIG_TINY, decode_attention=backend)
        model, params, tokens = _trained(mesh22, rng)
        sh = mesh_sharding(mesh22, "data", None)
        prompt = put(self._ragged_prompt(tokens), sh)
        lengths = jnp.asarray(self.LENGTHS)
        greedy = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=10, ragged=True
        )
        beam = make_beam_search_fn(
            cfg, mesh22, RULES_DP_TP, beam_size=1, max_new_tokens=10,
            ragged=True,
        )
        ref = np.asarray(
            greedy(params, prompt, jax.random.key(0), lengths=lengths)
        )
        got, _ = beam(params, prompt, lengths=lengths)
        np.testing.assert_array_equal(np.asarray(got), ref)

    @pytest.mark.parametrize("backend", ["dense", "blocked"])
    def test_beamk_matches_per_row_rectangular(self, mesh22, rng, backend):
        """Each row of the ragged batch must reproduce a RECTANGULAR
        beam search of that row alone at its true length — raggedness is
        pure batching, never a result change."""
        import dataclasses

        cfg = dataclasses.replace(CONFIG_TINY, decode_attention=backend)
        model, params, tokens = _trained(mesh22, rng)
        sh = mesh_sharding(mesh22, "data", None)
        prompt_np = self._ragged_prompt(tokens)
        beam = make_beam_search_fn(
            cfg, mesh22, RULES_DP_TP, beam_size=3, max_new_tokens=8,
            ragged=True,
        )
        got, scores = beam(
            params, put(prompt_np, sh), lengths=jnp.asarray(self.LENGTHS)
        )
        got, scores = np.asarray(got), np.asarray(scores)
        rect = make_beam_search_fn(
            cfg, mesh22, RULES_DP_TP, beam_size=3, max_new_tokens=8,
        )
        for b, n in enumerate(self.LENGTHS):
            # b=2 rows: the mesh's data axis must divide the batch.
            solo = np.repeat(prompt_np[b : b + 1, :n], 2, axis=0)
            ref, ref_sc = rect(params, put(solo, sh))
            ref, ref_sc = np.asarray(ref)[0], np.asarray(ref_sc)[0]
            np.testing.assert_array_equal(
                got[b, n : n + 8], ref[n:], err_msg=f"row {b} len {n}"
            )
            np.testing.assert_allclose(
                scores[b], ref_sc, rtol=1e-5, err_msg=f"row {b}"
            )

    def test_eos_with_ragged(self, mesh22, rng):
        """EOS pools + per-row lengths compose: each row still matches its
        solo rectangular run with the same eos."""
        model, params, tokens = _trained(mesh22, rng)
        sh = mesh_sharding(mesh22, "data", None)
        prompt_np = self._ragged_prompt(tokens)
        # Pick an eos the first row emits early in its greedy decode.
        greedy = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=8, ragged=True
        )
        g = np.asarray(
            greedy(
                params, put(prompt_np, sh), jax.random.key(0),
                lengths=jnp.asarray(self.LENGTHS),
            )
        )
        eos = int(g[0, self.LENGTHS[0] + 1])
        beam = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=2, max_new_tokens=8,
            eos_id=eos, ragged=True,
        )
        rect = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=2, max_new_tokens=8,
            eos_id=eos,
        )
        got, scores = beam(
            params, put(prompt_np, sh), lengths=jnp.asarray(self.LENGTHS)
        )
        got, scores = np.asarray(got), np.asarray(scores)
        for b, n in enumerate(self.LENGTHS):
            solo = np.repeat(prompt_np[b : b + 1, :n], 2, axis=0)
            ref, ref_sc = rect(params, put(solo, sh))
            np.testing.assert_array_equal(
                got[b, n : n + 8], np.asarray(ref)[0, n:],
                err_msg=f"row {b}",
            )
            np.testing.assert_allclose(scores[b], np.asarray(ref_sc)[0], rtol=1e-5)

    def test_lengths_validation(self, mesh22, rng):
        model, params, tokens = _trained(mesh22, rng, steps=1)
        sh = mesh_sharding(mesh22, "data", None)
        prompt = put(tokens[:4, :8], sh)
        rb = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=2, max_new_tokens=4,
            ragged=True,
        )
        with pytest.raises(ValueError, match="lengths"):
            rb(params, prompt)
        b = make_beam_search_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, beam_size=2, max_new_tokens=4,
        )
        with pytest.raises(ValueError, match="lengths"):
            b(params, prompt, lengths=jnp.full((4,), 8))
