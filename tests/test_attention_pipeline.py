"""Cases 5/6 as tests: logical partitioning, sharded init, train/apply.

Oracles from SURVEY.md §8, verified against the reference semantics by
execution: on a (2,2) data×model mesh under the reference rules, Wq (640,512)
shards to (320,512) and y (8,256,640) shards to (4,128,640) when the sequence
dim is sharded over 'model'.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention
from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.parallel import assert_shard_shape, put
from learning_jax_sharding_tpu.parallel.logical import (
    BATCH,
    EMBED,
    RULES_DP_TP,
    RULES_DP_TP_SP,
    RULES_REFERENCE,
    SEQ,
    logical_sharding,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_apply_fn,
    make_train_step,
    sharded_train_state,
)

# Reference model dims (`/root/reference/case6_attention.py:149-151,44-45`).
B, S, M = 8, 256, 640
HEADS_N, HEAD_DIM = 8, 64


def _setup(mesh22, rules):
    model = MultiHeadAttention(features=M, num_heads=HEADS_N, head_dim=HEAD_DIM)
    x_sharding = logical_sharding(mesh22, rules, BATCH, SEQ, EMBED)
    x = put(np.random.default_rng(1).standard_normal((B, S, M)).astype(np.float32),
            x_sharding)
    rngs = {"params": jax.random.key(0)}
    state, state_shardings = sharded_train_state(
        model, optax.adam(1e-3), x, rngs, mesh22, rules
    )
    return model, x, x_sharding, state, state_shardings


class TestDenseAttentionOp:
    def test_matches_naive_softmax(self, rng):
        q = rng.standard_normal((2, 16, 4, 8)).astype(np.float32)
        k = rng.standard_normal((2, 16, 4, 8)).astype(np.float32)
        v = rng.standard_normal((2, 16, 4, 8)).astype(np.float32)
        out = dot_product_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        # naive per-head reference
        qn = np.moveaxis(q, 2, 1)  # (B,N,S,H)
        kn = np.moveaxis(k, 2, 1)
        vn = np.moveaxis(v, 2, 1)
        scores = (qn @ np.swapaxes(kn, -1, -2)) / np.sqrt(8)
        w = np.exp(scores - scores.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        expected = np.moveaxis(w @ vn, 1, 2)
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)

    def test_causal_mask(self):
        m = causal_mask(4)
        assert m.shape == (1, 1, 4, 4)
        np.testing.assert_array_equal(np.asarray(m[0, 0]), np.tril(np.ones((4, 4))))

    def test_causal_attention_ignores_future(self, rng):
        q = jnp.asarray(rng.standard_normal((1, 8, 2, 4)).astype(np.float32))
        k = jnp.asarray(rng.standard_normal((1, 8, 2, 4)).astype(np.float32))
        v = jnp.asarray(rng.standard_normal((1, 8, 2, 4)).astype(np.float32))
        out_full = dot_product_attention(q, k, v, mask=causal_mask(8))
        # Perturbing the future must not change position 0's output.
        v2 = v.at[:, 4:].set(0.0)
        k2 = k.at[:, 4:].set(9.0)
        out_trunc = dot_product_attention(q, k2, v2, mask=causal_mask(8))
        np.testing.assert_allclose(
            np.asarray(out_full[:, :4]), np.asarray(out_trunc[:, :4]), rtol=1e-5
        )


class TestCase6Parity:
    """Reference-rule oracles on the (2,2) data×model mesh."""

    def test_params_born_sharded_wq_oracle(self, mesh22):
        _, _, _, state, _ = _setup(mesh22, RULES_REFERENCE)
        wq = state.params["query"]["kernel"]
        assert wq.shape == (M, HEADS_N * HEAD_DIM)
        # EMBED→model splits rows: (640,512) → (320,512)  (SURVEY §8 oracle).
        assert_shard_shape(wq, (M // 2, HEADS_N * HEAD_DIM))
        # Adam moments inherit the same sharding.
        mu_wq = state.opt_state[0].mu["query"]["kernel"]
        assert_shard_shape(mu_wq, (M // 2, HEADS_N * HEAD_DIM))

    def test_train_and_apply(self, mesh22):
        _, x, x_sharding, state, state_shardings = _setup(mesh22, RULES_REFERENCE)
        step = make_train_step(state_shardings, x_sharding, mesh22, RULES_REFERENCE)
        state2, loss = step(state, x)
        assert np.isfinite(float(loss))
        apply_fn = make_apply_fn(state_shardings, x_sharding, mesh22, RULES_REFERENCE)
        y = apply_fn(state2, x)
        assert y.shape == (B, S, M)
        # Under the reference rules EMBED→model, so the feature dim of x and y
        # splits over 'model': (8,256,640) → shard (4,256,320). (The
        # reference's own x placement instead sharded the sequence dim —
        # that oracle lives in test_sequence_sharded_y_oracle.)
        assert_shard_shape(y, (B // 2, S, M // 2))

    def test_sequence_sharded_y_oracle(self, mesh22):
        """The (4,128,640) oracle: sequence sharded over 'model' — the
        intentional version of the reference's accidental SP placement."""
        _, x, x_sharding, state, state_shardings = _setup(
            mesh22, RULES_DP_TP_SP
        )
        assert_shard_shape(x, (B // 2, S // 2, M))
        apply_fn = make_apply_fn(state_shardings, x_sharding, mesh22, RULES_DP_TP_SP)
        y = apply_fn(state, x)
        assert_shard_shape(y, (B // 2, S // 2, M))  # (4,128,640)

    def test_megatron_rules_split_heads(self, mesh22):
        _, _, _, state, _ = _setup(mesh22, RULES_DP_TP)
        wq = state.params["query"]["kernel"]
        # HEADS→model splits columns: (640,512) → (640,256).
        assert_shard_shape(wq, (M, HEADS_N * HEAD_DIM // 2))

    def test_training_reduces_mse_loss(self, mesh22):
        """Beyond the reference (its loss is y.sum() and never printed):
        a real regression target must actually descend."""
        model, x, x_sharding, state, state_shardings = _setup(
            mesh22, RULES_REFERENCE
        )
        target = jnp.ones((B, S, M), jnp.float32)

        def mse(y, batch):
            del batch
            return jnp.mean((y - target) ** 2)

        step = make_train_step(
            state_shardings, x_sharding, mesh22, RULES_REFERENCE, loss_fn=mse
        )
        losses = []
        for _ in range(5):
            state, loss = step(state, x)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
