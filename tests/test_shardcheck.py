"""shardcheck static passes: contract diffs, donation verdicts, lints.

Pins the three analysis levels the CI gate stands on:

* the CONTRACT DIFF ENGINE (added/removed/oversized collective,
  while-loop collectives, oversized constants, mesh mismatch) — pure
  logic on synthetic contracts, plus the real-compiler path where a
  deliberately wrong ``in_sharding`` must surface as contract drift;
* the DONATION pass — requested/applied/eligible verdicts read off real
  executables on the emulated-CPU path (this backend APPLIES donation,
  so the exact-alias path is pinned; the parser is additionally pinned
  on synthetic TPU-style multi-entry alias headers, the guarded path);
* the JAXPR lint (f32 promotion in bf16 graphs, dead equations) and the
  AST lint rules with the baseline-suppression budget.
"""

import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.analysis.contracts import (
    Contract,
    check_against_golden,
    check_contract,
    contract_of,
)
from learning_jax_sharding_tpu.analysis.donation import (
    aliased_params,
    donation_report,
)
from learning_jax_sharding_tpu.analysis.findings import Finding
from learning_jax_sharding_tpu.analysis.jaxpr_lint import lint_fn
from learning_jax_sharding_tpu.analysis.source_lint import (
    apply_baseline,
    lint_source,
)


def _contract(name="ep", collectives=None, while_c=0, const_b=0):
    return Contract(
        name=name, mesh_shape=[2, 4], mesh_axes=["x", "y"],
        collectives=collectives or {}, while_collectives=while_c,
        max_constant_bytes=const_b,
    )


class TestContractDiffEngine:
    def test_clean_self_diff(self):
        c = _contract(collectives={"all-reduce@y": {"count": 2, "max_bytes": 64}})
        assert check_contract(c, c) == []

    def test_added_collective(self):
        g = _contract(collectives={"all-reduce@y": {"count": 1, "max_bytes": 64}})
        o = _contract(collectives={
            "all-reduce@y": {"count": 1, "max_bytes": 64},
            "all-gather@x": {"count": 2, "max_bytes": 4096},
        })
        rules = [f.rule for f in check_contract(g, o)]
        assert rules == ["added-collective"]

    def test_missing_collective(self):
        g = _contract(collectives={"all-reduce@y": {"count": 2, "max_bytes": 64}})
        o = _contract(collectives={"all-reduce@y": {"count": 1, "max_bytes": 64}})
        [f] = check_contract(g, o)
        assert f.rule == "missing-collective"
        assert "replication" in f.message

    def test_oversized_collective_and_slack(self):
        g = _contract(collectives={"all-gather@y": {"count": 1, "max_bytes": 1000}})
        within = _contract(collectives={"all-gather@y": {"count": 1, "max_bytes": 1200}})
        past = _contract(collectives={"all-gather@y": {"count": 1, "max_bytes": 1300}})
        assert check_contract(g, within) == []          # inside 1.25x slack
        [f] = check_contract(g, past)
        assert f.rule == "oversized-collective"
        assert check_contract(g, past, byte_slack=2.0) == []

    def test_while_loop_collective(self):
        g = _contract(while_c=0)
        o = _contract(while_c=3)
        [f] = check_contract(g, o)
        assert f.rule == "while-loop-collective"
        assert "trip count" in f.message

    def test_oversized_constant(self):
        g = _contract(const_b=0)
        o = _contract(const_b=512 * 1024)
        [f] = check_contract(g, o)
        assert f.rule == "oversized-constant"

    def test_mesh_mismatch_short_circuits(self):
        g = _contract()
        o = Contract(
            name="ep", mesh_shape=[4, 2], mesh_axes=["x", "y"],
            collectives={"all-reduce@y": {"count": 9, "max_bytes": 1}},
            while_collectives=9, max_constant_bytes=1 << 30,
        )
        [f] = check_contract(g, o)
        assert f.rule == "mesh-mismatch"

    def test_json_round_trip(self):
        c = _contract(collectives={"all-to-all@x": {"count": 2, "max_bytes": 128}})
        assert Contract.from_json(c.to_json()) == c

    def test_missing_golden_is_a_finding(self, tmp_path):
        [f] = check_against_golden(tmp_path, _contract(name="unknown_ep"))
        assert f.rule == "no-golden"

    def test_golden_file_round_trip(self, tmp_path):
        c = _contract(collectives={"all-reduce@y": {"count": 1, "max_bytes": 64}})
        (tmp_path / "ep.json").write_text(c.to_json())
        assert check_against_golden(tmp_path, c) == []


class TestContractsOnRealCompiler:
    """contract_of against the real partitioner on the emulated mesh."""

    def test_psum_matmul_contract_records_its_all_reduce(self, mesh24, rng):
        from functools import partial

        from learning_jax_sharding_tpu.parallel.collectives import psum_matmul
        from tests.conftest import matmul_operands

        a, b = matmul_operands(rng)
        fn = partial(psum_matmul, mesh=mesh24, axis="y")
        good = contract_of("psum_matmul", fn, a, b, mesh=mesh24)
        assert good.collectives.get("all-reduce@y", {}).get("count", 0) >= 1

    def test_wrong_in_sharding_is_contract_drift(self, mesh24, tmp_path):
        """The seeded violation class of case20: a column-parallel matmul
        (weight sharded on its OUTPUT dim — zero comms) goldened, then
        recompiled with the weight row-sharded: GSPMD must now insert
        communication, and the check must name it rather than pass."""

        def mm(x, w):
            return x @ w

        x = np.ones((8, 16), np.float32)
        w = np.ones((16, 32), np.float32)
        out_sh = NamedSharding(mesh24, P(None, "y"))
        f = jax.jit(mm, out_shardings=out_sh)
        x_rep = jax.device_put(x, NamedSharding(mesh24, P()))
        w_col = jax.device_put(w, NamedSharding(mesh24, P(None, "y")))
        good = contract_of("mm", f, x_rep, w_col, mesh=mesh24)
        assert good.collectives == {}  # column-parallel: comms-free
        (tmp_path / "mm.json").write_text(good.to_json())
        assert check_against_golden(tmp_path, good) == []

        w_row = jax.device_put(w, NamedSharding(mesh24, P("y", None)))
        bad = contract_of("mm", f, x_rep, w_row, mesh=mesh24)
        findings = check_against_golden(tmp_path, bad)
        assert findings, "wrong in_sharding compiled to the SAME collectives"
        assert all(f.rule == "added-collective" for f in findings)

    def test_enforce_contract_raises_and_reports(self, mesh24, tmp_path):
        """The fail-loudly path fit(contract=) rides: drift raises
        ShardingContractError AND lands in the recorder first."""
        from learning_jax_sharding_tpu.analysis.contracts import (
            ShardingContractError,
            enforce_contract,
        )
        from learning_jax_sharding_tpu.telemetry.flight_recorder import (
            FlightRecorder,
        )

        def mm(x, w):
            return x @ w

        x = np.ones((8, 16), np.float32)
        w = np.ones((16, 32), np.float32)
        out_sh = NamedSharding(mesh24, P(None, "y"))
        f = jax.jit(mm, out_shardings=out_sh)
        x_rep = jax.device_put(x, NamedSharding(mesh24, P()))
        w_col = jax.device_put(w, NamedSharding(mesh24, P(None, "y")))
        golden = contract_of("mm", f, x_rep, w_col, mesh=mesh24)
        (tmp_path / "mm.json").write_text(golden.to_json())

        # Clean compile under the golden: passes, returns the observed.
        obs = enforce_contract(
            tmp_path, f, x_rep, w_col, mesh=mesh24, name="mm"
        )
        assert obs.collectives == golden.collectives

        rec = FlightRecorder()
        w_row = jax.device_put(w, NamedSharding(mesh24, P("y", None)))
        with pytest.raises(ShardingContractError) as ei:
            enforce_contract(
                tmp_path, f, x_rep, w_row, mesh=mesh24, name="mm",
                recorder=rec,
            )
        assert ei.value.findings
        assert rec.events("shardcheck_finding")  # recorded before raising

    def test_scan_collective_lands_in_while(self, mesh24):
        def scanned(x):
            def body(c, _):
                return jax.lax.psum(c, "y"), None

            r, _ = jax.lax.scan(body, x, None, length=4)
            return r

        f = jax.shard_map(
            scanned, mesh=mesh24, in_specs=P(None, "y"),
            out_specs=P(None, "y"), check_vma=False,
        )
        x = jax.device_put(
            np.ones((4, 16), np.float32), NamedSharding(mesh24, P(None, "y"))
        )
        c = contract_of("scanned", f, x, mesh=mesh24)
        assert c.while_collectives >= 1
        assert check_contract(c, c) == []  # a golden ADMITTING it passes


class TestDonationPass:
    def test_applied_donation_verdict(self):
        f = jax.jit(lambda s, x: s + x, donate_argnums=(0,))
        r = donation_report(f, jnp.ones((8, 8)), jnp.ones((8, 8)))
        assert [i["verdict"] for i in r["inputs"]] == ["donated", "ok"]
        assert r["backend_applied_any"]
        assert r["findings"] == []

    def test_requested_but_not_applied(self):
        # No output matches the donated buffer: the request drops and the
        # pass must say so (same verdict a donation-less backend yields —
        # the guarded path shares this code).
        f = jax.jit(lambda s, x: jnp.sum(s + x), donate_argnums=(0,))
        with pytest.warns(UserWarning, match="donated"):
            r = donation_report(f, jnp.ones((8, 8)), jnp.ones((8, 8)))
        assert [i["verdict"] for i in r["inputs"]] == ["not_applied", "ok"]
        assert [f.rule for f in r["findings"]] == ["donation-not-applied"]

    def test_eligible_never_requested(self):
        f = jax.jit(lambda s, x: s + x)
        r = donation_report(f, jnp.ones((8, 8)), jnp.ones((8, 8)))
        assert r["inputs"][0]["verdict"] == "eligible"
        assert [f.rule for f in r["findings"]] == ["donation-missed"]

    def test_alias_header_parser_multi_entry(self):
        # TPU-style header: tuple outputs, several aliased params — the
        # textual path the compiled-HLO parse must survive unchanged.
        hlo = (
            "HloModule jit_step, is_scheduled=true, input_output_alias="
            "{ {0}: (1, {}, may-alias), {2}: (3, {}, must-alias) }, "
            "entry_computation_layout={(f32[8]{0})->f32[8]{0}}"
        )
        assert aliased_params(hlo) == {1, 3}
        assert aliased_params("HloModule jit_f, is_scheduled=true") == set()

    def test_train_step_donation_is_applied(self, mesh24, rng):
        """The framework's own train step donates its state and the
        backend applies it — the clean-repo verdict the jaxpr pass rests
        on (and the cross-check that a donate_state=False step is caught
        lives in cases/case20_shardcheck.py, where the full pipeline is
        already built)."""
        import optax

        from learning_jax_sharding_tpu.analysis.donation import (
            missed_donation_bytes,
        )
        from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY

        # missed_donation_bytes: closed-form planner delta, no compile.
        at_stake = missed_donation_bytes(CONFIG_TINY, 8, 32)
        assert at_stake > 0
        # Donation on a state-shaped pytree: every floating leaf of the
        # (params, opt) input aliases an output when donated.
        params = {"w": jnp.ones((16, 16)), "b": jnp.ones((16,))}
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)

        def step(params, opt_state, g):
            updates, opt_state = opt.update(g, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        jitted = jax.jit(step, donate_argnums=(0, 1))
        r = donation_report(jitted, params, opt_state, params)
        donated = [i for i in r["inputs"] if i["donated"]]
        assert donated and all(i["verdict"] == "donated" for i in donated)


class TestJaxprLint:
    def test_f32_promotion_in_bf16_graph(self):
        def f(x):
            h = x * 2
            return jnp.sum(h.astype(jnp.float32))

        rules = [f.rule for f in lint_fn(f, jnp.ones((8, 8), jnp.bfloat16))]
        assert "f32-promotion" in rules

    def test_clean_bf16_graph_no_promotion_finding(self):
        def f(x):
            return x * 2 + x

        fs = lint_fn(f, jnp.ones((8, 8), jnp.bfloat16))
        assert [x for x in fs if x.rule == "f32-promotion"] == []

    def test_f32_graph_promotions_are_fine(self):
        # Majority-f32 graph: converting up is not a drift.
        def f(x):
            return jnp.sum(x.astype(jnp.float32))

        assert lint_fn(f, jnp.ones((8, 8), jnp.float32)) == []

    def test_f32_dot_in_bf16_graph(self):
        def f(x, w):
            return (x.astype(jnp.float32) @ w.astype(jnp.float32)).astype(
                jnp.bfloat16
            )

        rules = [
            f.rule
            for f in lint_fn(
                f, jnp.ones((8, 8), jnp.bfloat16), jnp.ones((8, 8), jnp.bfloat16)
            )
        ]
        assert "f32-dot-in-bf16-graph" in rules

    def test_dead_eqn(self):
        def f(x):
            _wasted = jnp.sum(x * 3)  # noqa: F841 — traced, never returned
            return x + 1

        rules = [x.rule for x in lint_fn(f, jnp.ones(4))]
        assert "dead-eqn" in rules

    def test_live_graph_has_no_dead_eqns(self):
        def f(x):
            return jnp.sum(x * 3) + jnp.prod(x)

        assert [x for x in lint_fn(f, jnp.ones(4)) if x.rule == "dead-eqn"] == []


class TestSourceLint:
    def _rules(self, src):
        return [f.rule for f in lint_source("mod.py", textwrap.dedent(src))]

    def test_jit_in_loop(self):
        src = """
        import jax
        for cfg in configs:
            step = jax.jit(make_step(cfg))
        """
        assert self._rules(src) == ["jit-in-loop"]

    def test_partial_jit_in_loop(self):
        src = """
        import jax
        from functools import partial
        while work:
            f = partial(jax.jit, static_argnames=("n",))(g)
        """
        assert self._rules(src) == ["jit-in-loop"]

    def test_jit_outside_loop_clean(self):
        src = """
        import jax
        step = jax.jit(make_step(cfg))
        for batch in data:
            step(batch)
        """
        assert self._rules(src) == []

    def test_nonhashable_static_default(self):
        src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("dims",))
        def f(x, dims=[1, 2]):
            return x
        """
        assert "nonhashable-static" in self._rules(src)

    def test_hashable_static_default_clean(self):
        src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("dims",))
        def f(x, dims=(1, 2)):
            return x
        """
        assert self._rules(src) == []

    def test_captured_device_array(self):
        src = """
        import jax
        import jax.numpy as jnp

        TABLE = jnp.arange(1024)

        @jax.jit
        def lookup(i):
            return TABLE[i]
        """
        assert "captured-device-array" in self._rules(src)

    def test_function_local_array_does_not_poison_globals(self):
        # A function-LOCAL `table = jnp...` must not mark the name, or an
        # unrelated global `table` read by a jitted fn false-positives.
        src = """
        import jax
        import jax.numpy as jnp

        def helper():
            table = jnp.arange(10)
            return table

        table = load_table_from_disk()

        @jax.jit
        def fn(x):
            return x + table
        """
        assert self._rules(src) == []

    def test_shadowing_binding_forms_are_locals_not_captures(self):
        # for-targets, tuple unpacking, and with-as all BIND the name —
        # shadowing the module-level array, not capturing it.
        src = """
        import jax
        import jax.numpy as jnp

        table = jnp.zeros((4,))

        @jax.jit
        def a(y):
            for table in (y, y):
                y = y + table
            return y

        @jax.jit
        def b(y):
            table, other = y, y
            return table + other

        @jax.jit
        def c(y):
            with open("f") as table:
                pass
            return y
        """
        assert self._rules(src) == []

    def test_argument_passing_is_clean(self):
        src = """
        import jax
        import jax.numpy as jnp

        TABLE = jnp.arange(1024)

        @jax.jit
        def lookup(table, i):
            return table[i]

        lookup(TABLE, 3)
        """
        assert self._rules(src) == []

    def test_bare_except_flags(self):
        src = """
        try:
            f()
        except:
            x = 1
        """
        assert self._rules(src) == ["swallowed-exception"]

    def test_bare_except_with_reraise_clean(self):
        src = """
        try:
            f()
        except:
            cleanup()
            raise
        """
        assert self._rules(src) == []

    def test_broad_except_pass_flags(self):
        src = """
        try:
            f()
        except Exception:
            pass
        """
        assert self._rules(src) == ["swallowed-exception"]

    def test_broad_except_in_tuple_pass_flags(self):
        src = """
        try:
            f()
        except (ValueError, Exception):
            ...
        """
        assert self._rules(src) == ["swallowed-exception"]

    def test_broad_except_with_handling_clean(self):
        # Recording the failure IS handling — the rule only hunts
        # failures that leave no trace.
        src = """
        try:
            f()
        except Exception as e:
            recorder.record("fault", error=str(e))
        """
        assert self._rules(src) == []

    def test_narrow_except_pass_clean(self):
        # A narrow `except KeyError: pass` is a deliberate, bounded
        # decision — only the broad catches gate.
        src = """
        try:
            f()
        except KeyError:
            pass
        """
        assert self._rules(src) == []

    def test_raw_clock_without_sync(self):
        src = """
        import time
        t0 = time.perf_counter()
        y = f(x)
        dt = time.perf_counter() - t0
        """
        assert self._rules(src) == ["raw-clock", "raw-clock"]

    def test_raw_clock_with_nearby_sync_clean(self):
        src = """
        import time
        t0 = time.perf_counter()
        y = f(x)
        jax.block_until_ready(y)
        dt = time.perf_counter() - t0
        """
        assert self._rules(src) == []

    def test_host_sync_in_engine_loop_flags(self):
        src = """
        import numpy as np

        class ContinuousEngine:
            def step(self, params):
                for slot in self.slots:
                    tok = np.asarray(self.buf[slot])
                    n = self.counts[slot].item()
                    self.out[slot].block_until_ready()
        """
        # Each sync draws BOTH rules: it sits in a loop (hot-loop rule)
        # and in an untimed engine phase (ledger-coverage rule).
        assert sorted(self._rules(src)) == (
            ["host-sync-in-hot-loop"] * 3 + ["untimed-engine-phase"] * 3
        )

    def test_host_sync_outside_loop_clean(self):
        # The engine's single designed sync point per dispatch — after
        # the loop, inside a ledger frame — is the pattern both rules
        # steer toward.
        src = """
        import numpy as np

        class ContinuousEngine:
            def step(self, params):
                with self._led_device(self._decode_fn):
                    tok = np.asarray(self.dispatch(params))
                for slot in self.slots:
                    self.retire(slot, tok[slot])
        """
        assert self._rules(src) == []

    def test_host_sync_outside_engine_class_clean(self):
        # Loops elsewhere legitimately read results back (bench timing,
        # data loading) — only the serving hot path gates.
        src = """
        import numpy as np

        def drain(streams):
            for s in streams:
                yield np.asarray(s)

        class ShardedBatchLoader:
            def batches(self):
                for b in self.source:
                    yield np.asarray(b)
        """
        assert self._rules(src) == []

    def test_jax_device_get_in_engine_loop_flags(self):
        src = """
        import jax

        class SpecEngine:
            def _drain(self):
                while self.has_work():
                    stats = jax.device_get(self.counters)
        """
        assert self._rules(src) == ["host-sync-in-hot-loop"]

    def test_untimed_engine_phase_flags_the_three_escape_hatches(self):
        # The ledger's 100%-accounting invariant (round 14) dies the
        # moment real work runs outside a frame. The rule names the
        # three ways seconds escape a phase method: a compiled dispatch,
        # a chaos seam, and a host sync.
        src = """
        import numpy as np

        class ContinuousEngine:
            def step(self, params):
                chaos_hook("engine.dispatch", phase="decode")
                out = self._decode_fn(params, self.state)
                return np.asarray(out)
        """
        assert self._rules(src) == ["untimed-engine-phase"] * 3

    def test_untimed_engine_phase_silent_inside_ledger_frames(self):
        # The same three calls, each under a frame (`measure(...)` or
        # the `_led_device` dispatch helper): every second lands in a
        # bucket, nothing to flag.
        src = """
        import numpy as np

        class ContinuousEngine:
            def step(self, params):
                with self.ledger.measure("recovery"):
                    chaos_hook("engine.dispatch", phase="decode")
                with self._led_device(self._decode_fn):
                    out = self._decode_fn(params, self.state)
                with self.ledger.measure("sched"):
                    return np.asarray(out)
        """
        assert self._rules(src) == []

    def test_untimed_engine_phase_only_gates_phase_methods(self):
        # Helpers and non-Engine classes dispatch freely — only the
        # named phases (step/_admit/..._dispatch) carry the ledger
        # contract, and only on classes matching `Engine`.
        src = """
        import numpy as np

        class ContinuousEngine:
            def debug_dump(self):
                return np.asarray(self._decode_fn(self.state))

        class FleetRouter:
            def step(self):
                return self._route_fn(self.pending)
        """
        assert self._rules(src) == []

    def test_unbounded_host_buffer_direct_device_append_flags(self):
        # The host-side KV leak: one device array retained per loop
        # iteration, container never evicted anywhere in the method.
        src = """
        import jax.numpy as jnp

        class ContinuousEngine:
            def _admit(self, req):
                for tok in req.tokens:
                    self._trace.append(jnp.asarray(tok))
        """
        assert self._rules(src) == ["unbounded-host-buffer"]

    def test_unbounded_host_buffer_via_local_name_flags(self):
        # The device value travels through a local binding — the rule
        # tracks names assigned from jnp./jax.random. makers.
        src = """
        import jax.numpy as jnp

        class SpecEngine:
            def step(self):
                while self.has_work():
                    logits = jnp.zeros((8, 1024))
                    self._history.append(logits)
        """
        assert self._rules(src) == ["unbounded-host-buffer"]

    def test_unbounded_host_buffer_evicted_container_clean(self):
        # Any eviction of the SAME container in scope bounds it: a
        # pop on a schedule, a del, or a rebinding trim.
        src = """
        import jax.numpy as jnp

        class ContinuousEngine:
            def step(self):
                for tok in self.stream:
                    self._trace.append(jnp.asarray(tok))
                    if len(self._trace) > 64:
                        self._trace.pop(0)

            def _admit(self, req):
                for tok in req.tokens:
                    self._window.append(jnp.asarray(tok))
                self._window = self._window[-64:]
        """
        assert self._rules(src) == []

    def test_unbounded_host_buffer_host_value_or_cold_path_clean(self):
        # Appending a host value retains no device buffer; appends
        # outside a loop or outside an *Engine class are one-shot /
        # not the serving hot path.
        src = """
        import jax.numpy as jnp

        class ContinuousEngine:
            def step(self):
                for tok in self.stream:
                    self._ids.append(tok)
                self._snapshot.append(jnp.zeros((4,)))

        class TraceRecorder:
            def record(self):
                for tok in self.stream:
                    self._trace.append(jnp.asarray(tok))
        """
        assert self._rules(src) == []

    def test_baseline_budget(self):
        fs = [
            Finding("ast", "raw-clock", "a.py:10", "m"),
            Finding("ast", "raw-clock", "a.py:20", "m"),
            Finding("ast", "jit-in-loop", "a.py:30", "m"),
        ]
        budget = {("a.py", "raw-clock"): 2}
        left = apply_baseline(fs, budget)
        assert [f.rule for f in left] == ["jit-in-loop"]
        # One NEW raw-clock past the budget gates again.
        fs.append(Finding("ast", "raw-clock", "a.py:40", "m"))
        left = apply_baseline(fs, budget)
        assert sorted(f.rule for f in left) == ["jit-in-loop", "raw-clock"]


class TestCheckedInGoldens:
    """The shipped goldens: present for the key entry points, parseable,
    and structurally sane — without paying entry-point compiles here
    (cases/case20_shardcheck.py runs the full loop)."""

    REQUIRED = (
        "train_step", "train_step_gn", "train_step_skip",
        "zero1_update", "zero1_update_q8", "prefill",
        "decode_step", "mixed_step",
        "spec_prefill", "spec_decode_step", "spec_mixed_step",
        "adapter_mixed_step", "spec_adapter_mixed_step",
        "kv_export", "kv_ingest", "kv_page_spill", "kv_page_fill",
        "swap_reshard", "swap_reshard_quant",
        "moe_dispatch", "ring_attention", "ulysses_attention",
    )

    def test_goldens_exist_and_parse(self):
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR

        for name in self.REQUIRED:
            c = Contract.load(GOLDEN_DIR / f"{name}.json")
            assert c.name == name
            assert c.mesh_shape and c.mesh_axes

    def test_goldens_and_entry_points_are_a_bijection(self):
        """Round-13 coverage audit: every entry point has a golden AND
        every golden names a live entry point — an orphaned golden (its
        program renamed or deleted) previously passed silently, pinning
        nothing. ``bench_headline.json`` is exempt: it is bench.py's
        collective contract, not an entry-point golden. Building the
        entry-point list is lazy (no compiles), so this stays cheap."""
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR
        from learning_jax_sharding_tpu.analysis.entrypoints import (
            build_entry_programs,
        )

        entry_names = {p.name for p in build_entry_programs()}
        golden_names = {
            f.stem for f in GOLDEN_DIR.glob("*.json")
        } - {"bench_headline"}
        missing = entry_names - golden_names
        assert not missing, (
            f"entry points without goldens (run scripts/shardcheck.py "
            f"--update-golden): {sorted(missing)}"
        )
        orphaned = golden_names - entry_names
        assert not orphaned, (
            f"goldens naming no live entry point (stale — delete or "
            f"re-wire): {sorted(orphaned)}"
        )

    def test_searchable_entries_are_live_entry_points(self):
        """Round-17 audit extension: every entry the layout search can
        target (``SEARCHABLE_ENTRIES``) must name a live entry-point
        program AND a checked-in golden — a search advisory against a
        renamed entry would otherwise point at nothing, and its emitted
        contract could never be diffed against the golden it claims to
        improve on."""
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR
        from learning_jax_sharding_tpu.analysis.entrypoints import (
            SEARCHABLE_ENTRIES,
            build_entry_programs,
        )

        entry_names = {p.name for p in build_entry_programs()}
        golden_names = {f.stem for f in GOLDEN_DIR.glob("*.json")}
        searchable = set(SEARCHABLE_ENTRIES)
        assert searchable <= entry_names, (
            f"searchable entries naming no live entry point: "
            f"{sorted(searchable - entry_names)}"
        )
        assert searchable <= golden_names, (
            f"searchable entries without a golden to diff against: "
            f"{sorted(searchable - golden_names)}"
        )
        # The search's contract emitter must preserve the entry name so
        # the emitted file is comparable against the golden of the same
        # entry (byte-format parity is pinned in test_layout_search).
        golden = Contract.load(GOLDEN_DIR / "train_step.json")
        assert golden.name == "train_step"

    def test_goldens_record_real_communication(self):
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR

        # The sharded entry points must not have recorded vacuous
        # (replicated, no-comms) contracts: each of these programs
        # provably communicates on its mesh.
        for name in ("train_step", "zero1_update", "zero1_update_q8",
                     "prefill", "decode_step", "mixed_step",
                     "spec_mixed_step", "adapter_mixed_step",
                     "spec_adapter_mixed_step", "moe_dispatch"):
            c = Contract.load(GOLDEN_DIR / f"{name}.json")
            assert c.collectives, f"{name} golden records no collectives"

    def test_kv_handoff_goldens_pin_zero_collectives(self):
        """The round-11 disaggregated-handoff claim, as checked-in
        contract: BOTH device-side programs of the KV handoff (the
        export gather, the ingest update) compile to ZERO collectives —
        every cross-replica byte rides the explicit, counted
        fleet/kv_transfer plan, never a hidden XLA reshard. The round-15
        tier ladder's page programs (the spill gather, the fill update)
        carry the same claim for the HBM↔host rungs: migration bytes
        live in the counted ``HostBuffer`` plans only."""
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR

        for name in (
            "kv_export", "kv_ingest", "kv_page_spill", "kv_page_fill",
        ):
            c = Contract.load(GOLDEN_DIR / f"{name}.json")
            assert c.collectives == {}, (name, c.collectives)
            assert c.while_collectives == 0

    def test_swap_reshard_goldens_pin_pure_data_movement(self):
        """The round-12 hot-swap staging claim, as checked-in contract:
        resharding an FSDP-layout checkpoint into the serving layout
        MOVES weights (the goldens record real collectives — a vacuous
        no-comms contract would mean the source layout silently matched
        serving and the program pins nothing), but never COMBINES them —
        an all-reduce appearing here would mean XLA is summing shards,
        arithmetic that could perturb the swapped weights."""
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR

        for name in ("swap_reshard", "swap_reshard_quant"):
            c = Contract.load(GOLDEN_DIR / f"{name}.json")
            assert c.collectives, f"{name} golden records no collectives"
            assert not any(
                k.startswith("all-reduce") for k in c.collectives
            ), (name, c.collectives)
            assert c.while_collectives == 0

    def test_q8_golden_records_the_ring(self):
        """The quantized grad-sync golden must pin the int8 ring's
        collective-permutes — the whole point of its contract: a silent
        fall-back to the fp32 all-reduce would show up as these ops
        vanishing."""
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR

        c = Contract.load(GOLDEN_DIR / "zero1_update_q8.json")
        assert any(
            k.startswith("collective-permute") for k in c.collectives
        ), c.collectives
        assert c.while_collectives >= 1   # the ring hops ride fori_loops

    def test_ring_golden_admits_while_collectives(self):
        from learning_jax_sharding_tpu.analysis import GOLDEN_DIR

        c = Contract.load(GOLDEN_DIR / "ring_attention.json")
        assert c.while_collectives >= 1  # the ring rotation is a scan


class TestFindingsWiring:
    def test_report_findings_lands_in_recorder_and_registry(self):
        from learning_jax_sharding_tpu.analysis.findings import (
            report_findings,
        )
        from learning_jax_sharding_tpu.telemetry import MetricsRegistry
        from learning_jax_sharding_tpu.telemetry.flight_recorder import (
            FlightRecorder,
        )

        rec = FlightRecorder()
        reg = MetricsRegistry()
        fs = [Finding("ast", "jit-in-loop", "a.py:1", "m")] * 2
        report_findings(fs, recorder=rec, registry=reg)
        assert len(rec.events("shardcheck_finding")) == 2
        snap = reg.snapshot()
        [(name, value)] = [
            (k, v) for k, v in snap.items() if k.startswith("shardcheck_")
        ]
        assert "jit_in_loop" in name
        assert value == 2
