"""Ulysses (all-to-all head/seq swap) attention vs dense attention.

The second long-context strategy absent from the reference (SURVEY.md §2.4
"Ulysses: ❌ — no all-to-all anywhere"). Sequence sharded 4-way over 'y';
correctness requires the head/sequence swap to reassemble full sequences per
head subset and swap back.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention
from learning_jax_sharding_tpu.ops.attention import causal_mask, dot_product_attention
from learning_jax_sharding_tpu.ops.ulysses import make_ulysses_attn_fn, ulysses_attention
from learning_jax_sharding_tpu.parallel import (
    assert_collectives,
    assert_shard_shape,
    mesh_sharding,
    put,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_SP, activate

B, S, N, H = 2, 128, 4, 16  # N=4 divisible by the 4-way 'y' axis


def _qkv(rng):
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, N, H)).astype(np.float32))
        for _ in range(3)
    )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh24, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None
        expected = dot_product_attention(q, k, v, mask=mask)
        got = ulysses_attention(q, k, v, mesh=mesh24, axis="y", causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_output_stays_sequence_sharded(self, mesh24, rng):
        q, k, v = _qkv(rng)
        sh = mesh_sharding(mesh24, None, "y", None, None)
        q, k, v = put(q, sh), put(k, sh), put(v, sh)
        got = jax.jit(
            functools.partial(ulysses_attention, mesh=mesh24, axis="y", causal=True)
        )(q, k, v)
        assert_shard_shape(got, (B, S // 4, N, H))

    def test_uses_all_to_all(self, mesh24, rng):
        q, k, v = _qkv(rng)
        sh = mesh_sharding(mesh24, None, "y", None, None)
        q, k, v = put(q, sh), put(k, sh), put(v, sh)
        fn = functools.partial(ulysses_attention, mesh=mesh24, axis="y")
        assert_collectives(fn, q, k, v, require=("all-to-all",))

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, mesh24, rng, causal):
        q, k, v = _qkv(rng)
        mask = causal_mask(S) if causal else None

        def dense_loss(q, k, v):
            return jnp.sum(jnp.square(dot_product_attention(q, k, v, mask=mask)))

        def ulysses_loss(q, k, v):
            out = ulysses_attention(q, k, v, mesh=mesh24, axis="y", causal=causal)
            return jnp.sum(jnp.square(out))

        dg = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        ug = jax.grad(ulysses_loss, argnums=(0, 1, 2))(q, k, v)
        for name, d, u in zip("qkv", dg, ug):
            np.testing.assert_allclose(
                np.asarray(u), np.asarray(d), rtol=5e-4, atol=5e-5,
                err_msg=f"d{name} mismatch",
            )

    def test_head_divisibility_guard(self, mesh24, rng):
        q = jnp.zeros((B, S, 3, H))  # 3 heads, 4-way axis
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh=mesh24, axis="y")

    def test_heads_axis_partitions_tp_dimension(self, mesh24, rng):
        """Heads sharded over 'x' (TP) while the sequence rides the 'y' ring:
        per-device head count is N/2, swapped over the 4-way 'y' axis."""
        n_heads = 8  # N/|x| = 4, divisible by |y| = 4
        q, k, v = (
            jnp.asarray(rng.standard_normal((B, S, n_heads, H)).astype(np.float32))
            for _ in range(3)
        )
        expected = dot_product_attention(q, k, v, mask=causal_mask(S))
        sh = mesh_sharding(mesh24, None, "y", "x", None)
        qs, ks, vs = put(q, sh), put(k, sh), put(v, sh)
        got = jax.jit(
            functools.partial(
                ulysses_attention, mesh=mesh24, axis="y", heads_axis="x", causal=True
            )
        )(qs, ks, vs)
        assert_shard_shape(got, (B, S // 4, n_heads // 2, H))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
        )

    def test_module_integration_under_dp_sp_rules(self, mesh22, rng):
        """MultiHeadAttention with the Ulysses backend under RULES_DP_SP
        (batch→data, seq→model) matches the dense backend."""
        x = jnp.asarray(rng.standard_normal((4, 64, 32)).astype(np.float32))
        make = lambda fn: MultiHeadAttention(
            features=32, num_heads=4, head_dim=8, causal=True, attn_fn=fn
        )
        with activate(mesh22, RULES_DP_SP):
            dense = make(None)
            params = dense.init({"params": jax.random.key(0)}, x)
            y_dense = dense.apply(params, x)
            ulysses = make(make_ulysses_attn_fn(mesh22, RULES_DP_SP))
            y_ulysses = ulysses.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(y_ulysses), np.asarray(y_dense), rtol=2e-4, atol=2e-5
        )

    def test_rules_conflict_guard(self, mesh22):
        # Within one spec flax resolves duplicate mappings (seq+heads→model)
        # by dropping the later one, so the conflict only arises when the ring
        # axis is forced explicitly onto the axis the rules give to HEADS.
        tp_rules = (("batch", "data"), ("heads", "model"))
        with pytest.raises(ValueError, match="SEQ and HEADS"):
            make_ulysses_attn_fn(mesh22, tp_rules, axis="model")

    def test_no_seq_axis_guard(self, mesh22):
        with pytest.raises(ValueError, match="no mesh axis"):
            make_ulysses_attn_fn(mesh22, (("batch", "data"),))
