"""Test harness: 8 emulated CPU devices, exercising the real GSPMD partitioner.

This is the reference's one testing mechanism — forcing host devices via
``XLA_FLAGS`` (`/root/reference/case1a.py:2-3`) — promoted to a pytest fixture
layer. Must run before any JAX device access, hence the module-top env setup.
"""

from learning_jax_sharding_tpu.parallel import build_mesh, force_emulated_devices

# Must precede backend initialization (i.e. before any test module's device
# access). 8 devices covers the (2,4) mesh of cases 1-4 and the (2,2) mesh of
# cases 5-6 (which use the first 4 devices). Raises if the backend beat us.
force_emulated_devices(8)

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh24():
    """(2,4) 'x','y' mesh — the layout of cases 1a/1b/2/3/4
    (`/root/reference/case1a.py:15`)."""
    return build_mesh((2, 4), ("x", "y"))


@pytest.fixture(scope="session")
def mesh22():
    """(2,2) 'data','model' mesh — the layout of cases 5/6
    (`/root/reference/case6_attention.py:155-156`)."""
    return build_mesh((2, 2), ("data", "model"), devices=jax.devices()[:4])


@pytest.fixture()
def rng():
    # Function-scoped: every test sees the same deterministic stream
    # regardless of execution order.
    return np.random.default_rng(0)


def matmul_operands(rng, m=4, k=16, n=4):
    """The A(4,16)·B(16,4) operand pair of cases 1a-4
    (`/root/reference/case1a.py:17-18`), shared across test modules."""
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    return a, b
