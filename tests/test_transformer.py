"""Case-7 composed transformer: sharded end-to-end training on a 2D mesh.

The north-star composition (`/root/repo/BASELINE.json`): case-4 FF + case-6
attention in one block, trained under data×model rules. Tests run the tiny
config on the emulated mesh; the 125M flagship runs in bench.py on hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_125M,
    CONFIG_TINY,
    Transformer,
    TransformerConfig,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import (
    assert_shard_shape,
    collective_counts,
    mesh_sharding,
    put,
)
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    activate,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


def _batch(mesh, cfg, b=8, s=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    return {
        "inputs": put(tokens[:, :-1], sh),
        "targets": put(tokens[:, 1:], sh),
    }


def _setup(mesh, cfg=CONFIG_TINY, b=8, s=32):
    model = Transformer(cfg)
    batch = _batch(mesh, cfg, b=b, s=s)
    state, state_shardings = sharded_train_state(
        model, optax.adamw(3e-4), batch["inputs"], {"params": jax.random.key(0)},
        mesh, RULES_DP_TP,
    )
    batch_shardings = {k: v.sharding for k, v in batch.items()}
    step = make_train_step(
        state_shardings, batch_shardings, mesh, RULES_DP_TP, loss_fn=next_token_loss
    )
    return model, batch, state, state_shardings, step


class TestTransformer:
    def test_param_count_125m(self):
        # BASELINE.json flagship: "composed 125M transformer".
        assert 120e6 < CONFIG_125M.param_count < 165e6

    def test_forward_shapes_and_tp_sharding(self, mesh22):
        cfg = CONFIG_TINY
        model, batch, state, _, _ = _setup(mesh22)
        # FF up-kernel (EMBED, MLP): MLP→model splits columns (128 → 64).
        up = state.params["block_0"]["ff"]["up"]["kernel"]
        assert up.shape == (cfg.features, cfg.hidden)
        assert_shard_shape(up, (cfg.features, cfg.hidden // 2))
        # QKV kernel (EMBED, HEADS): HEADS→model splits columns.
        wq = state.params["block_0"]["attn"]["query"]["kernel"]
        assert_shard_shape(wq, (cfg.features, cfg.num_heads * cfg.head_dim // 2))
        # Embedding (VOCAB, EMBED): VOCAB→model splits rows.
        emb = state.params["tok_embed"]["embedding"]
        assert_shard_shape(emb, (cfg.vocab_size // 2, cfg.features))

    def test_training_descends(self, mesh22):
        _, batch, state, _, step = _setup(mesh22)
        losses = []
        for _ in range(10):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # Initial loss should be near uniform-prediction entropy ln(V).
        assert abs(losses[0] - np.log(CONFIG_TINY.vocab_size)) < 1.0

    def test_step_is_single_spmd_program_with_collectives(self, mesh22):
        _, batch, state, _, step = _setup(mesh22)
        with activate(mesh22, RULES_DP_TP):
            counts = collective_counts(
                step.jitted.lower(state, batch).compile().as_text()
            )
        # DP grad sync + TP activation reductions must be inside the step.
        assert counts["all-reduce"] >= 1, counts

    def test_remat_matches_no_remat(self, mesh22):
        cfg = CONFIG_TINY
        cfg_remat = TransformerConfig(**{**cfg.__dict__, "remat": True})
        model, batch, state, _, step = _setup(mesh22, cfg)
        _, _, state_r, _, step_r = _setup(mesh22, cfg_remat)
        _, loss = step(state, batch)
        _, loss_r = step_r(state_r, batch)
        np.testing.assert_allclose(float(loss), float(loss_r), rtol=1e-5)

    def test_causality(self, mesh22):
        """Changing future tokens must not change past logits."""
        cfg = CONFIG_TINY
        model, batch, state, _, _ = _setup(mesh22)
        tokens = np.asarray(batch["inputs"])
        with activate(mesh22, RULES_DP_TP):
            logits1 = model.apply({"params": state.params}, jnp.asarray(tokens))
            tokens2 = tokens.copy()
            tokens2[:, 16:] = (tokens2[:, 16:] + 1) % cfg.vocab_size
            logits2 = model.apply({"params": state.params}, jnp.asarray(tokens2))
        np.testing.assert_allclose(
            np.asarray(logits1[:, :16]), np.asarray(logits2[:, :16]),
            rtol=1e-4, atol=1e-5,
        )

    def test_dropout_active_when_rng_given(self, mesh22):
        """With dropout_rng the step runs deterministic=False and per-step
        folded keys — two steps from the same state must see different
        dropout masks (different losses on identical data)."""
        cfg = TransformerConfig(**{**CONFIG_TINY.__dict__, "dropout_rate": 0.5})
        model = Transformer(cfg)
        batch = _batch(mesh22, cfg)
        state, state_sh = sharded_train_state(
            model, optax.adamw(3e-4), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        batch_sh = {k: v.sharding for k, v in batch.items()}
        step = make_train_step(
            state_sh, batch_sh, mesh22, RULES_DP_TP, loss_fn=next_token_loss,
            dropout_rng=jax.random.key(7), donate_state=False,
        )
        _, loss0 = step(state, batch)
        state1, _ = step(state, batch)
        _, loss1 = step(state1, batch)  # state.step advanced → new mask
        step_det = make_train_step(
            state_sh, batch_sh, mesh22, RULES_DP_TP, loss_fn=next_token_loss,
            donate_state=False,
        )
        _, loss_det = step_det(state, batch)
        # dropout changes the loss vs deterministic, and masks differ by step
        assert float(loss0) != float(loss_det)
        assert float(loss0) != float(loss1)

    def test_fused_loss_matches_unfused(self, mesh22):
        """Chunked logits head: identical loss AND grads to the full-logits
        path (CE is independent across positions), at a fraction of the
        memory — the large-batch enabler on real HBM."""
        import functools

        from learning_jax_sharding_tpu.models.transformer import (
            fused_next_token_loss,
        )

        cfg = CONFIG_TINY
        model, batch, state, state_sh, _ = _setup(mesh22)
        batch_sh = {k: v.sharding for k, v in batch.items()}
        step_fused = make_train_step(
            state_sh, batch_sh, mesh22, RULES_DP_TP,
            loss_fn=functools.partial(fused_next_token_loss, chunk_size=8),
            loss_needs_params=True, apply_kwargs={"return_hidden": True},
            donate_state=False,
        )
        step_plain = make_train_step(
            state_sh, batch_sh, mesh22, RULES_DP_TP,
            loss_fn=next_token_loss, donate_state=False,
        )
        state_f, loss_f = step_fused(state, batch)
        state_p, loss_p = step_plain(state, batch)
        np.testing.assert_allclose(float(loss_f), float(loss_p), rtol=1e-6)
        for a, b in zip(
            jax.tree.leaves(state_f.params), jax.tree.leaves(state_p.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_fused_loss_chunk_divisibility(self, mesh22):
        from learning_jax_sharding_tpu.models.transformer import (
            fused_next_token_loss,
        )

        hidden = jnp.zeros((2, 10, 8))
        with pytest.raises(ValueError, match="chunk_size"):
            fused_next_token_loss(
                hidden, {"targets": jnp.zeros((2, 10), jnp.int32)},
                {"lm_head": {"kernel": jnp.zeros((8, 16))}}, chunk_size=4,
            )

    def test_seq_len_guard(self, mesh22):
        cfg = CONFIG_TINY
        model = Transformer(cfg)
        tokens = jnp.zeros((2, cfg.max_seq_len + 1), jnp.int32)
        try:
            model.init({"params": jax.random.key(0)}, tokens)
            raise AssertionError("expected ValueError for overlong sequence")
        except ValueError as e:
            assert "max_seq_len" in str(e)
