"""Speculative decoding: exactness against plain greedy, for ANY draft.

The defining property of (greedy) speculative decoding is that the draft
model changes only the COST of decoding, never the output: acceptance is a
hard equality against the target's own greedy choices, rejections are
corrected from the target's logits. So the oracle is brutal and simple —
output must be bit-identical to ``make_generate_fn``'s greedy decode of the
target alone, whatever the draft params are (untrained garbage, a smaller
model, or the target itself for the full-acceptance path).
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.speculative import (
    make_speculative_generate_fn,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

DRAFT_CFG = dataclasses.replace(CONFIG_TINY, num_layers=1, hidden=64)


def _trained_target(mesh, rng, steps=5):
    model = Transformer(CONFIG_TINY)
    tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    for _ in range(steps):
        state, _ = step(state, batch)
    return state.params, tokens


def _draft_params(cfg=DRAFT_CFG):
    model = Transformer(cfg)
    toks = np.zeros((2, 8), np.int32)
    return nn.meta.unbox(
        model.init({"params": jax.random.key(7)}, toks)["params"]
    )


class TestSpeculativeExactness:
    @pytest.mark.parametrize("num_draft", [1, 3, 5])
    def test_matches_plain_greedy_any_draft(self, mesh22, rng, num_draft):
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()  # UNTRAINED draft: worst case, still exact
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))

        plain = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=16
        )
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=16, num_draft=num_draft,
        )
        out_plain = np.asarray(plain(t_params, prompt, jax.random.key(0)))
        out_spec = np.asarray(spec(t_params, d_params, prompt))
        np.testing.assert_array_equal(out_spec, out_plain)

    def test_full_acceptance_with_self_draft(self, mesh22, rng):
        """Draft == target: every proposal matches, so every round takes the
        m == num_draft path (draft-cache completeness edge) — and the output
        is still exactly plain greedy."""
        t_params, tokens = _trained_target(mesh22, rng)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        plain = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=12
        )
        spec = make_speculative_generate_fn(
            CONFIG_TINY, CONFIG_TINY, mesh22, RULES_DP_TP,
            max_new_tokens=12, num_draft=4,
        )
        out_plain = np.asarray(plain(t_params, prompt, jax.random.key(0)))
        out_spec = np.asarray(spec(t_params, t_params, prompt))
        np.testing.assert_array_equal(out_spec, out_plain)

    def test_inference_dtype_exactness(self, mesh22, rng):
        """bf16 serving: params cast eagerly (not per loop round) and the
        output still matches make_generate_fn's bf16 greedy decode."""
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        plain = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=12,
            inference_dtype=jnp.bfloat16,
        )
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=12, num_draft=3, inference_dtype=jnp.bfloat16,
        )
        out_plain = np.asarray(plain(t_params, prompt, jax.random.key(0)))
        out_spec = np.asarray(spec(t_params, d_params, prompt))
        np.testing.assert_array_equal(out_spec, out_plain)

    def test_batch_rows_decode_independently(self, mesh22, rng):
        """Batch-min acceptance must not leak tokens across rows: decoding a
        batch equals decoding each half separately."""
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=10, num_draft=3,
        )
        sh = mesh_sharding(mesh22, "data", None)
        full = np.asarray(spec(t_params, d_params, put(tokens[:4, :8], sh)))
        hi = np.asarray(spec(t_params, d_params, put(tokens[:2, :8], sh)))
        lo = np.asarray(spec(t_params, d_params, put(tokens[2:4, :8], sh)))
        np.testing.assert_array_equal(full, np.concatenate([hi, lo], axis=0))


class TestSpeculativeBlockedBackend:
    """The production TPU decode path (the blocked cache kernel, interpret
    mode on CPU) under the speculative loop. What this exercises that the
    dense variants cannot: acceptance ROLLBACK rewinds ``cache_index`` over
    the sequence-major ``(B, N_kv, L, H)`` cache (stale K/V past the index
    must be masked by the kernel's valid-blocks clamp, then overwritten by
    the next round's chunk write), and verification chunks ride the
    kernel's q-tiling. On the 4-device mesh the kernel runs through the
    shard_map wrapper (``make_decode_attn_fn``) — the multi-chip path."""

    @pytest.mark.parametrize("num_draft", [1, 3])
    def test_blocked_matches_plain_greedy(self, mesh22, rng, num_draft):
        cfg = dataclasses.replace(CONFIG_TINY, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()  # untrained: rejections (and rollback)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        plain = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=12)
        spec = make_speculative_generate_fn(
            cfg, dcfg, mesh22, RULES_DP_TP,
            max_new_tokens=12, num_draft=num_draft,
        )
        out_plain = np.asarray(plain(t_params, prompt, jax.random.key(0)))
        out_spec = np.asarray(spec(t_params, d_params, prompt))
        np.testing.assert_array_equal(out_spec, out_plain)

    def test_blocked_int8_cache_matches_plain(self, mesh22, rng):
        """int8 cache × speculative rollback: per-(token, head) scales are
        rewound/overwritten alongside the values, under the in-kernel
        dequant. Oracle: spec ≡ plain greedy on the SAME backend (the
        defining property must survive the quantized cache)."""
        cfg = dataclasses.replace(
            CONFIG_TINY, decode_attention="blocked", kv_cache_dtype=jnp.int8
        )
        dcfg = dataclasses.replace(
            DRAFT_CFG, decode_attention="blocked", kv_cache_dtype=jnp.int8
        )
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        plain = make_generate_fn(cfg, mesh22, RULES_DP_TP, max_new_tokens=10)
        spec = make_speculative_generate_fn(
            cfg, dcfg, mesh22, RULES_DP_TP, max_new_tokens=10, num_draft=2,
        )
        out_plain = np.asarray(plain(t_params, prompt, jax.random.key(0)))
        out_spec = np.asarray(spec(t_params, d_params, prompt))
        np.testing.assert_array_equal(out_spec, out_plain)


class TestSpeculativeValidation:
    def test_vocab_mismatch_rejected(self, mesh22):
        bad = dataclasses.replace(DRAFT_CFG, vocab_size=128)
        with pytest.raises(ValueError, match="vocab"):
            make_speculative_generate_fn(
                CONFIG_TINY, bad, mesh22, RULES_DP_TP, max_new_tokens=4
            )

    def test_bad_num_draft_rejected(self, mesh22):
        with pytest.raises(ValueError, match="num_draft"):
            make_speculative_generate_fn(
                CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
                max_new_tokens=4, num_draft=0,
            )

    def test_seq_len_overflow_rejected(self, mesh22, rng):
        t_params, tokens = _trained_target(mesh22, rng, steps=1)
        d_params = _draft_params()
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=CONFIG_TINY.max_seq_len, num_draft=2,
        )
        prompt = put(tokens[:2, :8], mesh_sharding(mesh22, "data", None))
        with pytest.raises(ValueError, match="max_seq_len"):
            spec(t_params, d_params, prompt)


class TestSpeculativeSampling:
    """temperature > 0: Leviathan rejection sampling. The oracle is
    DISTRIBUTIONAL — emitted tokens must follow exactly the target's own
    (filtered) sampling distribution, whatever the draft proposes."""

    def test_two_token_joint_matches_target_distribution(self, mesh22, rng):
        """4096 identical prompt rows → 4096 iid 2-token samples; their
        empirical joint must match the exact target joint (computed from the
        full-sequence model with the same top-k filter) in total variation.
        An untrained 1-layer draft makes acceptance genuinely partial, so
        the accept, residual, AND bonus paths all contribute."""
        from learning_jax_sharding_tpu.models.generate import top_k_filter

        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        b = 4096
        prompt_row = tokens[:1, :8]
        prompt = jnp.asarray(np.repeat(prompt_row, b, axis=0))
        gen = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=2, num_draft=2, temperature=1.0, top_k=4,
        )
        out = np.asarray(gen(t_params, d_params, prompt, jax.random.key(11)))
        pairs = out[:, 8:10]

        model = Transformer(CONFIG_TINY)
        v = CONFIG_TINY.vocab_size

        def filtered_probs(toks):
            logits = model.apply({"params": t_params}, jnp.asarray(toks))
            return np.asarray(
                jax.nn.softmax(
                    top_k_filter(logits[:, -1].astype(jnp.float32), 4), axis=-1
                )
            )

        p0 = filtered_probs(prompt_row)[0]
        exact = np.zeros((v, v))
        (support0,) = np.nonzero(p0)
        for t0 in support0:
            row = np.concatenate(
                [prompt_row, [[t0]]], axis=1
            ).astype(np.int32)
            exact[t0] = p0[t0] * filtered_probs(row)[0]
        emp = np.zeros((v, v))
        for t0, t1 in pairs:
            emp[t0, t1] += 1.0 / b
        # Samples may only land in the exact joint's support.
        assert (emp[exact == 0] == 0).all()
        tv = 0.5 * np.abs(emp - exact).sum()
        # 4096 samples over <=16(+ties) cells: expected TV ~0.03.
        assert tv < 0.1, f"total variation {tv:.3f}"

    def test_same_rng_deterministic_different_rng_varies(self, mesh22, rng):
        t_params, tokens = _trained_target(mesh22, rng, steps=2)
        d_params = _draft_params()
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        gen = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=10, num_draft=3, temperature=1.0,
        )
        a = np.asarray(gen(t_params, d_params, prompt, jax.random.key(1)))
        b_ = np.asarray(gen(t_params, d_params, prompt, jax.random.key(1)))
        c = np.asarray(gen(t_params, d_params, prompt, jax.random.key(2)))
        np.testing.assert_array_equal(a, b_)
        assert (a != c).any()

    def test_self_draft_full_acceptance_sampling(self, mesh22, rng):
        """Draft == target ⇒ p == q ⇒ every proposal accepted (u <= 1);
        output must be valid and deterministic per rng — the all-accept
        path of the sampling verifier."""
        t_params, tokens = _trained_target(mesh22, rng, steps=2)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        gen = make_speculative_generate_fn(
            CONFIG_TINY, CONFIG_TINY, mesh22, RULES_DP_TP,
            max_new_tokens=8, num_draft=4, temperature=1.0, top_k=16,
        )
        out = np.asarray(gen(t_params, t_params, prompt, jax.random.key(3)))
        assert out.shape == (4, 16)
        assert ((0 <= out) & (out < CONFIG_TINY.vocab_size)).all()


class TestSpeculativeRagged:
    """``ragged=True``: PER-ROW acceptance and rollback over the per-row
    ``cache_index`` machinery. The oracles:

    * greedy output bit-identical to ``make_generate_fn(ragged=True)``'s
      per-row greedy decode (mixed prompt lengths, dense AND blocked);
    * per-row acceptance counts are exact (self-draft pins the formula);
    * a row's output is independent of every other row (greedy AND
      sampled — the (row, position)-keyed randomness makes this hold for
      temperature > 0 too, which the batch-min path cannot promise).
    """

    LENGTHS = np.array([8, 5, 3, 7], np.int32)

    def _ragged_prompt(self, tokens):
        prompt = tokens[:4, :8].copy()
        for b, n in enumerate(self.LENGTHS):
            prompt[b, n:] = 0  # right padding (value irrelevant)
        return prompt

    @pytest.mark.parametrize("num_draft", [1, 3])
    def test_matches_plain_ragged_greedy(self, mesh22, rng, num_draft):
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()  # untrained draft: rejections + rewinds
        sh = mesh_sharding(mesh22, "data", None)
        prompt = put(self._ragged_prompt(tokens), sh)
        lengths = jnp.asarray(self.LENGTHS)

        plain = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=12, ragged=True
        )
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=12, num_draft=num_draft, ragged=True,
        )
        out_plain = np.asarray(
            plain(t_params, prompt, jax.random.key(0), lengths=lengths)
        )
        out_spec = np.asarray(
            spec(t_params, d_params, prompt, lengths=lengths)
        )
        np.testing.assert_array_equal(out_spec, out_plain)

    def test_blocked_matches_plain_ragged_greedy(self, mesh22, rng):
        """The production path: per-row rollback over the sequence-major
        blocked cache with FOLDED single-token writes (draft steps) and
        scattered chunk writes (verification)."""
        cfg = dataclasses.replace(CONFIG_TINY, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        sh = mesh_sharding(mesh22, "data", None)
        prompt = put(self._ragged_prompt(tokens), sh)
        lengths = jnp.asarray(self.LENGTHS)

        plain = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=10, ragged=True
        )
        spec = make_speculative_generate_fn(
            cfg, dcfg, mesh22, RULES_DP_TP,
            max_new_tokens=10, num_draft=2, ragged=True,
        )
        out_plain = np.asarray(
            plain(t_params, prompt, jax.random.key(0), lengths=lengths)
        )
        out_spec = np.asarray(
            spec(t_params, d_params, prompt, lengths=lengths)
        )
        np.testing.assert_array_equal(out_spec, out_plain)

    def test_per_row_acceptance_stats_self_draft(self, mesh22, rng):
        """Draft == target: every row accepts all num_draft proposals every
        round, so the stats are an exact formula — rounds =
        ceil((max_new - 1) / (num_draft + 1)), accepted = rounds*num_draft,
        emitted = 1 + rounds*(num_draft+1) — per ROW (no batch-min)."""
        t_params, tokens = _trained_target(mesh22, rng)
        sh = mesh_sharding(mesh22, "data", None)
        prompt = put(self._ragged_prompt(tokens), sh)
        lengths = jnp.asarray(self.LENGTHS)
        max_new, nd = 12, 3
        spec = make_speculative_generate_fn(
            CONFIG_TINY, CONFIG_TINY, mesh22, RULES_DP_TP,
            max_new_tokens=max_new, num_draft=nd, ragged=True,
        )
        _, stats = spec(
            t_params, t_params, prompt, lengths=lengths, return_stats=True
        )
        rounds = -(-(max_new - 1) // (nd + 1))
        np.testing.assert_array_equal(
            np.asarray(stats["accepted"]), np.full(4, rounds * nd)
        )
        assert int(stats["rounds"]) == rounds
        np.testing.assert_array_equal(
            np.asarray(stats["emitted"]), np.full(4, 1 + rounds * (nd + 1))
        )

    def test_rows_independent_greedy(self, mesh22, rng):
        """Per-row acceptance means row b's output cannot depend on any
        other row — decode the batch, then the batch with every OTHER row's
        prompt replaced; row b must be bit-identical."""
        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        sh = mesh_sharding(mesh22, "data", None)
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=10, num_draft=3, ragged=True,
        )
        prompt = self._ragged_prompt(tokens)
        a = np.asarray(
            spec(t_params, d_params, put(prompt, sh),
                 lengths=jnp.asarray(self.LENGTHS))
        )
        other = prompt.copy()
        other[0] = tokens[5, :8]  # different row-0 prompt, same length
        b = np.asarray(
            spec(t_params, d_params, put(other, sh),
                 lengths=jnp.asarray(self.LENGTHS))
        )
        np.testing.assert_array_equal(a[1:], b[1:])

    def test_rows_independent_sampled(self, mesh22, rng):
        """(row, position)-keyed randomness: a row's SAMPLED stream is also
        independent of the rest of the batch — the property per-dispatch
        keys (and batch-min rollback) cannot provide."""
        t_params, tokens = _trained_target(mesh22, rng, steps=2)
        d_params = _draft_params()
        sh = mesh_sharding(mesh22, "data", None)
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=10, num_draft=3, temperature=1.0, top_k=16,
            ragged=True,
        )
        prompt = self._ragged_prompt(tokens)
        key = jax.random.key(11)
        a = np.asarray(
            spec(t_params, d_params, put(prompt, sh), key,
                 lengths=jnp.asarray(self.LENGTHS))
        )
        other = prompt.copy()
        other[0] = tokens[5, :8]
        b = np.asarray(
            spec(t_params, d_params, put(other, sh), key,
                 lengths=jnp.asarray(self.LENGTHS))
        )
        np.testing.assert_array_equal(a[1:], b[1:])
        # Determinism: same rng reproduces; different rng varies.
        c = np.asarray(
            spec(t_params, d_params, put(prompt, sh), key,
                 lengths=jnp.asarray(self.LENGTHS))
        )
        np.testing.assert_array_equal(a, c)
        d = np.asarray(
            spec(t_params, d_params, put(prompt, sh), jax.random.key(12),
                 lengths=jnp.asarray(self.LENGTHS))
        )
        assert (a != d).any()

    def test_ragged_sampled_joint_matches_target_distribution(
        self, mesh22, rng
    ):
        """The ragged path's OWN rejection math (generate_ragged_sampled is
        a separate implementation from the rectangular verifier), pinned
        distributionally: 4096 identical rows with (row, position)-keyed
        draws are 4096 iid 2-token samples; their empirical joint must
        match the exact target joint under the same top-k filter."""
        from learning_jax_sharding_tpu.models.generate import top_k_filter

        t_params, tokens = _trained_target(mesh22, rng)
        d_params = _draft_params()
        b = 4096
        prompt_row = tokens[:1, :8]
        prompt = jnp.asarray(np.repeat(prompt_row, b, axis=0))
        gen = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=2, num_draft=2, temperature=1.0, top_k=4,
            ragged=True,
        )
        out = np.asarray(
            gen(
                t_params, d_params, prompt, jax.random.key(17),
                lengths=jnp.full((b,), 8, jnp.int32),
            )
        )
        pairs = out[:, 8:10]

        model = Transformer(CONFIG_TINY)
        v = CONFIG_TINY.vocab_size

        def filtered_probs(toks):
            logits = model.apply({"params": t_params}, jnp.asarray(toks))
            return np.asarray(
                jax.nn.softmax(
                    top_k_filter(logits[:, -1].astype(jnp.float32), 4),
                    axis=-1,
                )
            )

        p0 = filtered_probs(prompt_row)[0]
        exact = np.zeros((v, v))
        (support0,) = np.nonzero(p0)
        for t0 in support0:
            row = np.concatenate(
                [prompt_row, [[t0]]], axis=1
            ).astype(np.int32)
            exact[t0] = p0[t0] * filtered_probs(row)[0]
        emp = np.zeros((v, v))
        for t0, t1 in pairs:
            emp[t0, t1] += 1.0 / b
        assert (emp[exact == 0] == 0).all()
        tv = 0.5 * np.abs(emp - exact).sum()
        # 4096 samples over <=16(+ties) cells: expected TV ~0.03.
        assert tv < 0.1, f"total variation {tv:.3f}"

    def test_lengths_validation(self, mesh22, rng):
        t_params, tokens = _trained_target(mesh22, rng, steps=1)
        d_params = _draft_params()
        sh = mesh_sharding(mesh22, "data", None)
        prompt = put(tokens[:4, :8], sh)
        spec_r = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP,
            max_new_tokens=4, ragged=True,
        )
        with pytest.raises(ValueError, match="lengths"):
            spec_r(t_params, d_params, prompt)
        spec = make_speculative_generate_fn(
            CONFIG_TINY, DRAFT_CFG, mesh22, RULES_DP_TP, max_new_tokens=4,
        )
        with pytest.raises(ValueError, match="lengths"):
            spec(t_params, d_params, prompt, lengths=jnp.full((4,), 8))
        with pytest.raises(ValueError, match="return_stats"):
            spec(t_params, d_params, prompt, return_stats=True)
