"""Device-resident multi-step scheduling (models/serving.py, ``horizon>1``).

THE oracle, inherited from test_mixed_step.py and applied to the fused
HORIZON: scheduling must never change results. ``multi_step`` scans the
exact ``mixed_step`` body N times per dispatch with the slot bookkeeping
carried device-side, so every output — fresh prompts, boundary admits,
prefix hits, budget starvation, speculative rounds, multi-tenant rows,
retirement mid-horizon — must be BIT-IDENTICAL to the horizon=1 engine
(itself pinned to the split engine), greedy and sampled alike. On top of
the value oracle, this file pins the PROGRAM contract: ``horizon=1``
dispatches exactly today's programs (no multi program compiled, multi
counters silent), and ``horizon>1`` adds exactly ONE steady-state
executable per engaged program family.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.serving import (
    ContinuousEngine,
    make_continuous_engine,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    RULES_TP_SERVING,
)

NEW = 6

DRAFT_CFG = dataclasses.replace(
    CONFIG_TINY, num_layers=1, hidden=64, dtype=jnp.float32
)


@pytest.fixture(scope="module")
def setup(mesh22):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    rng = np.random.default_rng(11)
    model = Transformer(cfg)
    probe = np.zeros((2, 8), np.int32)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), probe
        )["params"]
    )
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in (3, 9, 5, 1, 12, 7, 4)
    ]
    return cfg, params, prompts


@pytest.fixture(scope="module")
def mixed_ref(setup, mesh22):
    """The horizon=1 fused engine the multi-step engine is held
    bit-identical to (itself pinned to the split engine in
    test_mixed_step.py)."""
    cfg, params, prompts = setup
    serve = make_continuous_engine(
        cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        refill_chunk=4, mixed=True,
    )
    return serve(params, prompts)


def _draft_params():
    model = Transformer(DRAFT_CFG)
    toks = np.zeros((2, 8), np.int32)
    return nn.meta.unbox(
        model.init({"params": jax.random.key(7)}, toks)["params"]
    )


class TestMultiStep:
    def test_matches_mixed_engine(self, setup, mesh22, mixed_ref):
        """7 mixed-length requests through 2 slots at horizon=4: every
        output equals the horizon=1 engine's bit for bit. With NEW=6 and
        staggered completion, rows retire at links INSIDE the horizon
        (the device active-mask freezes them; the host retires at the
        boundary sync) — the retirement-mid-horizon case rides the base
        oracle. Exactly ONE ``multi_step`` executable compiles and the
        per-link ``mixed_step`` program never dispatches."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, horizon=4,
        )
        outs = serve(params, prompts)
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)
        eng = serve.engine
        cc = eng.compile_counts()
        assert cc["multi_step"] == 1
        assert cc["mixed_step"] == 0
        assert eng._c_multi_n.value > 0
        # The whole point: > 1 engine iteration per host dispatch.
        lat = serve.last_latency
        assert lat["steps_per_dispatch"] > 1.0
        assert eng.ledger.reconcile()["ok"]

    def test_horizon_one_is_todays_loop(self, setup, mesh22, mixed_ref):
        """``horizon=1`` must reduce EXACTLY to the current engine: same
        outputs, same dispatched program set (no multi program compiled,
        let alone dispatched), multi counters silent, and no staged
        plans — byte-for-byte today's loop."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, horizon=1,
        )
        outs = serve(params, prompts)
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)
        eng = serve.engine
        cc = eng.compile_counts()
        assert "multi_step" not in cc
        assert cc["mixed_step"] == 1
        assert eng._c_multi_n.value == 0
        assert eng._c_plan_staged.value == 0
        assert eng._staged_plan is None
        assert "steps_per_dispatch" not in serve.last_latency
        names = [n for n, _f, _a in eng._dispatched_programs()]
        assert "multi_step" not in names

    @pytest.mark.slow
    def test_horizon_sweep(self, setup, mesh22, mixed_ref):
        """Horizons beyond the chain cap, below it, and absurdly past
        the longest request: fixed-shape padding and the per-step live
        gate keep every rung bit-identical."""
        cfg, params, prompts = setup
        for horizon in (2, 8, 16):
            serve = make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2,
                max_new_tokens=NEW, refill_chunk=4, mixed=True,
                horizon=horizon,
            )
            outs = serve(params, prompts)
            for r, g in zip(mixed_ref, outs):
                np.testing.assert_array_equal(g, r)
            assert serve.engine.compile_counts()["multi_step"] == 1

    def test_refill_lands_at_boundary(self, setup, mesh22, mixed_ref):
        """Requests admitted WHILE a horizon is in flight: the async
        planner cannot see them (its staged plan's fingerprint misses),
        so they refill at the NEXT boundary — outputs unchanged, and the
        planner's stage/reuse accounting stays consistent."""
        cfg, params, prompts = setup
        eng = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, horizon=4,
        ).engine
        eng.add_request(prompts[0], rid=0)
        eng.add_request(prompts[1], rid=1)
        outs, steps, pending = {}, 0, list(range(2, 7))
        while eng.has_work() or pending:
            eng.step(params)
            steps += 1
            if pending:
                i = pending.pop(0)
                eng.add_request(prompts[i], rid=i)
            outs.update(eng.pop_finished())
        for i, r in enumerate(mixed_ref):
            np.testing.assert_array_equal(outs[i], r)
        assert eng._c_plan_reused.value <= eng._c_plan_staged.value

    @pytest.mark.slow
    def test_budget_starved(self, setup, mesh22, mixed_ref):
        """A token budget smaller than one refill chunk: prompts trickle
        across horizon links (and across horizons) while decode rows
        keep advancing — results must not move."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, token_budget=3, horizon=4,
        )
        outs = serve(params, prompts)
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)

    @pytest.mark.slow
    def test_eos_retires_mid_horizon(self, setup, mesh22):
        """EOS emitted at a link INSIDE the horizon: the host retires
        the row at the boundary sync exactly where the horizon=1 engine
        stops it (consume truncates at EOS; the device active-mask only
        ever freezes rows)."""
        cfg, params, prompts = setup
        base = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        ref = base(params, prompts)
        eos = int(ref[0][len(prompts[0]) + 1])
        ref_eng = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, eos_id=eos, mixed=True,
        )
        eos_ref = ref_eng(params, prompts)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, eos_id=eos, mixed=True, horizon=4,
        )
        outs = serve(params, prompts)
        for r, g in zip(eos_ref, outs):
            np.testing.assert_array_equal(g, r)

    def test_paged_long_prompt(self, setup, mesh22):
        """The paged engine at horizon=4 with a 44-token prompt through
        8-token chunks: the planner's virtual page ensures cover refill
        AND decode writes of the whole horizon."""
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, max_seq_len=64, decode_attention="blocked"
        )
        rng = np.random.default_rng(5)
        long_prompts = [
            rng.integers(1, cfg.vocab_size, size=(44,)).astype(np.int32),
            prompts[0], prompts[2],
        ]
        ref_eng = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            paged_pages=16, page_size=8,
        )
        ref = ref_eng(params, long_prompts)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            paged_pages=16, page_size=8, horizon=4,
        )
        outs = serve(params, long_prompts)
        for r, g in zip(ref, outs):
            np.testing.assert_array_equal(g, r)

    def test_prefix_hits_across_calls(self, setup, mesh22):
        """Prefix caching at horizon=4: the warm pass re-admits
        shared-prefix prompts with pages already mapped (reset_to > 0
        riding the scan's link-0 reset row) — outputs bit-identical,
        hits counted."""
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, max_seq_len=64, decode_attention="blocked"
        )
        rng = np.random.default_rng(9)
        system = rng.integers(
            1, cfg.vocab_size, size=(16,)
        ).astype(np.int32)
        queue = [
            np.concatenate([
                system,
                rng.integers(1, cfg.vocab_size, size=(4,)).astype(
                    np.int32
                ),
            ])
            for _ in range(4)
        ]
        ref_eng = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            paged_pages=16, page_size=8, prefix_cache=True,
        )
        ref = ref_eng(params, queue)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            paged_pages=16, page_size=8, prefix_cache=True, horizon=4,
        )
        cold = serve(params, queue)
        warm = serve(params, queue)
        for r, g in zip(ref, cold):
            np.testing.assert_array_equal(g, r)
        for r, g in zip(ref, warm):
            np.testing.assert_array_equal(g, r)
        assert serve.last_stats["prefix_hits"] == len(queue)

    @pytest.mark.slow
    def test_sampled_schedule_independent(self, setup, mesh22):
        """temperature > 0 at horizon=4 under a starving budget (a
        maximally different schedule from the horizon=1 reference): the
        IDENTICAL sampled stream per request — draws are keyed by
        (request id, generated position), never by schedule."""
        cfg, params, prompts = setup
        ref_eng = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, temperature=0.7, top_k=8, mixed=True,
        )
        multi = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
            refill_chunk=4, temperature=0.7, top_k=8, mixed=True,
            token_budget=5, horizon=4,
        )
        a = ref_eng(params, prompts, rng=jax.random.key(42))
        b = multi(params, prompts, rng=jax.random.key(42))
        for r, g in zip(a, b):
            np.testing.assert_array_equal(g, r)

    def test_validation(self, setup, mesh22):
        cfg, params, prompts = setup
        with pytest.raises(ValueError, match="horizon must be >= 1"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2,
                max_new_tokens=NEW, mixed=True, horizon=0,
            )
        with pytest.raises(ValueError, match="requires mixed=True"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2,
                max_new_tokens=NEW, horizon=4,
            )

    def test_runtime_tunable(self, setup, mesh22, mixed_ref):
        """The horizon is a runtime knob read at each dispatch: the SAME
        engine serves at horizon=1, is retuned to 4, and serves again —
        both passes bit-identical, the multi program compiling only once
        engaged."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        outs = serve(params, prompts)
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert "multi_step" not in serve.engine.compile_counts()
        serve.engine.horizon = 4
        outs = serve(params, prompts)
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert serve.engine.compile_counts()["multi_step"] == 1


class TestSpeculativeMulti:
    """spec_multi_step: N scanned draft-verify rounds per dispatch, the
    per-row rollback index and BOTH caches carried device-side, emission
    buffers riding the scan ys."""

    def test_weak_draft_matches(self, setup, mesh22, mixed_ref):
        """Weak draft (near-zero acceptance) at horizon=4: per-row
        rollback runs inside the scan — outputs bit-identical to the
        plain horizon=1 engine, one spec multi executable."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, draft_config=DRAFT_CFG,
            num_draft=3, horizon=4,
        )
        outs = serve(params, prompts, draft_params=_draft_params())
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert serve.engine.compile_counts()["multi_step"] == 1

    @pytest.mark.slow
    def test_self_draft_fast_forward(self, setup, mesh22, mixed_ref):
        """Self-draft (acceptance 1.0) at horizon=4: rows fast-forward
        num_draft+1 tokens per scanned round — the live-mask gate must
        freeze the padded steps past the planned links even though rows
        drain FASTER than the optimistic chain cap assumed. Acceptance
        stats survive the ys path."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, draft_config=cfg, num_draft=2,
            horizon=4,
        )
        outs = serve(params, prompts, draft_params=params)
        for r, g in zip(mixed_ref, outs):
            np.testing.assert_array_equal(g, r)
        assert serve.last_stats["spec_accept_rate"] == 1.0

    @pytest.mark.slow
    def test_paged_speculative(self, setup, mesh22):
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, max_seq_len=64, decode_attention="blocked"
        )
        dcfg = dataclasses.replace(
            DRAFT_CFG, max_seq_len=64, decode_attention="blocked"
        )
        ref_eng = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            draft_config=dcfg, num_draft=2, paged_pages=20, page_size=8,
        )
        dp = _draft_params()
        ref = ref_eng(params, prompts[:4], draft_params=dp)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, mixed=True,
            draft_config=dcfg, num_draft=2, paged_pages=20, page_size=8,
            horizon=4,
        )
        outs = serve(params, prompts[:4], draft_params=dp)
        for r, g in zip(ref, outs):
            np.testing.assert_array_equal(g, r)


class TestAdapterMulti:
    def test_multi_tenant_bit_identical(self, setup, mesh22):
        """Base + tenant rows through one ``adapter_multi_step`` batch
        at horizon=4: every stream equals the horizon=1 adapter engine's
        (itself pinned to solo merged-weight engines in
        test_ztenancy.py), with the per-row adapter gather hoisted once
        outside the scan."""
        from learning_jax_sharding_tpu.tenancy import AdapterPool
        from learning_jax_sharding_tpu.training.lora import init_lora

        cfg, params, prompts = setup
        ad1 = jax.tree.map(
            lambda x: x + 0.02, init_lora(jax.random.key(1), params, 4)
        )
        names = {0: None, 1: "t1", 2: "t1", 3: None, 4: "t1", 5: None}

        def drive(eng):
            for rid in range(6):
                eng.add_request(
                    prompts[rid], rid=rid, adapter=names[rid]
                )
            out, steps = {}, 0
            while eng.has_work():
                eng.step(params)
                out.update(eng.pop_finished())
                steps += 1
                assert steps <= 400, "engine wedged"
            out.update(eng.pop_finished())
            cc = eng.compile_counts()
            eng.close()
            return out, cc

        def pool():
            p = AdapterPool(params, slots=4, rank=4)
            p.add("t1", ad1, alpha=16.0)
            return p

        ref_eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, adapter_pool=pool(),
        )
        ref, _ = drive(ref_eng)
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, adapter_pool=pool(), horizon=4,
        )
        out, cc = drive(eng)
        assert sorted(out) == sorted(ref)
        for rid in out:
            np.testing.assert_array_equal(out[rid], ref[rid])
        assert cc["adapter_multi_step"] == 1
        assert cc["adapter_mixed_step"] == 0
