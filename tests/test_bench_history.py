"""The longitudinal bench view: sparklines over BENCH_r*.json rounds.

``scripts/bench_history.py`` renders the whole metric trajectory that
``bench_compare.py`` only gates two rounds at a time; these tests pin
what keeps it readable and honest — the metric set comes from
bench_compare's own pattern table (one source of truth), gaps render as
gaps instead of fabricated zeros, the first→last delta is judged in the
metric's OWN good/bad direction, and rounds sort numerically.
"""

import importlib.util
import json
import pathlib

_SPEC = importlib.util.spec_from_file_location(
    "bench_history",
    pathlib.Path(__file__).resolve().parents[1] / "scripts"
    / "bench_history.py",
)
bench_history = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_history)


def _doc(tail_lines):
    return {"tail": "\n".join(tail_lines)}


def _write(tmp_path, n, doc):
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(doc))


class TestSparkline:
    def test_levels_and_gaps(self):
        s = bench_history.sparkline([1.0, None, 8.0])
        assert len(s) == 3
        assert s[0] == "▁" and s[1] == "·" and s[2] == "█"

    def test_flat_series_sits_mid_scale(self):
        assert bench_history.sparkline([5.0, 5.0, 5.0]) == "▄▄▄"

    def test_all_gaps(self):
        assert bench_history.sparkline([None, None]) == "··"


class TestCollect:
    def test_history_across_rounds_with_gaps(self, tmp_path):
        _write(tmp_path, 1, _doc(
            ["[bench] decode: 1,000 tok/s, 2.0 ms/step"]
        ))
        _write(tmp_path, 2, _doc(["[bench] decode: 1,500 tok/s"]))
        # r10 after r02/r09: numeric round order, not lexicographic.
        _write(tmp_path, 10, _doc(
            ["[bench] decode: 2,000 tok/s, 1.0 ms/step"]
        ))
        rounds, series = bench_history.collect_history(tmp_path)
        assert rounds == [1, 2, 10]
        assert series["decode:tok_s"]["values"] == [
            1000.0, 1500.0, 2000.0,
        ]
        assert series["decode:tok_s"]["higher"] is True
        # The round that dropped ms/step is a GAP, not a zero.
        assert series["decode:ms_per_step"]["values"] == [
            2.0, None, 1.0,
        ]

    def test_last_n_window(self, tmp_path):
        for n, v in ((1, "1,000"), (2, "1,500"), (3, "2,000")):
            _write(tmp_path, n, _doc([f"[bench] decode: {v} tok/s"]))
        rounds, series = bench_history.collect_history(tmp_path, last=2)
        assert rounds == [2, 3]
        assert series["decode:tok_s"]["values"] == [1500.0, 2000.0]


class TestRender:
    def test_direction_aware_tags(self, tmp_path):
        _write(tmp_path, 1, _doc(
            ["[bench] decode: 1,000 tok/s, 2.0 ms/step"]
        ))
        _write(tmp_path, 2, _doc(
            ["[bench] decode: 500 tok/s, 1.0 ms/step"]
        ))
        rounds, series = bench_history.collect_history(tmp_path)
        out = "\n".join(bench_history.render(rounds, series))
        tok = next(
            ln for ln in out.splitlines() if "decode:tok_s" in ln
        )
        ms = next(
            ln for ln in out.splitlines() if "decode:ms_per_step" in ln
        )
        # tok/s HALVED: worse. ms/step halved: better (lower is better).
        assert "WORSE" in tok and "v  50.0%" in tok
        assert "ok" in ms and "WORSE" not in ms

    def test_min_rounds_drops_one_round_metrics(self, tmp_path):
        _write(tmp_path, 1, _doc(["[bench] decode: 1,000 tok/s"]))
        _write(tmp_path, 2, _doc(
            ["[bench] decode: 1,100 tok/s",
             "[bench] newcomer: 5.0 ms/step"]
        ))
        rounds, series = bench_history.collect_history(tmp_path)
        out = "\n".join(bench_history.render(rounds, series))
        assert "decode:tok_s" in out
        assert "newcomer" not in out


class TestMain:
    def test_exit_codes_and_filter(self, tmp_path, capsys):
        assert bench_history.main(["--repo", str(tmp_path)]) == 2
        _write(tmp_path, 1, _doc(
            ["[bench] decode: 1,000 tok/s, 2.0 ms/step"]
        ))
        _write(tmp_path, 2, _doc(
            ["[bench] decode: 1,200 tok/s, 1.5 ms/step"]
        ))
        assert bench_history.main(["--repo", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "rounds r01..r02" in out and "decode:tok_s" in out
        assert bench_history.main(
            ["--repo", str(tmp_path), "--filter", "ms_per_step"]
        ) == 0
        out = capsys.readouterr().out
        assert "ms_per_step" in out and "tok_s" not in out

    def test_json_output(self, tmp_path, capsys):
        _write(tmp_path, 1, _doc(["[bench] decode: 1,000 tok/s"]))
        _write(tmp_path, 2, _doc(["[bench] decode: 1,200 tok/s"]))
        assert bench_history.main(
            ["--repo", str(tmp_path), "--json"]
        ) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["rounds"] == [1, 2]
        m = doc["metrics"]["decode:tok_s"]
        assert m["values"] == [1000.0, 1200.0]
        assert m["higher_is_better"] is True
        assert len(m["sparkline"]) == 2
