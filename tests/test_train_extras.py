"""Sampling filters, LR schedules, gradient accumulation.

All complete-framework additions over the reference (whose optimizer is bare
Adam(1e-3), `/root/reference/case6_attention.py:181`, and which has no
inference or schedule machinery at all).
"""


import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.generate import top_k_filter, top_p_filter
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import TrainLoopConfig, lr_schedule
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


class TestSamplingFilters:
    def test_top_k_keeps_k_largest(self):
        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
        out = np.asarray(top_k_filter(logits, 2))
        assert np.isfinite(out[0, [1, 4]]).all()
        assert np.isneginf(out[0, [0, 2, 3]]).all()

    def test_top_k_full_vocab_is_identity(self):
        logits = jnp.asarray([[1.0, 5.0, 3.0]])
        np.testing.assert_array_equal(np.asarray(top_k_filter(logits, 3)), np.asarray(logits))

    def test_top_k_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            top_k_filter(jnp.zeros((1, 4)), 0)

    def test_top_p_nucleus(self):
        # probs = [0.5, 0.25, 0.125, 0.125]; p=0.6 → keep {0.5, 0.25}.
        probs = np.array([[0.5, 0.25, 0.125, 0.125]])
        logits = jnp.asarray(np.log(probs))
        out = np.asarray(top_p_filter(logits, 0.6))
        assert np.isfinite(out[0, [0, 1]]).all()
        assert np.isneginf(out[0, [2, 3]]).all()

    def test_top_p_ties_cut_exactly_at_nucleus(self):
        # probs [0.53, 0.2, 0.2, 0.07], p=0.6: nucleus = {0.53, first 0.2}.
        # A probability-threshold implementation would keep BOTH 0.2 tokens
        # (0.93 mass); the exact nucleus keeps 0.73.
        probs = np.array([[0.53, 0.2, 0.2, 0.07]])
        out = np.asarray(top_p_filter(jnp.asarray(np.log(probs)), 0.6))
        assert np.isfinite(out[0, 0])
        # Exactly ONE of the tied 0.2 tokens survives (which one is the sort
        # order's tie-break — immaterial); kept mass is 0.73, not 0.93.
        assert np.isfinite(out[0, [1, 2]]).sum() == 1
        assert np.isneginf(out[0, 3])

    def test_top_p_one_is_identity(self):
        logits = jnp.asarray([[1.0, 2.0, 3.0]])
        out = np.asarray(top_p_filter(logits, 1.0))
        assert np.isfinite(out).all()

    def test_top_p_always_keeps_argmax(self):
        # Tiny p: the single most likely token must survive.
        logits = jnp.asarray([[0.0, 10.0, 1.0]])
        out = np.asarray(top_p_filter(logits, 1e-6))
        assert np.isfinite(out[0, 1])
        assert np.isneginf(out[0, [0, 2]]).all()

    def test_top_p_rejects_out_of_range(self):
        with pytest.raises(ValueError):
            top_p_filter(jnp.zeros((1, 4)), 0.0)
        with pytest.raises(ValueError):
            top_p_filter(jnp.zeros((1, 4)), 1.5)

    def test_min_p_confidence_scaled_cutoff(self):
        from learning_jax_sharding_tpu.models.generate import min_p_filter

        # probs [0.5, 0.3, 0.15, 0.05]; min_p=0.5 → cutoff 0.25 → keep {0,1}.
        probs = np.array([[0.5, 0.3, 0.15, 0.05]])
        out = np.asarray(min_p_filter(jnp.asarray(np.log(probs)), 0.5))
        assert np.isfinite(out[0, [0, 1]]).all()
        assert np.isneginf(out[0, [2, 3]]).all()
        # Flat distribution at the same min_p keeps everything.
        flat = np.asarray(min_p_filter(jnp.zeros((1, 4)), 0.5))
        assert np.isfinite(flat).all()
        with pytest.raises(ValueError):
            min_p_filter(jnp.zeros((1, 4)), 0.0)

    def test_repetition_penalty_pushes_both_signs_down(self):
        from learning_jax_sharding_tpu.models.generate import (
            repetition_penalty_filter,
        )

        logits = jnp.asarray([[2.0, -2.0, 2.0, -2.0]])
        seen = jnp.asarray([[True, True, False, False]])
        out = np.asarray(repetition_penalty_filter(logits, seen, 2.0))
        np.testing.assert_allclose(out[0], [1.0, -4.0, 2.0, -2.0])
        with pytest.raises(ValueError):
            repetition_penalty_filter(logits, seen, 0.0)


class TestLrSchedule:
    def _cfg(self, **kw):
        return TrainLoopConfig(steps=100, global_batch_size=8, learning_rate=1e-3, **kw)

    def test_constant(self):
        s = lr_schedule(self._cfg())
        assert float(s(0)) == pytest.approx(1e-3)
        assert float(s(99)) == pytest.approx(1e-3)

    def test_warmup_then_cosine_decays_to_floor(self):
        s = lr_schedule(self._cfg(
            warmup_steps=10, lr_schedule="cosine", min_learning_rate=1e-4
        ))
        assert float(s(0)) == pytest.approx(0.0)
        assert float(s(10)) == pytest.approx(1e-3, rel=1e-2)
        assert float(s(100)) == pytest.approx(1e-4, rel=1e-2)

    def test_linear_decay(self):
        s = lr_schedule(self._cfg(lr_schedule="linear", min_learning_rate=0.0))
        assert float(s(0)) == pytest.approx(1e-3)
        assert float(s(100)) == pytest.approx(0.0, abs=1e-8)

    def test_unknown_schedule_rejected(self):
        with pytest.raises(ValueError, match="unknown lr_schedule"):
            lr_schedule(self._cfg(lr_schedule="exponential"))


class TestGradAccumulation:
    def _setup(self, mesh22, accum):
        cfg = CONFIG_TINY
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, size=(8, 17)).astype(np.int32)
        sh = mesh_sharding(mesh22, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        state, state_sh = sharded_train_state(
            model, optax.sgd(0.1), batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
            grad_accum_steps=accum,
        )
        return state, step, batch

    def test_accum_matches_single_step(self, mesh22):
        """Accumulated microbatch gradients == one full-batch gradient (mean
        CE over equal-size microbatches averages exactly)."""
        s1, step1, batch = self._setup(mesh22, accum=1)
        s2, step2, _ = self._setup(mesh22, accum=4)
        new1, loss1 = step1(s1, batch)
        new2, loss2 = step2(s2, batch)
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(new1.params), jax.tree.leaves(new2.params)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-4, atol=1e-6,
            )

    def test_indivisible_batch_rejected(self, mesh22):
        state, step, batch = self._setup(mesh22, accum=3)
        with pytest.raises(ValueError, match="not divisible"):
            step(state, batch)


class TestStepsPerCall:
    def test_scanned_steps_match_sequential(self, mesh22):
        """K steps in one jitted lax.scan call == K sequential single-step
        calls over the same batches: same final params, same per-step
        losses. (steps_per_call amortizes host dispatch and keeps the state
        update in place — the bench's sustained-training timing mode.)"""
        cfg = CONFIG_TINY
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        K = 3
        sh = mesh_sharding(mesh22, "data", None)
        toks = [
            rng.integers(0, cfg.vocab_size, size=(8, 17)).astype(np.int32)
            for _ in range(K)
        ]
        batches = [
            {"inputs": put(t[:, :-1], sh), "targets": put(t[:, 1:], sh)}
            for t in toks
        ]
        x_sh = {k: v.sharding for k, v in batches[0].items()}

        def fresh_state():
            return sharded_train_state(
                model, optax.sgd(0.1), batches[0]["inputs"],
                {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
            )

        state1, state_sh = fresh_state()
        single = make_train_step(
            state_sh, x_sh, mesh22, RULES_DP_TP, loss_fn=next_token_loss,
            donate_state=False,
        )
        losses = []
        for bt in batches:
            state1, loss = single(state1, bt)
            losses.append(float(loss))

        state2, state_sh = fresh_state()
        multi = make_train_step(
            state_sh, x_sh, mesh22, RULES_DP_TP, loss_fn=next_token_loss,
            donate_state=False, steps_per_call=K,
        )
        stacked = {
            k: put(
                np.stack([np.asarray(b[k]) for b in batches]),
                mesh_sharding(mesh22, None, "data", None),
            )
            for k in ("inputs", "targets")
        }
        state2, loss_vec = multi(state2, stacked)
        np.testing.assert_allclose(np.asarray(loss_vec), losses, rtol=1e-5)
        for a, b in zip(
            jax.tree.leaves(state1.params), jax.tree.leaves(state2.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=2e-4, atol=1e-6,
            )


class TestOptimizerPresets:
    def _cfg(self, **kw):
        kw.setdefault("learning_rate", 1e-3)
        return TrainLoopConfig(steps=20, global_batch_size=8, **kw)

    @pytest.mark.parametrize("name", ["adamw", "lion", "adafactor"])
    def test_presets_descend_loss(self, mesh22, name):
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
            next_token_loss,
        )
        from learning_jax_sharding_tpu.training.loop import default_optimizer
        from learning_jax_sharding_tpu.training.pipeline import (
            make_train_step,
            sharded_train_state,
        )

        rng = np.random.default_rng(0)
        tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put

        sh = mesh_sharding(mesh22, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        lr = {"adamw": 3e-3, "lion": 3e-4, "adafactor": 3e-2}[name]
        opt = default_optimizer(self._cfg(optimizer=name, learning_rate=lr))
        state, state_sh = sharded_train_state(
            Transformer(CONFIG_TINY), opt, batch["inputs"],
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        first = None
        for _ in range(8):
            state, loss = step(state, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first, (name, first, float(loss))

    def test_lion_state_is_single_moment(self, mesh22):
        # Lion's memory pitch: one momentum tensor per param (AdamW has two).
        import optax
        from learning_jax_sharding_tpu.training.loop import default_optimizer

        params = {"w": jnp.zeros((4, 4))}
        lion_state = default_optimizer(self._cfg(optimizer="lion")).init(params)
        adamw_state = default_optimizer(self._cfg()).init(params)
        count = lambda s: sum(
            x.size for x in jax.tree.leaves(s) if getattr(x, "size", 0) > 1
        )
        assert count(lion_state) == count(adamw_state) // 2

    def test_unknown_preset_rejected(self):
        from learning_jax_sharding_tpu.training.loop import default_optimizer

        with pytest.raises(ValueError, match="optimizer"):
            default_optimizer(self._cfg(optimizer="sgd9000"))

    def test_adafactor_factored_state_borns_sharded(self, mesh22):
        """Exercise the FACTORED path (optax factors only dims >= 128): the
        rank-1 v_row/v_col vectors inherit the kernel's rank-2 spec from the
        logical metadata and must fall back to replicated instead of
        crashing the born-sharded init; params keep their TP shardings."""
        import dataclasses

        from jax.sharding import PartitionSpec as P

        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
        )
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.training.loop import default_optimizer
        from learning_jax_sharding_tpu.training.pipeline import (
            sharded_train_state,
        )

        cfg = dataclasses.replace(
            CONFIG_TINY, features=128, hidden=256, head_dim=32
        )
        rng = np.random.default_rng(0)
        x = put(
            rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32),
            mesh_sharding(mesh22, "data", None),
        )
        state, _ = sharded_train_state(
            Transformer(cfg),
            default_optimizer(self._cfg(optimizer="adafactor")),
            x, {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        flat = jax.tree_util.tree_flatten_with_path(state.opt_state)[0]
        v_rows = [x for p, x in flat if any(
            getattr(k, "name", getattr(k, "key", "")) == "v_row" for k in p
        )]
        factored = [v for v in v_rows if v.ndim >= 1 and v.size > 1]
        assert factored, "no factored leaves — config too small to exercise the path"
        for v in factored:
            assert v.sharding.spec == P()  # rank-safe fallback: replicated
        # Params keep their rule-derived shardings.
        up = state.params["block_0"]["ff"]["up"]["kernel"]
        assert up.sharding.spec == P(None, "model")
