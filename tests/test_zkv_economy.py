"""KV economy (round 15): prefix-aware placement + the HBM→host→peer
tier ladder.

Named to sort LAST alongside ``test_zfleet``/``test_ztenancy`` (same
rationale: the end-to-end oracles build engines, and the tier-1 window
should spend its budget on the faster oracles first).

Four layers, cheapest first:

* ``TierStore`` as a pure host-side structure — LRU byte budget,
  weights-version fencing (``get`` drops a stale entry, ``peek``
  leaves it for a mixed-version fleet mid-rolling-swap);
* the ENGINE tier seam — spill/fill round-trips a retained page
  bit-identically with every byte booked to the ledger's
  ``kv_handoff`` bucket, the digest speaks page-aligned truth at
  partial-page boundaries, a ``swap_weights`` commit invalidates it,
  and a predicted-hit page evicted mid-route degrades to a counted
  re-prefill, never a wrong token;
* the ECONOMY over a 2-replica fleet — placement lands on the
  longest-prefix replica, demotion feeds the host tier, promotion
  (host AND peer) restores chains the admission then realizes, and
  ``latency_stats`` books hit/miss rates while every replica's ledger
  still reconciles.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.fleet import (
    FleetPolicy,
    FleetRouter,
    KvEconomy,
    TierStore,
    make_replicas,
    replicated_params,
)
from learning_jax_sharding_tpu.models.serving import ContinuousEngine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

PAGE = 4
ENGINE_KW = dict(
    batch_size=2, max_new_tokens=4, refill_chunk=8,
    paged_pages=12, page_size=PAGE, prefix_cache=True,
)


@pytest.fixture(scope="module")
def served():
    cfg = dataclasses.replace(
        CONFIG_TINY, dtype=jnp.float32, decode_attention="blocked",
    )
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), np.zeros((2, 8), np.int32)
        )["params"]
    )
    rng = np.random.default_rng(23)
    base = rng.integers(1, cfg.vocab_size, size=(9,)).astype(np.int32)
    return cfg, params, base, rng


def _engine(cfg, **over):
    mesh = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
    return ContinuousEngine(
        cfg, mesh, RULES_DP_TP, **{**ENGINE_KW, **over}
    ), mesh


def _rows_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _edrain(eng, params, max_steps=200):
    out = {}
    steps = 0
    while eng.has_work():
        eng.step(params)
        out.update(eng.pop_finished())
        steps += 1
        assert steps <= max_steps, "engine wedged"
    out.update(eng.pop_finished())
    return out


class TestTierStore:
    def test_lru_byte_budget_evicts_oldest(self):
        t = TierStore(capacity_bytes=100)
        t.put(b"a", ["ra"], version=0, nbytes=40)
        t.put(b"b", ["rb"], version=0, nbytes=40)
        assert t.get(b"a", version=0) == ["ra"]   # refresh: a is newest
        t.put(b"c", ["rc"], version=0, nbytes=40)  # 120 > 100: evict b
        assert b"b" not in t
        assert b"a" in t and b"c" in t
        assert t.evictions == 1
        assert t.bytes_held == 80

    def test_budget_always_keeps_latest_entry(self):
        t = TierStore(capacity_bytes=10)
        t.put(b"big", ["r"], version=0, nbytes=500)
        assert b"big" in t and len(t) == 1

    def test_get_drops_stale_version_peek_keeps_it(self):
        t = TierStore(capacity_bytes=100)
        t.put(b"k", ["r"], version=1, nbytes=10)
        # peek at the wrong version: miss, but the entry survives — a
        # peer mid-rolling-swap must not destroy another version's page.
        assert t.peek(b"k", version=2) is None
        assert b"k" in t
        assert t.peek(b"k", version=1) == ["r"]
        # get at the wrong version: the owner's versions only move
        # forward, so the stale entry is garbage — dropped.
        assert t.get(b"k", version=2) is None
        assert b"k" not in t
        assert t.bytes_held == 0

    def test_put_refresh_replaces_bytes(self):
        t = TierStore(capacity_bytes=100)
        t.put(b"k", ["r1"], version=0, nbytes=30)
        t.put(b"k", ["r2"], version=1, nbytes=50)
        assert len(t) == 1
        assert t.bytes_held == 50
        assert t.get(b"k", version=1) == ["r2"]


class TestEngineTier:
    def test_spill_fill_round_trip_bit_identical_and_ledgered(
        self, served
    ):
        cfg, params, base, _ = served
        eng, mesh = _engine(cfg)
        p = replicated_params(params, mesh)
        ref = eng.serve(p, [base])[0]
        (key, *_) = eng.retained_prefixes()
        epoch0, digest0 = eng.prefix_digest()
        rows, st = eng.spill_page(key, drop=True)
        assert st["bytes"] > 0 and st["segments"] > 0
        epoch1, digest1 = eng.prefix_digest()
        assert epoch1 > epoch0
        assert eng.prefix_hash(key) in digest0
        assert eng.prefix_hash(key) not in digest1
        st2 = eng.fill_page(key, rows)
        assert st2["bytes"] == st["bytes"]
        assert eng.prefix_hash(key) in eng.prefix_digest()[1]
        # The promoted page serves the SAME tokens the recompute would.
        again = eng.serve(p, [base])[0]
        np.testing.assert_array_equal(ref, again)
        # ... and a second spill returns bit-identical rows.
        rows2, _ = eng.spill_page(key, drop=False)
        _rows_equal(rows, rows2)
        # Every byte booked: the ledger window closes with kv_handoff
        # busy-time and still accounts for 100% of the wall.
        assert eng.ledger.reconcile()["ok"]
        assert eng.ledger.window_report()["buckets"]["kv_handoff"] > 0

    def test_fill_rejects_resident_key_spill_rejects_unknown(
        self, served
    ):
        cfg, params, base, _ = served
        eng, mesh = _engine(cfg)
        eng.serve(replicated_params(params, mesh), [base])
        (key, *_) = eng.retained_prefixes()
        rows, _ = eng.spill_page(key, drop=False)
        with pytest.raises(ValueError):
            eng.fill_page(key, rows)          # still resident
        with pytest.raises(KeyError):
            eng.spill_page(b"nope", drop=False)

    def test_digest_is_page_aligned_at_partial_boundaries(self, served):
        """A 9-token prompt on 4-token pages retains prefixes [:4] and
        [:8] — never the ragged [:9] (the last prompt token always
        recomputes, so no key can cover it)."""
        cfg, params, base, _ = served
        eng, mesh = _engine(cfg)
        eng.serve(replicated_params(params, mesh), [base])
        _, digest = eng.prefix_digest()
        assert eng.prefix_hash(base[:4].tobytes()) in digest
        assert eng.prefix_hash(base[:8].tobytes()) in digest
        assert eng.prefix_hash(base[:9].tobytes()) not in digest
        retained = set(eng.retained_prefixes())
        assert base[:4].tobytes() in retained
        assert base[:8].tobytes() in retained

    def test_partial_page_overlap_realizes_whole_pages_only(
        self, served
    ):
        """A second prompt sharing 6 of 8 cached tokens realizes ONE
        page (4 tokens): hits never split a page."""
        cfg, params, base, rng = served
        eng, mesh = _engine(cfg)
        p = replicated_params(params, mesh)
        eng.serve(p, [base])
        o = np.concatenate([
            base[:6],
            rng.integers(1, cfg.vocab_size, size=(3,)).astype(np.int32),
        ])
        solo, m2 = _engine(cfg)
        ref = solo.serve(replicated_params(params, m2), [o])[0]
        rid = eng.add_request(o)
        eng.expected_prefix[rid] = 2 * PAGE    # router predicted [:8]
        out = _edrain(eng, p)[rid]
        np.testing.assert_array_equal(ref, np.asarray(out))
        assert eng.prefix_realized.pop(rid) == PAGE

    def test_evicted_mid_route_degrades_to_counted_re_prefill(
        self, served
    ):
        """Score said hit, admission finds the page gone: the request
        re-prefills from the prompt (bit-identical tokens) and the
        tier-miss counter records the wasted placement."""
        cfg, params, base, _ = served
        eng, mesh = _engine(cfg)
        p = replicated_params(params, mesh)
        ref = eng.serve(p, [base])[0]
        miss0 = eng._c_tier_miss.value
        # Route-time view: both pages resident → predict 8 tokens ...
        predicted = 2 * PAGE
        rid = eng.add_request(base)
        eng.expected_prefix[rid] = predicted
        # ... then the deeper page vanishes before admission.
        eng.spill_page(base[:8].tobytes(), drop=True)
        out = _edrain(eng, p)[rid]
        np.testing.assert_array_equal(ref, np.asarray(out))
        assert eng.prefix_realized.pop(rid) == PAGE
        assert eng._c_tier_miss.value == miss0 + 1

    def test_swap_commit_drops_registry_and_digest(self, served):
        cfg, params, base, _ = served
        eng, mesh = _engine(cfg)
        eng.serve(replicated_params(params, mesh), [base])
        epoch0, digest0 = eng.prefix_digest()
        assert digest0
        new_params = jax.tree.map(
            lambda x: x * (1.0 + 1e-3),
            replicated_params(params, mesh),
        )
        assert eng.swap_weights(new_params, version=5)
        epoch1, digest1 = eng.prefix_digest()
        assert eng.weights_version == 5
        assert not digest1          # old-params KV must not seed v5
        assert epoch1 > epoch0
        assert eng.retained_prefixes() == []


class TestEconomyFleet:
    @pytest.fixture(scope="class")
    def fleet(self, served):
        cfg, params, base, _ = served
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 1),
            **ENGINE_KW,
        )
        from learning_jax_sharding_tpu.telemetry.flight_recorder import (
            FlightRecorder,
        )

        econ = KvEconomy(hbm_retained_target=0, burn_threshold=1e9)
        router = FleetRouter(
            reps, policy=FleetPolicy(prefix_weight=0.5), kv_economy=econ,
            recorder=FlightRecorder(),
        )
        return router, econ

    def test_attach_rejects_mixed_page_size(self, served):
        cfg, params, _, _ = served
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            **ENGINE_KW,
        ) + make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            offset=1, **{**ENGINE_KW, "page_size": 8},
        )
        with pytest.raises(ValueError):
            FleetRouter(reps, kv_economy=KvEconomy())

    def test_placement_lands_on_longest_prefix_replica(
        self, served, fleet
    ):
        cfg, params, base, rng = served
        router, econ = fleet
        # Warm the base chain onto whichever replica placement picks.
        router.add_request(base)
        router.drain()
        hits = econ.predicted_hits(base)
        assert sorted(hits.values(), reverse=True)[0] == 2 * PAGE
        home = max(hits, key=hits.get)
        cold = next(n for n in router.replicas if n != home)
        assert hits[cold] == 0
        # An overlapping request must land ON the home replica even
        # when its queue is deeper than the cold one's.
        o = np.concatenate([
            base[:8],
            rng.integers(1, cfg.vocab_size, size=(3,)).astype(np.int32),
        ])
        rid = router.add_request(o)
        router.drain()
        fin = next(
            e for e in router.recorder.events("fleet.finish")
            if e["rid"] == rid
        )
        assert fin["replica"] == home
        rec = next(c for c in router._completed if c["rid"] == rid)
        assert rec["prefix_predicted"] == 2 * PAGE
        assert rec["prefix_realized"] == 2 * PAGE
        stats = router.latency_stats()
        assert stats["prefix_hit_rate"] > 0
        assert stats["tier_miss_rate"] == 0.0

    def test_demotion_feeds_host_tier_and_promotion_realizes(
        self, served, fleet
    ):
        cfg, params, base, rng = served
        router, econ = fleet
        router.add_request(base)
        router.drain()
        home = max(econ.predicted_hits(base), key=econ.predicted_hits(base).get)
        # hbm_retained_target=0: the sweep demotes the chain to the
        # host tier (write-back — the HBM copy stays evictable).
        demoted = econ.maintain()
        tier = econ.tier_of(home)
        version = router.replicas[home].engine.weights_version
        assert tier.has(base[:4].tobytes(), version=version)
        assert tier.has(base[:8].tobytes(), version=version)
        # Force the HBM copies out, then promotion restores the chain
        # from the host tier and the NEXT admission realizes it.
        eng = router.replicas[home].engine
        for key in (base[:8].tobytes(), base[:4].tobytes()):
            eng.spill_page(key, drop=True)
        assert econ.predicted_hits(base)[home] == 2 * PAGE   # tier-held
        filled = econ.promote(router.replicas[home], base)
        assert filled == 2
        rep = econ.tier_report()
        assert rep["promotions"] >= 2
        assert rep["fill_bytes"] > 0
        assert rep["replicas"][home]["host_pages"] >= 2
        # Every replica's ledger still accounts for 100% of its wall.
        assert router.goodput_report()["reconcile_ok"]

    def test_peer_promotion_copies_without_disturbing_owner(
        self, served, fleet
    ):
        cfg, params, base, _ = served
        router, econ = fleet
        router.add_request(base)
        router.drain()
        hits = econ.predicted_hits(base)
        home = max(hits, key=hits.get)
        cold = next(n for n in router.replicas if n != home)
        before = econ.tier_report()["peer_promotions"]
        owner_digest = router.replicas[home].engine.prefix_digest()[1]
        filled = econ.promote(router.replicas[cold], base)
        assert filled == 2
        assert econ.tier_report()["peer_promotions"] >= before + 2
        # The owner's pages were read non-destructively.
        assert router.replicas[home].engine.prefix_digest()[1] == (
            owner_digest
        )
        # The copy is real: the cold replica now predicts the hit too.
        assert econ.predicted_hits(base)[cold] == 2 * PAGE

    def test_swap_commit_invalidates_router_prediction(
        self, served, fleet
    ):
        """Runs LAST in the class: commits a swap on every replica, so
        all cached KV (HBM and tier) is stale for the new version —
        predicted hits must drop to zero fleet-wide."""
        cfg, params, base, _ = served
        router, econ = fleet
        router.add_request(base)
        router.drain()
        assert max(econ.predicted_hits(base).values()) == 2 * PAGE
        for rep in router.replicas.values():
            new_params = jax.tree.map(
                lambda x: x * (1.0 + 1e-3), rep.params,
            )
            assert rep.engine.swap_weights(new_params, version=7)
        hits = econ.predicted_hits(base)
        assert all(v == 0 for v in hits.values())
