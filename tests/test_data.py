"""Input pipeline: datasets, packed-token files, and the sharded loader."""

import itertools

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.data import (
    MemmapTokenDataset,
    ShardedBatchLoader,
    SyntheticLMDataset,
    write_token_file,
)
from learning_jax_sharding_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def mesh_dm():
    return build_mesh((2, 4), ("data", "model"))


class TestSyntheticLMDataset:
    def test_deterministic_and_shifted(self):
        ds = SyntheticLMDataset(vocab_size=100, seq_len=16, seed=3)
        b1 = ds.batch(5, batch_size=4)
        b2 = ds.batch(5, batch_size=4)
        np.testing.assert_array_equal(b1["inputs"], b2["inputs"])
        # targets are inputs shifted left by one
        np.testing.assert_array_equal(b1["inputs"][:, 1:], b1["targets"][:, :-1])
        assert b1["inputs"].shape == (4, 16)
        assert (ds.batch(6, batch_size=4)["inputs"] != b1["inputs"]).any()

    def test_row_slice_matches_global(self):
        # A host materializing rows 2:4 must see exactly those rows of the
        # global batch — the multi-host feeding invariant.
        ds = SyntheticLMDataset(vocab_size=100, seq_len=16)
        full = ds.batch(0, batch_size=8)
        part = ds.batch(0, rows=slice(2, 4), batch_size=8)
        np.testing.assert_array_equal(part["inputs"], full["inputs"][2:4])


class TestMemmapTokenDataset:
    def test_roundtrip_and_windows(self, tmp_path):
        tokens = np.arange(1000) % 500
        path = write_token_file(tmp_path / "toks.bin", tokens)
        ds = MemmapTokenDataset(path, seq_len=32)
        b = ds.batch(0, batch_size=4)
        assert b["inputs"].shape == (4, 32)
        # Every window must be a contiguous run of the source sequence.
        for row_in, row_tg in zip(b["inputs"], b["targets"]):
            np.testing.assert_array_equal(row_tg[:-1], row_in[1:])
            idx = np.where(tokens == row_in[0])[0]
            assert any(
                np.array_equal(tokens[i : i + 32], row_in)
                for i in idx if i + 33 <= len(tokens)
            )

    def test_deterministic(self, tmp_path):
        path = write_token_file(tmp_path / "t.bin", np.arange(500) % 100)
        ds1 = MemmapTokenDataset(path, seq_len=16, seed=1)
        ds2 = MemmapTokenDataset(path, seq_len=16, seed=1)
        np.testing.assert_array_equal(
            ds1.batch(3, batch_size=2)["inputs"],
            ds2.batch(3, batch_size=2)["inputs"],
        )

    def test_too_short_file(self, tmp_path):
        path = write_token_file(tmp_path / "s.bin", np.arange(10))
        with pytest.raises(ValueError, match="need at least"):
            MemmapTokenDataset(path, seq_len=32)

    def test_dtype_range_guard(self, tmp_path):
        with pytest.raises(ValueError, match="range"):
            write_token_file(tmp_path / "o.bin", np.array([70000]), np.uint16)


class TestShardedBatchLoader:
    def test_yields_sharded_batches(self, mesh_dm):
        ds = SyntheticLMDataset(vocab_size=100, seq_len=16)
        loader = ShardedBatchLoader(ds, mesh_dm, batch_size=8, spec=P("data"))
        batches = list(itertools.islice(iter(loader), 3))
        want_sh = NamedSharding(mesh_dm, P("data"))
        for b in batches:
            assert isinstance(b["inputs"], jax.Array)
            assert b["inputs"].sharding == want_sh
            assert b["inputs"].shape == (8, 16)
        # values match the dataset's global batches
        np.testing.assert_array_equal(
            np.asarray(batches[1]["inputs"]), ds.batch(1, batch_size=8)["inputs"]
        )

    def test_resume_from_index(self, mesh_dm):
        ds = SyntheticLMDataset(vocab_size=100, seq_len=16)
        loader = ShardedBatchLoader(ds, mesh_dm, batch_size=8, start_index=5)
        first = next(iter(loader))
        np.testing.assert_array_equal(
            np.asarray(first["inputs"]), ds.batch(5, batch_size=8)["inputs"]
        )
        # random access for checkpoint-resume
        np.testing.assert_array_equal(
            np.asarray(loader.batch_at(7)["inputs"]),
            ds.batch(7, batch_size=8)["inputs"],
        )


class TestPrefetch:
    def test_prefetched_matches_batch_at(self, mesh24):
        from learning_jax_sharding_tpu.data import SyntheticLMDataset

        loader = ShardedBatchLoader(
            SyntheticLMDataset(vocab_size=64, seq_len=8, seed=1), mesh24,
            batch_size=4, spec=("x",), start_index=3,
        )
        it = loader.prefetched(depth=2)
        try:
            for i in range(3, 8):
                got = next(it)
                want = loader.batch_at(i)
                np.testing.assert_array_equal(
                    np.asarray(got["inputs"]), np.asarray(want["inputs"])
                )
        finally:
            it.close()

    def test_prefetched_propagates_dataset_errors(self, mesh24):
        class Exploding:
            def batch(self, index, rows=None, batch_size=8):
                raise RuntimeError("disk on fire")

        loader = ShardedBatchLoader(Exploding(), mesh24, batch_size=4, spec=("x",))
        it = loader.prefetched()
        with pytest.raises(RuntimeError, match="disk on fire"):
            next(it)

    def test_bad_depth_rejected(self, mesh24):
        from learning_jax_sharding_tpu.data import SyntheticLMDataset

        loader = ShardedBatchLoader(
            SyntheticLMDataset(vocab_size=64, seq_len=8, seed=1), mesh24,
            batch_size=4, spec=("x",),
        )
        with pytest.raises(ValueError, match="depth"):
            loader.prefetched(depth=0)

    def test_close_without_consuming_stops_producer(self, mesh24):
        """Regression: a resume landing past the last step closes the
        iterator before any next() — the producer thread must still stop."""
        from learning_jax_sharding_tpu.data import SyntheticLMDataset

        loader = ShardedBatchLoader(
            SyntheticLMDataset(vocab_size=64, seq_len=8, seed=1), mesh24,
            batch_size=4, spec=("x",),
        )
        it = loader.prefetched(depth=2)
        it.close()
        it._thread.join(timeout=10)
        assert not it._thread.is_alive()

    def test_next_after_close_or_error_fails_fast(self, mesh24):
        """Regression: a drained queue with a dead producer must raise, not
        block forever."""
        from learning_jax_sharding_tpu.data import SyntheticLMDataset

        loader = ShardedBatchLoader(
            SyntheticLMDataset(vocab_size=64, seq_len=8, seed=1), mesh24,
            batch_size=4, spec=("x",),
        )
        it = loader.prefetched(depth=1)
        next(it)
        it.close()
        with pytest.raises(RuntimeError, match="closed"):
            next(it)

        class Exploding:
            def batch(self, index, rows=None, batch_size=8):
                raise RuntimeError("disk on fire")

        it2 = ShardedBatchLoader(
            Exploding(), mesh24, batch_size=4, spec=("x",)
        ).prefetched()
        with pytest.raises(RuntimeError, match="disk on fire"):
            next(it2)
        with pytest.raises(RuntimeError, match="closed"):
            next(it2)
