"""Continuous batching (models/serving.py): slot reuse over ragged caches.

THE oracle: scheduling must never change results — every request's output
is bit-identical to a rectangular single-prompt ``make_generate_fn`` run
of the same params (greedy, fp32, CPU backend), whatever batch size,
queue order, refill chunking, or slot the request landed on.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.serving import make_continuous_engine
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    RULES_TP_SERVING,
)

NEW = 6


@pytest.fixture(scope="module")
def setup(mesh22):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    rng = np.random.default_rng(11)
    model = Transformer(cfg)
    probe = np.zeros((2, 8), np.int32)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), probe
        )["params"]
    )
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in (3, 9, 5, 1, 12, 7, 4)
    ]
    return cfg, params, prompts


def _rect_reference(cfg, mesh22, params, prompt, eos_id=None):
    gen = make_generate_fn(
        cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW, eos_id=eos_id
    )
    # b=2: the mesh's data axis must divide the batch.
    out = np.asarray(
        gen(params, np.repeat(prompt[None, :], 2, axis=0), jax.random.key(0))
    )
    return out[0]


class TestContinuousBatching:
    @pytest.mark.parametrize("backend", ["dense", "blocked"])
    def test_requests_match_single_runs(self, setup, mesh22, backend):
        """7 mixed-length requests through 2 slots: every output equals the
        rectangular single run — slots are reused ≥ 3 times each, and the
        12-token prompt streams through multiple refill chunks."""
        cfg, params, prompts = setup
        cfg = dataclasses.replace(cfg, decode_attention=backend)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        outs = serve(params, prompts)
        assert len(outs) == len(prompts)
        for prompt, got in zip(prompts, outs):
            ref = _rect_reference(cfg, mesh22, params, prompt)
            np.testing.assert_array_equal(
                got, ref[: len(got)],
                err_msg=f"prompt len {len(prompt)}",
            )
            assert len(got) == len(prompt) + NEW

    def test_eos_retires_and_refills(self, setup, mesh22):
        """With an eos known to fire early for one request, its slot must
        retire at eos (output ends there) and still serve later queue
        entries correctly."""
        cfg, params, prompts = setup
        # Find an eos that row 0 emits as its second generated token.
        plain = _rect_reference(cfg, mesh22, params, prompts[0])
        eos = int(plain[len(prompts[0]) + 1])
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, eos_id=eos,
        )
        outs = serve(params, prompts)
        for prompt, got in zip(prompts, outs):
            ref = _rect_reference(cfg, mesh22, params, prompt, eos_id=eos)
            np.testing.assert_array_equal(got, ref[: len(got)])
            # Output ends at eos (inclusive) or at the budget.
            if eos in got[len(prompt):].tolist():
                assert got[-1] == eos
            else:
                assert len(got) == len(prompt) + NEW

    def test_more_slots_than_requests(self, setup, mesh22):
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=4, max_new_tokens=NEW,
            refill_chunk=8,
        )
        outs = serve(params, prompts[:2])
        for prompt, got in zip(prompts[:2], outs):
            ref = _rect_reference(cfg, mesh22, params, prompt)
            np.testing.assert_array_equal(got, ref[: len(got)])

    def test_masked_write_never_clamps_onto_history(self):
        """row_update_masked — the write primitive behind mixed
        refill/decode batches (the round-3 review bug): a zero-length row
        whose window start would CLAMP below its index (idx near the
        buffer end) must leave its buffer untouched, and a clamped
        PARTIAL chunk must land at its true offset."""
        from learning_jax_sharding_tpu.models.attention import (
            row_update_masked,
        )

        rng = np.random.default_rng(0)
        L, s = 64, 16
        buf = jnp.asarray(rng.normal(size=(3, L, 4)), jnp.float32)
        chunk = jnp.asarray(rng.normal(size=(3, s, 4)), jnp.float32)
        idx = jnp.asarray([60, 5, 56], jnp.int32)     # 60, 56 clamp (>48)
        lengths = jnp.asarray([0, 16, 8], jnp.int32)  # idle, full, partial
        out = np.asarray(
            row_update_masked(buf, chunk, idx, lengths, seq_dim=1)
        )
        # Row 0 (zero-length, clamped window): bitwise untouched.
        np.testing.assert_array_equal(out[0], np.asarray(buf[0]))
        # Row 1 (plain full write at 5): chunk lands at [5, 21).
        np.testing.assert_array_equal(out[1, 5:21], np.asarray(chunk[1]))
        np.testing.assert_array_equal(out[1, :5], np.asarray(buf[1, :5]))
        np.testing.assert_array_equal(out[1, 21:], np.asarray(buf[1, 21:]))
        # Row 2 (clamped partial): first 8 chunk positions land at their
        # TRUE offset 56..64; everything below 56 keeps history.
        np.testing.assert_array_equal(out[2, 56:], np.asarray(chunk[2, :8]))
        np.testing.assert_array_equal(out[2, :56], np.asarray(buf[2, :56]))

    def test_validation(self, setup, mesh22):
        cfg, params, prompts = setup
        with pytest.raises(ValueError, match="batch_size"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=0, max_new_tokens=2
            )
        with pytest.raises(ValueError, match="max_new_tokens"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=0
            )
        with pytest.raises(ValueError, match="refill_chunk"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=2,
                refill_chunk=cfg.max_seq_len + 1,
            )
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2,
            max_new_tokens=cfg.max_seq_len,
        )
        with pytest.raises(ValueError, match="max_seq_len"):
            serve(params, [np.ones((8,), np.int32)])


DRAFT_CFG = dataclasses.replace(
    CONFIG_TINY, num_layers=1, hidden=64, dtype=jnp.float32
)


def _draft_params():
    model = Transformer(DRAFT_CFG)
    toks = np.zeros((2, 8), np.int32)
    return nn.meta.unbox(
        model.init({"params": jax.random.key(7)}, toks)["params"]
    )


class TestSpeculativeEngine:
    """Speculative decode blocks inside the continuous engine: a draft
    model proposes inside every decode dispatch, acceptance and cache
    rollback are per-row. Oracle: output bit-identical to the plain
    (non-speculative) greedy engine — which is itself pinned to
    rectangular single runs — whatever the draft proposes."""

    @pytest.mark.parametrize("backend", ["dense", "blocked"])
    def test_matches_plain_engine(self, setup, mesh22, backend):
        cfg, params, prompts = setup
        cfg = dataclasses.replace(cfg, decode_attention=backend)
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention=backend)
        plain = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        spec = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, draft_config=dcfg, num_draft=3,
        )
        ref = plain(params, prompts)
        got = spec(params, prompts, draft_params=_draft_params())
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_eos_truncates_in_round(self, setup, mesh22):
        """EOS emitted mid-round (inside an accepted draft run) must
        truncate that row's emission exactly where the plain engine
        stops."""
        cfg, params, prompts = setup
        plain_out = _rect_reference(cfg, mesh22, params, prompts[0])
        eos = int(plain_out[len(prompts[0]) + 1])
        plain = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, eos_id=eos,
        )
        spec = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, eos_id=eos, draft_config=DRAFT_CFG, num_draft=3,
        )
        ref = plain(params, prompts)
        got = spec(params, prompts, draft_params=_draft_params())
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_self_draft_matches_too(self, setup, mesh22):
        """Draft == target: the all-accept path (every round emits
        num_draft+1 tokens) — still bit-identical."""
        cfg, params, prompts = setup
        plain = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        )
        spec = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            draft_config=cfg, num_draft=2,
        )
        ref = plain(params, prompts[:3])
        got = spec(params, prompts[:3], draft_params=params)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_acceptance_stats(self, setup, mesh22):
        """serve.last_stats surfaces verifier acceptance: self-draft is
        exactly 1.0; an untrained draft against the trained-ish target is
        below it; the plain engine reports None."""
        cfg, params, prompts = setup
        spec = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, draft_config=cfg, num_draft=3,
        )
        spec(params, prompts[:3], draft_params=params)
        stats = spec.last_stats
        assert stats["spec_accept_rate"] == 1.0
        assert stats["spec_proposed"] > 0
        weak = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, draft_config=DRAFT_CFG, num_draft=3,
        )
        weak(params, prompts[:3], draft_params=_draft_params())
        assert weak.last_stats["spec_accept_rate"] < 1.0
        plain = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        )
        plain(params, prompts[:3])
        assert plain.last_stats is None

    def test_validation(self, setup, mesh22):
        cfg, params, prompts = setup
        spec = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            draft_config=DRAFT_CFG,
        )
        with pytest.raises(ValueError, match="draft_params"):
            spec(params, prompts[:1])
        plain = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
        )
        with pytest.raises(ValueError, match="draft_config"):
            plain(params, prompts[:1], draft_params=_draft_params())


class TestReproducibleSampling:
    """temperature > 0: every draw is keyed by (request id, generated
    position), so a request's sampled stream is a function of (rng,
    request index, its own prompt) — NOT of scheduling. The same queue
    served under any batch size / chunking yields identical outputs."""

    def test_schedule_independent(self, setup, mesh22):
        cfg, params, prompts = setup
        key = jax.random.key(5)
        outs = []
        for bs, chunk in ((2, 4), (3, 8), (4, 16)):
            serve = make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=bs, max_new_tokens=NEW,
                refill_chunk=chunk, temperature=1.0, top_k=16,
            )
            outs.append(serve(params, prompts, rng=key))
        for other in outs[1:]:
            for a, b in zip(outs[0], other):
                np.testing.assert_array_equal(a, b)

    def test_rng_varies(self, setup, mesh22):
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            temperature=1.0, top_k=16,
        )
        a = serve(params, prompts[:3], rng=jax.random.key(5))
        b = serve(params, prompts[:3], rng=jax.random.key(6))
        assert any((x.shape != y.shape) or (x != y).any() for x, y in zip(a, b))


class TestQuantizedEngine:
    """Quantized weights through the continuous engine (`dequantize=`,
    mirroring make_generate_fn). Oracle: every request bit-identical to
    the same-dequantize rectangular single run."""

    def _ref(self, cfg, mesh22, tree, prompt, dequantize):
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=NEW,
            dequantize=dequantize,
        )
        out = np.asarray(
            gen(tree, np.repeat(prompt[None, :], 2, axis=0),
                jax.random.key(0))
        )
        return out[0]

    @pytest.mark.parametrize("dequantize,bits", [(True, 8), ("fused", 4)])
    def test_matches_single_runs(self, setup, mesh22, dequantize, bits):
        from learning_jax_sharding_tpu.models.quantize import quantize_tree

        cfg, params, prompts = setup
        tree = quantize_tree(params, bits=bits)
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, dequantize=dequantize,
        )
        outs = serve(tree, prompts[:4])
        for p, got in zip(prompts[:4], outs):
            ref = self._ref(cfg, mesh22, tree, p, dequantize)
            np.testing.assert_array_equal(got, ref[: len(got)])

    def test_validation(self, setup, mesh22):
        cfg, _, _ = setup
        with pytest.raises(ValueError, match="dequantize"):
            make_continuous_engine(
                cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
                dequantize="nope",
            )


class TestSampledSpeculativeEngine:
    """Speculative SAMPLING inside the engine: Leviathan rejection with
    draws keyed by (request id, generated position, stream tag). Oracles:
    a request's sampled output is independent of scheduling — same queue
    under any batch size / refill chunk, and equal to the request served
    ALONE — and different rngs give different streams."""

    def _engine(self, cfg, mesh22, **kw):
        args = dict(
            batch_size=2, max_new_tokens=NEW, refill_chunk=4,
            draft_config=DRAFT_CFG, num_draft=3, temperature=1.0, top_k=16,
        )
        args.update(kw)
        return make_continuous_engine(cfg, mesh22, RULES_DP_TP, **args)

    def test_schedule_independent(self, setup, mesh22):
        cfg, params, prompts = setup
        key = jax.random.key(7)
        dp = _draft_params()
        outs = []
        for bs, chunk in ((2, 4), (3, 8), (4, 16)):
            serve = self._engine(cfg, mesh22, batch_size=bs,
                                 refill_chunk=chunk)
            outs.append(serve(params, prompts, rng=key, draft_params=dp))
        for other in outs[1:]:
            for a, b in zip(outs[0], other):
                np.testing.assert_array_equal(a, b)

    def test_equals_request_served_alone(self, setup, mesh22):
        cfg, params, prompts = setup
        key = jax.random.key(7)
        dp = _draft_params()
        batched_engine = self._engine(cfg, mesh22, batch_size=4)
        solo_engine = self._engine(cfg, mesh22, batch_size=1)
        for i, p in enumerate(prompts[:4]):
            # Request identity is the QUEUE INDEX: served alone a request
            # is request 0, so rotate the queue to put prompt i at the
            # head — its keys then match the solo run's.
            rotated = prompts[i:] + prompts[:i]
            batched = batched_engine(
                params, rotated, rng=key, draft_params=dp
            )
            solo = solo_engine(params, [p], rng=key, draft_params=dp)
            np.testing.assert_array_equal(batched[0], solo[0])

    def test_rng_varies(self, setup, mesh22):
        cfg, params, prompts = setup
        dp = _draft_params()
        serve = self._engine(cfg, mesh22)
        a = serve(params, prompts[:2], rng=jax.random.key(1), draft_params=dp)
        b = serve(params, prompts[:2], rng=jax.random.key(2), draft_params=dp)
        assert any(
            (x.shape != y.shape) or (x != y).any() for x, y in zip(a, b)
        )

    def test_joint_matches_target_distribution(self, setup, mesh22):
        """The Leviathan math itself, pinned at engine level: 1024
        requests with the SAME prompt are 1024 iid (request-id-keyed)
        2-token samples whose first token comes from the refill's plain
        filtered sampling and whose second comes through the spec block's
        accept/residual paths (an untrained draft keeps acceptance
        genuinely partial). Their empirical joint must match the exact
        target joint — a wrong acceptance rule or residual skews it."""
        from learning_jax_sharding_tpu.models.generate import top_k_filter
        from learning_jax_sharding_tpu.models.transformer import Transformer

        cfg, params, _ = setup
        dp = _draft_params()
        n = 1024
        prompt_row = np.asarray(
            np.random.default_rng(4).integers(1, cfg.vocab_size, size=(1, 8)),
            np.int32,
        )
        serve = self._engine(
            cfg, mesh22, batch_size=32, max_new_tokens=2, num_draft=1,
            top_k=4, refill_chunk=8,
        )
        outs = serve(
            params, [prompt_row[0]] * n, rng=jax.random.key(13),
            draft_params=dp,
        )
        pairs = np.stack([o[8:10] for o in outs])

        model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32))
        v = cfg.vocab_size

        def filtered_probs(toks):
            logits = model.apply({"params": params}, jnp.asarray(toks))
            return np.asarray(
                jax.nn.softmax(
                    top_k_filter(logits[:, -1].astype(jnp.float32), 4),
                    axis=-1,
                )
            )

        p0 = filtered_probs(prompt_row)[0]
        exact = np.zeros((v, v))
        (support0,) = np.nonzero(p0)
        for t0 in support0:
            row = np.concatenate(
                [prompt_row, [[t0]]], axis=1
            ).astype(np.int32)
            exact[t0] = p0[t0] * filtered_probs(row)[0]
        emp = np.zeros((v, v))
        for t0, t1 in pairs:
            emp[t0, t1] += 1.0 / n
        assert (emp[exact == 0] == 0).all()
        tv = 0.5 * np.abs(emp - exact).sum()
        # 1024 samples over <=16 cells: expected TV ~0.06.
        assert tv < 0.15, f"total variation {tv:.3f}"

    def test_greedy_spec_unchanged(self, setup, mesh22):
        """temperature=0 speculative must still be bit-identical to plain
        greedy engine output (the pre-existing oracle, re-pinned across
        this change)."""
        cfg, params, prompts = setup
        dp = _draft_params()
        plain = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        spec = self._engine(cfg, mesh22, temperature=0.0, top_k=None)
        a = plain(params, prompts)
        b = spec(params, prompts, draft_params=dp)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestPagedKVCache:
    """Paged serving: per-layer page pools + host-owned block tables.
    Oracles: outputs bit-identical to the unpaged engine; measured page
    high-water scales with tokens in flight (NOT batch × max_seq_len);
    allocation/release conserve the pool across slot reuse; exhaustion
    raises instead of corrupting."""

    PAGE = 16

    def _engine(self, cfg, mesh22, **kw):
        # Paged pools are shared across rows, so the batch must stay
        # replicated: TP-only rules (the guard in make_decode_attn_fn
        # rejects batch-sharding rules — RULES_DP_TP here raises).
        return make_continuous_engine(
            cfg, mesh22, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, **kw,
        )

    def test_matches_unpaged_engine(self, setup, mesh22):
        cfg, params, prompts = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        plain = self._engine(cfg, mesh22)
        paged = self._engine(cfg, mesh22, paged_pages=9, page_size=self.PAGE)
        ref = plain(params, prompts)
        got = paged(params, prompts)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)
        # The footprint claim: the whole 7-request mixed-length workload
        # through 2 slots never needed the full slot-reservation
        # (2 slots × 4 blocks = 8 pages).
        stats = paged.last_stats
        assert stats["page_high_water"] < 2 * (cfg.max_seq_len // self.PAGE)
        assert stats["page_high_water"] >= 1

    def test_high_water_tracks_in_flight_tokens(self, setup, mesh22):
        """Short requests (1 page each) vs long requests (2+ pages each)
        must show different high-water marks — the footprint follows the
        tokens actually held, not the configured maximum."""
        cfg, params, _ = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(5)
        short = [
            rng.integers(1, cfg.vocab_size, size=(3,)).astype(np.int32)
            for _ in range(4)
        ]
        long = [
            rng.integers(1, cfg.vocab_size, size=(30,)).astype(np.int32)
            for _ in range(4)
        ]
        eng = self._engine(cfg, mesh22, paged_pages=9, page_size=self.PAGE)
        eng(params, short)
        hw_short = eng.last_stats["page_high_water"]
        eng(params, long)
        hw_long = eng.last_stats["page_high_water"]
        assert hw_short <= 2          # 2 slots × 1 page
        assert hw_long >= 2 * 2       # 2 slots × >=2 pages mid-flight
        assert hw_long > hw_short

    def test_paged_speculative_matches(self, setup, mesh22):
        cfg, params, prompts = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        plain = self._engine(cfg, mesh22)
        paged_spec = self._engine(
            cfg, mesh22, paged_pages=9, page_size=self.PAGE,
            draft_config=dcfg, num_draft=2,
        )
        ref = plain(params, prompts)
        got = paged_spec(params, prompts, draft_params=_draft_params())
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_paged_int8_kv_matches_unpaged(self, setup, mesh22):
        """Paged pools carry the int8 KV scales in page-shaped pools of
        their own — the quantized cache must page bit-identically to its
        unpaged (quantized) self."""
        cfg, params, prompts = setup
        cfg = dataclasses.replace(
            cfg, decode_attention="blocked", kv_cache_dtype=jnp.int8
        )
        plain = self._engine(cfg, mesh22)
        paged = self._engine(cfg, mesh22, paged_pages=9, page_size=self.PAGE)
        ref = plain(params, prompts)
        got = paged(params, prompts)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_prefix_cache_matches_and_reuses(self, setup, mesh22):
        """Prefix caching: repeated prompts re-admit with retired
        requests' prompt pages already in their tables — outputs stay
        bit-identical to the unpaged engine, and the stats show real
        reuse (hits for both full repeats and shared-prefix variants)."""
        cfg, params, _ = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(9)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        variant = base.copy()
        variant[self.PAGE + 1] += 1     # same first page, different tail
        queue = [base, variant, base, base.copy(), variant.copy()]
        plain = self._engine(cfg, mesh22)
        ref = plain(params, queue)
        pfx = self._engine(
            cfg, mesh22, paged_pages=9, page_size=self.PAGE,
            prefix_cache=True,
        )
        got = pfx(params, queue)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)
        stats = pfx.last_stats
        # 2 slots serve 5 requests: at least the later base repeats and
        # the tail variant admit after a retirement registered page 0.
        assert stats["prefix_hits"] >= 2
        assert stats["prefix_pages_reused"] >= stats["prefix_hits"]

    def test_prefix_cache_eviction_under_pressure(self, setup, mesh22):
        """Retained pages must yield to live requests: distinct prompts
        through a pool sized with no slack for retention still serve
        (LRU eviction), bit-identical to the unpaged engine."""
        cfg, params, _ = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(10)
        queue = [
            rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
            for _ in range(6)
        ]
        plain = self._engine(cfg, mesh22)
        ref = plain(params, queue)
        # 2 slots × 20+NEW=26 tokens → 2 pages/slot live + scratch; 5
        # pages total leaves ZERO headroom for retention.
        pfx = self._engine(
            cfg, mesh22, paged_pages=5, page_size=self.PAGE,
            prefix_cache=True,
        )
        got = pfx(params, queue)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_prefix_cache_speculative(self, setup, mesh22):
        """Prefix sharing + speculative decode blocks: the draft pool's
        pages share through the same tables, in lockstep."""
        cfg, params, _ = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        rng = np.random.default_rng(11)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        queue = [base, base.copy(), base.copy()]
        plain = self._engine(cfg, mesh22)
        ref = plain(params, queue)
        pfx = self._engine(
            cfg, mesh22, paged_pages=9, page_size=self.PAGE,
            prefix_cache=True, draft_config=dcfg, num_draft=2,
        )
        got = pfx(params, queue, draft_params=_draft_params())
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)
        assert pfx.last_stats["prefix_hits"] >= 1

    def test_everything_composes(self, setup, mesh22):
        """The whole round-4 serving stack AT ONCE — int4-fused weights +
        paged KV + prefix cache + speculative decode blocks — must still
        be bit-identical to the plain int4 engine. The features were each
        pinned alone; this is the composition oracle."""
        from learning_jax_sharding_tpu.models.quantize import quantize_tree

        cfg, params, _ = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        rng = np.random.default_rng(12)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        queue = [base, base.copy(), base.copy(), base.copy()]
        q4 = quantize_tree(params, bits=4)
        plain = self._engine(cfg, mesh22, dequantize="fused")
        ref = plain(q4, queue)
        allon = self._engine(
            cfg, mesh22, dequantize="fused", paged_pages=9,
            page_size=self.PAGE, prefix_cache=True, draft_config=dcfg,
            num_draft=2,
        )
        got = allon(q4, queue, draft_params=_draft_params())
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)
        stats = allon.last_stats
        assert stats["prefix_hits"] >= 1
        assert stats["spec_proposed"] > 0

    def test_everything_composes_quantized_draft(self, setup, mesh22):
        """The all-on stack with the DRAFT quantized too (int4-fused
        target + int8 in-jit-dequant draft + paged + prefix + spec):
        still bit-identical to the plain int4 engine — a quantized draft
        changes only what gets proposed, never what gets emitted."""
        from learning_jax_sharding_tpu.models.quantize import quantize_tree

        cfg, params, _ = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        rng = np.random.default_rng(13)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        queue = [base, base.copy(), base.copy(), base.copy()]
        q4 = quantize_tree(params, bits=4)
        d8 = quantize_tree(_draft_params(), bits=8)
        plain = self._engine(cfg, mesh22, dequantize="fused")
        ref = plain(q4, queue)
        allon = self._engine(
            cfg, mesh22, dequantize="fused", paged_pages=9,
            page_size=self.PAGE, prefix_cache=True, draft_config=dcfg,
            draft_dequantize=True, num_draft=2,
        )
        got = allon(q4, queue, draft_params=d8)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)
        assert allon.last_stats["prefix_hits"] >= 1
        assert allon.last_stats["spec_proposed"] > 0

    def test_prefix_cache_requires_paged(self, setup, mesh22):
        cfg, _, _ = setup
        with pytest.raises(ValueError, match="prefix_cache"):
            make_continuous_engine(
                dataclasses.replace(cfg, decode_attention="blocked"),
                mesh22, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
                prefix_cache=True,
            )

    def test_pool_exhaustion_raises(self, setup, mesh22):
        cfg, params, prompts = setup
        cfg = dataclasses.replace(cfg, decode_attention="blocked")
        eng = self._engine(cfg, mesh22, paged_pages=2, page_size=self.PAGE)
        with pytest.raises(RuntimeError, match="page pool exhausted"):
            eng(params, [prompts[4], prompts[1]])  # 12- and 9-token prompts

    def test_validation(self, setup, mesh22):
        cfg, params, prompts = setup
        with pytest.raises(ValueError, match="blocked"):
            self._engine(
                dataclasses.replace(cfg, decode_attention="dense"),
                mesh22, paged_pages=8, page_size=self.PAGE,
            )
        blocked = dataclasses.replace(cfg, decode_attention="blocked")
        with pytest.raises(ValueError, match="paged_pages"):
            self._engine(blocked, mesh22, paged_pages=1, page_size=self.PAGE)
        with pytest.raises(ValueError, match="multiple"):
            self._engine(blocked, mesh22, paged_pages=8, page_size=48)
        # Batch-sharding rules must be rejected: any row can read any
        # page, so a batch shard would need its own pool.
        eng_dp = make_continuous_engine(
            blocked, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, paged_pages=9, page_size=self.PAGE,
        )
        with pytest.raises(ValueError, match="cannot shard the batch"):
            eng_dp(params, prompts[:1])


class TestPersistentEngine:
    """Round 5: the engine OBJECT owns the cache, page pool, and prefix
    registry — state survives across serve() calls (and streaming
    sessions), so prefix hits span calls, the cache-creating refill runs
    once per engine ever, and requests can arrive over time."""

    PAGE = 16

    def _paged(self, cfg, mesh22, **kw):
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        return ContinuousEngine(
            dataclasses.replace(cfg, decode_attention="blocked"),
            mesh22, RULES_TP_SERVING, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, paged_pages=9, page_size=self.PAGE, **kw,
        )

    def test_prefix_hit_spans_serve_calls(self, setup, mesh22):
        """THE persistence payoff: a second serve() call with the same
        system prompt reuses the pages the first call retired — zero
        hits in call 1, hits in call 2, outputs bit-identical both
        times."""
        cfg, params, _ = setup
        bcfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(21)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        plain = make_continuous_engine(
            bcfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=4,
        )
        eng = self._paged(cfg, mesh22, prefix_cache=True)
        ref = plain(params, [base])
        got1 = eng.serve(params, [base])
        assert eng.last_stats["prefix_hits"] == 0
        assert eng.last_stats["prefix_pages_retained"] >= 1
        got2 = eng.serve(params, [base.copy()])
        assert eng.last_stats["prefix_hits"] == 1
        assert eng.last_stats["prefix_pages_reused"] >= 1
        np.testing.assert_array_equal(got1[0], ref[0])
        np.testing.assert_array_equal(got2[0], ref[0])

    def test_cache_created_once_across_calls(self, setup, mesh22):
        """No per-call reallocation: the cache-creating first refill runs
        on the first call only; the second call reuses the live arrays
        (counter pinned, paged and unpaged)."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        eng.serve(params, prompts[:2])
        assert eng.cache_creations == 1
        eng.serve(params, prompts[2:4])
        assert eng.cache_creations == 1
        paged = self._paged(cfg, mesh22)
        paged.serve(params, prompts[:2])
        paged.serve(params, prompts[2:4])
        assert paged.cache_creations == 1

    def test_streaming_matches_rectangular(self, setup, mesh22):
        """add_request/step/pop_finished — requests admitted OVER TIME
        (two up front, the rest injected while the engine is mid-flight)
        still produce bit-identical outputs per request."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        rids = {}
        for p in prompts[:2]:
            rids[eng.add_request(p)] = p
        results = {}
        steps = 0
        late = list(prompts[2:5])
        while eng.has_work() or late:
            eng.step(params)
            results.update(eng.pop_finished())
            steps += 1
            if late and steps >= 2:      # arrivals while serving
                p = late.pop(0)
                rids[eng.add_request(p)] = p
        assert set(results) == set(rids)
        for rid, p in rids.items():
            ref = _rect_reference(cfg, mesh22, params, p)
            np.testing.assert_array_equal(
                results[rid], ref[: len(results[rid])]
            )
            assert len(results[rid]) == len(p) + NEW

    def test_latency_telemetry(self, setup, mesh22):
        """serve() reports per-request latency percentiles: TTFT, TPOT,
        ITL, queue wait — all positive and ordered sanely."""
        cfg, params, prompts = setup
        serve = make_continuous_engine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4,
        )
        serve(params, prompts)
        lat = serve.last_latency
        assert lat["requests"] == len(prompts)
        for k in ("ttft_p50", "ttft_p99", "tpot_p50", "queue_wait_p50",
                  "e2e_p50", "itl_p50"):
            assert k in lat, k
        assert 0 < lat["ttft_p50"] <= lat["ttft_p99"]
        assert lat["ttft_p50"] <= lat["e2e_p50"]
        assert lat["tpot_p50"] > 0

    def test_serve_requires_idle(self, setup, mesh22):
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        )
        eng.add_request(prompts[0])
        with pytest.raises(RuntimeError, match="idle"):
            eng.serve(params, prompts[:1])
        while eng.has_work():
            eng.step(params)
        eng.pop_finished()
        eng.serve(params, prompts[:1])   # idle again: fine

    def test_flush_prefix_cache(self, setup, mesh22):
        """flush_prefix_cache returns every retained page to the free
        pool (the params-swap hook); the next same-prompt call re-fills
        from scratch (no hit) but still matches."""
        cfg, params, _ = setup
        rng = np.random.default_rng(22)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        eng = self._paged(cfg, mesh22, prefix_cache=True)
        got1 = eng.serve(params, [base])
        assert eng.last_stats["prefix_pages_retained"] >= 1
        eng.flush_prefix_cache()
        assert len(eng._cached_lru) == 0
        assert len(eng._free_pages) == 8    # the whole pool is free again
        got2 = eng.serve(params, [base.copy()])
        assert eng.last_stats["prefix_hits"] == 0
        np.testing.assert_array_equal(got2[0], got1[0])

    def test_engine_reusable_after_exhaustion(self, setup, mesh22):
        """A pool-exhaustion raise must not wedge the persistent engine:
        reset() runs automatically and the next (feasible) call serves."""
        cfg, params, prompts = setup
        eng = self._paged(cfg, mesh22)
        eng2 = self._paged(cfg, mesh22)
        small = dataclasses.replace(cfg, decode_attention="blocked")
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        tight = ContinuousEngine(
            small, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=4, paged_pages=2,
            page_size=self.PAGE,
        )
        with pytest.raises(RuntimeError, match="page pool exhausted"):
            tight.serve(params, [prompts[4], prompts[1]])
        got = tight.serve(params, [prompts[3]])    # 1-token prompt fits
        ref = eng.serve(params, [prompts[3]])
        np.testing.assert_array_equal(got[0], ref[0])
        del eng2

    def test_serve_preserves_streaming_results(self, setup, mesh22):
        """Un-popped streaming results survive an interleaved serve()
        call — serve's per-call rid namespace must not collide with
        them (review finding, round 5)."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        )
        rid = eng.add_request(prompts[0])     # rid 0 — collides with serve's
        while eng.has_work():
            eng.step(params)
        # NOT popped; serve() must stash it.
        out = eng.serve(params, [prompts[1]])
        ref0 = _rect_reference(cfg, mesh22, params, prompts[0])
        ref1 = _rect_reference(cfg, mesh22, params, prompts[1])
        np.testing.assert_array_equal(out[0], ref1[: len(out[0])])
        fin = eng.pop_finished()
        assert set(fin) == {rid}
        np.testing.assert_array_equal(fin[rid], ref0[: len(fin[rid])])

    def test_close_releases_and_recreates(self, setup, mesh22):
        """close() drops the device cache (HBM reclaim for multi-engine
        processes); the engine stays usable and re-creates on demand."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        )
        a = eng.serve(params, [prompts[0]])
        assert eng.cache_creations == 1
        eng.close()
        assert eng._cache is None
        b = eng.serve(params, [prompts[0]])
        assert eng.cache_creations == 2
        np.testing.assert_array_equal(a[0], b[0])

    def test_preemption_under_pressure_is_exact(self, setup, mesh22):
        """Pool pressure triggers RECOMPUTE preemption instead of a
        raise whenever another request holds reclaimable pages: two
        2-page requests through a 3-page pool must preempt (one row
        yields, requeues, restarts) and still emit bit-identical
        outputs — scheduling, including preemption, never changes
        results."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, _ = setup
        bcfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(23)
        # 14-token prompts: 1 page to refill, a 2nd page mid-decode
        # (14 + 6 tokens > 16), so two concurrent rows want 4 of 3 pages.
        queue = [
            rng.integers(1, cfg.vocab_size, size=(14,)).astype(np.int32)
            for _ in range(2)
        ]
        plain = make_continuous_engine(
            bcfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=4,
        )
        ref = plain(params, queue)
        tight = ContinuousEngine(
            bcfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=4, paged_pages=4,
            page_size=self.PAGE,
        )
        got = tight.serve(params, queue)
        assert tight.last_stats["preemptions"] >= 1
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_sampled_preemption_is_exact(self, setup, mesh22):
        """Same pressure at temperature > 0: the preempted request's
        re-derived draws are keyed by (request id, position), so even
        SAMPLED output is identical to the unpressured engine."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, _ = setup
        bcfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(24)
        queue = [
            rng.integers(1, cfg.vocab_size, size=(14,)).astype(np.int32)
            for _ in range(2)
        ]
        kw = dict(
            batch_size=2, max_new_tokens=NEW, refill_chunk=4,
            temperature=1.0, top_k=16,
        )
        roomy = ContinuousEngine(
            bcfg, mesh22, RULES_TP_SERVING, paged_pages=9,
            page_size=self.PAGE, **kw,
        )
        tight = ContinuousEngine(
            bcfg, mesh22, RULES_TP_SERVING, paged_pages=4,
            page_size=self.PAGE, **kw,
        )
        key = jax.random.key(31)
        ref = roomy.serve(params, queue, rng=key)
        assert roomy.last_stats["preemptions"] == 0
        got = tight.serve(params, queue, rng=key)
        assert tight.last_stats["preemptions"] >= 1
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(g, r)

    def test_busy_guards_and_duplicate_rid(self, setup, mesh22):
        """flush_prefix_cache() refuses a busy engine (re-exposing
        old-params K/V); duplicate explicit rids are rejected instead of
        silently overwriting results. close() no longer refuses a busy
        engine — it DRAINS in-flight work to a terminal status (round
        10; pinned in tests/test_zero_downtime.py)."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        eng = self._paged(cfg, mesh22, prefix_cache=True)
        eng.add_request(prompts[0], rid=7)
        with pytest.raises(ValueError, match="already in use"):
            eng.add_request(prompts[1], rid=7)
        with pytest.raises(RuntimeError, match="idle"):
            eng.flush_prefix_cache()
        while eng.has_work():
            eng.step(params)
        with pytest.raises(ValueError, match="already in use"):
            eng.add_request(prompts[1], rid=7)   # finished, un-popped
        assert set(eng.pop_finished()) == {7}
        eng.close()                              # idle: fine

    def test_invalid_prompt_preserves_registry(self, setup, mesh22):
        """A validation error in serve() must raise BEFORE touching any
        state: the persistent prefix registry survives (review finding —
        the failure path resets the pool, so validation must be atomic)."""
        cfg, params, _ = setup
        rng = np.random.default_rng(25)
        base = rng.integers(1, cfg.vocab_size, size=(20,)).astype(np.int32)
        eng = self._paged(cfg, mesh22, prefix_cache=True)
        eng.serve(params, [base])
        assert eng.last_stats["prefix_pages_retained"] >= 1
        too_long = rng.integers(
            1, cfg.vocab_size, size=(cfg.max_seq_len,)
        ).astype(np.int32)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.serve(params, [base.copy(), too_long])
        got = eng.serve(params, [base.copy()])
        assert eng.last_stats["prefix_hits"] == 1   # registry intact

    def test_long_prompt_chunked_paged_matches(self, setup, mesh22):
        """A longer prompt (112 tokens) streamed through 7 refill chunks
        over the paged pool — the composition the long-context serving
        measurement runs at depth — stays bit-identical to the plain
        engine."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, _ = setup
        bcfg = dataclasses.replace(cfg, decode_attention="blocked")
        rng = np.random.default_rng(26)
        # 44 tokens through 8-token chunks: 6 refill dispatches (last one
        # partial), 3 pages — long relative to every shape dimension.
        long_p = rng.integers(1, cfg.vocab_size, size=(44,)).astype(np.int32)
        plain = make_continuous_engine(
            bcfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8,
        )
        paged = ContinuousEngine(
            bcfg, mesh22, RULES_TP_SERVING, batch_size=2,
            max_new_tokens=NEW, refill_chunk=8, paged_pages=11,
            page_size=self.PAGE,
        )
        ref = plain(params, [long_p])
        got = paged.serve(params, [long_p.copy()])
        np.testing.assert_array_equal(got[0], ref[0])
        assert paged.last_stats["page_high_water"] >= 44 // self.PAGE

    @pytest.mark.parametrize("temp", [0.0, 1.0])
    def test_decode_chain_bit_identical(self, setup, mesh22, temp):
        """decode_chain > 1 (device-carried block chaining, one host
        sync per chain) cannot change results: greedy AND sampled, with
        EOS retirement mid-chain, vs the chain=1 engine."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        kw = dict(
            batch_size=2, max_new_tokens=NEW, refill_chunk=4,
            decode_block_steps=2, temperature=temp,
            top_k=16 if temp else None,
        )
        key_probe = jax.random.key(9)
        if temp == 0.0:
            plain_ref = _rect_reference(cfg, mesh22, params, prompts[0])
            eos = int(plain_ref[len(prompts[0]) + 1])
        else:
            # Derive an eos the SAMPLED streams actually emit, so EOS
            # retirement mid-chain is exercised at temperature > 0 too.
            probe = ContinuousEngine(cfg, mesh22, RULES_DP_TP, **kw)
            outs = probe.serve(params, prompts, rng=key_probe)
            gen = np.concatenate(
                [o[len(p):] for o, p in zip(outs, prompts)]
            )
            eos = int(np.bincount(gen).argmax())
        one = ContinuousEngine(cfg, mesh22, RULES_DP_TP, eos_id=eos, **kw)
        chained = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, eos_id=eos, decode_chain=3, **kw
        )
        a = one.serve(params, prompts, rng=key_probe)
        b = chained.serve(params, prompts, rng=key_probe)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(y, x)

    def test_decode_chain_speculative_paged(self, setup, mesh22):
        """Chained SPECULATIVE blocks over the paged pool — the whole
        carry set (tok/pos/active/remaining + both caches) rides the
        chain; outputs stay bit-identical to the unchained engine."""
        from learning_jax_sharding_tpu.models.serving import ContinuousEngine

        cfg, params, prompts = setup
        bcfg = dataclasses.replace(cfg, decode_attention="blocked")
        dcfg = dataclasses.replace(DRAFT_CFG, decode_attention="blocked")
        kw = dict(
            batch_size=2, max_new_tokens=NEW, refill_chunk=4,
            decode_block_steps=2, draft_config=dcfg, num_draft=2,
            paged_pages=9, page_size=self.PAGE,
        )
        dp = _draft_params()
        one = ContinuousEngine(bcfg, mesh22, RULES_TP_SERVING, **kw)
        chained = ContinuousEngine(
            bcfg, mesh22, RULES_TP_SERVING, decode_chain=4, **kw
        )
        a = one.serve(params, prompts[:4], draft_params=dp)
        b = chained.serve(params, prompts[:4], draft_params=dp)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(y, x)
