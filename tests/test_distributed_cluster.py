"""A REAL 2-process JAX distributed cluster, on CPU.

SURVEY.md §7 lists multi-host as a hard part that "can't be fully tested in
this 1-chip environment — … verify on emulated multi-process CPU where
possible". This is that verification, and it is not an emulation of the
runtime: two OS processes bootstrap through ``multihost.initialize`` (Gloo
rendezvous — the CPU stand-in for the DCN path), see one global 4-device
system, assemble per-host batch slices into global arrays, and execute one
SPMD train step whose gradient all-reduce crosses the process boundary.
Both ranks must report the identical loss — the single-controller illusion
the whole multi-host design promises.

Subprocess-based because the distributed runtime binds the process: the
in-suite JAX (8 emulated devices, no cluster) must stay untouched.
"""

import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "_distributed_worker.py"
NPROC = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skip(reason=(
    "this jaxlib's XLA:CPU client cannot compile a computation that "
    "spans processes: the 2-process Gloo rendezvous succeeds, both "
    "ranks see the global 4-device system, and then the FIRST jit of "
    "the sharded train-state init dies with XlaRuntimeError "
    "INVALID_ARGUMENT 'Multiprocess computations aren't implemented "
    "on the CPU backend' (training/pipeline.py sharded_train_state). "
    "A backend capability gap, not a repo sharding bug — the same "
    "program partitions fine single-process on 8 emulated devices "
    "(test_multihost.py). Re-enable when the pinned jaxlib grows "
    "multi-process XLA:CPU; triage trail in analysis/baseline.json."
))
def test_two_process_cluster_train_step():
    # (timeout enforced via communicate(timeout=240) below — no plugin needed)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(rank), str(NPROC), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=WORKER.parent.parent,
        )
        for rank in range(NPROC)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out (rendezvous hang?)")

    for rc, out, err in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err[-2000:]}"

    losses = {}
    for rc, out, err in outs:
        for line in out.splitlines():
            if line.startswith("RANK"):
                rank, _, loss = line.split()
                losses[rank] = float(loss)
    assert len(losses) == NPROC, f"missing rank output: {outs}"
    vals = list(losses.values())
    assert vals[0] == pytest.approx(vals[1], abs=1e-6), (
        f"ranks disagree on the replicated loss: {losses}"
    )
