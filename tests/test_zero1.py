"""ZeRO-1: optimizer state sharded over the data axis.

The reference replicates Adam state wherever the params live
(`/root/reference/case6_attention.py:181`); case 3 shows the zero-redundancy
placement idea on a matmul (`/root/reference/case3_fully_sharded.py:23-60`).
These tests pin the framework's application of that idea to optimizer state:
moments born 1/D-sharded over 'data', update trajectory identical to the
replicated baseline (ZeRO-1 is an exact rearrangement, not an approximation).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.precision import master_weights
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)
from learning_jax_sharding_tpu.training.zero import (
    make_zero1_update,
    zero1_shardings,
)


def _make_state(mesh, rng, tx, zero1_axis=None, cfg=CONFIG_TINY):
    model = Transformer(cfg)
    tokens = rng.integers(0, cfg.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, tx, batch["inputs"], {"params": jax.random.key(0)},
        mesh, RULES_DP_TP, zero1_axis=zero1_axis,
    )
    return state, state_sh, batch


class TestZero1Shardings:
    def test_moments_sharded_params_untouched(self, mesh22, rng):
        state, _, _ = _make_state(
            mesh22, rng, optax.adamw(3e-3), zero1_axis="data"
        )
        # Embedding table (vocab, embed): vocab→model under RULES_DP_TP, so
        # ZeRO stacks 'data' — params keep the plain spec, moments add it.
        emb = state.params["tok_embed"]["embedding"]
        mu = state.opt_state[0].mu["tok_embed"]["embedding"]
        data = mesh22.shape["data"]
        assert "data" not in str(emb.sharding.spec)
        assert (
            mu.addressable_shards[0].data.shape[0] * data
            == emb.addressable_shards[0].data.shape[0]
        ), (mu.sharding, emb.sharding)
        # Moment bytes per device shrink by the data-axis factor.
        assert (
            mu.addressable_shards[0].data.size
            == emb.addressable_shards[0].data.size // data
        )

    def test_scalar_count_stays_replicated(self, mesh22, rng):
        state, _, _ = _make_state(
            mesh22, rng, optax.adamw(3e-3), zero1_axis="data"
        )
        count = state.opt_state[0].count
        assert count.sharding.is_fully_replicated

    def test_already_data_sharded_leaf_unchanged(self, mesh22):
        abstract = jax.ShapeDtypeStruct((8, 4), jnp.float32)
        sh = NamedSharding(mesh22, PartitionSpec("data", None))
        out = zero1_shardings(abstract, sh, mesh22, "data")
        assert out is sh

    def test_indivisible_leaf_left_replicated(self, mesh22):
        abstract = jax.ShapeDtypeStruct((3, 5), jnp.float32)
        sh = NamedSharding(mesh22, PartitionSpec())
        out = zero1_shardings(abstract, sh, mesh22, "data")
        assert out.spec == PartitionSpec()


class TestZero1Parity:
    def test_trajectory_matches_replicated(self, mesh22, rng):
        """ZeRO-1 is an exact rearrangement: losses match the replicated
        baseline step for step (same init key, same batch)."""
        losses = {}
        for axis in (None, "data"):
            state, state_sh, batch = _make_state(
                mesh22, np.random.default_rng(0), optax.adamw(3e-3),
                zero1_axis=axis,
            )
            step = make_train_step(
                state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
                RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
            )
            out = []
            for _ in range(5):
                state, loss = step(state, batch)
                out.append(float(loss))
            losses[axis] = out
        np.testing.assert_allclose(losses[None], losses["data"], rtol=1e-5)
        assert losses["data"][-1] < losses["data"][0]

    @pytest.mark.slow
    def test_explicit_sync_matches_fused_step(self, mesh22):
        """make_zero1_update with the exact fp32 sync is the same update
        make_train_step's implicit GSPMD reduction computes — per-slice
        mean-of-means reproduces the global mean (tight tolerance: only
        reduction order differs)."""
        losses = {}
        for name, builder in (
            ("fused", make_train_step), ("explicit", make_zero1_update),
        ):
            # Each step is built against ITS state's shardings: TrainState
            # pytree metadata embeds the optimizer closures, so states
            # from different sharded_train_state calls never interchange.
            state, state_sh, batch = _make_state(
                mesh22, np.random.default_rng(0), optax.adamw(3e-3),
                zero1_axis="data",
            )
            step = builder(
                state_sh, {k: v.sharding for k, v in batch.items()},
                mesh22, RULES_DP_TP, loss_fn=next_token_loss,
                donate_state=False,
            )
            out = []
            for _ in range(5):
                state, loss = step(state, batch)
                out.append(float(loss))
            losses[name] = out
        np.testing.assert_allclose(
            losses["fused"], losses["explicit"], rtol=1e-4
        )

    def test_quantized_comm_accuracy_gate(self, mesh22):
        """The int8-ring grad sync (quantized_comm=True,
        parallel.collectives.quantized_all_reduce): the loss trajectory
        must track the fp32-sync baseline within tolerance on the tiny
        config AND keep learning — the accuracy gate for shipping
        quantized collectives on the training side."""
        trajectories = {}
        for q in (False, True):
            state, state_sh, batch = _make_state(
                mesh22, np.random.default_rng(0), optax.adamw(3e-3),
                zero1_axis="data",
            )
            step = make_zero1_update(
                state_sh, {k: v.sharding for k, v in batch.items()},
                mesh22, RULES_DP_TP, loss_fn=next_token_loss,
                quantized_comm=q, donate_state=False,
            )
            out = []
            for _ in range(6):
                state, loss = step(state, batch)
                out.append(float(loss))
            trajectories[q] = out
        fp32, q8 = np.asarray(trajectories[False]), np.asarray(
            trajectories[True]
        )
        # Requantization error is bounded per hop (~1.6% grad L2 at D=8,
        # test_collectives) — the LOSS trajectory stays within 1%.
        np.testing.assert_allclose(q8, fp32, rtol=1e-2)
        assert q8[-1] < q8[0]
        # And it is genuinely quantized, not the exact path: trajectories
        # must differ (else the sync silently fell back to fp32).
        assert not np.array_equal(q8, fp32)

    def test_indivisible_batch_raises(self, mesh22):
        state, state_sh, batch = _make_state(
            mesh22, np.random.default_rng(0), optax.adamw(3e-3),
            zero1_axis="data",
        )
        step = make_zero1_update(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        # jit's own sharding check may fire first (slicing a sharded
        # array re-shards host-side); either way the indivisible batch
        # must raise, never silently truncate a shard's contribution.
        bad = {k: np.asarray(v)[:7] for k, v in batch.items()}
        with pytest.raises(ValueError, match="divisible"):
            step(state, bad)

    def test_composes_with_master_weights(self, mesh22, rng):
        """bf16 params + fp32 masters + ZeRO-1: the masters (the big fp32
        copies ZeRO-1 exists to slim down) are sharded over data."""
        cfg = dataclasses.replace(CONFIG_TINY, param_dtype=jnp.bfloat16)
        state, state_sh, batch = _make_state(
            mesh22, rng, master_weights(optax.adamw(3e-3)),
            zero1_axis="data", cfg=cfg,
        )
        master = state.opt_state.master["tok_embed"]["embedding"]
        param = state.params["tok_embed"]["embedding"]
        data = mesh22.shape["data"]
        assert (
            master.addressable_shards[0].data.size
            == param.addressable_shards[0].data.size // data
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh22,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        losses = []
        for _ in range(6):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
