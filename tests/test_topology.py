"""Unit tests for the two-tier interconnect model (round 21).

The integration surface — ``shardcheck --topo`` reconcile, the seeded
layout-search canary, the ``dcn_degrade`` matrix cell — lives in
``test_layout_search.py`` / ``test_resharding.py`` /
``test_zero_downtime.py``.  This file pins the MODEL itself: the
profile's tier algebra, its JSON contract (the checked-in profile must
keep loading), and the tier-bucketed overlap-discounted pricing math
that every consumer leans on.
"""

import math

import pytest

from learning_jax_sharding_tpu.analysis import costmodel
from learning_jax_sharding_tpu.analysis.costmodel import (
    _ring_factor,
    price_event,
    price_event_topo,
    price_multiset,
    price_multiset_topo,
    table_profile,
)
from learning_jax_sharding_tpu.analysis.shardflow import CommEvent
from learning_jax_sharding_tpu.analysis.topology import (
    DEFAULT_TIERS,
    REFERENCE_LINKS,
    TIER_DCN,
    TIER_ICI,
    TopologyProfile,
    reference_two_tier,
    segment_tier,
)

TOPO = reference_two_tier(("data", "model"), (2, 4))
PROFILE = table_profile("TPU v5 lite")
SIZES = {"data": 2, "model": 4}


def _ev(axes, nbytes=1 << 20, op="all-reduce", in_loop=False, trip=None):
    return CommEvent(
        kind="reduce",
        axes=tuple(axes),
        bytes=nbytes,
        where="test:1",
        primitive="dot_general",
        reason="test event",
        realizations=((op, "+".join(axes)),),
        in_loop=in_loop,
        trip=trip,
    )


class TestProfileAlgebra:
    def test_reference_two_tier_tags_leading_axis_dcn(self):
        assert TOPO.tier_of("data") == TIER_DCN
        assert TOPO.tier_of("model") == TIER_ICI
        # ICI-domain grain = product of ICI-axis extents.
        assert TOPO.ici_domain_devices == 4
        a = TOPO.axis_tier("data")
        assert (a.alpha_s, a.beta_bytes_per_s) == REFERENCE_LINKS[TIER_DCN]

    def test_untagged_axis_defaults_to_ici(self):
        # An unknown axis must not silently price at DCN rates.
        assert TOPO.tier_of("ghost") == TIER_ICI
        assert TOPO.alpha_beta(("ghost",)) is None

    def test_bucket_any_dcn_axis_wins(self):
        assert TOPO.bucket(("model",)) == TIER_ICI
        assert TOPO.bucket(("data",)) == TIER_DCN
        # The slow hop dominates the ring.
        assert TOPO.bucket(("model", "data")) == TIER_DCN

    def test_alpha_beta_adds_latency_takes_slowest_link(self):
        a_d, b_d = REFERENCE_LINKS[TIER_DCN]
        a_i, b_i = REFERENCE_LINKS[TIER_ICI]
        assert TOPO.alpha_beta(("data", "model")) == (a_d + a_i, min(b_d, b_i))

    def test_domain_carving(self):
        # grain 4 on 8 devices: {0..3} | {4..7}.
        assert [TOPO.domain_of_id(i) for i in range(8)] == [0] * 4 + [1] * 4
        ici_only = reference_two_tier(
            ("data", "model"), (2, 4), tiers={"data": TIER_ICI}
        )
        assert ici_only.ici_domain_devices == 8
        assert ici_only.dcn_axes() == ()
        # No DCN axis tagged → the reference DCN link, never free.
        assert ici_only.dcn_alpha_beta() == REFERENCE_LINKS[TIER_DCN]

    def test_dcn_seconds(self):
        alpha, beta = TOPO.dcn_alpha_beta()
        assert TOPO.dcn_seconds(0) == 0.0
        assert TOPO.dcn_seconds(1 << 20) == pytest.approx(
            alpha + (1 << 20) / beta
        )

    def test_overlap_ratio_lookup(self):
        t = reference_two_tier(
            ("data",), (2,), overlap={"train_step": 0.7, "_default": 0.2}
        )
        assert t.overlap_ratio("train_step") == 0.7
        assert t.overlap_ratio("decode_step") == 0.2
        assert t.overlap_ratio(None) == 0.2
        assert TOPO.overlap_ratio("train_step") is None


class TestProfileSerialization:
    def test_round_trip_preserves_identity(self):
        t = reference_two_tier(
            ("data", "model"), (2, 4), overlap={"train_step": 0.68}
        )
        assert TopologyProfile.from_dict(t.to_dict()).key() == t.key()

    def test_version_gate(self):
        d = TOPO.to_dict()
        d["version"] = 999
        with pytest.raises(ValueError, match="version 999"):
            TopologyProfile.from_dict(d)

    def test_save_load(self, tmp_path):
        p = TOPO.save(tmp_path / "profiles" / "t.json")
        assert TopologyProfile.load(p).key() == TOPO.key()

    def test_default_path_shape(self):
        p = TopologyProfile.default_path("cpu", (2, 4))
        assert p.name == "topology_cpu_2x4.json"
        assert p.parent.name == "profiles"

    def test_checked_in_profile_loads(self):
        # The versioned profile the topo pass ships with must keep
        # loading — this is the JSON contract the pass depends on.
        path = TopologyProfile.default_path("cpu", (2, 4))
        t = TopologyProfile.load(path)
        assert t.tier_of("data") == TIER_DCN
        assert t.tier_of("model") == TIER_ICI
        assert t.ici_domain_devices == 4
        assert 0.0 < t.overlap_ratio("train_step") <= 1.0

    def test_default_tiers_cover_canonical_axes(self):
        assert DEFAULT_TIERS["data"] == TIER_DCN
        assert DEFAULT_TIERS["model"] == TIER_ICI


class TestTopoPricing:
    def test_event_buckets_by_tier(self):
        t_ici, wire_i, dcn_i = price_event_topo(
            _ev(("model",)), PROFILE, SIZES, TOPO
        )
        t_dcn, wire_d, dcn_d = price_event_topo(
            _ev(("data",)), PROFILE, SIZES, TOPO
        )
        assert not dcn_i and dcn_d
        # Same op, same bytes: the DCN tier must price strictly slower
        # (75µs vs 1µs α, 3.125 vs 45 GB/s β) even though its ring
        # moves FEWER bytes (n=2 vs n=4 ring factor).
        assert wire_d < wire_i
        assert t_dcn > t_ici

    def test_event_matches_tier_alpha_beta(self):
        ev = _ev(("data",), nbytes=1 << 20)
        t, wire, _ = price_event_topo(ev, PROFILE, SIZES, TOPO)
        alpha, beta = REFERENCE_LINKS[TIER_DCN]
        expect_wire = (1 << 20) * _ring_factor("all-reduce", 2)
        assert wire == pytest.approx(expect_wire)
        assert t == pytest.approx(alpha + expect_wire / beta)

    def test_untagged_axis_falls_back_flat(self):
        ev = _ev(("ghost",), nbytes=1 << 20)
        sizes = dict(SIZES, ghost=4)
        t, _, is_dcn = price_event_topo(ev, PROFILE, sizes, TOPO)
        assert not is_dcn
        assert t == pytest.approx(price_event(ev, PROFILE, sizes))

    def test_in_loop_trip_multiplies(self):
        once = price_event_topo(_ev(("data",)), PROFILE, SIZES, TOPO)
        looped = price_event_topo(
            _ev(("data",), in_loop=True, trip=8), PROFILE, SIZES, TOPO
        )
        assert looped[0] == pytest.approx(8 * once[0])
        assert looped[1] == pytest.approx(8 * once[1])

    def test_multiset_overlap_discount(self):
        events = [_ev(("data",)), _ev(("model",)), _ev(("model",), 1 << 18)]
        tp = price_multiset_topo(
            events, PROFILE, SIZES, topology=TOPO, overlap_ratio=0.75
        )
        # exposed = (1 − r) · serial; buckets partition the totals.
        assert tp.collective_s == pytest.approx(0.25 * tp.serial_s)
        assert tp.serial_s == pytest.approx(tp.ici_s + tp.dcn_s)
        assert tp.wire_bytes == pytest.approx(tp.ici_bytes + tp.dcn_bytes)
        assert tp.dcn_bytes > 0 and tp.ici_bytes > 0

    def test_multiset_none_ratio_bills_serial(self):
        events = [_ev(("data",))]
        tp = price_multiset_topo(events, PROFILE, SIZES, topology=TOPO)
        assert tp.overlap_ratio is None
        assert tp.collective_s == pytest.approx(tp.serial_s)
        # Out-of-range ratios clip instead of going negative.
        clipped = price_multiset_topo(
            events, PROFILE, SIZES, topology=TOPO, overlap_ratio=1.5
        )
        assert clipped.collective_s == 0.0

    def test_flat_path_unchanged_and_topo_delegates(self):
        events = [_ev(("data",)), _ev(("model",))]
        flat_s, flat_b, _ = price_multiset(events, PROFILE, SIZES)
        assert flat_s == pytest.approx(
            sum(price_event(e, PROFILE, SIZES) for e in events)
        )
        topo_s, topo_b, _ = price_multiset(
            events, PROFILE, SIZES, topology=TOPO, overlap_ratio=0.5
        )
        tp = price_multiset_topo(
            events, PROFILE, SIZES, topology=TOPO, overlap_ratio=0.5
        )
        assert (topo_s, topo_b) == (tp.collective_s, tp.wire_bytes)

    def test_memo_respects_topology_identity(self):
        # A re-tagged axis must never serve the other profile's price.
        ev = [_ev(("data",))]
        base = price_multiset_topo(ev, PROFILE, SIZES, topology=TOPO)
        flipped = reference_two_tier(
            ("data", "model"), (2, 4),
            tiers={"data": TIER_ICI, "model": TIER_DCN},
        )
        other = price_multiset_topo(ev, PROFILE, SIZES, topology=flipped)
        assert other.dcn_bytes == 0 and base.dcn_bytes > 0
        assert other.serial_s != pytest.approx(base.serial_s)


class _Dev:
    def __init__(self, id):
        self.id = id


class _Seg:
    def __init__(self, src, dst):
        self.src_device = src
        self.dst_device = dst


class TestSegmentTier:
    def test_cross_domain_is_dcn(self):
        assert segment_tier(_Seg(_Dev(0), _Dev(4)), TOPO) == TIER_DCN
        assert segment_tier(_Seg(_Dev(1), _Dev(3)), TOPO) == TIER_ICI

    def test_host_endpoint_classifies_ici(self):
        # Host staging is local to the device end's domain — charging
        # it DCN would double-count the explicit host hop.
        assert segment_tier(_Seg(object(), _Dev(5)), TOPO) == TIER_ICI
