"""Grouped-query attention (GQA/MQA) and rotary embeddings (RoPE).

Neither exists in the reference (its attention is full-MHA with no position
signal, `/root/reference/case6_attention.py:42-143`); they are
complete-framework additions. Oracles:

* GQA with num_kv_heads == num_heads is exactly MHA; k/v params and the
  decode KV cache shrink by the group factor; repeat_kv reproduces the dense
  result computed with explicitly repeated heads.
* RoPE is norm-preserving, identity at position 0, and relative: shifting
  q and k positions by the same offset leaves attention scores unchanged.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.attention import MultiHeadAttention, repeat_kv
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.ops.rope import apply_rope

B, S, M = 2, 16, 32
N, H = 4, 8


def _x(rng):
    return jnp.asarray(rng.standard_normal((B, S, M)).astype(np.float32))


class TestGQA:
    def test_kv_param_shapes_shrink(self, rng):
        model = MultiHeadAttention(features=M, num_heads=N, head_dim=H, num_kv_heads=2)
        params = model.init({"params": jax.random.key(0)}, _x(rng))["params"]
        import flax.linen as nn

        params = nn.meta.unbox(params)
        assert params["query"]["kernel"].shape == (M, N * H)
        assert params["key"]["kernel"].shape == (M, 2 * H)
        assert params["value"]["kernel"].shape == (M, 2 * H)
        assert params["out"]["kernel"].shape == (N * H, M)

    def test_full_kv_heads_is_mha(self, rng):
        """num_kv_heads=num_heads must be bit-identical to the default."""
        x = _x(rng)
        mha = MultiHeadAttention(features=M, num_heads=N, head_dim=H)
        gqa = MultiHeadAttention(features=M, num_heads=N, head_dim=H, num_kv_heads=N)
        p = mha.init({"params": jax.random.key(0)}, x)
        np.testing.assert_array_equal(
            np.asarray(mha.apply(p, x)), np.asarray(gqa.apply(p, x))
        )

    def test_repeat_kv_matches_manual_expansion(self, rng):
        kv = jnp.asarray(rng.standard_normal((B, S, 2, H)).astype(np.float32))
        out = repeat_kv(kv, N)
        assert out.shape == (B, S, N, H)
        # Head g of the expansion is kv head g // group.
        for g in range(N):
            np.testing.assert_array_equal(
                np.asarray(out[:, :, g]), np.asarray(kv[:, :, g // 2])
            )

    def test_mqa_runs_and_differs_from_mha(self, rng):
        x = _x(rng)
        mqa = MultiHeadAttention(features=M, num_heads=N, head_dim=H, num_kv_heads=1)
        p = mqa.init({"params": jax.random.key(0)}, x)
        y = mqa.apply(p, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()

    def test_bad_group_rejected(self, rng):
        model = MultiHeadAttention(features=M, num_heads=N, head_dim=H, num_kv_heads=3)
        with pytest.raises(ValueError, match="must divide"):
            model.init({"params": jax.random.key(0)}, _x(rng))

    def test_decode_cache_stores_kv_heads_only(self, rng):
        """The GQA win: the KV cache holds num_kv_heads, not num_heads."""
        model = MultiHeadAttention(
            features=M, num_heads=N, head_dim=H, num_kv_heads=2,
            causal=True, decode=True, max_decode_len=S,
        )
        variables = model.init({"params": jax.random.key(0)}, _x(rng))
        cache = variables["cache"]
        assert cache["cached_key"].shape == (B, S, 2, H)
        assert cache["cached_value"].shape == (B, S, 2, H)

    def test_gqa_decode_matches_train_forward(self, rng):
        """Chunked cached decode == one-shot causal forward (GQA + RoPE)."""
        x = _x(rng)
        kw = dict(features=M, num_heads=N, head_dim=H, num_kv_heads=2, rope=True)
        train = MultiHeadAttention(causal=True, **kw)
        p = train.init({"params": jax.random.key(0)}, x)["params"]
        full = train.apply({"params": p}, x)

        dec = MultiHeadAttention(causal=True, decode=True, max_decode_len=S, **kw)
        cache = None  # first mutable apply creates the zeroed caches
        outs = []
        for t in range(S):
            variables = {"params": p} if cache is None else {"params": p, "cache": cache}
            y, mut = dec.apply(variables, x[:, t : t + 1], mutable=["cache"])
            cache = mut["cache"]
            outs.append(y)
        stepwise = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(full), np.asarray(stepwise), atol=2e-5
        )


class TestRope:
    def test_identity_at_position_zero(self, rng):
        x = jnp.asarray(rng.standard_normal((B, 1, N, H)).astype(np.float32))
        y = apply_rope(x, jnp.arange(1))
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    def test_norm_preserving(self, rng):
        x = jnp.asarray(rng.standard_normal((B, S, N, H)).astype(np.float32))
        y = apply_rope(x, jnp.arange(S))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            rtol=1e-5,
        )

    def test_relative_position_invariance(self, rng):
        """<rope(q,i), rope(k,j)> depends only on i - j: shifting both by a
        constant offset leaves every q·k score unchanged."""
        q = jnp.asarray(rng.standard_normal((1, S, 1, H)).astype(np.float32))
        k = jnp.asarray(rng.standard_normal((1, S, 1, H)).astype(np.float32))

        def scores(offset):
            pos = jnp.arange(S) + offset
            qr, kr = apply_rope(q, pos), apply_rope(k, pos)
            return jnp.einsum("bqnh,bknh->bnqk", qr, kr)

        np.testing.assert_allclose(
            np.asarray(scores(0)), np.asarray(scores(7)), atol=1e-4
        )

    def test_odd_head_dim_rejected(self):
        with pytest.raises(ValueError, match="even"):
            apply_rope(jnp.zeros((1, 2, 1, 7)), jnp.arange(2))


class TestTransformerVariants:
    def test_gqa_rope_transformer_trains(self, rng):
        """End-to-end: GQA + RoPE config initializes and takes a step."""
        cfg = dataclasses.replace(CONFIG_TINY, num_kv_heads=2, rope=True)
        model = Transformer(cfg)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(2, 16)), jnp.int32
        )
        variables = model.init({"params": jax.random.key(0)}, tokens)
        import flax.linen as nn

        params = nn.meta.unbox(variables["params"])
        assert "pos_embed" not in params  # rope replaces the learned table
        logits = model.apply({"params": params}, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits, np.float32)).all()

    def test_rmsnorm_variant(self, rng):
        """norm='rmsnorm': scale-only norms, model runs; unknown kind raises."""
        cfg = dataclasses.replace(CONFIG_TINY, norm="rmsnorm")
        model = Transformer(cfg)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 16)), jnp.int32)
        import flax.linen as nn

        params = nn.meta.unbox(
            model.init({"params": jax.random.key(0)}, tokens)["params"]
        )
        assert "bias" not in params["block_0"]["ln_attn"]
        assert "scale" in params["ln_out"]
        y = model.apply({"params": params}, tokens)
        assert np.isfinite(np.asarray(y, np.float32)).all()

        bad = Transformer(dataclasses.replace(CONFIG_TINY, norm="batchnorm"))
        with pytest.raises(ValueError, match="unknown norm"):
            bad.init({"params": jax.random.key(0)}, tokens)

    def test_param_count_tracks_gqa(self):
        dense = CONFIG_TINY
        gqa = dataclasses.replace(CONFIG_TINY, num_kv_heads=1)
        saved_per_layer = 2 * dense.features * (dense.num_heads - 1) * dense.head_dim
        assert dense.param_count - gqa.param_count == dense.num_layers * saved_per_layer
