"""The GSPMD propagation simulator + roofline cost model (round 13).

Pins the three layers shardcheck's ``--explain`` pass and the bench
``shardflow`` block stand on:

* PROPAGATION — trace-only specs through dots (matched contracting →
  pending partial → all-reduce attributed to the CAUSING line;
  mismatched → reshard all-gather), transposes (spec permuted, zero
  events), scanned shard_map collectives (in-loop, trip-multiplied) and
  ``while_trip_hint`` for loops whose trip the trace can't see;
* RECONCILIATION — every actual collective must be claimed by a
  predicted event (exact, axis-wildcard, or the RS+AG split form);
  leftovers gate (``unexplained-collective``) while elided predictions
  only report — including against the REAL partitioner, where a
  deliberately mis-sharded weight is caught pre-compile at the exact
  source line in THIS file and the compiled HLO confirms it;
* PRICING — the roofline terms (thin-dot bucket at its own achieved
  rate, ring wire factors, loop trips), ``table_profile`` access, and
  the ``compare`` record the bench gate consumes.
"""

import inspect

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.analysis import costmodel
from learning_jax_sharding_tpu.analysis.contracts import Contract, contract_of
from learning_jax_sharding_tpu.analysis.shardflow import (
    CommEvent,
    ShardflowReport,
    Spec,
    reconcile,
    reconcile_findings,
    render_explanation,
    spec_of_sharding,
    trace_shardflow,
)

THIS_FILE = "test_shardflow.py"


def _put(mesh, x, *axes):
    return jax.device_put(x, NamedSharding(mesh, P(*axes)))


def _events(report):
    return [e for e in report.events if e.kind != "slice"]


def megatron_pair(x, w1, w2):
    h = jax.nn.relu(x @ w1)
    return h @ w2  # SECOND-DOT: partials materialize / reshard lands here


def _second_dot_tag():
    src, first = inspect.getsourcelines(megatron_pair)
    line = first + next(i for i, l in enumerate(src) if "SECOND-DOT" in l)
    return f"{THIS_FILE}:{line}"


class TestPropagation:
    B, D, H = 8, 16, 64

    def _operands(self, mesh, *, bad=False):
        x = _put(mesh, np.ones((self.B, self.D), np.float32), "x", None)
        w1 = _put(mesh, np.ones((self.D, self.H), np.float32), None, "y")
        w2 = _put(
            mesh, np.ones((self.H, self.D), np.float32),
            *((None, "y") if bad else ("y", None)),
        )
        return x, w1, w2

    def test_matched_contracting_predicts_all_reduce_at_causing_line(
        self, mesh24
    ):
        rep = trace_shardflow(
            "mm", megatron_pair, *self._operands(mesh24), mesh=mesh24
        )
        [ev] = _events(rep)
        assert ev.kind == "reduce"
        assert ev.realizations[0] == ("all-reduce", "y")
        assert ev.where.endswith(_second_dot_tag())
        # Per-device payload: the (B, D) f32 output, batch-sharded on x.
        assert ev.bytes == self.B * self.D * 4 // 2

    def test_mis_sharded_weight_predicts_gather_same_line(self, mesh24):
        rep = trace_shardflow(
            "mm_bad", megatron_pair, *self._operands(mesh24, bad=True),
            mesh=mesh24,
        )
        ops = {e.realizations[0][0] for e in _events(rep)}
        assert "all-gather" in ops and "all-reduce" not in ops
        assert any(
            e.where.endswith(_second_dot_tag()) for e in _events(rep)
        )

    def test_transpose_rewrites_spec_without_events(self, mesh24):
        x = _put(mesh24, np.ones((8, 16), np.float32), "x", "y")
        rep = trace_shardflow(
            "t", lambda a: jnp.transpose(a), x, mesh=mesh24
        )
        assert _events(rep) == []
        [out] = rep.out_specs
        assert out.dims == (("y",), ("x",))

    def test_flops_and_thin_bucket(self, mesh24):
        x = _put(mesh24, np.ones((4, 256), np.float32))
        w = _put(mesh24, np.ones((256, 256), np.float32))
        rep = trace_shardflow("thin", lambda a, b: a @ b, x, w, mesh=mesh24)
        assert rep.flops == pytest.approx(2 * 4 * 256 * 256)
        assert rep.flops_thin == pytest.approx(rep.flops)  # m=4 < 64: GEMV
        big = _put(mesh24, np.ones((128, 256), np.float32))
        rep2 = trace_shardflow("sq", lambda a, b: a @ b, big, w, mesh=mesh24)
        assert rep2.flops_thin == 0.0

    def test_scanned_explicit_collective_is_trip_multiplied(self, mesh24):
        def scanned(x):
            def body(c, _):
                return jax.lax.psum(c, "y"), None

            r, _ = jax.lax.scan(body, x, None, length=4)
            return r

        f = jax.shard_map(
            scanned, mesh=mesh24, in_specs=P(None, "y"),
            out_specs=P(None, "y"), check_vma=False,
        )
        x = _put(mesh24, np.ones((4, 16), np.float32), None, "y")
        # Wrapped in a plain lambda: shard_map objects expose the
        # UNMAPPED body via __wrapped__, which trace_shardflow prefers
        # (it is how it unwraps jax.jit).
        rep = trace_shardflow("scanned", lambda a: f(a), x, mesh=mesh24)
        evs = [e for e in _events(rep) if e.kind == "explicit"]
        assert evs and all(e.in_loop and e.trip == 4 for e in evs)

    def test_while_trip_hint_prices_opaque_loops(self, mesh24):
        # w rides into the while eqn as a body const WITH its spec (a
        # fully closed-over array would be a spec-less jaxpr constant).
        def loop(x, w):
            def body(c):
                i, v = c
                return i + 1, jax.nn.relu(v @ w)

            def cond(c):
                return c[0] < 3

            return jax.lax.while_loop(cond, body, (0, x))[1]

        x = _put(mesh24, np.ones((8, 16), np.float32), None, "y")
        w = _put(mesh24, np.ones((16, 16), np.float32), "y", None)
        rep = trace_shardflow(
            "loop", loop, x, w, mesh=mesh24, while_trip_hint=7
        )
        evs = [e for e in _events(rep) if e.in_loop]
        assert evs and all(e.trip == 7 for e in evs)

    def test_spec_of_sharding_and_helpers(self, mesh24):
        s = spec_of_sharding(NamedSharding(mesh24, P(("x", "y"), None)), 2)
        assert s.dims == (("x", "y"), ())
        assert s.sharded_axes() == {"x", "y"}
        assert s.shard_factor({"x": 2, "y": 4}) == 8
        assert Spec.replicated(2).dims == ((), ())


def _report(events, *, flops=0.0, thin=0.0, hbm=0.0):
    return ShardflowReport(
        name="r", mesh_axes=["x", "y"], mesh_shape=[2, 4],
        events=events, flops=flops, hbm_bytes=hbm, flops_thin=thin,
    )


def _ar_event(**kw):
    base = dict(
        kind="reduce", axes=("y",), bytes=1_000_000, where="f.py:1",
        primitive="dot_general", reason="partial",
        realizations=(("all-reduce", "y"),),
    )
    base.update(kw)
    return CommEvent(**base)


def _contract(collectives):
    return Contract(
        name="r", mesh_shape=[2, 4], mesh_axes=["x", "y"],
        collectives={
            k: {"count": n, "max_bytes": 1} for k, n in collectives.items()
        },
        while_collectives=0, max_constant_bytes=0,
    )


class TestReconcile:
    def test_exact_claim(self):
        rec = reconcile(_report([_ar_event()]), _contract({"all-reduce@y": 1}))
        assert rec["matched"] == 1
        assert rec["unexplained"] == {} and rec["elided"] == {}

    def test_wildcard_axis_claim(self):
        rec = reconcile(
            _report([_ar_event()]), _contract({"all-reduce@unattributed": 1})
        )
        assert rec["unexplained"] == {}

    def test_rs_ag_split_claimed_by_one_reduce(self):
        rec = reconcile(
            _report([_ar_event(realizations=(
                ("all-reduce", "y"), ("reduce-scatter", "y"),
            ))]),
            _contract({"reduce-scatter@y": 1, "all-gather@y": 1}),
        )
        assert rec["unexplained"] == {}

    def test_leftover_actual_gates(self):
        rec = reconcile(_report([]), _contract({"all-to-all@x": 2}))
        assert rec["unexplained"] == {"all-to-all@x": 2}
        [f] = reconcile_findings(rec)
        assert f.rule == "unexplained-collective"
        assert f.data["unexplained"] == 2

    def test_leftover_prediction_is_elided_not_gated(self):
        rec = reconcile(_report([_ar_event()]), _contract({}))
        assert rec["elided"] == {"all-reduce@y": 1}
        assert reconcile_findings(rec) == []

    def test_slice_events_are_free(self):
        ev = _ar_event(kind="slice", realizations=(("slice", "y"),))
        rec = reconcile(_report([ev]), _contract({}))
        assert rec["elided"] == {} and rec["unexplained"] == {}

    def test_against_real_partitioner_both_layouts(self, mesh24):
        """case24's micro demo, held in CI: trace-only predictions for
        the correctly- and the mis-sharded layout BOTH reconcile with
        zero unexplained against the compiled HLO, and the bad layout's
        compiled contract really does grow the predicted all-gather."""
        t = TestPropagation()
        for bad in (False, True):
            args = t._operands(mesh24, bad=bad)
            rep = trace_shardflow("mm", megatron_pair, *args, mesh=mesh24)
            con = contract_of(
                "mm", jax.jit(megatron_pair), *args, mesh=mesh24
            )
            rec = reconcile(rep, con)
            assert rec["unexplained"] == {}, (bad, rec)
            grouped = {k.split("@")[0] for k in con.collectives}
            assert ("all-gather" in grouped) == bad, (bad, con.collectives)

    def test_render_explanation_names_lines(self):
        text = render_explanation(_report([_ar_event()]))
        assert "f.py:1" in text and "all-reduce@y" in text


class TestCostModel:
    PROFILE = costmodel.Profile(
        "test", peak_flops=1e12, hbm_bw=1e12, link_bw=1e11,
        mfu_eff=0.5, mbu_eff=0.5, thin_flops=1e10,
    )

    def test_roofline_terms(self):
        cost = costmodel.price(
            _report([_ar_event()], flops=1e9, hbm=1e6), self.PROFILE
        )
        # compute: (1e9/8 dev) / (1e12 * 0.5);  memory: 1e6 / (1e12 * 0.5)
        assert cost.compute_s == pytest.approx(2.5e-4)
        assert cost.memory_s == pytest.approx(2e-6)
        # wire: 1 MB * ring 2(n-1)/n on y (n=4) / 1e11
        assert cost.collective_s == pytest.approx(1.5e-5)
        assert cost.bound == "compute"
        assert cost.predicted_s == cost.compute_s

    def test_thin_flops_priced_at_thin_rate(self):
        dense = costmodel.price(_report([], flops=1e9), self.PROFILE)
        thin = costmodel.price(
            _report([], flops=1e9, thin=1e9), self.PROFILE
        )
        # 1e10 thin rate vs 5e11 effective dense rate: 50x slower.
        assert thin.compute_s == pytest.approx(dense.compute_s * 50)

    def test_loop_events_multiply_trip(self):
        sizes = {"x": 2, "y": 4}
        once = costmodel.price_event(_ar_event(), self.PROFILE, sizes)
        looped = costmodel.price_event(
            _ar_event(in_loop=True, trip=5), self.PROFILE, sizes
        )
        assert looped == pytest.approx(once * 5)

    def test_ring_factors(self):
        n = 4
        assert costmodel._ring_factor("all-reduce", n) == pytest.approx(1.5)
        assert costmodel._ring_factor("all-gather", n) == pytest.approx(0.75)
        assert costmodel._ring_factor("slice", n) == 0.0
        assert costmodel._ring_factor("all-reduce", 1) == 0.0

    def test_compare_record(self):
        rec = costmodel.compare(0.9e-3, 1.0e-3)
        assert rec["predicted_ms"] == pytest.approx(0.9)
        assert rec["measured_ms"] == pytest.approx(1.0)
        assert rec["err_pct"] == pytest.approx(10.0)
        assert rec["signed_err_pct"] == pytest.approx(-10.0)

    def test_table_profile_access(self):
        p = costmodel.table_profile("TPU v5 lite")
        assert p.link_bw == pytest.approx(45e9)
        assert p.mfu_eff == pytest.approx(0.50)
        with pytest.raises(KeyError):
            costmodel.table_profile("Abacus 9000")

    def test_predicted_mfu_is_per_chip(self):
        cost = costmodel.price(_report([], flops=4e9), self.PROFILE)
        # compute-bound at 50% effective rate: per-chip MFU is exactly
        # the efficiency factor, regardless of device count (n_dev=8).
        assert cost.n_dev == 8
        assert cost.predicted_mfu == pytest.approx(0.5)


class TestPriceMultiset:
    """The round-17 batch pricing API the layout search's inner loop
    rides: term-exact against per-event ``price_event``, memoized per
    (profile, mesh, realization, axes, bytes, trip), and abortable
    mid-sum for dominance pruning."""

    PROFILE = TestCostModel.PROFILE
    SIZES = {"x": 2, "y": 4}

    def test_term_exact_vs_price_event(self):
        events = [
            _ar_event(),
            _ar_event(bytes=3_000_000, realizations=(("all-gather", "y"),)),
            _ar_event(axes=("x",), realizations=(("all-reduce", "x"),),
                      in_loop=True, trip=7),
        ]
        total, wire, aborted = costmodel.price_multiset(
            events, self.PROFILE, self.SIZES
        )
        exact = sum(
            costmodel.price_event(e, self.PROFILE, self.SIZES)
            for e in events
        )
        assert not aborted
        assert total == pytest.approx(exact, rel=0, abs=0)  # term-exact
        assert wire == pytest.approx(total * self.PROFILE.link_bw)

    def test_price_goes_through_multiset(self):
        events = [_ar_event(), _ar_event(in_loop=True, trip=3)]
        cost = costmodel.price(_report(events), self.PROFILE)
        total, _, _ = costmodel.price_multiset(
            events, self.PROFILE, self.SIZES
        )
        assert cost.collective_s == pytest.approx(total, rel=0, abs=0)

    def test_memoizes_repeated_terms(self, monkeypatch):
        calls = {"n": 0}
        real = costmodel._ring_factor

        def counting(op, n):
            calls["n"] += 1
            return real(op, n)

        monkeypatch.setattr(costmodel, "_ring_factor", counting)
        costmodel._MULTISET_MEMO.clear()
        events = [_ar_event() for _ in range(50)]
        costmodel.price_multiset(events, self.PROFILE, self.SIZES)
        first = calls["n"]
        assert first <= len(_ar_event().realizations) * 2  # priced once
        costmodel.price_multiset(events, self.PROFILE, self.SIZES)
        assert calls["n"] == first  # second batch fully memoized

    def test_abort_above_cuts_mid_sum(self):
        one = costmodel.price_event(_ar_event(), self.PROFILE, self.SIZES)
        events = [_ar_event() for _ in range(10)]
        total, _, aborted = costmodel.price_multiset(
            events, self.PROFILE, self.SIZES, abort_above=2.5 * one
        )
        assert aborted
        # Cut as soon as the partial sum crossed the incumbent: three
        # terms in, not ten.
        assert total == pytest.approx(3 * one)

    def test_abort_above_not_triggered_at_exact_total(self):
        one = costmodel.price_event(_ar_event(), self.PROFILE, self.SIZES)
        total, _, aborted = costmodel.price_multiset(
            [_ar_event()] * 4, self.PROFILE, self.SIZES,
            abort_above=4 * one + 1e-18
        )
        assert not aborted
        assert total == pytest.approx(4 * one)

    def test_loop_trip_keys_separately(self):
        once, _, _ = costmodel.price_multiset(
            [_ar_event()], self.PROFILE, self.SIZES
        )
        looped, _, _ = costmodel.price_multiset(
            [_ar_event(in_loop=True, trip=5)], self.PROFILE, self.SIZES
        )
        assert looped == pytest.approx(once * 5)
