"""Checkpoint/resume: sharded save + restore of the TrainState.

The subsystem the reference lacks (SURVEY.md §5 "Checkpoint / resume" — its
TrainState lives only in memory, `/root/reference/case6_attention.py:171-178`).
Oracle: a resumed run must continue from exactly the trained weights, with
every restored leaf carrying the same sharding it was saved with.
"""

import jax
import numpy as np
import optax

from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer, next_token_loss
from learning_jax_sharding_tpu.parallel import mesh_sharding, put, shard_shapes
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.checkpoint import CheckpointManager, as_abstract
from learning_jax_sharding_tpu.training.pipeline import make_train_step, sharded_train_state


def _setup(mesh, seed=0):
    cfg = CONFIG_TINY
    model = Transformer(cfg)
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-4), batch["inputs"], {"params": jax.random.key(0)},
        mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh, RULES_DP_TP,
        loss_fn=next_token_loss, donate_state=False,
    )
    return batch, state, step


class TestCheckpoint:
    def test_roundtrip_preserves_values_and_shardings(self, mesh22, tmp_path):
        batch, state, step = _setup(mesh22)
        for _ in range(3):
            state, _ = step(state, batch)

        with CheckpointManager(tmp_path / "ckpt") as ckpt:
            assert ckpt.save(3, state)
            ckpt.wait()
            _, fresh, _ = _setup(mesh22)
            restored = ckpt.restore(3, like=fresh)

        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state.params, restored.params,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state.opt_state, restored.opt_state,
        )
        assert int(restored.step) == 3
        # Restored leaves are born sharded: per-device shard shapes match.
        assert jax.tree.map(shard_shapes, state.params) == jax.tree.map(
            shard_shapes, restored.params
        )

    def test_resume_continues_identically(self, mesh22, tmp_path):
        """train 2 + save + train 2 more == restore + train 2 more."""
        batch, state, step = _setup(mesh22)
        for _ in range(2):
            state, _ = step(state, batch)

        with CheckpointManager(tmp_path / "ckpt") as ckpt:
            ckpt.save(2, state)
            ckpt.wait()

            cont = state
            cont_losses = []
            for _ in range(2):
                cont, loss = step(cont, batch)
                cont_losses.append(float(loss))

            # A resuming process rebuilds model/optimizer/step from scratch
            # (its TrainState metadata — apply_fn/tx closures — is its own),
            # then overwrites the fresh state from disk.
            batch2, fresh, step2 = _setup(mesh22)
            resumed = ckpt.restore_latest(like=fresh)
        res_losses = []
        for _ in range(2):
            resumed, loss = step2(resumed, batch2)
            res_losses.append(float(loss))
        np.testing.assert_allclose(cont_losses, res_losses, rtol=1e-6)

    def test_retention_and_latest(self, mesh22, tmp_path):
        batch, state, step = _setup(mesh22)
        with CheckpointManager(tmp_path / "ckpt", max_to_keep=2) as ckpt:
            for s in (1, 2, 3):
                state, _ = step(state, batch)
                ckpt.save(s, state)
            ckpt.wait()
            assert ckpt.latest_step() == 3
            assert ckpt.all_steps() == [2, 3]

    def test_save_interval_skips(self, mesh22, tmp_path):
        _, state, _ = _setup(mesh22)
        with CheckpointManager(tmp_path / "ckpt", save_interval_steps=5) as ckpt:
            assert ckpt.save(0, state)       # step 0 is on the interval
            assert not ckpt.save(3, state)   # skipped
            assert ckpt.save(3, state, force=True)
            ckpt.wait()
            assert ckpt.all_steps() == [0, 3]

    def test_restore_latest_empty_dir_returns_none(self, mesh22, tmp_path):
        _, state, _ = _setup(mesh22)
        with CheckpointManager(tmp_path / "empty") as ckpt:
            assert ckpt.restore_latest(like=as_abstract(state)) is None

    def test_restore_params_for_serving_lands_in_dst_layout(
        self, mesh22, tmp_path
    ):
        """The deploy half of the hot-swap: a trained checkpoint's
        params restore + reshard into the requested serving layout in
        one motion — values bit-identical, every leaf under its
        destination sharding, empty-directory contract preserved."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from learning_jax_sharding_tpu.training.checkpoint import (
            restore_params_for_serving,
        )

        batch, state, step = _setup(mesh22)
        state, _ = step(state, batch)
        dst = jax.tree.map(
            lambda x: NamedSharding(mesh22, P()), state.params
        )
        with CheckpointManager(tmp_path / "ckpt") as ckpt:
            ckpt.save(1, state)
            ckpt.wait()
            _, fresh, _ = _setup(mesh22)
            out = restore_params_for_serving(
                ckpt, like=fresh, dst_shardings=dst
            )
            assert out is not None
            staged, stats = out
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            state.params, staged,
        )
        for leaf, d in zip(jax.tree.leaves(staged), jax.tree.leaves(dst)):
            assert leaf.sharding == d
        assert stats["mode"] in ("device", "host") and stats["bytes"] > 0
        _, fresh, _ = _setup(mesh22)
        with CheckpointManager(tmp_path / "empty") as ckpt:
            assert restore_params_for_serving(
                ckpt, like=as_abstract(fresh), dst_shardings=dst
            ) is None

    def test_corrupted_latest_falls_back_to_previous(self, mesh22, tmp_path):
        """A truncated newest checkpoint (a preemption mid-write, bit
        rot) must not kill the resume: restore_latest FALLS BACK to the
        previous retained step — that is what retention exists for —
        and records the corrupt/fallback trail in the flight recorder.
        strict=True keeps the old fail-fast contract."""
        import pytest

        from learning_jax_sharding_tpu.robustness.chaos import (
            corrupt_latest_checkpoint,
        )
        from learning_jax_sharding_tpu.telemetry.flight_recorder import (
            FlightRecorder,
        )

        batch, state, step = _setup(mesh22)
        rec = FlightRecorder()
        with CheckpointManager(tmp_path / "ckpt", recorder=rec) as ckpt:
            ckpt.save(1, state)
            stepped, _ = step(state, batch)
            ckpt.save(2, stepped)
            ckpt.wait()
            assert corrupt_latest_checkpoint(tmp_path / "ckpt") == 2
            restored = ckpt.restore_latest(like=state)
            # The fallback restored checkpoint step 1 — the PRE-step
            # state's content, not step 2's.
            assert int(restored.step) == int(state.step)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)
                ),
                state.params, restored.params,
            )
            assert [e["step"] for e in rec.events("checkpoint.corrupt")] == [2]
            assert rec.events("checkpoint.fallback")
            with pytest.raises(Exception):
                ckpt.restore_latest(like=state, strict=True)


class TestCrossMeshRestore:
    def test_restore_onto_a_different_mesh(self, mesh22, tmp_path):
        """Elastic resharding: save under a 2×2 mesh, restore under 4×2 —
        values identical, every leaf resharded to the NEW mesh's layout
        (what lets a run resume after the slice size changes). Through
        ``restore_latest``: the PREEMPTION-RESUME entry point (a
        preempted run often comes back on a different slice shape)."""
        from learning_jax_sharding_tpu.parallel import build_mesh

        _, state, _ = _setup(mesh22)
        mesh42 = build_mesh((4, 2), ("data", "model"))
        with CheckpointManager(tmp_path) as ckpt:
            assert ckpt.save(1, state, force=True)
            ckpt.wait()

            # Rebuild the abstract target under the new mesh, then restore.
            _, new_state, _ = _setup(mesh42)
            restored = ckpt.restore_latest(like=new_state)

        old_kernel = state.params["block_0"]["attn"]["query"]["kernel"]
        new_kernel = restored.params["block_0"]["attn"]["query"]["kernel"]
        np.testing.assert_array_equal(
            np.asarray(old_kernel, np.float32), np.asarray(new_kernel, np.float32)
        )
        assert dict(new_kernel.sharding.mesh.shape) == {"data": 4, "model": 2}
        # Restored leaf carries exactly the layout the NEW mesh's pipeline
        # assigned (same spec as a fresh init under that mesh).
        target_kernel = new_state.params["block_0"]["attn"]["query"]["kernel"]
        assert new_kernel.sharding.spec == target_kernel.sharding.spec
        assert shard_shapes(new_kernel) == shard_shapes(target_kernel)
