"""Unified telemetry subsystem (telemetry/): spans, registry, compile
watch — plus the engine/training integrations and the case18 smoke.

The pinned claims: Chrome-trace output is structurally valid (Perfetto
semantics: complete events nest by containment, async pairs match by
id), Prometheus exposition parses, registry-backed engine stats keep the
pre-telemetry contract, and compile accounting observes real compiles
and real recompiles.
"""

import dataclasses
import json
import math
import re
import runpy
import sys
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.telemetry import (
    CompileWatch,
    MetricsRegistry,
    Tracer,
    executable_report,
    watched,
)


class TestTracer:
    def test_nested_spans_nest_by_containment(self):
        t = Tracer()
        with t.span("outer", phase="demo"):
            time.sleep(0.002)
            with t.span("inner"):
                time.sleep(0.002)
        evs = {e["name"]: e for e in t.events}
        outer, inner = evs["outer"], evs["inner"]
        assert outer["ph"] == inner["ph"] == "X"
        # Perfetto infers nesting from interval containment per tid.
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
        assert inner["args"]["parent"] == "outer"
        assert outer["args"]["phase"] == "demo"

    def test_async_pairs_and_instants(self):
        t = Tracer()
        t.async_begin("request", 5, prompt_len=7)
        t.instant("request.first_token", rid=5)
        t.async_end("request", 5)
        phases = [e["ph"] for e in t.events]
        assert phases == ["b", "i", "e"]
        b, i, e = t.events
        assert b["id"] == e["id"] == 5 and b["cat"] == "request"
        assert i["s"] == "t" and i["args"]["rid"] == 5

    def test_chrome_trace_and_jsonl_roundtrip(self, tmp_path):
        t = Tracer()
        with t.span("s"):
            pass
        t.dump_chrome_trace(tmp_path / "trace.json")
        t.dump_jsonl(tmp_path / "trace.jsonl")
        ct = json.loads((tmp_path / "trace.json").read_text())
        assert ct["traceEvents"] and ct["displayTimeUnit"] == "ms"
        lines = (tmp_path / "trace.jsonl").read_text().splitlines()
        assert [json.loads(l)["name"] for l in lines] == ["s"]

    def test_sync_is_honest_and_recorded(self):
        t = Tracer()
        out = jax.jit(lambda x: x * 2)(jnp.ones((8,)))
        t.sync(out)
        (ev,) = t.events
        assert ev["name"] == "device_sync" and ev["ph"] == "X"

    def test_bounded_ring_keeps_newest_and_counts_drops(self):
        t = Tracer(max_events=3)
        for i in range(5):
            t.instant(f"e{i}")
        assert [e["name"] for e in t.events] == ["e2", "e3", "e4"]
        assert t.dropped == 2

    def test_disabled_tracer_records_nothing(self):
        t = Tracer(enabled=False)
        with t.span("s"):
            t.instant("i")
        assert t.events == []


class TestRegistry:
    def test_counter_gauge_histogram(self):
        r = MetricsRegistry()
        c = r.counter("reqs_total")
        c.inc()
        c.inc(2)
        assert c.value == 3
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)
        g = r.gauge("depth")
        g.set(5)
        g.set(2)
        assert g.value == 2 and g.high_water == 5
        g.reset_high_water()
        assert g.high_water == 2
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(9.0)
        assert h.count == 3 and h.sum == pytest.approx(9.55)
        assert h.cumulative() == [(0.1, 1), (1.0, 2), (math.inf, 3)]

    def test_get_or_create_and_kind_conflict(self):
        r = MetricsRegistry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("x")
        r.histogram("h", buckets=(1.0,))
        with pytest.raises(ValueError, match="different"):
            r.histogram("h", buckets=(2.0,))

    def test_prometheus_text_parses(self):
        r = MetricsRegistry()
        r.counter("a_total", "things").inc(7)
        r.gauge("b").set(1.5)
        h = r.histogram("c_seconds", buckets=(0.5,))
        h.observe(0.2)
        text = r.prometheus_text()
        # Exposition-format shape: every sample line is `name{labels} value`.
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le="[^"]+"\})? [0-9.+eEInf-]+$'
        )
        for line in text.strip().splitlines():
            assert line.startswith("#") or sample.match(line), line
        assert "# TYPE a_total counter" in text
        assert "a_total 7" in text
        assert "# HELP a_total things" in text
        assert 'c_seconds_bucket{le="+Inf"} 1' in text
        assert "c_seconds_count 1" in text

    def test_snapshot_is_json_able(self):
        r = MetricsRegistry()
        r.counter("a").inc()
        r.gauge("g").set(2)
        r.histogram("h", buckets=(1.0,)).observe(3.0)
        snap = json.loads(json.dumps(r.snapshot()))
        assert snap["a"] == 1 and snap["g"] == 2
        assert snap["g__high_water"] == 2
        assert snap["h"]["count"] == 1


class TestCompileWatch:
    def test_counts_compiles_inside_watch_only(self):
        w = CompileWatch()
        with w:
            jax.jit(lambda x: x * 3 + 1)(jnp.ones((5,)))
        seen = w.backend_compiles
        assert seen >= 1
        assert w.backend_compile_seconds > 0
        jax.jit(lambda x: x * 5 - 2)(jnp.ones((5,)))   # outside: not counted
        assert w.backend_compiles == seen
        rep = w.report()
        assert rep["monitoring_available"]
        assert rep["traces"] >= 1 and rep["trace_seconds"] > 0

    def test_registry_mirror(self):
        r = MetricsRegistry()
        with CompileWatch(registry=r):
            jax.jit(lambda x: x - 7)(jnp.ones((3,)))
        assert r.counter("compile_backend_compile_total").value >= 1
        assert r.counter("compile_backend_compile_seconds_total").value > 0

    def test_watched_function_flags_recompiling_calls(self):
        f = watched(jax.jit(lambda x: x + 1), "plus1")
        f(jnp.ones((2,)))
        f(jnp.ones((2,)))
        f(jnp.ones((4,)))   # new shape: recompile
        s = f.stats()
        assert s["calls"] == 3 and s["compiles"] == 2
        assert s["compile_calls"] == [1, 3]

    def test_executable_report_flops_memory_collectives(self):
        rep = executable_report(
            lambda a, b: a @ b, jnp.ones((32, 64)), jnp.ones((64, 16))
        )
        assert rep["flops"] == pytest.approx(2 * 32 * 64 * 16, rel=1)
        assert rep["memory"]["output_bytes"] == 32 * 16 * 4
        assert set(rep["collectives"]) == {
            "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
            "collective-permute",
        }
        assert sum(rep["collectives"].values()) == 0   # single device

    def test_executable_report_sees_sharded_collectives(self, mesh24, rng):
        from functools import partial

        from learning_jax_sharding_tpu.parallel.collectives import (
            psum_matmul,
        )
        from tests.conftest import matmul_operands

        a, b = matmul_operands(rng)
        rep = executable_report(
            partial(psum_matmul, mesh=mesh24, axis="y"), a, b
        )
        assert rep["collectives"]["all-reduce"] >= 1


class TestEngineTelemetry:
    """The serving engine metered through the registry/tracer: the
    pinned ``last_stats``/``last_latency`` contract is now a window over
    cumulative metrics, and the per-request timeline is exported."""

    @pytest.fixture(scope="class")
    def served(self, mesh22):
        from learning_jax_sharding_tpu.models.serving import (
            ContinuousEngine,
        )
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY, Transformer,
        )
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

        cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
        rng = np.random.default_rng(31)
        model = Transformer(cfg)
        params = nn.meta.unbox(
            jax.jit(lambda r, t: model.init({"params": r}, t))(
                jax.random.key(3), np.zeros((2, 8), np.int32)
            )["params"]
        )
        prompts = [
            rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
            for n in (3, 9, 5)
        ]
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=4,
        )
        outs = eng.serve(params, prompts)
        return eng, prompts, outs

    def test_counters_back_last_stats_window(self, served):
        eng, prompts, outs = served
        snap = eng.registry.snapshot()
        assert snap["engine_requests_total"] == len(prompts)
        assert snap["engine_requests_finished_total"] == len(prompts)
        assert snap["engine_tokens_generated_total"] == sum(
            len(o) - len(p) for o, p in zip(outs, prompts)
        )
        assert snap["engine_cache_creations_total"] == eng.cache_creations
        # The split last_latency reports is the counter-window delta.
        lat = eng.last_latency
        assert lat["refill_s"] == pytest.approx(
            snap["engine_refill_seconds_total"]
        )
        assert lat["decode_s"] == pytest.approx(
            snap["engine_decode_seconds_total"]
        )
        # Same observations landed in the export histograms.
        assert snap["engine_ttft_seconds"]["count"] == len(prompts)
        assert snap["engine_e2e_seconds"]["count"] == len(prompts)

    def test_request_timeline_events(self, served):
        eng, prompts, _ = served
        evs = eng.tracer.events
        names = [e["name"] for e in evs]
        for needed in ("request.arrival", "request.admit",
                       "request.first_token", "engine.serve"):
            assert needed in names, needed
        begins = {e["id"] for e in evs
                  if e["ph"] == "b" and e["name"] == "request"}
        ends = {e["id"] for e in evs
                if e["ph"] == "e" and e["name"] == "request"}
        assert begins == ends == set(range(len(prompts)))
        # Dispatch spans carry the host-observed durations.
        assert any(e["name"] == "engine.refill" for e in evs)
        assert any(e["name"] == "engine.decode" for e in evs)

    def test_prometheus_export_has_engine_series(self, served):
        eng, _, _ = served
        text = eng.registry.prometheus_text()
        assert "# TYPE engine_requests_total counter" in text
        assert "# TYPE engine_queue_depth gauge" in text
        assert "# TYPE engine_ttft_seconds histogram" in text

    def test_compile_counts_exposed(self, served):
        eng, _, _ = served
        counts = eng.compile_counts()
        assert set(counts) == {
            "first_refill", "refill_step", "decode_block",
        }
        assert all(v and v <= 2 for v in counts.values()), counts

    def test_window_semantics_across_serves(self, served, mesh22):
        """A second serve() resets the WINDOW, not the counters: the
        cumulative registry keeps growing while last_stats stays
        per-call (the re-derivation contract) — and the warm call
        compiles nothing new."""
        eng, prompts, _ = served
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY, Transformer,
        )

        cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
        params = nn.meta.unbox(
            jax.jit(
                lambda r, t: Transformer(cfg).init({"params": r}, t)
            )(jax.random.key(3), np.zeros((2, 8), np.int32))["params"]
        )
        total_before = eng.registry.snapshot()[
            "engine_requests_finished_total"
        ]
        compiles_before = eng.compile_counts()
        eng.serve(params, prompts[:1])
        snap = eng.registry.snapshot()
        assert snap["engine_requests_finished_total"] == total_before + 1
        assert eng.last_latency["requests"] == 1   # window, not lifetime
        assert eng.compile_counts() == compiles_before


class TestTrainingTelemetry:
    def test_metrics_logger_mirrors_into_registry(self):
        from learning_jax_sharding_tpu.utils import MetricsLogger

        r = MetricsRegistry()
        with MetricsLogger(stream=None, tokens_per_step=64,
                           registry=r) as m:
            for s in range(3):
                m.log(s, loss=2.0 - s)
        snap = r.snapshot()
        assert snap["train_steps_total"] == 3
        assert snap["train_loss"] == 0.0           # latest
        assert snap["train_seconds_per_step"] > 0
        assert snap["train_tokens_per_second"] > 0
        assert snap["train_step_seconds"]["count"] == 2


class TestCase18Smoke:
    """CI smoke for the observability driver: run
    cases/case18_observability.py on the emulated 8-device mesh (the
    conftest already forced it — the case's own force is then a no-op)
    and assert the three artifacts parse and carry the expected keys."""

    def test_case18_artifacts(self, tmp_path):
        import pathlib

        repo = pathlib.Path(__file__).resolve().parents[1]
        argv = sys.argv
        path = sys.path[:]
        sys.argv = ["case18_observability.py", str(tmp_path)]
        sys.path.insert(0, str(repo / "cases"))
        try:
            runpy.run_path(
                str(repo / "cases" / "case18_observability.py"),
                run_name="__main__",
            )
        finally:
            sys.argv = argv
            sys.path[:] = path

        trace = json.loads((tmp_path / "trace.json").read_text())
        assert trace["traceEvents"], "empty trace"
        for ev in trace["traceEvents"]:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)

        prom = (tmp_path / "metrics.prom").read_text()
        assert "# TYPE engine_requests_finished_total counter" in prom
        assert "# TYPE engine_ttft_seconds histogram" in prom
        assert 'engine_ttft_seconds_bucket{le="+Inf"}' in prom

        report = json.loads((tmp_path / "report.json").read_text())
        for key in (
            "ttft_p50", "ttft_p99", "tpot_p50", "page_pool", "compile",
            "collectives_per_step", "requests",
        ):
            assert key in report, key
        assert report["ttft_p50"] > 0
        assert report["page_pool"]["high_water"] >= 1
        assert report["compile"]["per_program_compiles"]["refill_step"]
        decode = report["collectives_per_step"]["decode_block"]
        assert set(decode) == {
            "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
            "collective-permute",
        }
        assert sum(decode.values()) > 0    # TP decode puts ops on the wire
