"""Comm observatory units (round 19, ``telemetry/commscope.py``).

Pure algebra pinned exactly — the α–β fit on noiseless synthetic
timings, profile JSON round-trip + version gating, the proportional
measured-seconds attribution, and the overlap decomposition's
sums-back-to-device invariant (both standalone and through the goodput
ledger) — plus the costmodel's calibrated-axis pricing path with its
pinned-table fallback, and one small REAL ladder integration on the
emulated mesh (feasible since the ladder syncs every call)."""

import json

import pytest

from learning_jax_sharding_tpu.analysis import costmodel
from learning_jax_sharding_tpu.analysis.shardflow import (
    CommEvent,
    ShardflowReport,
)
from learning_jax_sharding_tpu.telemetry import commscope
from learning_jax_sharding_tpu.telemetry.commscope import (
    AxisProfile,
    CommProfile,
    attribute_measured_seconds,
    decompose_overlap,
    fit_alpha_beta,
    fit_axis_profiles,
    fit_errors,
    wire_bytes,
)
from learning_jax_sharding_tpu.telemetry.ledger import GoodputLedger
from learning_jax_sharding_tpu.telemetry.registry import MetricsRegistry


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, s):
        self.t += s


# --- wire volumes and the α–β fit -----------------------------------------


class TestFit:
    def test_wire_bytes_ring_volumes(self):
        b = 1024.0
        assert wire_bytes("psum", 4, b) == pytest.approx(2 * b * 3 / 4)
        assert wire_bytes("all_gather", 4, b) == pytest.approx(3 * b)
        assert wire_bytes("reduce_scatter", 4, b) == pytest.approx(b * 3 / 4)
        assert wire_bytes("ppermute", 4, b) == pytest.approx(b)
        # a 1-device axis runs no collective at all
        for op in commscope.LADDER_OPS:
            assert wire_bytes(op, 1, b) == 0.0
        with pytest.raises(ValueError):
            wire_bytes("all_to_nowhere", 4, b)

    def test_fit_recovers_exact_alpha_beta(self):
        """Noiseless t = α + w/β must round-trip through the fit."""
        alpha, beta = 5e-6, 2.5e9
        pts = [(w, alpha + w / beta)
               for w in (1e4, 1e5, 1e6, 1e7)]
        a, b, r2 = fit_alpha_beta(pts)
        assert a == pytest.approx(alpha, rel=1e-9)
        assert b == pytest.approx(beta, rel=1e-9)
        assert r2 == pytest.approx(1.0)

    def test_fit_clamps_negative_intercept_to_zero(self):
        # bandwidth-only data with a negative LSQ intercept: α clamps,
        # β stays positive
        pts = [(1e6, 1e-4), (2e6, 3e-4)]
        a, b, _ = fit_alpha_beta(pts)
        assert a == 0.0
        assert b > 0
        with pytest.raises(ValueError):
            fit_alpha_beta([(1e6, 1e-4)])       # one point can't fit

    def test_fit_axis_profiles_pools_ops_per_axis(self):
        alpha, beta = 2e-6, 1e9
        ms = []
        for op in ("psum", "all_gather"):
            for b in (1 << 16, 1 << 20):
                w = wire_bytes(op, 4, float(b))
                ms.append({"op": op, "axis": "model", "n": 4,
                           "bytes": float(b), "wire_bytes": w,
                           "seconds": alpha + w / beta})
        profs = fit_axis_profiles(ms)
        assert set(profs) == {"model"}
        ap = profs["model"]
        assert ap.points == 4 and ap.n_devices == 4
        assert ap.alpha_s == pytest.approx(alpha, rel=1e-9)
        assert ap.beta_bytes_per_s == pytest.approx(beta, rel=1e-9)
        # a perfect fit reconciles at 0% everywhere
        errs = fit_errors(profs, ms)
        assert errs["model"] == pytest.approx(0.0, abs=1e-6)

    def test_fit_errors_reports_worst_cell(self):
        ap = AxisProfile(axis="data", alpha_s=0.0,
                         beta_bytes_per_s=1e9, n_devices=2, points=2,
                         r2=1.0)
        ms = [
            {"axis": "data", "wire_bytes": 1e6, "seconds": 1e-3},  # 0%
            {"axis": "data", "wire_bytes": 1e6, "seconds": 2e-3},  # 50%
        ]
        errs = fit_errors({"data": ap}, ms)
        assert errs["data"] == pytest.approx(50.0)


# --- persisted profile -----------------------------------------------------


class TestProfilePersistence:
    def _profile(self):
        return CommProfile(
            platform="cpu", mesh_axes=("data", "model"),
            mesh_shape=(2, 4),
            axes={"data": AxisProfile(
                axis="data", alpha_s=1e-6, beta_bytes_per_s=5e9,
                n_devices=2, points=8, r2=0.99)},
            measurements=[{"op": "psum", "axis": "data", "n": 2,
                           "bytes": 4096.0, "wire_bytes": 4096.0,
                           "seconds": 2e-6}],
            created_unix=1e9,
        )

    def test_round_trip_preserves_everything(self, tmp_path):
        p = self._profile()
        path = p.save(tmp_path / "prof.json")
        back = CommProfile.load(path)
        assert back == p
        assert back.version == commscope.PROFILE_VERSION
        assert back.axis_alpha_beta() == (("data", 1e-6, 5e9),)

    def test_version_mismatch_is_rejected(self, tmp_path):
        d = self._profile().to_dict()
        d["version"] = commscope.PROFILE_VERSION + 1
        path = tmp_path / "future.json"
        path.write_text(json.dumps(d))
        with pytest.raises(ValueError, match="version"):
            CommProfile.load(path)

    def test_default_path_names_platform_and_shape(self):
        p = self._profile()
        assert p.default_path().name == "comm_profile_cpu_2x4.json"
        assert p.default_path().parent == commscope.PROFILE_DIR

    def test_checked_in_reference_profile_loads(self):
        ref = commscope.PROFILE_DIR / "comm_profile_cpu_2x4.json"
        prof = CommProfile.load(ref)
        assert prof.platform == "cpu"
        assert set(prof.axes) == {"data", "model"}
        for ap in prof.axes.values():
            assert ap.beta_bytes_per_s > 0


# --- attribution algebra ---------------------------------------------------


class TestAttribution:
    def test_measured_seconds_split_proportionally(self):
        attr = attribute_measured_seconds(
            {"a.py:1": 3e-3, "b.py:2": 1e-3}, 8.0)
        assert attr["a.py:1"]["measured_s"] == pytest.approx(6.0)
        assert attr["b.py:2"]["measured_s"] == pytest.approx(2.0)
        total = sum(a["measured_s"] for a in attr.values())
        assert total == pytest.approx(8.0)   # nothing dropped

    def test_zero_predictions_split_evenly(self):
        attr = attribute_measured_seconds(
            {"a.py:1": 0.0, "b.py:2": 0.0}, 4.0)
        assert attr["a.py:1"]["measured_s"] == pytest.approx(2.0)
        assert attr["b.py:2"]["measured_s"] == pytest.approx(2.0)
        assert attribute_measured_seconds({}, 4.0) == {}

    def test_line_report_pools_shared_lines(self):
        ev = lambda where, nbytes: CommEvent(          # noqa: E731
            kind="reduce", axes=("model",), bytes=nbytes, where=where,
            primitive="dot_general", reason="t",
            realizations=(("all-reduce", "model"),))
        rep = ShardflowReport(
            name="t", mesh_axes=["data", "model"], mesh_shape=[2, 4],
            events=[ev("a.py:1", 1 << 20), ev("a.py:1", 1 << 20),
                    ev("b.py:2", 1 << 20)],
            flops=0, hbm_bytes=0, out_specs=[],
        )
        prof = costmodel.table_profile("TPU v5 lite")
        rows = commscope.line_report(rep, prof, 3.0)
        assert [r["where"] for r in rows] == ["a.py:1", "b.py:2"]
        assert rows[0]["measured_s"] == pytest.approx(2.0)
        assert rows[1]["measured_s"] == pytest.approx(1.0)
        assert rows[0]["ops"] == ["all-reduce@model"]


# --- overlap decomposition -------------------------------------------------


class TestDecomposeOverlap:
    @pytest.mark.parametrize("d,c,k", [
        (10.0, 6.0, 2.0),    # comm fully exposed past compute
        (10.0, 9.5, 2.0),    # partially exposed, partially hidden
        (10.0, 12.0, 2.0),   # compute over-predicts: comm fully hidden
        (10.0, 0.0, 15.0),   # comm over-predicts: capped at device
        (10.0, 4.0, 0.0),    # no predicted comm: pure compute
        (0.0, 1.0, 1.0),     # empty window
    ])
    def test_parts_always_sum_to_device(self, d, c, k):
        dec = decompose_overlap(d, c, k)
        total = (dec["compute_s"] + dec["exposed_comm_s"]
                 + dec["overlapped_comm_s"])
        assert total == pytest.approx(d)
        assert all(dec[p] >= 0.0 for p in
                   ("compute_s", "exposed_comm_s", "overlapped_comm_s"))

    def test_exposed_is_device_minus_compute_capped_at_comm(self):
        dec = decompose_overlap(10.0, 6.0, 2.0)
        assert dec["exposed_comm_s"] == pytest.approx(2.0)
        assert dec["overlapped_comm_s"] == pytest.approx(0.0)
        assert dec["realized_overlap_ratio"] == pytest.approx(0.0)
        dec = decompose_overlap(10.0, 9.5, 2.0)
        assert dec["exposed_comm_s"] == pytest.approx(0.5)
        assert dec["overlapped_comm_s"] == pytest.approx(1.5)
        assert dec["realized_overlap_ratio"] == pytest.approx(0.75)

    def test_no_predicted_comm_has_no_ratio(self):
        assert decompose_overlap(5.0, 5.0, 0.0)[
            "realized_overlap_ratio"] is None


# --- the goodput ledger's per-family split ---------------------------------


class TestLedgerOverlapReport:
    def test_family_decomposition_sums_to_device_bucket(self):
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("device", family="decode_block"):
            clk.tick(4.0)
        with led.measure("device", family="decode_block"):
            clk.tick(4.0)
        with led.measure("device", family="first_refill"):
            clk.tick(2.0)
        with led.measure("device"):          # sync with no family tag
            clk.tick(1.0)
        rep = led.overlap_report(predicted={
            # per-dispatch prediction: x2 dispatches = 6 compute + 1 comm
            "decode_block": {"compute_s": 3.0, "comm_s": 0.5},
        })
        fams = rep["families"]
        db = fams["decode_block"]
        assert db["calls"] == 2
        assert db["predicted_compute_s"] == pytest.approx(6.0)  # scaled
        assert db["predicted_comm_s"] == pytest.approx(1.0)
        assert db["exposed_comm_s"] == pytest.approx(1.0)
        # no prediction → pure compute, predicted fields None (not 0)
        fr = fams["first_refill"]
        assert fr["predicted_comm_s"] is None
        assert fr["compute_s"] == pytest.approx(2.0)
        assert fr["exposed_comm_s"] == 0.0
        # untagged frames stay visible under the "unattributed" family
        assert rep["device_s"] == pytest.approx(11.0)
        assert rep["attributed_s"] + rep["residual_s"] == pytest.approx(
            rep["device_s"])
        assert fams["unattributed"]["device_s"] == pytest.approx(1.0)
        assert fams["unattributed"]["predicted_comm_s"] is None
        for row in fams.values():
            total = (row["compute_s"] + row["exposed_comm_s"]
                     + row["overlapped_comm_s"])
            assert total == pytest.approx(row["device_s"])
        # and the ledger still reconciles — the split is a VIEW over the
        # device bucket, not a new booking
        assert led.reconcile()["ok"]

    def test_exposed_comm_books_under_device_never_telemetry(self):
        """The ledger invariant the goodput gate leans on: arming the
        overlap view must not move a single second out of ``device`` —
        exposed comm is a decomposition of device time, so the
        ``telemetry`` bucket stays empty and the window's device total
        is byte-identical before and after the report."""
        clk = _Clock()
        led = GoodputLedger(clock=clk)
        with led.measure("device", family="mixed_step"):
            clk.tick(3.0)
        before = led.window_buckets()
        rep = led.overlap_report(predicted={
            "mixed_step": {"compute_s": 1.0, "comm_s": 5.0},
        })
        after = led.window_buckets()
        assert rep["families"]["mixed_step"]["exposed_comm_s"] > 0
        assert after["device"] == before["device"] == pytest.approx(3.0)
        assert after.get("telemetry", 0.0) == 0.0
        assert led.reconcile()["ok"]


# --- calibrated pricing ----------------------------------------------------


class TestCalibratedPricing:
    def _event(self, axes=("model",), nbytes=1 << 20, op="all-reduce"):
        return CommEvent(
            kind="reduce", axes=axes, bytes=nbytes, where="x.py:1",
            primitive="dot_general", reason="t",
            realizations=((op, axes[0] if axes else "-"),))

    def _comm_profile(self):
        return CommProfile(
            platform="cpu", mesh_axes=("data", "model"),
            mesh_shape=(2, 4),
            axes={"model": AxisProfile(
                axis="model", alpha_s=1e-5, beta_bytes_per_s=1e9,
                n_devices=4, points=4, r2=1.0)},
        )

    def test_calibrated_axis_prices_alpha_beta(self):
        base = costmodel.table_profile("TPU v5 lite")
        prof = costmodel.calibrate_axis_profiles(
            self._comm_profile(), base=base)
        ev = self._event()
        wire = ev.bytes * 2 * 3 / 4          # all-reduce ring on n=4
        got = costmodel.price_event(ev, prof, {"data": 2, "model": 4})
        assert got == pytest.approx(1e-5 + wire / 1e9)
        # the pinned table fallback prices the same event flat
        flat = costmodel.price_event(ev, base, {"data": 2, "model": 4})
        assert flat == pytest.approx(wire / base.link_bw)

    def test_uncalibrated_axis_falls_back_to_table(self):
        base = costmodel.table_profile("TPU v5 lite")
        prof = costmodel.calibrate_axis_profiles(
            self._comm_profile(), base=base)     # only "model" measured
        ev = self._event(axes=("data",))
        wire = ev.bytes * 2 * 1 / 2              # ring on n=2
        got = costmodel.price_event(ev, prof, {"data": 2, "model": 4})
        assert got == pytest.approx(wire / base.link_bw)

    def test_calibration_preserves_base_profile_fields(self):
        base = costmodel.table_profile("TPU v5 lite")
        prof = costmodel.calibrate_axis_profiles(
            self._comm_profile(), base=base)
        assert prof.link_bw == base.link_bw
        assert prof.peak_flops == base.peak_flops
        assert prof.axis_profiles == (("model", 1e-5, 1e9),)

    def test_calibrate_from_raw_ladder_records(self):
        alpha, beta = 2e-6, 1e9
        ms = []
        for b in (1 << 16, 1 << 20):
            w = wire_bytes("psum", 4, float(b))
            ms.append({"op": "psum", "axis": "model", "n": 4,
                       "bytes": float(b), "wire_bytes": w,
                       "seconds": alpha + w / beta})
        prof = costmodel.calibrate_axis_profiles(
            ms, base=costmodel.table_profile("TPU v5 lite"))
        (axis, a, b) = prof.axis_profiles[0]
        assert axis == "model"
        assert a == pytest.approx(alpha, rel=1e-6)
        assert b == pytest.approx(beta, rel=1e-6)


# --- registry export -------------------------------------------------------


class TestGaugeExport:
    def test_profile_and_exposed_gauges(self):
        reg = MetricsRegistry()
        prof = CommProfile(
            platform="cpu", mesh_axes=("data",), mesh_shape=(2,),
            axes={"data": AxisProfile(
                axis="data", alpha_s=2e-6, beta_bytes_per_s=3e9,
                n_devices=2, points=4, r2=1.0)},
        )
        commscope.export_profile_gauges(reg, prof)
        commscope.export_exposed_gauges(
            reg, "decode_block", 0.5, {"data": 0.8, "model": 0.2})
        text = reg.prometheus_text()
        assert 'comm_axis_bandwidth_bytes_per_s{axis="data"} 3' in text
        assert 'comm_axis_alpha_seconds{axis="data"}' in text
        assert ('comm_exposed_seconds_total{family="decode_block",'
                'axis="data"} 0.4') in text
        assert ('comm_exposed_seconds_total{family="decode_block",'
                'axis="model"} 0.1') in text

    def test_exposed_gauges_without_shares_use_placeholder_axis(self):
        reg = MetricsRegistry()
        commscope.export_exposed_gauges(reg, "first_refill", 0.25, {})
        text = reg.prometheus_text()
        assert ('comm_exposed_seconds_total{family="first_refill",'
                'axis="-"} 0.25') in text


# --- one real (tiny) ladder ------------------------------------------------


class TestLadderIntegration:
    def test_tiny_ladder_fits_a_profile(self, mesh22):
        """One real timed cellset on the emulated mesh: 2 ops x 1 size
        on one 2-device axis. Feasible at test budget because the
        ladder syncs every call (the CPU rendezvous constraint) and
        min_time is tiny; asserts structure, not speed."""
        ms = commscope.run_ladder(
            mesh22, ops=("psum", "ppermute"), sizes_bytes=(1 << 12, 1 << 14),
            axes=("data",), min_time=0.0, repeats=1, warmup=1,
        )
        assert len(ms) == 4
        assert all(m["seconds"] > 0 for m in ms)
        assert all(m["wire_bytes"] > 0 for m in ms)
        prof = commscope.fit_profile(mesh22, ms)
        assert "data" in prof.axes
        assert prof.axes["data"].n_devices == 2
        back = CommProfile.from_dict(prof.to_dict())
        assert back == prof
