"""The wire codec's numerics contract (round 22, ``parallel/compression.py``).

Pure host/trace-level tests — no engines (the engine-level drift gate and
page-boundary oracles live in ``tests/test_zcompression.py``, sorted last
with the other engine suites). Pinned here:

* block quantization round-trip error ≤ scale/2 per element, per dtype
  and block size — and int arrays pass through raw (quantizing a block
  table would corrupt it);
* **fp32 requantization is an exact fixed point**: encode∘decode∘encode
  ships a bit-identical payload, the property every compressed
  spill→fill→re-spill cycle and the ZeRO ring's gather phase stand on;
* the delta codec ships ONLY the blocks a version bump changed, decodes
  bit-identically to the full int8 encode, and refuses a wrong-shaped
  base loudly;
* the traced (:func:`quantize_blocks`) and host (:func:`_np_quantize`)
  quantizers agree bit-for-bit on the same data — one codec, two wires;
* ``wire_scale`` matches what the payloads actually weigh, so the
  costmodel's priced compression and the codec's real compression
  cannot drift apart.
"""

import numpy as np
import pytest

from learning_jax_sharding_tpu.parallel.compression import (
    Codec,
    CommCompression,
    Int8Codec,
    Int8DeltaCodec,
    get_codec,
    wire_scale,
)


def _rand(rng, shape, dtype=np.float32):
    return (rng.standard_normal(shape) * 3.0).astype(dtype)


class TestInt8RoundTrip:
    @pytest.mark.parametrize("dtype", [np.float32, np.float16, np.float64])
    @pytest.mark.parametrize("block", [8, 32, 64])
    def test_error_bounded_by_half_scale(self, rng, dtype, block):
        x = _rand(rng, (7, 33), dtype)          # deliberately ragged
        codec = Int8Codec(block=block)
        p = codec.encode(x)
        y = codec.decode(p)
        assert y.shape == x.shape and y.dtype == x.dtype
        flat = x.astype(np.float32).reshape(-1)
        pad = (-flat.size) % block
        blocks = np.pad(flat, (0, pad)).reshape(-1, block)
        scales = np.max(np.abs(blocks), axis=1, keepdims=True) / 127.0
        bound = np.repeat(
            np.maximum(scales, 0), block, axis=1
        ).reshape(-1)[: flat.size] / 2.0
        err = np.abs(y.astype(np.float32).reshape(-1) - flat)
        # half-ulp slack for the low-precision dtypes' own rounding
        eps = np.finfo(dtype).eps * np.abs(flat)
        assert np.all(err <= bound + eps + 1e-12)

    def test_int_arrays_pass_through_raw(self, rng):
        x = rng.integers(0, 100, size=(16,)).astype(np.int32)
        p = Int8Codec().encode(x)
        assert p["codec"] == "raw"
        assert p["wire_bytes"] == x.nbytes
        np.testing.assert_array_equal(Int8Codec().decode(p), x)

    def test_zero_blocks_quantize_exactly(self):
        x = np.zeros((64,), np.float32)
        p = Int8Codec().encode(x)
        np.testing.assert_array_equal(Int8Codec().decode(p), x)
        assert np.all(p["scales"] == 1.0)       # no 0/0

    def test_f32_requantization_is_fixed_point(self, rng):
        x = _rand(rng, (256,))
        codec = Int8Codec()
        p1 = codec.encode(x)
        y = codec.decode(p1)
        p2 = codec.encode(y)
        np.testing.assert_array_equal(p1["q"], p2["q"])
        np.testing.assert_array_equal(p1["scales"], p2["scales"])
        np.testing.assert_array_equal(y, codec.decode(p2))

    def test_wire_bytes_match_wire_scale(self, rng):
        # Block-aligned f32 input: payload weight must equal the factor
        # the costmodel prices with, exactly.
        x = _rand(rng, (4, 256))
        p = Int8Codec(block=32).encode(x)
        assert p["raw_bytes"] == x.nbytes
        assert p["wire_bytes"] == int(x.nbytes * wire_scale(4, 32))
        assert p["wire_bytes"] < p["raw_bytes"] / 3   # ≥ 3x reduction


class TestTracedHostAgreement:
    def test_quantize_blocks_matches_np_quantize(self, rng):
        import jax.numpy as jnp

        from learning_jax_sharding_tpu.parallel.compression import (
            _np_quantize,
            dequantize_blocks,
            quantize_blocks,
        )

        x = _rand(rng, (5, 37))
        qj, sj = quantize_blocks(jnp.asarray(x), 32)
        qn, sn = _np_quantize(x.reshape(-1), 32)
        np.testing.assert_array_equal(np.asarray(qj), qn)
        np.testing.assert_array_equal(np.asarray(sj), sn)
        y = dequantize_blocks(qj, sj, x.shape, jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(y), Int8Codec().decode(Int8Codec().encode(x))
        )


class TestDeltaCodec:
    def test_no_base_degrades_to_full_int8(self, rng):
        x = _rand(rng, (128,))
        full = Int8Codec().encode(x)
        p = Int8DeltaCodec().encode(x, base=None)
        assert p["codec"] == "int8"
        np.testing.assert_array_equal(p["q"], full["q"])

    def test_identical_base_ships_zero_blocks(self, rng):
        x = _rand(rng, (128,))
        codec = Int8DeltaCodec()
        p = codec.encode(x, base=x.copy())
        assert p["codec"] == "int8_delta"
        assert p["idx"].size == 0
        assert p["wire_bytes"] == 0
        np.testing.assert_array_equal(
            codec.decode(p, base=x.copy()),
            Int8Codec().decode(Int8Codec().encode(x)),
        )

    def test_version_bump_ships_only_changed_blocks(self, rng):
        # A page re-spilled after a weights bump: the first 3 blocks are
        # untouched, the last block carries the new version's rows.
        base = _rand(rng, (128,))
        new = base.copy()
        new[96:] = _rand(rng, (32,))
        codec = Int8DeltaCodec()
        p = codec.encode(new, base=base)
        assert list(p["idx"]) == [3]
        assert p["wire_bytes"] < Int8Codec().encode(new)["wire_bytes"]
        np.testing.assert_array_equal(
            codec.decode(p, base=base),
            Int8Codec().decode(Int8Codec().encode(new)),
        )

    def test_chained_version_bumps_stay_bit_identical(self, rng):
        # v0 -> v1 -> v2, each delta decoded against the PREVIOUS decoded
        # copy (exactly the TierStore re-demotion flow): every hop must
        # land on the full encode's grid, or drift would compound.
        codec = Int8DeltaCodec()
        cur = _rand(rng, (256,))
        held = codec.decode(codec.encode(cur))      # v0 full
        for lo in (64, 192):
            nxt = held.copy()
            nxt[lo : lo + 32] = _rand(rng, (32,))
            p = codec.encode(nxt, base=held)
            held = codec.decode(p, base=held)
            np.testing.assert_array_equal(
                held, Int8Codec().decode(Int8Codec().encode(nxt))
            )

    def test_wrong_base_refuses_loudly(self, rng):
        codec = Int8DeltaCodec()
        x = _rand(rng, (128,))
        p = codec.encode(x, base=x.copy())
        with pytest.raises(ValueError, match="base"):
            codec.decode(p, base=None)
        with pytest.raises(ValueError, match="blocks"):
            codec.decode(p, base=_rand(rng, (256,)))

    def test_shape_mismatched_base_degrades_to_full(self, rng):
        x = _rand(rng, (128,))
        p = Int8DeltaCodec().encode(x, base=_rand(rng, (64,)))
        assert p["codec"] == "int8"


class TestRegistryAndConfig:
    def test_get_codec_resolution(self):
        assert get_codec(None) is None
        assert isinstance(get_codec("none"), Codec)
        assert get_codec("int8").name == "int8"
        assert get_codec("int8_delta", block=16).block == 16
        with pytest.raises(ValueError, match="unknown codec"):
            get_codec("zstd")

    def test_comm_compression_validation(self):
        with pytest.raises(ValueError):
            CommCompression(block=0)
        with pytest.raises(ValueError):
            CommCompression(kv_codec="nope")
        comp = CommCompression()
        assert comp.active
        comp.enabled = False                # the drift ladder's flip
        assert not comp.active
        assert not CommCompression(collectives=False).active

    def test_wire_scale_table(self):
        assert wire_scale(4, 32) == pytest.approx(0.28125)
        assert wire_scale(2, 32) == pytest.approx(0.5625)
        # bigger blocks amortize the scales further
        assert wire_scale(4, 64) < wire_scale(4, 32)
