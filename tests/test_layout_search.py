"""Round-17 layout search: candidate enumeration, coordinate descent,
recovery of seeded mis-shardings, determinism, budget/pruning
accounting, and the golden-format contract the argmin emits.

Everything here is abstract — the search never compiles a candidate —
so the whole file runs on the emulated-CPU mesh the conftest builds.
"""

import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.analysis import costmodel
from learning_jax_sharding_tpu.analysis.contracts import Contract
from learning_jax_sharding_tpu.analysis.layout_search import (
    apply_assignment,
    candidate_dims,
    default_vary,
    dims_str,
    partition_spec,
    search_layout,
)
from learning_jax_sharding_tpu.analysis.shardflow import trace_shardflow
from learning_jax_sharding_tpu.analysis.topology import reference_two_tier
from learning_jax_sharding_tpu.parallel import build_mesh, mesh_sharding, put

PROFILE = costmodel.table_profile("TPU v5 lite")
SIZES_24 = {"data": 2, "model": 4}
# Two-tier view of the same mesh: leading axis 'data' crosses DCN,
# 'model' stays on ICI (reference α/β).
TOPO_24 = reference_two_tier(("data", "model"), (2, 4))


@pytest.fixture(scope="module")
def mesh():
    return build_mesh((2, 4), ("data", "model"))


def _ff(x, w1, w2):
    h = np.tanh(0)  # keep flake quiet about unused names in doc runs
    del h
    import jax.numpy as jnp

    return jnp.einsum("bsh,hd->bsd", jnp.maximum(x @ w1, 0.0), w2)


def _ff_args(mesh, *, w2_dims=("model", None)):
    B, S, D, H = 8, 64, 128, 512
    x = put(np.ones((B, S, D), np.float32),
            mesh_sharding(mesh, "data", None, None))
    w1 = put(np.ones((D, H), np.float32), mesh_sharding(mesh, None, "model"))
    w2 = put(np.ones((H, D), np.float32), mesh_sharding(mesh, *w2_dims))
    return x, w1, w2


def _weights_only(path, leaf):
    return default_vary(path, leaf) and leaf.ndim == 2


class TestCandidateDims:
    def test_first_candidate_is_replicated(self):
        cands = candidate_dims((8, 8), SIZES_24)
        assert cands[0] == ((), ())

    def test_enumerates_all_divisible_placements(self):
        # 2 axes x (unused | dim0 | dim1) = 9 combos, all divisible.
        cands = candidate_dims((8, 8), SIZES_24)
        assert len(cands) == 9
        assert (("data",), ("model",)) in cands
        assert (("data", "model"), ()) in cands

    def test_divisibility_filters_placements(self):
        # dim1 of size 2 cannot carry the 4-way 'model' axis.
        cands = candidate_dims((8, 2), SIZES_24)
        assert all("model" not in d[1] for d in cands)
        assert (("model",), ("data",)) in cands

    def test_degenerate_axes_are_dropped(self):
        cands = candidate_dims((8, 8), {"data": 2, "one": 1})
        assert all("one" not in d0 + d1 for d0, d1 in cands)

    def test_deterministic_order(self):
        a = candidate_dims((16, 16), SIZES_24)
        b = candidate_dims((16, 16), SIZES_24)
        assert a == b

    def test_scalar_leaf_only_replicated(self):
        assert candidate_dims((), SIZES_24) == ((),)


class TestRendering:
    def test_dims_str(self):
        assert dims_str((("data",), (), ("model",))) == \
            "('data', None, 'model')"
        assert dims_str((("data", "model"), ())) == "(data+model, None)"

    def test_partition_spec(self):
        assert partition_spec((("data",), (), ("model",))) == \
            P("data", None, "model")
        assert partition_spec((("data", "model"), ())) == \
            P(("data", "model"), None)
        assert partition_spec(((), ())) == P(None, None)


class TestSearch:
    def test_recovers_transposed_w2(self, mesh):
        """The case24 scenario: w2 arrives (None,'model') instead of
        ('model',None); the search must price at or below the
        hand-tuned layout without compiling anything."""
        x, w1, w2_good = _ff_args(mesh)
        hand = costmodel.price(
            trace_shardflow("t_hand", _ff, x, w1, w2_good, mesh=mesh),
            PROFILE,
        )
        x, w1, w2_bad = _ff_args(mesh, w2_dims=(None, "model"))
        res = search_layout(
            "t_search", _ff, x, w1, w2_bad, mesh=mesh,
            vary=_weights_only, budget=96, profile=PROFILE,
        )
        assert res.best.predicted_s <= hand.predicted_s * (1 + 1e-9)
        assert res.gap_pct > 0.0
        # The transposed kernel is among the moved leaves.
        assert any("w2" in p or "[2]" in p for p in res.changed)

    def test_good_start_is_kept(self, mesh):
        x, w1, w2 = _ff_args(mesh)
        res = search_layout(
            "t_keep", _ff, x, w1, w2, mesh=mesh,
            vary=_weights_only, budget=96, profile=PROFILE,
        )
        # Incumbent wins ties (strict < tie-break) -> hand layout, or a
        # strictly cheaper one; never a regression.
        assert res.best.predicted_s <= res.baseline.predicted_s

    def test_deterministic(self, mesh):
        args = _ff_args(mesh, w2_dims=(None, "model"))
        runs = [
            search_layout("t_det", _ff, *args, mesh=mesh,
                          vary=_weights_only, budget=64, profile=PROFILE)
            for _ in range(2)
        ]
        assert runs[0].contract.to_json() == runs[1].contract.to_json()
        assert runs[0].assignment == runs[1].assignment
        assert runs[0].evaluated == runs[1].evaluated
        assert runs[0].pruned == runs[1].pruned

    def test_budget_one_returns_incumbent(self, mesh):
        args = _ff_args(mesh, w2_dims=(None, "model"))
        res = search_layout("t_b1", _ff, *args, mesh=mesh,
                            vary=_weights_only, budget=1, profile=PROFILE)
        assert res.evaluated == 1
        assert res.exhausted  # the incumbent eval consumed the budget
        assert res.assignment == res.baseline_assignment
        assert res.changed == {}
        assert res.best.predicted_s == res.baseline.predicted_s

    def test_budget_rejected_below_one(self, mesh):
        args = _ff_args(mesh)
        with pytest.raises(ValueError, match="budget"):
            search_layout("t_bad", _ff, *args, mesh=mesh, budget=0,
                          profile=PROFILE)

    def test_dominance_pruning_fires_on_bad_start(self, mesh):
        args = _ff_args(mesh, w2_dims=(None, "model"))
        res = search_layout("t_prune", _ff, *args, mesh=mesh,
                            vary=_weights_only, budget=96, profile=PROFILE)
        # Plenty of candidates price above the incumbent on this mesh;
        # the abort_above cut must be taking them early.
        assert res.pruned >= 1
        assert res.evaluated <= res.budget

    def test_contract_is_golden_format(self, mesh):
        args = _ff_args(mesh, w2_dims=(None, "model"))
        res = search_layout("t_fmt", _ff, *args, mesh=mesh,
                            vary=_weights_only, budget=32, profile=PROFILE)
        c = res.contract
        assert c.name == "t_fmt"
        rt = Contract.from_json(c.to_json())
        assert rt.to_json() == c.to_json()
        assert c.to_json().endswith("\n")
        assert list(c.collectives) == sorted(c.collectives)

    def test_apply_assignment_commits_argmin(self, mesh):
        args = _ff_args(mesh, w2_dims=(None, "model"))
        res = search_layout("t_apply", _ff, *args, mesh=mesh,
                            vary=_weights_only, budget=64, profile=PROFILE)
        (fixed, kw) = apply_assignment(res, args, mesh)
        assert kw == {}
        flat_paths = {
            p: partition_spec(d[1]) for p, d in res.changed.items()
        }
        import jax

        for kp, leaf in jax.tree_util.tree_flatten_with_path(
            (fixed, {})
        )[0]:
            path = jax.tree_util.keystr(kp)
            if path in flat_paths:
                want = NamedSharding(mesh, flat_paths[path])
                assert leaf.sharding.is_equivalent_to(want, leaf.ndim), path
        # Untouched leaves keep shapes/values.
        assert all(a.shape == b.shape for a, b in zip(fixed, args))

    def test_default_vary(self, mesh):
        x, w1, _ = _ff_args(mesh)
        assert default_vary(".x", x)           # f32 rank-3
        assert default_vary(".w1", w1)         # f32 rank-2
        assert not default_vary(".b", np.ones((8,), np.float32))
        assert not default_vary(".t", np.ones((4, 4), np.int32))
        assert not default_vary(".s", 3.0)


def _mm(x, w):
    import jax.numpy as jnp

    return jnp.einsum("bh,hd->bd", x, w)


def _mm_args(mesh):
    """The seeded two-tier scenario: B=2 is divisible only by 'data',
    D=7 by nothing — every searchable placement lands on the
    contraction dim H, so the search's ONLY real decision is which
    mesh axis the matmul's all-reduce crosses. The incumbent pins both
    contraction shardings on 'data' (the DCN tier)."""
    x = put(np.ones((2, 1024), np.float32),
            mesh_sharding(mesh, None, "data"))
    w = put(np.ones((1024, 7), np.float32),
            mesh_sharding(mesh, "data", None))
    return x, w


def _dcn_bytes_of(report):
    """Price a (possibly flat-searched) report under the two-tier
    profile — the cross-tier bytes its layout would really move."""
    return costmodel.price_multiset_topo(
        report.events, PROFILE, SIZES_24, topology=TOPO_24,
    ).dcn_bytes


class TestTopologySearch:
    """The ISSUE-18 seeded acceptance case: flat pricing prefers the
    all-reduce on the SMALLER axis (ring factor 2(n-1)/n favors n=2 =
    'data'), which is exactly the DCN tier; hierarchy-aware pricing
    must route the hot all-reduce onto ICI instead."""

    def test_flat_argmin_is_dcn_heavy(self, mesh):
        res = search_layout(
            "t_flat_tier", _mm, *_mm_args(mesh), mesh=mesh,
            budget=96, profile=PROFILE,
        )
        # Flat pricing keeps the seeded data-axis contraction: the
        # n=2 all-reduce is the cheapest wire under a uniform link.
        ops = {r for ev in res.report.events for r in ev.realizations[:1]}
        assert ("all-reduce", "data") in ops
        assert _dcn_bytes_of(res.report) > 0

    def test_topo_argmin_strictly_lower_dcn_bytes(self, mesh):
        flat = search_layout(
            "t_flat_tier", _mm, *_mm_args(mesh), mesh=mesh,
            budget=96, profile=PROFILE,
        )
        topo = search_layout(
            "t_topo_tier", _mm, *_mm_args(mesh), mesh=mesh,
            budget=96, profile=PROFILE, topology=TOPO_24,
        )
        assert isinstance(topo.best, costmodel.TopoPredictedCost)
        assert topo.topology is TOPO_24
        # Strictly lower priced DCN bytes than the flat argmin — the
        # acceptance criterion. Here the search gets all the way to
        # zero: the all-reduce moves to the ICI axis.
        assert topo.best.comm.dcn_bytes < _dcn_bytes_of(flat.report)
        assert topo.best.comm.dcn_bytes == 0
        ops = {r for ev in topo.report.events for r in ev.realizations[:1]}
        assert ("all-reduce", "model") in ops
        assert ("all-reduce", "data") not in ops
        # ... and it really moved leaves to get there.
        assert topo.changed != {}

    def test_topo_search_deterministic(self, mesh):
        runs = [
            search_layout("t_topo_det", _mm, *_mm_args(mesh), mesh=mesh,
                          budget=96, profile=PROFILE, topology=TOPO_24)
            for _ in range(2)
        ]
        assert runs[0].assignment == runs[1].assignment
        assert runs[0].evaluated == runs[1].evaluated
        assert runs[0].best.comm.to_dict() == runs[1].best.comm.to_dict()

    def test_to_dict_carries_topology_and_split(self, mesh):
        res = search_layout("t_topo_dict", _mm, *_mm_args(mesh), mesh=mesh,
                            budget=32, profile=PROFILE, topology=TOPO_24)
        d = res.to_dict()
        assert d["topology"] == TOPO_24.name
        assert "dcn_bytes" in d["best_cost"]
        assert "overlap_ratio" in d["best_cost"]

    def test_overlap_discount_tightens_prediction(self, mesh):
        """Overlap-aware prediction sits between the serial upper
        bound and the compute/memory floor, and a higher measured
        overlap ratio only ever lowers it (monotone discount)."""
        x, w = _mm_args(mesh)
        rep = trace_shardflow("t_overlap", _mm, x, w, mesh=mesh)
        serial = costmodel.price_topo(
            rep, PROFILE, topology=TOPO_24, overlap_ratio=0.0)
        half = costmodel.price_topo(
            rep, PROFILE, topology=TOPO_24, overlap_ratio=0.5)
        full = costmodel.price_topo(
            rep, PROFILE, topology=TOPO_24, overlap_ratio=1.0)
        assert serial.predicted_s > half.predicted_s > full.predicted_s
        assert full.predicted_s == pytest.approx(
            max(full.compute_s, full.memory_s))
        assert serial.predicted_s == pytest.approx(
            serial.serial_predicted_s, rel=1e-6, abs=1e-12,
        ) or serial.predicted_s >= max(serial.compute_s, serial.memory_s)


class TestSearchEntry:
    @pytest.mark.slow
    def test_train_step_smoke(self):
        from learning_jax_sharding_tpu.analysis.layout_search import (
            search_entry,
        )

        res = search_entry("train_step", budget=8)
        assert res.name == "train_step"
        assert res.evaluated <= 8
        assert res.best.predicted_s <= res.baseline.predicted_s
        assert res.contract.name == "train_step"
