"""Round-18 memflow: per-device peak-HBM liveness analysis and its wiring.

Pins the four contracts the memory gate stands on:

* BUFFER SIZING — memflow's ``buffer_bytes`` and shardflow's
  ``_aval_bytes`` agree on every aval in the searchable entry points'
  traced jaxprs (unsharded), and sharded sizing divides by the spec's
  shard factor (ceil for uneven remainders);
* the LIVENESS MODEL — scan peaks are carry + per-iteration body
  high-water (NOT trip-count x body), donation frees the input
  generation, and XLA-virtual broadcasts carry no bytes;
* RECONCILIATION — the predicted peak squares against
  ``compiled.memory_analysis()`` within the tolerance pinned in
  ``analysis/baseline.json`` (``memflow_tolerance_pct``) with zero
  unexplained byte classes, and against ``utils.memory.memory_plan``'s
  closed forms on ``CONFIG_TINY`` so hand formulas and program analysis
  cannot silently diverge;
* the SEEDED-OOM loop closure — un-sharded optimizer moments at 1.4B
  scale are flagged by memflow, fail ``shardcheck --memory``, and the
  HBM-budgeted layout search returns a FITTING layout where the
  unconstrained round-17 search provably keeps the replicated
  (OOMing) incumbent.

Everything except the reconciliation tests is abstract (trace-only, no
compiles) on the conftest's 8 emulated CPU devices.
"""

import importlib.util
import json
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.analysis import (
    BASELINE_PATH,
    costmodel,
    run_memflow_pass,
)
from learning_jax_sharding_tpu.analysis.entrypoints import (
    SEARCHABLE_ENTRIES,
    build_search_inputs,
)
from learning_jax_sharding_tpu.analysis.layout_search import search_layout
from learning_jax_sharding_tpu.analysis.memflow import (
    MemflowReport,
    analyze_entry,
    buffer_bytes,
    memory_findings,
    memory_stats_dict,
    reconcile_memory,
    simulate_memflow,
    trace_memflow,
)
from learning_jax_sharding_tpu.analysis.shardflow import (
    Spec,
    _aval_bytes,
    _sub_jaxprs,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import activate

SIZES_24 = {"data": 2, "model": 4}


@pytest.fixture(scope="module")
def mesh():
    return build_mesh((2, 4), ("data", "model"))


def _all_vars(jaxpr):
    """Every var (invars, constvars, eqn in/outvars) in a jaxpr nest."""
    seen = []
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        seen.extend(v for v in list(j.invars) + list(j.constvars)
                    if hasattr(v, "aval"))
        for eqn in j.eqns:
            seen.extend(v for v in list(eqn.invars) + list(eqn.outvars)
                        if hasattr(v, "aval"))
            stack.extend(sub for _k, sub in _sub_jaxprs(eqn))
    return seen


class TestBufferSizing:
    def test_agrees_with_aval_bytes_on_searchable_entries(self):
        # The property the reconciliation rests on: without a spec,
        # memflow sizes every buffer exactly as shardflow does — over
        # EVERY aval of every searchable entry point's traced program.
        for entry in SEARCHABLE_ENTRIES:
            t = build_search_inputs(entry, None)
            inner = getattr(t["fn"], "__wrapped__", t["fn"])
            with activate(t["mesh"], t["rules"]):
                closed = jax.make_jaxpr(inner)(*t["args"], **t["kwargs"])
            vs = _all_vars(closed.jaxpr)
            assert vs, entry
            for v in vs:
                assert buffer_bytes(v) == _aval_bytes(v), (entry, v)

    def test_sharded_buffer_divides_by_shard_factor(self):
        closed = jax.make_jaxpr(lambda x: x + 1.0)(
            jnp.zeros((16, 64), jnp.float32))
        x = closed.jaxpr.invars[0]
        full = _aval_bytes(x)
        spec = Spec((("data", "model"), ()))
        assert buffer_bytes(x, spec, SIZES_24) == full // 8

    def test_uneven_shard_rounds_up(self):
        closed = jax.make_jaxpr(lambda x: x + 1.0)(
            jnp.zeros((10,), jnp.float32))
        x = closed.jaxpr.invars[0]
        spec = Spec((("model",),))
        # 40 bytes over 4 shards of a 10-long dim: ceil(40/4) = 10.
        assert buffer_bytes(x, spec, SIZES_24) == 10


class TestLivenessModel:
    def _scan_peak(self, mesh, length):
        def fn(x):
            def body(c, _):
                return jnp.tanh(c @ c) + x, None

            c, _ = jax.lax.scan(body, x, None, length=length)
            return c

        x = jnp.zeros((32, 32), jnp.float32)
        return trace_memflow(f"scan{length}", fn, x, mesh=mesh).peak_bytes

    def test_scan_peak_is_not_trip_multiplied(self, mesh):
        # carry + per-iteration body high-water: 100x the trips, same peak.
        assert self._scan_peak(mesh, 1000) == self._scan_peak(mesh, 10)

    def test_donation_frees_the_input_generation(self, mesh):
        # state's last use is the first eqn: donation drops it before
        # the eqn's output is charged (XLA's input_output_alias), so the
        # peak is one full generation smaller.
        def step(state, g):
            return (state - g) * 0.5

        s = jnp.zeros((256, 256), jnp.float32)
        g = jnp.zeros((256, 256), jnp.float32)
        kept = trace_memflow("kept", step, s, g, mesh=mesh)
        freed = trace_memflow("freed", step, s, g, mesh=mesh, donated=(0,))
        assert freed.peak_bytes < kept.peak_bytes
        assert freed.donated_bytes == _aval_bytes(
            jax.make_jaxpr(step)(s, g).jaxpr.invars[0])

    def test_broadcast_is_virtual(self, mesh):
        # jnp.zeros is XLA-fused into its consumer: the mask constant
        # must not be charged as a live buffer next to in + out.
        def fn(x):
            return x + jnp.zeros((512, 512), jnp.float32)

        x = jnp.zeros((512, 512), jnp.float32)
        rep = trace_memflow("bcast", fn, x, mesh=mesh)
        nb = _aval_bytes(jax.make_jaxpr(fn)(x).jaxpr.invars[0])
        assert rep.peak_bytes <= 2 * nb

    def test_report_dict_shape(self, mesh):
        rep = trace_memflow(
            "toy", lambda x: x * 2.0, jnp.zeros((8, 8)), mesh=mesh)
        d = rep.to_dict()
        assert d["peak_bytes"] > 0
        assert d["peak_buffers"] and {"bytes", "where", "kind", "label"} \
            <= set(d["peak_buffers"][0])


class TestReconciliation:
    def test_toy_matmul_reconciles_tightly(self, mesh):
        # One compiled program end-to-end: donated sharded matmul; the
        # predicted peak must land within 30% of the allocator's view
        # with zero unexplained byte classes (measured 25% here — the
        # donated input's alias credit vs XLA's generated-code bytes).
        sh = NamedSharding(mesh, P("data", None))
        x = jax.device_put(np.ones((16, 16), np.float32), sh)
        w = jax.device_put(np.ones((16, 16), np.float32),
                           NamedSharding(mesh, P()))

        def fn(x, w):
            return x @ w

        jitted = jax.jit(fn, donate_argnums=(0,))
        lowered = jitted.lower(x, w)
        compiled = lowered.compile()
        rep = trace_memflow("toy_mm", fn, x, w, mesh=mesh, donated=(0,))
        rec = reconcile_memory(rep, memory_stats_dict(compiled))
        assert rec["measured_bytes"] is not None
        assert rec["err_pct"] <= 30.0
        assert rec["unexplained"] == {}

    def test_train_step_within_pinned_tolerance(self):
        # The round-18 acceptance bar on the cheapest entry: reconciled
        # within the baseline-pinned tolerance, zero unexplained classes,
        # and the drift CONSERVATIVE (memflow over-predicts, so the OOM
        # gate errs toward flagging, never toward missing).
        tol = json.loads(BASELINE_PATH.read_text())["memflow_tolerance_pct"]
        analysis = analyze_entry("train_step")
        rec = analysis["reconciled"]
        assert rec["measured_bytes"] is not None
        assert rec["err_pct"] <= tol["train_step"]
        assert rec["signed_err_pct"] > 0
        assert rec["unexplained"] == {}
        assert analysis["donated"], "train step should donate its state"

    @pytest.mark.slow
    def test_all_searchable_entries_reconcile(self):
        findings, reports = run_memflow_pass(budget_bytes=None)
        assert [r["name"] for r in reports] == list(SEARCHABLE_ENTRIES)
        assert findings == []
        for r in reports:
            assert r["reconciled"]["unexplained"] == {}

    def test_tolerances_pin_exactly_the_searchable_entries(self):
        # Staleness audit, same spirit as test_repo_lint's dead-budget
        # check: a tolerance for a gone entry point or a searchable
        # entry with no pinned tolerance are both rot.
        tol = json.loads(BASELINE_PATH.read_text())["memflow_tolerance_pct"]
        keys = {k for k in tol if not k.startswith("_")}
        assert keys == set(SEARCHABLE_ENTRIES)

    def test_memory_plan_and_memflow_cannot_silently_diverge(self):
        # Tentpole (c): the hand closed forms vs the program analysis on
        # CONFIG_TINY. Both are per-device estimates of the same step;
        # memflow is structurally conservative (liveness sum, replicated
        # custom-vjp boundaries), measured at ~2.5x the plan here. An
        # order-of-magnitude drift on either side breaks the bound.
        from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY
        from learning_jax_sharding_tpu.utils.memory import memory_plan

        t = build_search_inputs("train_step", None)
        with activate(t["mesh"], t["rules"]):
            rep = trace_memflow(
                "train_step", t["fn"], *t["args"], mesh=t["mesh"],
                while_trip_hint=t["while_trip_hint"], **t["kwargs"])
        batch = t["args"][1]["inputs"] if isinstance(
            t["args"][1], dict) else t["args"][1]
        b, s = int(batch.shape[0]), int(batch.shape[1])
        plan = memory_plan(CONFIG_TINY, b, s,
                           n_model_shards=4, n_data_shards=2)
        ratio = rep.peak_bytes / plan.total
        assert 1.0 <= ratio <= 3.5, (rep.peak_bytes, plan.total)


# The seeded-OOM scenario of the acceptance criteria: a 1.4B-param
# adam-shaped update whose params/moments/grads are REPLICATED (the
# classic un-sharded optimizer bug) feeding a weight-stationary matmul.
# The trailing dim is odd, so every enumerable sharding lands on the
# contraction dim and buys an all-reduce that prices WORSE than its
# HBM-streaming saving — the comms-only search provably never moves.
_K, _N, _B = 32768, 43007, 8192
_HBM = 16e9
_HEADROOM = 0.8


def _oom_state(mesh):
    rep = NamedSharding(mesh, P())
    dsh = NamedSharding(mesh, P("data", None))

    def sds(shape, sh):
        return jax.ShapeDtypeStruct(shape, jnp.float32, sharding=sh)

    return {
        "p": sds((_K, _N), rep), "m": sds((_K, _N), rep),
        "v": sds((_K, _N), rep), "g": sds((_K, _N), rep),
        "x": sds((_B, _K), dsh),
    }


def _adam_forward(s):
    b1, b2, lr, eps = 0.9, 0.999, 1e-3, 1e-8
    m = b1 * s["m"] + (1 - b1) * s["g"]
    v = b2 * s["v"] + (1 - b2) * s["g"] ** 2
    p = s["p"] - lr * m / (jnp.sqrt(v) + eps)
    return p, m, v, s["x"] @ p


def _oom_vary(path, leaf):
    return any(k in path for k in ("'p'", "'m'", "'v'", "'g'"))


class TestSeededOOM:
    def test_memflow_flags_replicated_moments(self, mesh):
        rep = trace_memflow("seeded_oom", _adam_forward, _oom_state(mesh),
                            mesh=mesh)
        cap = _HBM * _HEADROOM
        assert rep.peak_bytes > cap
        found = memory_findings(
            {"report": rep, "reconciled": reconcile_memory(rep, None)},
            budget_bytes=_HBM, headroom=_HEADROOM, tolerance_pct=None)
        assert [f.rule for f in found] == ["memflow-over-budget"]
        assert found[0].data["peak_bytes"] == rep.peak_bytes

    def test_unconstrained_search_keeps_the_oom_layout(self, mesh):
        # Round-17 semantics: cheapest comms. Every single-coordinate
        # sharding move introduces the contraction all-reduce, so the
        # greedy search returns the replicated incumbent unchanged —
        # which memflow says cannot fit. This is the gap the HBM budget
        # closes.
        res = search_layout(
            "seeded_oom", _adam_forward, _oom_state(mesh), mesh=mesh,
            vary=_oom_vary, budget=64,
            profile=costmodel.table_profile("TPU v5 lite"))
        assert res.changed == {}
        assert res.fits is None  # unconstrained searches don't judge HBM
        peak = trace_memflow("seeded_oom", _adam_forward, _oom_state(mesh),
                             mesh=mesh).peak_bytes
        assert peak > _HBM * _HEADROOM

    def test_budgeted_search_returns_a_fitting_layout(self, mesh):
        res = search_layout(
            "seeded_oom", _adam_forward, _oom_state(mesh), mesh=mesh,
            vary=_oom_vary, budget=64,
            profile=costmodel.table_profile("TPU v5 lite"),
            hbm_budget_bytes=_HBM, hbm_headroom=_HEADROOM)
        cap = _HBM * _HEADROOM
        assert res.fits is True
        assert res.baseline_peak_bytes > cap
        assert res.peak_bytes <= cap
        assert res.oom_rejected > 0
        # The moments moved off replication — the fix the search found.
        moved = set(res.changed)
        assert any("'m'" in p for p in moved)
        assert any("'v'" in p for p in moved)
        assert "hbm" in res.to_dict()

    def test_budgeted_search_is_deterministic(self, mesh):
        kw = dict(mesh=mesh, vary=_oom_vary, budget=64,
                  profile=costmodel.table_profile("TPU v5 lite"),
                  hbm_budget_bytes=_HBM, hbm_headroom=_HEADROOM)
        a = search_layout("seeded_oom", _adam_forward, _oom_state(mesh), **kw)
        b = search_layout("seeded_oom", _adam_forward, _oom_state(mesh), **kw)
        assert a.assignment == b.assignment
        assert a.peak_bytes == b.peak_bytes


class TestMemoryPassCLI:
    @pytest.fixture(scope="class")
    def shardcheck(self):
        spec = importlib.util.spec_from_file_location(
            "shardcheck",
            pathlib.Path(__file__).resolve().parents[1] / "scripts"
            / "shardcheck.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_memory_pass_fails_on_budget_violation(self, shardcheck,
                                                   capsys):
        # train_step's predicted peak is ~2.1 MiB/device; a 2 MB budget
        # at 0.8 headroom must fail the run — OOM as a pre-compile
        # review finding.
        rc = shardcheck.main([
            "--pass", "memory", "--only", "train_step",
            "--memory-budget-bytes", "2e6",
        ])
        out = capsys.readouterr().out
        assert rc == 1
        assert "memflow-over-budget" in out

    def test_family_attribution(self, shardcheck):
        fam = shardcheck._family
        assert fam("train_step_gn") == "train"
        assert fam("spec_adapter_mixed_step") == "engine"
        assert fam("spec_multi_step") == "engine"
        assert fam("zero1_update_q8") == "zero1"
        assert fam("spec_first_prefill") == "serving"
        assert fam("kv_page_spill") == "kv"
        assert fam("swap_reshard_quant") == "reshard"
        assert fam("ring_attention") == "ops"
