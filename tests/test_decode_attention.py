"""Length-aware blocked decode attention (ops/decode_attention.py).

The kernel's claims, pinned:

* parity with the dense cached path (the masked ``dot_product_attention``
  oracle) across GQA grouping, chunked prefill, sliding windows, and int8
  caches — every configuration the serving stack composes;
* the model-level blocked backend (``decode_attention="blocked"``) generates
  the SAME tokens as the dense backend, end to end through
  ``make_generate_fn`` — including through the shard_map wrapper on the
  emulated multi-device mesh (``make_decode_attn_fn``), which multi-chip
  serving uses because GSPMD cannot partition a Pallas custom call.

The bandwidth claim (per-token HBM traffic scales with valid cache length,
not buffer length) is a real-TPU measurement, recorded in PERF.md — the
interpreter cannot observe DMA elision.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, TransformerConfig
from learning_jax_sharding_tpu.ops.attention import dot_product_attention
from learning_jax_sharding_tpu.ops.decode_attention import (
    auto_block_k,
    decode_attention,
    make_decode_attn_fn,
)


def _dense_oracle(q, kc, vc, idx, window=None):
    """Masked dense attention over the (B, N_kv, L, H) cache layout."""
    b, s, n, h = q.shape
    n_kv, length = kc.shape[1], kc.shape[2]
    group = n // n_kv
    k = jnp.repeat(kc.transpose(0, 2, 1, 3), group, axis=2)
    v = jnp.repeat(vc.transpose(0, 2, 1, 3), group, axis=2)
    q_pos = idx + jnp.arange(s)[:, None]
    k_pos = jnp.arange(length)[None, :]
    mask = k_pos <= q_pos
    if window is not None:
        mask = mask & (k_pos > q_pos - window)
    return dot_product_attention(q, k, v, mask=mask[None, None])


class TestKernelParity:
    B, L, NKV, H = 2, 64, 2, 16

    def _rand(self, rng, *shape):
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    @pytest.mark.parametrize(
        "s,idx,group,window,block_k",
        [
            (1, 17, 1, None, None),     # single-token MHA decode
            (1, 0, 1, None, None),      # first token
            (1, 33, 3, None, 16),       # GQA decode, multi-block
            (5, 20, 1, None, 16),       # chunked prefill
            (7, 30, 2, 16, 16),         # GQA chunk + sliding window
            (1, 40, 1, 8, 8),           # SWA decode: band start skips blocks
            (4, 60, 2, None, None),     # chunk ending at the buffer edge
        ],
    )
    def test_matches_dense(self, rng, s, idx, group, window, block_k):
        n = self.NKV * group
        q = self._rand(rng, self.B, s, n, self.H)
        kc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        vc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        with jax.default_matmul_precision("float32"):
            out = decode_attention(
                q, kc, vc, idx, window=window, block_k=block_k, interpret=True
            )
            ref = _dense_oracle(q, kc, vc, idx, window=window)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    @pytest.mark.parametrize("s,block_q,group", [(7, 4, 1), (9, 2, 2), (16, 8, 1)])
    def test_q_tiling(self, rng, s, block_q, group):
        """Chunks tile over block_q-row grid steps (incl. a non-dividing
        last tile) — what bounds prefill VMEM for long prompts."""
        n = self.NKV * group
        q = self._rand(rng, self.B, s, n, self.H)
        kc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        vc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        with jax.default_matmul_precision("float32"):
            out = decode_attention(
                q, kc, vc, 20, block_k=16, block_q=block_q, interpret=True
            )
            ref = _dense_oracle(q, kc, vc, 20)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_int8_cache(self, rng):
        group, s, idx = 3, 1, 21
        n = self.NKV * group
        q = self._rand(rng, self.B, s, n, self.H)
        kf = rng.normal(size=(self.B, self.NKV, self.L, self.H))
        vf = rng.normal(size=(self.B, self.NKV, self.L, self.H))
        ks = np.abs(kf).max(-1) / 127.0
        vs = np.abs(vf).max(-1) / 127.0
        ki = np.round(kf / ks[..., None]).astype(np.int8)
        vi = np.round(vf / vs[..., None]).astype(np.int8)
        with jax.default_matmul_precision("float32"):
            out = decode_attention(
                q, jnp.asarray(ki), jnp.asarray(vi), idx,
                k_scale=jnp.asarray(ks, jnp.float32),
                v_scale=jnp.asarray(vs, jnp.float32),
                block_k=16, interpret=True,
            )
            ref = _dense_oracle(
                q,
                jnp.asarray(ki * ks[..., None], jnp.float32),
                jnp.asarray(vi * vs[..., None], jnp.float32),
                idx,
            )
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_only_valid_slots_read(self, rng):
        """Slots past index+S can hold ANY garbage without changing the
        output — the behavioral face of 'the tail is never fetched'."""
        q = self._rand(rng, self.B, 1, self.NKV, self.H)
        kc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        vc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        idx = 9
        poison = jnp.full_like(kc, 1e9).at[:, :, : idx + 1].set(kc[:, :, : idx + 1])
        poison_v = jnp.full_like(vc, 1e9).at[:, :, : idx + 1].set(vc[:, :, : idx + 1])
        with jax.default_matmul_precision("float32"):
            clean = decode_attention(q, kc, vc, idx, block_k=8, interpret=True)
            dirty = decode_attention(q, poison, poison_v, idx, block_k=8, interpret=True)
        np.testing.assert_allclose(clean, dirty, atol=1e-6)

    def test_shard_map_wrapper(self, rng, mesh22):
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

        group = 2
        n = self.NKV * group
        q = self._rand(rng, self.B, 1, n, self.H)
        kc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        vc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        fn = make_decode_attn_fn(mesh22, RULES_DP_TP, block_k=16, interpret=True)
        with jax.default_matmul_precision("float32"):
            out = jax.jit(fn)(q, kc, vc, jnp.asarray(25, jnp.int32))
            ref = _dense_oracle(q, kc, vc, 25)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_validation(self, rng):
        q = self._rand(rng, self.B, 1, self.NKV, self.H)
        kc = self._rand(rng, self.B, self.NKV, self.L, self.H)
        with pytest.raises(ValueError, match="k_scale and v_scale"):
            decode_attention(q, kc, kc, 0, k_scale=jnp.ones((self.B, self.NKV, self.L)))
        with pytest.raises(ValueError, match="not divisible"):
            decode_attention(q, kc, kc, 0, block_k=48, interpret=True)

    def test_auto_block_k(self):
        assert auto_block_k(1024) == 256
        assert auto_block_k(64) == 64
        assert auto_block_k(96) == 32
        assert auto_block_k(100) == 100  # no p2 factor ≥ 8 → single block


class TestModelParity:
    """make_generate_fn with decode_attention='blocked' vs 'dense': same
    greedy tokens through prefill + the whole decode loop."""

    def _generate(self, cfg, mesh, prompt, **kw):
        import dataclasses

        import optax

        from learning_jax_sharding_tpu.models.generate import make_generate_fn
        from learning_jax_sharding_tpu.models.transformer import Transformer
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
        from learning_jax_sharding_tpu.training.pipeline import sharded_train_state

        train_cfg = dataclasses.replace(cfg, decode=False)
        x = put(np.asarray(prompt), mesh_sharding(mesh, "data", None))
        state, _ = sharded_train_state(
            Transformer(train_cfg), optax.adamw(3e-4), x,
            {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
        )
        gen = make_generate_fn(cfg, mesh, RULES_DP_TP, max_new_tokens=8, **kw)
        return np.asarray(gen(state.params, prompt))

    @pytest.mark.parametrize(
        "variant",
        ["mha", "gqa_rope", "int8_cache", "window"],
    )
    def test_blocked_matches_dense(self, mesh22, variant):
        import dataclasses

        mods = {
            "mha": {},
            "gqa_rope": dict(num_kv_heads=2, rope=True),
            "int8_cache": dict(kv_cache_dtype=jnp.int8),
            "window": dict(window=16),
        }[variant]
        base = dataclasses.replace(CONFIG_TINY, **mods)
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, base.vocab_size, (4, 12)),
            jnp.int32,
        )
        with jax.default_matmul_precision("float32"):
            dense = self._generate(
                dataclasses.replace(base, decode_attention="dense"),
                mesh22, prompt,
            )
            blocked = self._generate(
                dataclasses.replace(
                    base, decode_attention="blocked", decode_block_k=16
                ),
                mesh22, prompt,
            )
        np.testing.assert_array_equal(dense, blocked)


class TestFoldedWriteEnable:
    """``write_enable``: a frozen row (zero chunk length in a mixed ragged
    batch) must leave its cache buffers BIT-IDENTICAL through a folded
    write — no garbage token at its un-advanced slot, not even
    transiently (the round-3 advisor finding)."""

    def test_disabled_row_cache_untouched(self):
        rng = np.random.default_rng(3)
        b, n_kv, length, h = 2, 2, 64, 16
        kc = jnp.asarray(rng.normal(size=(b, n_kv, length, h)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(b, n_kv, length, h)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, n_kv, h)), jnp.float32)
        k_new = jnp.asarray(rng.normal(size=(b, n_kv, 1, h)), jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(b, n_kv, 1, h)), jnp.float32)
        idx = jnp.asarray([17, 9], jnp.int32)
        enable = jnp.asarray([1, 0], jnp.int32)

        out, k_out, v_out = decode_attention(
            q, kc, vc, idx, k_new=k_new, v_new=v_new,
            write_enable=enable, block_k=16, interpret=True,
        )
        k_out, v_out = np.asarray(k_out), np.asarray(v_out)
        # Row 0 (enabled): new token lands at its slot, rest unchanged.
        np.testing.assert_array_equal(k_out[0, :, 17], np.asarray(k_new)[0, :, 0])
        np.testing.assert_array_equal(v_out[0, :, 17], np.asarray(v_new)[0, :, 0])
        np.testing.assert_array_equal(k_out[0, :, :17], np.asarray(kc)[0, :, :17])
        # Row 1 (disabled): every buffer bit-identical.
        np.testing.assert_array_equal(k_out[1], np.asarray(kc)[1])
        np.testing.assert_array_equal(v_out[1], np.asarray(vc)[1])
        # Row 0's output equals the dense oracle over the merged cache.
        merged_k = kc.at[0, :, 17].set(k_new[0, :, 0])
        merged_v = vc.at[0, :, 17].set(v_new[0, :, 0])
        ref = _dense_oracle(q[:1], merged_k[:1], merged_v[:1], 17)
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(ref)[0], rtol=1e-5, atol=1e-5
        )

    def test_write_enable_requires_fold(self):
        rng = np.random.default_rng(0)
        kc = jnp.asarray(rng.normal(size=(1, 1, 16, 8)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 8)), jnp.float32)
        with pytest.raises(ValueError, match="write_enable"):
            decode_attention(
                q, kc, kc, 3, write_enable=jnp.ones((1,), jnp.int32),
                interpret=True,
            )


class TestPagedCache:
    """Paged layout: (P, N_kv, page, H) pools indirected through per-row
    block tables. Oracle: bit-identical attention (and folded writes) to
    the contiguous layout holding the same logical contents, for ANY page
    permutation — the table is pure indirection."""

    def _paged_from_contiguous(self, kc, vc, page, rng):
        b, n_kv, L, h = kc.shape
        T = L // page
        P = b * T + 1
        table = rng.permutation(np.arange(1, P)).reshape(b, T)
        pool_k = np.zeros((P, n_kv, page, h), np.float32)
        pool_v = np.zeros((P, n_kv, page, h), np.float32)
        for bi in range(b):
            for t in range(T):
                pool_k[table[bi, t]] = np.asarray(kc)[bi, :, t*page:(t+1)*page]
                pool_v[table[bi, t]] = np.asarray(vc)[bi, :, t*page:(t+1)*page]
        return (
            jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table, jnp.int32),
        )

    @pytest.mark.parametrize("s,group", [(1, 1), (1, 2), (5, 1)])
    def test_read_parity(self, s, group):
        rng = np.random.default_rng(0)
        b, n_kv, page, h, T = 3, 2, 16, 8, 4
        L = T * page
        kc = jnp.asarray(rng.normal(size=(b, n_kv, L, h)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(b, n_kv, L, h)), jnp.float32)
        idx = jnp.asarray([17, 33, 5], jnp.int32)
        q = jnp.asarray(
            rng.normal(size=(b, s, n_kv * group, h)), jnp.float32
        )
        ref = decode_attention(q, kc, vc, idx, block_k=page, interpret=True)
        pk, pv, table = self._paged_from_contiguous(kc, vc, page, rng)
        out = decode_attention(
            q, pk, pv, idx, block_table=table, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6
        )

    def test_folded_write_parity(self):
        rng = np.random.default_rng(1)
        b, n_kv, page, h, T = 2, 2, 16, 8, 4
        L = T * page
        kc = jnp.asarray(rng.normal(size=(b, n_kv, L, h)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(b, n_kv, L, h)), jnp.float32)
        idx = jnp.asarray([17, 9], jnp.int32)
        q = jnp.asarray(rng.normal(size=(b, 1, n_kv, h)), jnp.float32)
        k_new = jnp.asarray(rng.normal(size=(b, n_kv, 1, h)), jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(b, n_kv, 1, h)), jnp.float32)
        ref, rk, rv = decode_attention(
            q, kc, vc, idx, k_new=k_new, v_new=v_new, block_k=page,
            interpret=True,
        )
        pk, pv, table = self._paged_from_contiguous(kc, vc, page, rng)
        out, ok, ov = decode_attention(
            q, pk, pv, idx, k_new=k_new, v_new=v_new, block_table=table,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6
        )
        ok, ov = np.asarray(ok), np.asarray(ov)
        tbl = np.asarray(table)
        for bi in range(b):
            i = int(idx[bi])
            t, o = i // page, i % page
            np.testing.assert_array_equal(
                ok[tbl[bi, t], :, o], np.asarray(rk)[bi, :, i]
            )
            np.testing.assert_array_equal(
                ov[tbl[bi, t], :, o], np.asarray(rv)[bi, :, i]
            )

    def test_block_k_mismatch_rejected(self):
        rng = np.random.default_rng(0)
        pk = jnp.zeros((5, 1, 16, 8), jnp.float32)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 8)), jnp.float32)
        table = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="page"):
            decode_attention(
                q, pk, pk, 3, block_table=table, block_k=8, interpret=True
            )
