"""Fleet serving (round 11): router, disaggregated handoff, failover.

Named to sort LAST in the suite alongside ``test_zero_downtime`` (same
rationale as that file): the end-to-end oracles build several engine
replicas each, and the tier-1 window should spend its budget on the
faster oracles first.

Four layers, cheapest first:

* the KV TRANSFER PLAN as pure redistribution algebra — cross-mesh
  reshard round-trips, page streaming, valid-length clipping (no
  engines, milliseconds after device bring-up);
* the LABELED registry merge + snapshot Prometheus renderer (pure
  dicts);
* ROUTER POLICY — placement under burn-rate skew, fleet-level shedding
  above the replicas' own bounds;
* the END-TO-END oracles: a disaggregated 2-prefill + 2-decode fleet on
  (1,2) sub-meshes of the emulated 8-device mesh produces token streams
  BIT-IDENTICAL to a single engine of the same mesh shape — greedy AND
  sampled — and a replica kill mid-stream reroutes its work (visible as
  ``rerouted``) to survivors that recompute it bit-identically.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.fleet import (
    FleetPolicy,
    FleetRouter,
    execute_transfer,
    make_replicas,
    plan_transfer,
    replicated_params,
    sub_meshes,
    transfer_tree,
)
from learning_jax_sharding_tpu.models.serving import (
    AdmissionError,
    ContinuousEngine,
    RequestFailure,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel import build_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.parallel.multihost import (
    merge_registry_snapshots,
)
from learning_jax_sharding_tpu.robustness import ChaosInjector, Fault
from learning_jax_sharding_tpu.telemetry.flight_recorder import FlightRecorder


@pytest.fixture(scope="module")
def served():
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), np.zeros((2, 8), np.int32)
        )["params"]
    )
    rng = np.random.default_rng(11)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in (5, 9, 4, 7)
    ]
    return cfg, params, prompts


def _baseline(cfg, params, prompts, *, temperature=0.0, rng=None):
    """The single-engine oracle on a (1,2) sub-mesh — the SAME mesh
    shape every fleet replica uses, so programs (and ulps) match."""
    mesh = build_mesh((1, 2), ("data", "model"), devices=jax.devices()[:2])
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=4,
        refill_chunk=8, temperature=temperature,
    )
    return eng.serve(replicated_params(params, mesh), prompts, rng=rng)


class TestTransferPlan:
    def test_cross_mesh_reshard_round_trips(self):
        devs = jax.devices()
        m_a = build_mesh((1, 2), ("data", "model"), devices=devs[:2])
        m_b = build_mesh((1, 4), ("data", "model"), devices=devs[4:])
        x = np.arange(16 * 4 * 8, dtype=np.float32).reshape(16, 4, 8)
        xa = jax.device_put(x, NamedSharding(m_a, P(None, "model", None)))
        dst = NamedSharding(m_b, P(None, None, "model"))
        plan = plan_transfer(
            x.shape, 4, xa.sharding, dst, seq_dim=0, page_tokens=8,
        )
        out, stats = execute_transfer(plan, xa)
        np.testing.assert_array_equal(np.asarray(out), x)
        assert out.sharding == dst
        # Every element crossed exactly once: full-row volume.
        assert stats["bytes"] == x.nbytes == plan.bytes_total
        # ... and came back bit-identically through the reverse plan.
        back, _ = execute_transfer(
            plan_transfer(
                x.shape, 4, out.sharding,
                NamedSharding(m_a, P(None, "model", None)), seq_dim=0,
            ),
            out,
        )
        np.testing.assert_array_equal(np.asarray(back), x)

    def test_stop_clips_pages_and_counts_less(self):
        devs = jax.devices()
        m_a = build_mesh((1, 2), ("data", "model"), devices=devs[:2])
        m_b = build_mesh((1, 2), ("data", "model"), devices=devs[2:4])
        x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
        xa = jax.device_put(x, NamedSharding(m_a, P(None, "model")))
        plan = plan_transfer(
            x.shape, 4, xa.sharding, NamedSharding(m_b, P(None, "model")),
            seq_dim=0, page_tokens=4,
        )
        out, stats = execute_transfer(plan, xa, stop=5)
        got = np.asarray(out)
        np.testing.assert_array_equal(got[:5], x[:5])
        # Pages past the straddling one never crossed; their region is 0.
        assert np.all(got[8:] == 0)
        assert stats["segments_skipped"] > 0
        assert stats["bytes"] < x.nbytes

    def test_replication_is_priced_per_destination_copy(self):
        devs = jax.devices()
        m_a = build_mesh((1, 2), ("data", "model"), devices=devs[:2])
        m_b = build_mesh((1, 2), ("data", "model"), devices=devs[2:4])
        x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
        xa = jax.device_put(x, NamedSharding(m_a, P(None, "model")))
        # Sharded → fully REPLICATED: each of the two destination
        # devices needs the whole array — twice the wire bytes.
        plan = plan_transfer(
            x.shape, 4, xa.sharding, NamedSharding(m_b, P()), seq_dim=0,
        )
        out, stats = execute_transfer(plan, xa)
        np.testing.assert_array_equal(np.asarray(out), x)
        assert stats["bytes"] == 2 * x.nbytes

    def test_transfer_tree_handles_scalars_and_caches_plans(self):
        devs = jax.devices()
        m_a = build_mesh((1, 2), ("data", "model"), devices=devs[:2])
        m_b = build_mesh((1, 2), ("data", "model"), devices=devs[2:4])
        x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
        tree = {
            "k": jax.device_put(x, NamedSharding(m_a, P(None, "model"))),
            "idx": jax.device_put(
                jnp.int32(7), NamedSharding(m_a, P())
            ),
        }
        dst = {
            "k": NamedSharding(m_b, P(None, "model")),
            "idx": NamedSharding(m_b, P()),
        }
        cache: dict = {}
        out, stats = transfer_tree(tree, dst, stop=8, plan_cache=cache)
        assert int(out["idx"]) == 7
        np.testing.assert_array_equal(np.asarray(out["k"]), x)
        n_plans = len(cache)
        out2, _ = transfer_tree(tree, dst, stop=8, plan_cache=cache)
        assert len(cache) == n_plans   # replayed, not re-planned
        np.testing.assert_array_equal(np.asarray(out2["k"]), x)


class TestLabeledMerge:
    SNAPS = [
        {"c_total": 3.0, "g": 2.0, "g__high_water": 5.0,
         "h": {"buckets": [1.0], "counts": [1, 2], "sum": 0.5, "count": 2}},
        {"c_total": 4.0, "g": 1.0, "g__high_water": 7.0,
         "h": {"buckets": [1.0], "counts": [0, 1], "sum": 2.0, "count": 1}},
    ]

    def test_unlabeled_path_bit_compatible(self):
        merged = merge_registry_snapshots(self.SNAPS)
        labeled = merge_registry_snapshots(
            self.SNAPS, labels=["a", "b"]
        )
        for k, v in merged.items():
            assert labeled[k] == v     # the sums are untouched
        assert merged["c_total"] == 7.0
        assert merged["g__high_water"] == 7.0
        assert merged["h"]["counts"] == [1, 3]

    def test_labels_add_per_source_series(self):
        labeled = merge_registry_snapshots(self.SNAPS, labels=["a", "b"])
        assert labeled['c_total{replica="a"}'] == 3.0
        assert labeled['c_total{replica="b"}'] == 4.0
        assert labeled['h{replica="b"}']["count"] == 1
        # Labeled histograms are COPIES: mutating the merge must not
        # reach back into the source snapshot.
        labeled['h{replica="a"}']["counts"][0] = 99
        assert self.SNAPS[0]["h"]["counts"][0] == 1

    def test_label_count_mismatch_raises(self):
        with pytest.raises(ValueError, match="labels"):
            merge_registry_snapshots(self.SNAPS, labels=["only-one"])

    def test_prometheus_renderer_carries_labels(self):
        from learning_jax_sharding_tpu.telemetry.registry import (
            snapshot_prometheus_text,
        )

        text = snapshot_prometheus_text(
            merge_registry_snapshots(self.SNAPS, labels=["a", "b"])
        )
        assert 'c_total{replica="a"} 3' in text
        assert "c_total 7" in text
        assert 'h_bucket{replica="b",le="1"} 0' in text
        assert 'h_bucket{le="+Inf"} 3' in text
        assert "g_high_water 7" in text


class TestRouterPolicy:
    def _fleet(self, served, n=2, *, slos=False, **kw):
        from learning_jax_sharding_tpu.telemetry.slo import (
            SLOMonitor,
            SLOTarget,
        )

        cfg, params, _ = served
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=n, mesh_shape=(1, 1),
            batch_size=2, max_new_tokens=4, refill_chunk=8, **kw,
        )
        if slos:
            for r in reps:
                r.engine.slo = SLOMonitor(
                    [SLOTarget("ttft", 0.5, objective=0.5)],
                )
        return reps

    def test_routes_around_burn_rate_skew(self, served):
        rec = FlightRecorder()
        reps = self._fleet(served, slos=True)
        # Replica unified0 is burning error budget hard; unified1 is
        # clean. Every placement must land on unified1 even though both
        # are equally idle.
        for _ in range(32):
            reps[0].engine.slo.observe("ttft", 99.0)
        router = FleetRouter(reps, recorder=rec)
        cfg, params, prompts = served
        for p in prompts[:2]:
            router.add_request(p)
        routed = [e["replica"] for e in rec.events("fleet.route")]
        assert routed == ["unified1", "unified1"], routed
        router.drain(max_steps=200)

    def test_fleet_level_shedding_bounds_inflight(self, served):
        reps = self._fleet(served)
        router = FleetRouter(
            reps, policy=FleetPolicy(max_inflight=2),
        )
        cfg, params, prompts = served
        router.add_request(prompts[0])
        router.add_request(prompts[1])
        with pytest.raises(AdmissionError, match="max_inflight"):
            router.add_request(prompts[2])
        assert router.registry.counter("fleet_shed_total").value == 1
        out = router.drain(max_steps=200)
        assert set(out) == {0, 1}

    def test_all_replicas_refusing_sheds_at_fleet_level(self, served):
        # Replica-level bounds (max_queue=1, batch_size fills): once
        # every replica's own admission refuses, the FLEET sheds — the
        # arrival is never half-enqueued anywhere.
        reps = self._fleet(served, max_queue=1)
        router = FleetRouter(reps)
        cfg, params, prompts = served
        for _ in range(2 * (2 + 1)):   # fill both queues past bound
            try:
                router.add_request(prompts[0])
            except AdmissionError:
                break
        with pytest.raises(AdmissionError, match="every replica refused"):
            router.add_request(prompts[1])
        assert router.registry.counter("fleet_shed_total").value >= 1
        router.drain(max_steps=400)

    def test_validation(self, served):
        cfg, params, prompts = served
        reps = self._fleet(served)
        with pytest.raises(ValueError, match="unique"):
            FleetRouter([reps[0], reps[0]])
        with pytest.raises(ValueError, match="at least one replica"):
            FleetRouter([])
        with pytest.raises(ValueError, match="max_inflight"):
            FleetPolicy(max_inflight=0)
        with pytest.raises(ValueError, match="prefill"):
            make_replicas(
                cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
                role="prefill", batch_size=2, max_new_tokens=4,
            )
        with pytest.raises(ValueError, match="role"):
            make_replicas(
                cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
                role="router", batch_size=2, max_new_tokens=4,
            )
        # A disaggregated fleet needs both halves.
        pre = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            role="prefill", batch_size=2, max_new_tokens=1,
        )
        with pytest.raises(ValueError, match="decode"):
            FleetRouter(pre)
        # Unified replicas must agree on the generation budget, or a
        # failover requeue could not recompute bit-identically.
        mixed = self._fleet(served) + make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            prefix="odd", batch_size=2, max_new_tokens=8,
        )
        with pytest.raises(ValueError, match="disagree on max_new"):
            FleetRouter(mixed)


def _disagg_fleet(cfg, params, *, temperature=0.0, rng_key=None):
    pre = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
        role="prefill", batch_size=2, max_new_tokens=1, refill_chunk=8,
        temperature=temperature,
    )
    dec = make_replicas(
        cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
        role="decode", offset=4, batch_size=2, max_new_tokens=4,
        refill_chunk=8, temperature=temperature,
    )
    if rng_key is not None:
        for r in pre + dec:
            r.engine.rng = rng_key
    return pre, dec, FleetRouter(pre + dec)


class TestDisaggregatedHandoff:
    def test_greedy_bit_identical_to_single_engine(self, served):
        cfg, params, prompts = served
        ref = _baseline(cfg, params, prompts)
        pre, dec, router = _disagg_fleet(cfg, params)
        for i, p in enumerate(prompts):
            router.add_request(p, rid=i)
        out = router.drain(max_steps=400)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(out[i], ref[i])
        # Telemetry: every handed-off request streamed counted KV bytes.
        handoffs = router.registry.counter("fleet_handoffs_total").value
        assert handoffs == len(prompts)
        assert router.registry.counter(
            "fleet_kv_transfer_bytes_total"
        ).value > 0
        assert router.registry.counter(
            "fleet_kv_transfer_segments_total"
        ).value >= handoffs
        for r in dec:
            n = r.engine.registry.counter("engine_kv_ingests_total").value
            assert n > 0   # the policy spread work over both decoders

    def test_sampled_bit_identical_to_single_engine(self, served):
        cfg, params, prompts = served
        key = jax.random.key(0)
        ref = _baseline(
            cfg, params, prompts, temperature=0.8, rng=key
        )
        pre, dec, router = _disagg_fleet(
            cfg, params, temperature=0.8, rng_key=key
        )
        for i, p in enumerate(prompts):
            router.add_request(p, rid=i)
        out = router.drain(max_steps=400)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(out[i], ref[i])

    def test_blocked_backend_handoff_bit_identical(self, served):
        """The TPU default decode backend ('blocked') caches rows
        HEAD-major (n_kv, S, h): the transfer plan must clip the real
        sequence dim (kv_row_seq_dims derives it from the layout), not
        assume dim 0 — a hard-coded dim-0 clip would truncate KV heads
        and hand the decode replica zeroed heads. Short prompts
        (length < n_kv) are the sharpest probe."""
        cfg, params, prompts = served
        bcfg = dataclasses.replace(cfg, decode_attention="blocked")
        short = [np.asarray([3, 5], np.int32)] + prompts[:2]
        mesh = build_mesh(
            (1, 2), ("data", "model"), devices=jax.devices()[:2]
        )
        eng = ContinuousEngine(
            bcfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=4,
            refill_chunk=8,
        )
        ref = eng.serve(replicated_params(params, mesh), short)
        pre = make_replicas(
            bcfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="prefill", batch_size=2, max_new_tokens=1,
            refill_chunk=8,
        )
        dec = make_replicas(
            bcfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="decode", offset=4, batch_size=2, max_new_tokens=4,
            refill_chunk=8,
        )
        router = FleetRouter(pre + dec)
        for i, p in enumerate(short):
            router.add_request(p, rid=i)
        out = router.drain(max_steps=300)
        for i in range(len(short)):
            np.testing.assert_array_equal(out[i], ref[i])
        dims = dec[0].engine.kv_row_seq_dims()
        assert 1 in jax.tree.leaves(dims)   # head-major rows detected

    def test_handoff_rows_match_decode_row_layout(self, served):
        # The transfer plan's destination IS the decode cache's own row
        # layout (kv_row_shardings), which is what makes kv_ingest the
        # purely local update its golden pins.
        cfg, params, prompts = served
        pre, dec, router = _disagg_fleet(cfg, params)
        router.add_request(prompts[0], rid=0)
        router.drain(max_steps=200)
        eng = next(
            r.engine for r in dec
            if r.engine.registry.counter("engine_kv_ingests_total").value
        )
        args = eng._last_kv_ingest_args()
        rows, shardings = args[1], eng.kv_row_shardings()
        jax.tree.map(
            lambda x, s: None if x.sharding == s else pytest.fail(
                f"ingested row sharding {x.sharding} != cache row {s}"
            ),
            rows, shardings,
        )
        progs = [name for name, *_ in eng._dispatched_programs()]
        assert "kv_ingest" in progs
        assert eng.compile_counts()["kv_ingest"] == 1


class TestFailover:
    def test_kill_mid_stream_reroutes_bit_identically(self, served):
        cfg, params, prompts = served
        ref = _baseline(cfg, params, prompts)
        rec = FlightRecorder()
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
            batch_size=2, max_new_tokens=4, refill_chunk=8, recorder=rec,
        )
        router = FleetRouter(reps, recorder=rec)
        with ChaosInjector(
            Fault("fleet.step", "raise", at=2, count=1), recorder=rec,
        ):
            for i, p in enumerate(prompts):
                router.add_request(p, rid=i)
            out = router.drain(max_steps=400)
        dead = [r for r in reps if not r.alive]
        assert len(dead) == 1
        for i in range(len(prompts)):
            assert not isinstance(out[i], RequestFailure), out[i]
            np.testing.assert_array_equal(out[i], ref[i])
        # The failover is VISIBLE: the dead replica retired its work as
        # "rerouted" (never a silent drop, never a fake fresh admission),
        # and the router logged the decision chain.
        assert dead[0].engine.registry.counter(
            "engine_rerouted_total"
        ).value >= 1
        assert rec.events("fleet.failover")
        assert any(
            e["requeue"] for e in rec.events("fleet.route")
        )
        assert router.registry.counter("fleet_reroutes_total").value >= 1
        lat = router.latency_stats()
        assert lat["reroutes"] >= 1 and lat["ok"] == len(prompts)

    def test_losing_every_replica_is_terminal_not_silent(self, served):
        cfg, params, prompts = served
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            batch_size=2, max_new_tokens=4, refill_chunk=8,
        )
        router = FleetRouter(reps)
        router.add_request(prompts[0], rid=0)
        router.step()
        router.kill_replica("unified0")
        out = router.pop_finished()
        assert isinstance(out[0], RequestFailure)
        # NOT "rerouted" — that status is the ignorable internal requeue
        # marker; a request the fleet actually lost wears its own.
        assert out[0].status == "failover_failed"
        assert not router.has_work()
        # ... and the loss is NOT an admission shed: a shed-rate
        # dashboard must not misread replica-death losses as overload.
        assert router.registry.counter("fleet_shed_total").value == 0

    def test_killing_last_decode_replica_terminates(self, served):
        """A disaggregated fleet that loses its only decode replica must
        TERMINATE every affected request ("failover_failed"), not park
        re-prefilled handoffs forever while drain() spins."""
        cfg, params, prompts = served
        pre = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="prefill", batch_size=2, max_new_tokens=1,
            refill_chunk=8,
        )
        dec = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="decode", offset=2, batch_size=2, max_new_tokens=4,
            refill_chunk=8,
        )
        router = FleetRouter(pre + dec)
        for i, p in enumerate(prompts):
            router.add_request(p, rid=i)
        while not dec[0].engine.has_work():
            router.step()          # until at least one handoff ingested
        router.kill_replica("decode0")
        out = router.drain(max_steps=300)   # must terminate, not wedge
        assert set(out) == set(range(len(prompts)))
        failed = [
            v for v in out.values() if isinstance(v, RequestFailure)
        ]
        assert failed and all(
            f.status == "failover_failed" for f in failed
        )

    def test_degraded_decode_replica_still_serves_accepted_work(
        self, served
    ):
        """A decode replica degraded to SHEDDING still takes handoffs:
        level 3 sheds NEW fleet admissions (the prefill pool's own
        add_request), never work the fleet already accepted — and an
        idle degraded replica could not de-escalate anyway (no traffic
        freezes its burn window), so gating handoffs on the ladder
        would wedge accepted requests forever."""
        from learning_jax_sharding_tpu.robustness import DegradationLadder

        cfg, params, prompts = served
        pre = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="prefill", batch_size=2, max_new_tokens=1,
            refill_chunk=8,
        )
        dec = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="decode", offset=2, batch_size=2, max_new_tokens=4,
            refill_chunk=8,
        )
        ladder = DegradationLadder()
        ladder.level = 3             # shedding — but the replica LIVES
        dec[0].engine._ladder = ladder
        router = FleetRouter(pre + dec)
        ref = _baseline(cfg, params, prompts[:2])
        for i, p in enumerate(prompts[:2]):
            router.add_request(p, rid=i)
        out = router.drain(max_steps=200)
        for i in range(2):
            np.testing.assert_array_equal(out[i], ref[i])

    def test_handoff_backpressure_and_parked_deadline(self, served):
        """A congested decode side must not grow the handoff queue
        without bound (each entry pins an exported KV-row tree): past
        ``max_pending_handoffs`` the router stops stepping prefill
        replicas. And the round-10 TTL holds in the handoff stage — a
        request that expires while parked fails with ``"deadline"``
        BEFORE paying the transfer or a decode slot."""
        cfg, params, prompts = served
        pre = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="prefill", batch_size=2, max_new_tokens=1,
            refill_chunk=8,
        )
        dec = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 2),
            role="decode", offset=2, batch_size=1, max_new_tokens=4,
            refill_chunk=8,
        )
        router = FleetRouter(pre + dec, max_pending_handoffs=1)
        for i, p in enumerate(prompts):
            router.add_request(p, rid=i, deadline_s=120.0)
        out: dict = {}
        high_water = 0
        steps = 0
        aged = None
        while router.has_work():
            router.step()
            out.update(router.pop_finished())
            high_water = max(high_water, len(router._handoffs))
            if aged is None and router._handoffs:
                # Age one parked request past its TTL (white-box: the
                # wall clock is too coarse to race reliably).
                freq = router._handoffs[0]["freq"]
                freq.arrival_t -= 121.0
                aged = freq.rid
            steps += 1
            assert steps < 400, "fleet wedged"
        out.update(router.pop_finished())
        assert high_water <= 1            # the bound held
        assert aged is not None
        assert isinstance(out[aged], RequestFailure)
        assert out[aged].status == "deadline"
        done = [r for r, v in out.items()
                if not isinstance(v, RequestFailure)]
        assert len(done) == len(prompts) - 1   # the rest completed

    def test_eos_must_agree_across_replicas(self, served):
        cfg, params, prompts = served
        a = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            batch_size=2, max_new_tokens=4,
        )
        b = make_replicas(
            dataclasses.replace(cfg, dtype=jnp.float32),
            RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            prefix="b", offset=1, batch_size=2, max_new_tokens=4,
            eos_id=7,
        )
        with pytest.raises(ValueError, match="eos"):
            FleetRouter(a + b)

    def test_finished_requests_do_not_accumulate(self, served):
        """The canonical request records must hold only LIVE work —
        inflight() runs on every admission/step, and retained prompts
        would grow with every request the fleet has ever served."""
        cfg, params, prompts = served
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=1, mesh_shape=(1, 1),
            batch_size=2, max_new_tokens=4, refill_chunk=8,
        )
        router = FleetRouter(reps)
        for _ in range(3):
            for p in prompts[:2]:
                router.add_request(p)
            router.drain(max_steps=200)
        assert router._requests == {}
        assert router.inflight() == 0


class TestGoodputTracing:
    """Round 14: the fleet's wall-clock ledgers and request traces.

    Every replica ledger must RECONCILE over a served window; every
    retired request must carry a COMPLETE critical path whose trace id
    was minted once at router admission and survived every hop — the KV
    handoff, a mid-stream replica kill's reroute, and a rolling weight
    swap's version pin."""

    def test_disagg_ledgers_reconcile_and_paths_complete(self, served):
        cfg, params, prompts = served
        pre, dec, router = _disagg_fleet(cfg, params)
        minted = {}
        for i, p in enumerate(prompts):
            router.add_request(p, rid=i)
            minted[i] = router.traces.trace_of(i)
        assert len(set(minted.values())) == len(prompts)
        out = router.drain(max_steps=400)
        assert sorted(out) == list(range(len(prompts)))

        rep = router.goodput_report()
        assert rep["reconcile_ok"], {
            n: r["reconcile"] for n, r in rep["replicas"].items()
        }
        assert rep["fleet_buckets"]["device"] > 0.0
        assert rep["fleet_buckets"]["kv_handoff"] > 0.0
        assert rep["host_share"] is not None and 0 < rep["host_share"] <= 1

        cps = {cp["rid"]: cp for cp in router.traces.completed()}
        assert sorted(cps) == list(range(len(prompts)))
        for i, cp in cps.items():
            assert cp["trace_id"] == minted[i]      # the id never changed
            assert cp["status"] == "ok"
            for stage in ("queue", "prefill", "handoff", "decode"):
                assert cp["stages"].get(stage, 0.0) > 0.0, (i, stage, cp)
            assert cp["ttft_s"] is not None and cp["ttft_s"] > 0.0
        # The merged Perfetto timeline carries both engine-dispatch
        # tracks and the request tracks on one clock.
        doc = router.merged_chrome_trace()
        names = {
            e["args"]["name"] for e in doc["traceEvents"]
            if e["ph"] == "M" and "name" in e["args"]
        }
        assert {"replica prefill0", "replica decode0"} <= names
        assert any(n.startswith("requests: ") for n in names)
        prom = router.prometheus_text()
        assert 'ledger_seconds_total{bucket="device",replica="' in prom
        assert 'trace_stage_seconds_bucket{stage="handoff"' in prom

    def test_trace_id_survives_a_mid_stream_reroute(self, served):
        cfg, params, prompts = served
        rec = FlightRecorder()
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 2),
            batch_size=2, max_new_tokens=4, refill_chunk=8, recorder=rec,
        )
        router = FleetRouter(reps, recorder=rec)
        with ChaosInjector(
            Fault("fleet.step", "raise", at=2, count=1), recorder=rec,
        ):
            minted = {}
            for i, p in enumerate(prompts):
                router.add_request(p, rid=i)
                minted[i] = router.traces.trace_of(i)
            out = router.drain(max_steps=400)
        dead = [r for r in reps if not r.alive]
        assert len(dead) == 1
        assert not any(
            isinstance(v, RequestFailure) for v in out.values()
        )
        cps = {cp["rid"]: cp for cp in router.traces.completed()}
        assert sorted(cps) == list(range(len(prompts)))
        rerouted = [cp for cp in cps.values() if cp["reroutes"] >= 1]
        assert rerouted, "the kill must mark at least one trace rerouted"
        for cp in cps.values():
            # SAME trace id end to end: the reroute appended spans and a
            # marker to the existing trace, it minted nothing new.
            assert cp["trace_id"] == minted[cp["rid"]]
            assert cp["status"] == "ok"
        for cp in rerouted:
            r = router.traces.record(cp["rid"])
            replicas = {s["replica"] for s in r["spans"]}
            assert dead[0].name in replicas          # the wasted legs
            assert len(replicas - {dead[0].name}) >= 1   # the survivor's
            assert any(s["attrs"].get("wasted") for s in r["spans"])
            assert cp["wasted_s"] >= 0.0
            (ev,) = [e for e in r["events"] if e["name"] == "reroute"]
            assert ev["replica"] == dead[0].name
        # The fleet still accounts 100% of its (surviving) wall.
        rep = router.goodput_report()
        assert rep["reconcile_ok"]

    def test_trace_pins_rolling_swap_versions(self, served):
        cfg, params, prompts = served
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 1),
            batch_size=2, max_new_tokens=4, refill_chunk=4,
        )
        router = FleetRouter(reps)
        # Oversubscribe on purpose: the version pin lands on requests
        # QUEUED at commit time (in-flight rows finish on the old
        # version in drain mode), so the queues must outlast the slots.
        queue = list(prompts) * 3
        minted = {}
        for i, p in enumerate(queue):
            router.add_request(p, rid=i)
            minted[i] = router.traces.trace_of(i)
        router.step()
        new_params = jax.tree.map(lambda x: x * 1.02, params)
        timeline = router.rolling_swap(new_params, version=5)
        assert [t["committed"] for t in timeline] == [True, True]
        out = router.drain(max_steps=600)
        assert sorted(out) == list(range(len(queue)))
        cps = {cp["rid"]: cp for cp in router.traces.completed()}
        assert sorted(cps) == list(range(len(queue)))
        pinned = [cp for cp in cps.values() if cp["swap_pins"]]
        assert pinned, "a queued request must carry the commit's pin"
        for cp in pinned:
            assert set(cp["swap_pins"]) == {5}
            assert cp["trace_id"] == minted[cp["rid"]]
        versions = {}
        for rep in reps:
            versions.update(rep.engine.finished_versions)
        # Pinned traces really were served on the new weights.
        assert all(versions[cp["rid"]] == 5 for cp in pinned)
