"""Hybrid ICI×DCN meshes and held-out evaluation.

Both absent from the reference (single-slice emulated meshes only; no eval —
its train_step discards even the training loss, SURVEY.md §5).
"""

import jax
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.data.datasets import SyntheticLMDataset
from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY, Transformer
from learning_jax_sharding_tpu.parallel import build_hybrid_mesh
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.loop import evaluate
from learning_jax_sharding_tpu.training.pipeline import sharded_train_state


class TestHybridMesh:
    def test_slice_major_layout(self):
        """2 slices × 4 chips, DP across slices / TP within: the data axis
        must vary across slices (device index blocks under the emulated
        fallback), the model axis within one slice."""
        mesh = build_hybrid_mesh(ici_shape=(1, 4), dcn_shape=(2, 1))
        assert dict(mesh.shape) == {"data": 2, "model": 4}
        ids = np.vectorize(lambda d: d.id)(mesh.devices)
        # Row r (slice r) holds ids [4r .. 4r+3] — in-slice devices contiguous.
        np.testing.assert_array_equal(ids, [[0, 1, 2, 3], [4, 5, 6, 7]])

    def test_mixed_axis_interleaving(self):
        """dcn=(2,1) × ici=(2,2): each mesh axis merges its (dcn, ici) pair
        slice-major."""
        mesh = build_hybrid_mesh(ici_shape=(2, 2), dcn_shape=(2, 1))
        assert dict(mesh.shape) == {"data": 4, "model": 2}
        ids = np.vectorize(lambda d: d.id)(mesh.devices)
        # Slice 0 = ids 0-3 (rows 0-1), slice 1 = ids 4-7 (rows 2-3).
        np.testing.assert_array_equal(ids, [[0, 1], [2, 3], [4, 5], [6, 7]])

    def test_device_count_must_match_exactly(self):
        with pytest.raises(ValueError, match="exactly"):
            build_hybrid_mesh(ici_shape=(1, 2), dcn_shape=(2, 1))

    def test_rank_mismatch_rejected(self):
        with pytest.raises(ValueError, match="rank"):
            build_hybrid_mesh(ici_shape=(1, 2, 1), dcn_shape=(2, 1))

    def test_trains_like_any_mesh(self, rng):
        """A hybrid mesh is a normal Mesh: the sharded pipeline runs on it."""
        from learning_jax_sharding_tpu.models.transformer import next_token_loss
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.training.pipeline import make_train_step

        mesh = build_hybrid_mesh(ici_shape=(1, 4), dcn_shape=(2, 1))
        cfg = CONFIG_TINY
        model = Transformer(cfg)
        tokens = rng.integers(0, cfg.vocab_size, size=(4, 17)).astype(np.int32)
        sh = mesh_sharding(mesh, "data", None)
        batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
        state, state_sh = sharded_train_state(
            model, optax.adamw(1e-3), batch["inputs"],
            {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
        )
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
            RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
        )
        _, loss = step(state, batch)
        assert np.isfinite(float(loss))


class TestEvaluate:
    def test_loss_and_perplexity(self, mesh22):
        cfg = CONFIG_TINY
        model = Transformer(cfg)
        data = SyntheticLMDataset(vocab_size=cfg.vocab_size, seq_len=16, seed=0)
        state, _ = sharded_train_state(
            model, optax.adamw(1e-3),
            jax.device_put(
                np.zeros((4, 16), np.int32),
                jax.sharding.NamedSharding(mesh22, jax.sharding.PartitionSpec("data")),
            ),
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        out = evaluate(
            state, data, mesh22, RULES_DP_TP, batch_size=4, num_batches=3,
        )
        assert out["batches"] == 3
        assert np.isfinite(out["loss"])
        # Untrained model ≈ uniform: loss near log(V), perplexity near V.
        assert out["loss"] == pytest.approx(np.log(cfg.vocab_size), rel=0.15)
        assert out["perplexity"] == pytest.approx(np.exp(out["loss"]), rel=1e-6)

    def test_zero_batches_rejected(self, mesh22):
        with pytest.raises(ValueError, match="at least one"):
            evaluate(
                None, SyntheticLMDataset(vocab_size=16, seq_len=8, seed=0),
                mesh22, RULES_DP_TP, batch_size=4, num_batches=0,
            )
