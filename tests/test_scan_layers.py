"""Scanned layer stacks: one nn.scan'd block vs N unrolled blocks.

The reference unrolls nothing (its deepest model is ONE attention module,
`/root/reference/case6_attention.py:42-143`); a real framework trains deep
stacks, where per-layer unrolling costs compile time linear in depth. The
``scan_layers`` path compiles the block body once and stacks params along a
leading ``LAYERS`` dim. These tests pin the three contracts that make it safe
to flip on:

* **math parity** — with identical weights, scan and loop produce the same
  logits (and the same loss);
* **sharding parity** — stacked kernels keep their per-layer specs with the
  layer dim whole (``LAYERS`` is unmapped in every rule set);
* **composition** — remat (with every named policy), MoE aux losses, and the
  sharded train-step pipeline all run under the scan.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    CONFIG_TINY_MOE,
    Transformer,
    next_token_loss,
    resolve_remat_policy,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import (
    RULES_DP_TP,
    RULES_DP_TP_EP,
)
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)

CFG_SCAN = dataclasses.replace(CONFIG_TINY, scan_layers=True)


def _tokens(cfg, b=4, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


def _stack_loop_params(loop_params, num_layers):
    """Restructure unrolled ``block_i`` subtrees into the scanned ``blocks``
    stacked layout (leaves gain a leading layer dim)."""
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[loop_params[f"block_{i}"] for i in range(num_layers)],
    )
    rest = {k: v for k, v in loop_params.items() if not k.startswith("block_")}
    return {**rest, "blocks": stacked}


class TestScanStructure:
    def test_params_stacked_with_layers_axis(self):
        model = Transformer(CFG_SCAN)
        boxed = model.init({"params": jax.random.key(0)}, _tokens(CFG_SCAN))
        params = nn.meta.unbox(boxed["params"])
        q = params["blocks"]["attn"]["query"]["kernel"]
        assert q.shape == (
            CFG_SCAN.num_layers,
            CFG_SCAN.features,
            CFG_SCAN.num_heads * CFG_SCAN.head_dim,
        )
        # metadata_params records the new leading axis as LAYERS in the
        # logical names, ahead of the block's own ('embed','heads').
        spec = nn.get_partition_spec(boxed)
        assert spec["params"]["blocks"]["attn"]["query"]["kernel"] == P(
            "layers", "embed", "heads"
        )

    def test_layers_get_distinct_init(self):
        # split_rngs must give each layer its own params stream — identical
        # layers would make the stack depth-1 in disguise.
        model = Transformer(CFG_SCAN)
        params = nn.meta.unbox(
            model.init({"params": jax.random.key(0)}, _tokens(CFG_SCAN))["params"]
        )
        q = params["blocks"]["attn"]["query"]["kernel"]
        assert not np.allclose(np.asarray(q[0]), np.asarray(q[1]))

    def test_decode_mode_rejected(self):
        cfg = dataclasses.replace(CFG_SCAN, decode=True)
        with pytest.raises(ValueError, match="scan_layers"):
            Transformer(cfg).init(
                {"params": jax.random.key(0)}, _tokens(cfg, s=1)
            )


class TestScanParity:
    def test_forward_matches_unrolled(self):
        """Same weights → same logits: stack the loop model's per-block params
        and run them through the scanned model."""
        tok = _tokens(CONFIG_TINY)
        loop = Transformer(CONFIG_TINY)
        loop_params = nn.meta.unbox(
            loop.init({"params": jax.random.key(0)}, tok)["params"]
        )
        scan_params = _stack_loop_params(loop_params, CONFIG_TINY.num_layers)
        y_loop = loop.apply({"params": loop_params}, tok)
        y_scan = Transformer(CFG_SCAN).apply({"params": scan_params}, tok)
        np.testing.assert_allclose(
            np.asarray(y_scan), np.asarray(y_loop), atol=2e-6
        )

    def test_remat_scan_matches_plain_scan(self):
        tok = _tokens(CFG_SCAN)
        params = nn.meta.unbox(
            Transformer(CFG_SCAN).init({"params": jax.random.key(0)}, tok)[
                "params"
            ]
        )
        y_plain = Transformer(CFG_SCAN).apply({"params": params}, tok)
        for policy in (None, "dots", "dots_no_batch"):
            cfg = dataclasses.replace(
                CFG_SCAN, remat=True, remat_policy=policy
            )
            y = Transformer(cfg).apply({"params": params}, tok)
            np.testing.assert_allclose(
                np.asarray(y), np.asarray(y_plain), atol=1e-6
            )

    def test_remat_policy_names(self):
        assert resolve_remat_policy(None) is None
        assert resolve_remat_policy("nothing") is None
        assert resolve_remat_policy("dots") is not None
        with pytest.raises(ValueError, match="remat_policy"):
            resolve_remat_policy("everything")

    def test_config_rejects_orphan_or_bogus_policy(self):
        # A policy without remat=True would be silently ignored; a typo'd
        # name must fail at construction, not deep inside a trace.
        with pytest.raises(ValueError, match="remat=False"):
            dataclasses.replace(CONFIG_TINY, remat_policy="dots")
        with pytest.raises(ValueError, match="remat_policy"):
            dataclasses.replace(CONFIG_TINY, remat=True, remat_policy="dotz")

    @pytest.mark.parametrize("scan", [False, True])
    def test_dropout_under_remat(self, scan):
        # nn.Dropout branches on `deterministic` in Python, so remat must
        # keep it static (static_argnums counts self=0 → deterministic is 2);
        # a mis-aimed argnum traces it and raises TracerBoolConversionError.
        cfg = dataclasses.replace(
            CONFIG_TINY, scan_layers=scan, remat=True, dropout_rate=0.1
        )
        tok = _tokens(cfg)
        model = Transformer(cfg)
        params = nn.meta.unbox(
            model.init({"params": jax.random.key(0)}, tok)["params"]
        )
        y = jax.jit(
            lambda p, t: model.apply(
                {"params": p}, t, deterministic=False,
                rngs={"dropout": jax.random.key(1)},
            )
        )(params, tok)
        assert np.isfinite(np.asarray(y, np.float32)).all()


class TestScanShardedTraining:
    def _batch(self, mesh, cfg, b=8, s=32):
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(
            np.int32
        )
        sh = mesh_sharding(mesh, "data", None)
        return {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}

    def test_train_step_runs_and_shards(self, mesh22):
        mesh = mesh22
        cfg = CFG_SCAN
        batch = self._batch(mesh, cfg)
        state, state_sh = sharded_train_state(
            Transformer(cfg), optax.adamw(3e-4), batch["inputs"],
            {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
        )
        # Stacked q kernel: layer dim whole, heads dim over 'model' — the
        # same per-layer spec the unrolled stack gets, shifted right by one.
        q = state.params["blocks"]["attn"]["query"]["kernel"]
        assert q.sharding.spec == P(None, None, "model")
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
            RULES_DP_TP, loss_fn=next_token_loss,
        )
        state, loss = step(state, batch)
        assert np.isfinite(float(loss))

    def test_scan_and_loop_losses_match(self, mesh22):
        """End-to-end check through the full sharded pipeline: seed the scan
        state with the loop state's stacked params → identical first loss."""
        mesh = mesh22
        batch = self._batch(mesh, CONFIG_TINY)
        shardings = {k: v.sharding for k, v in batch.items()}

        def first_loss(cfg, params_override=None):
            state, state_sh = sharded_train_state(
                Transformer(cfg), optax.adamw(3e-4), batch["inputs"],
                {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
            )
            if params_override is not None:
                state = state.replace(params=params_override)
            step = make_train_step(
                state_sh, shardings, mesh, RULES_DP_TP,
                loss_fn=next_token_loss, donate_state=False,
            )
            return state, float(step(state, batch)[1])

        loop_state, loop_loss = first_loss(CONFIG_TINY)
        stacked = _stack_loop_params(
            jax.device_get(loop_state.params), CONFIG_TINY.num_layers
        )
        _, scan_loss = first_loss(CFG_SCAN, params_override=stacked)
        assert scan_loss == pytest.approx(loop_loss, abs=1e-5)

    def test_moe_aux_losses_under_scan(self, mesh22):
        mesh = mesh22
        cfg = dataclasses.replace(CONFIG_TINY_MOE, scan_layers=True)
        batch = self._batch(mesh, cfg)
        state, state_sh = sharded_train_state(
            Transformer(cfg), optax.adamw(3e-4), batch["inputs"],
            {"params": jax.random.key(0)}, mesh, RULES_DP_TP_EP,
        )
        # Expert kernels stack to (L, E, M, H) with E over 'model'.
        up = state.params["blocks"]["moe"]["up"]
        assert up.shape[:2] == (cfg.num_layers, cfg.num_experts)
        assert up.sharding.spec[1] == "model"
        step = make_train_step(
            state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
            RULES_DP_TP_EP, loss_fn=next_token_loss,
            aux_loss_collection="losses",
        )
        state, loss = step(state, batch)
        assert np.isfinite(float(loss))


class TestScanServing:
    """Train-with-scan → serve: the stacked tree unstacks to the unrolled
    layout and drives decode / export unchanged (VERDICT r1 item 5)."""

    def test_unstack_matches_unrolled_apply(self):
        from learning_jax_sharding_tpu.models.convert import (
            stack_scan_params,
            unstack_scan_params,
        )

        model_scan = Transformer(CFG_SCAN)
        tokens = _tokens(CFG_SCAN)
        scanned = nn.meta.unbox(
            model_scan.init({"params": jax.random.key(0)}, tokens)["params"]
        )
        unrolled = unstack_scan_params(scanned)
        # Same weights through the unrolled stack → identical logits.
        want = model_scan.apply({"params": scanned}, tokens)
        got = Transformer(CONFIG_TINY).apply({"params": unrolled}, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
        # Round trip restores the stacked layout exactly.
        restacked = stack_scan_params(unrolled)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            restacked, scanned,
        )
        # Pass-through: already-unrolled / already-stacked trees are no-ops.
        assert unstack_scan_params(unrolled) is unrolled
        assert stack_scan_params(scanned) is scanned

    def test_generate_from_scanned_params(self, mesh22):
        """make_generate_fn on a scan_layers config accepts the STACKED tree
        directly and matches generation from the unrolled layout."""
        from learning_jax_sharding_tpu.models.convert import unstack_scan_params
        from learning_jax_sharding_tpu.models.generate import make_generate_fn

        scanned = nn.meta.unbox(
            Transformer(CFG_SCAN).init(
                {"params": jax.random.key(0)}, _tokens(CFG_SCAN)
            )["params"]
        )
        prompt = _tokens(CFG_SCAN, b=2, s=8, seed=3)
        gen_scan = make_generate_fn(CFG_SCAN, mesh22, RULES_DP_TP, max_new_tokens=6)
        gen_plain = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=6
        )
        out_scan = np.asarray(gen_scan(scanned, prompt))
        out_plain = np.asarray(gen_plain(unstack_scan_params(scanned), prompt))
        np.testing.assert_array_equal(out_scan, out_plain)
        assert out_scan.shape == (2, 14)

    def test_export_scanned_tree(self):
        """HF export unstacks scan_layers trees automatically."""
        pytest.importorskip("torch")
        from learning_jax_sharding_tpu.models.convert import state_dict_from_params

        cfg = dataclasses.replace(CFG_SCAN, use_bias=True)
        params = nn.meta.unbox(
            Transformer(cfg).init({"params": jax.random.key(0)}, _tokens(cfg))[
                "params"
            ]
        )
        sd = state_dict_from_params(params, tie_head=False)
        for i in range(cfg.num_layers):
            assert f"transformer.h.{i}.attn.c_attn.weight" in sd
