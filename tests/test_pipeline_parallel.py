"""Pipeline parallelism: schedule correctness, grads, shardings, composition.

The reference has no pipeline parallelism (SURVEY.md §2.4) — these tests
cover the framework's addition: the circular GPipe schedule of
``parallel.pipeline.spmd_pipeline`` and the dp×tp×pp composed
``models.pipelined.PipelinedTransformer``, on a (pipe=2, data=2, model=2)
emulated mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from learning_jax_sharding_tpu.models.pipelined import PipelinedTransformer
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import build_mesh, collective_counts
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, activate
from learning_jax_sharding_tpu.parallel.pipeline import (
    spmd_pipeline,
    stack_stage_params,
)


@pytest.fixture(scope="module")
def mesh_pp():
    """(pipe=4, data=2) mesh for raw-schedule tests."""
    return build_mesh((4, 2), ("pipe", "data"))


@pytest.fixture(scope="module")
def mesh_ppdp():
    """(pipe=2, data=2, model=2) mesh for the composed model."""
    return build_mesh((2, 2, 2), ("pipe", "data", "model"))


def _stage_fn(w, h):
    return jnp.tanh(h @ w)


def _operands(rng, stages=4, batch=16, d=8):
    w = jnp.asarray(rng.standard_normal((stages, d, d)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((batch, d)), jnp.float32)
    return w, x


def _sequential(w, x):
    for i in range(w.shape[0]):
        x = _stage_fn(w[i], x)
    return x


#: This jaxlib's CPU SPMD partitioner rejects the PartitionId instruction
#: (shard_map pipelines lower ``lax.axis_index`` to it), so every test
#: that EXECUTES the pipeline fails on emulated-CPU with
#: "UNIMPLEMENTED: PartitionId" — a backend limitation, not a repo
#: sharding bug (triaged in analysis/baseline.json notes, PR 3). Skip
#: them on CPU instead of burning tier-1 budget on guaranteed failures;
#: they run (and must pass) on TPU. Validation/layout tests stay live.
_cpu_spmd_unsupported = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="CPU SPMD partitioner lacks PartitionId (see baseline.json notes)",
)


class TestSpmdPipeline:
    @_cpu_spmd_unsupported
    def test_forward_matches_sequential(self, mesh_pp, rng):
        w, x = _operands(rng)
        y = jax.jit(
            lambda w, x: spmd_pipeline(
                _stage_fn, w, x, mesh=mesh_pp, num_microbatches=8
            )
        )(w, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(_sequential(w, x)),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("m", [4, 8, 16])
    @_cpu_spmd_unsupported
    def test_microbatch_counts(self, mesh_pp, rng, m):
        # Any M with M | batch gives identical results; only the bubble
        # fraction (P-1)/(M+P-1) changes.
        w, x = _operands(rng)
        y = jax.jit(
            lambda w, x: spmd_pipeline(
                _stage_fn, w, x, mesh=mesh_pp, num_microbatches=m
            )
        )(w, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(_sequential(w, x)),
                                   rtol=1e-6, atol=1e-6)

    @_cpu_spmd_unsupported
    def test_grad_matches_sequential(self, mesh_pp, rng):
        w, x = _operands(rng)

        def loss_pp(w):
            return jnp.sum(
                spmd_pipeline(_stage_fn, w, x,
                              mesh=mesh_pp, num_microbatches=8) ** 2
            )

        def loss_seq(w):
            return jnp.sum(_sequential(w, x) ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(w)
        g_seq = jax.jit(jax.grad(loss_seq))(w)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                                   rtol=1e-5, atol=1e-6)

    @_cpu_spmd_unsupported
    def test_composes_with_data_sharding(self, mesh_pp, rng):
        # The batch stays sharded over 'data' (auto axis) while 'pipe' is
        # manual — dp×pp in one program.
        w, x = _operands(rng)
        ws = jax.device_put(w, NamedSharding(mesh_pp, P("pipe")))
        xs = jax.device_put(x, NamedSharding(mesh_pp, P("data")))
        y = jax.jit(
            lambda w, x: spmd_pipeline(
                _stage_fn, w, x, mesh=mesh_pp, num_microbatches=8
            )
        )(ws, xs)
        np.testing.assert_allclose(np.asarray(y), np.asarray(_sequential(w, x)),
                                   rtol=1e-6, atol=1e-6)

    @_cpu_spmd_unsupported
    def test_ppermute_in_hlo(self, mesh_pp, rng):
        # The stage handoff must be a collective-permute ring, not gathers.
        w, x = _operands(rng)
        f = jax.jit(
            lambda w, x: spmd_pipeline(
                _stage_fn, w, x, mesh=mesh_pp, num_microbatches=8
            )
        )
        counts = collective_counts(f.lower(w, x).compile().as_text())
        assert counts["collective-permute"] >= 1, counts

    def test_batch_divisibility_error(self, mesh_pp, rng):
        w, x = _operands(rng, batch=10)
        with pytest.raises(ValueError, match="not divisible"):
            spmd_pipeline(_stage_fn, w, x, mesh=mesh_pp,
                          num_microbatches=4)

    def test_stack_stage_params_divisibility(self):
        with pytest.raises(ValueError, match="not divisible"):
            stack_stage_params({"w": jnp.zeros((6, 2))}, 4)

    def test_stack_stage_params_layout(self):
        stacked = stack_stage_params({"w": jnp.arange(12).reshape(6, 2)}, 3)
        assert stacked["w"].shape == (3, 2, 2)
        # Contiguous assignment: stage 0 owns layers 0-1.
        np.testing.assert_array_equal(
            np.asarray(stacked["w"][0]), np.arange(4).reshape(2, 2)
        )


def _pp_model(mesh, m=4):
    return PipelinedTransformer(
        CONFIG_TINY, mesh, RULES_DP_TP, num_stages=2, num_microbatches=m
    )


def _tokens(cfg, b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, size=(b, s)), jnp.int32)


class TestPipelinedTransformer:
    def test_param_shardings(self, mesh_ppdp):
        model = _pp_model(mesh_ppdp)
        tokens = _tokens(CONFIG_TINY)
        params, shardings = model.init_sharded(jax.random.key(0), tokens)
        # Every block leaf is (stages, layers/stage, ...) with the stage dim
        # on 'pipe'; TP dims keep their logical mapping (e.g. the FF
        # up-kernel's MLP dim on 'model').
        for leaf in jax.tree.leaves(params["blocks"]):
            assert leaf.shape[0] == 2
            assert leaf.sharding.spec[0] == "pipe"
        up = params["blocks"]["ff"]["up"]["kernel"]
        assert up.sharding.spec == P("pipe", None, None, "model")
        # Per-device stage slice: 1 stage × 1 layer × full embed × half mlp.
        assert up.addressable_shards[0].data.shape == (
            1, 1, CONFIG_TINY.features, CONFIG_TINY.hidden // 2,
        )

    @_cpu_spmd_unsupported
    def test_forward_matches_sequential_blocks(self, mesh_ppdp):
        cfg = CONFIG_TINY
        model = _pp_model(mesh_ppdp)
        tokens = _tokens(cfg)
        params, _ = model.init_sharded(jax.random.key(0), tokens)
        with activate(mesh_ppdp, RULES_DP_TP):
            logits = jax.jit(model.apply)(params, tokens)

        flat = jax.tree.map(
            lambda p: p.reshape(cfg.num_layers, *p.shape[2:]), params["blocks"]
        )

        def ref_apply(params, tokens):
            x = model._embed.apply({"params": params["embed"]}, tokens)
            for i in range(cfg.num_layers):
                lp = jax.tree.map(lambda p: p[i], flat)
                x = model._block.apply({"params": lp}, x)
            return model._head.apply({"params": params["head"]}, x)

        with activate(mesh_ppdp, RULES_DP_TP):
            ref = jax.jit(ref_apply)(params, tokens)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    @_cpu_spmd_unsupported
    def test_training_descends(self, mesh_ppdp):
        cfg = CONFIG_TINY
        model = _pp_model(mesh_ppdp)
        tokens = _tokens(cfg, s=17)
        batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
        params, _ = model.init_sharded(jax.random.key(0), batch["inputs"])
        opt = optax.adamw(1e-3)
        carry = (params, model.init_optimizer(params, opt))
        step = model.make_train_step(opt, next_token_loss)
        losses = []
        for _ in range(5):
            carry, loss = step(carry, batch)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        assert abs(losses[0] - np.log(cfg.vocab_size)) < 1.0

    def test_layer_divisibility_error(self, mesh_ppdp):
        with pytest.raises(ValueError, match="not divisible"):
            PipelinedTransformer(CONFIG_TINY, mesh_ppdp, RULES_DP_TP,
                                 num_stages=4)  # 2 layers, 4 stages — but
        # mesh check fires first only when sizes match; ensure message clear

    def test_mesh_axis_size_error(self, mesh_ppdp):
        import dataclasses as dc

        cfg = dc.replace(CONFIG_TINY, num_layers=4)
        with pytest.raises(ValueError, match="mesh axis"):
            PipelinedTransformer(cfg, mesh_ppdp, RULES_DP_TP, num_stages=4)

    def test_unsupported_config_rejected(self, mesh_ppdp):
        import dataclasses as dc

        with pytest.raises(ValueError, match="MoE"):
            PipelinedTransformer(
                dc.replace(CONFIG_TINY, num_experts=4), mesh_ppdp,
                RULES_DP_TP, num_stages=2,
            )
        with pytest.raises(ValueError, match="dropout"):
            PipelinedTransformer(
                dc.replace(CONFIG_TINY, dropout_rate=0.1), mesh_ppdp,
                RULES_DP_TP, num_stages=2,
            )

    @_cpu_spmd_unsupported
    def test_remat_matches_no_remat(self, mesh_ppdp):
        import dataclasses as dc

        cfg = CONFIG_TINY
        tokens = _tokens(cfg, s=17)
        batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
        losses = []
        for remat in (False, True):
            model = PipelinedTransformer(
                dc.replace(cfg, remat=remat), mesh_ppdp, RULES_DP_TP,
                num_stages=2, num_microbatches=4,
            )
            params, _ = model.init_sharded(jax.random.key(0), batch["inputs"])
            opt = optax.adamw(1e-3)
            carry = (params, model.init_optimizer(params, opt))
            step = model.make_train_step(opt, next_token_loss)
            _, loss = step(carry, batch)
            losses.append(float(loss))
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


class TestInterleavedSchedule:
    """interleave=V: round-robin layer chunks, V ring trips per microbatch —
    the Megatron-style interleaved assignment that shrinks the GPipe bubble
    ~V-fold (exact tick counts pinned below)."""

    def _chunk_fn(self, w, h):
        def body(h, wi):
            return _stage_fn(wi, h), None

        return jax.lax.scan(body, h, w)[0]

    def test_schedule_ticks(self):
        from learning_jax_sharding_tpu.parallel.pipeline import schedule_ticks

        # V=1 IS circular GPipe: M + P - 1 ticks.
        assert schedule_ticks(4, 4, 1) == 7
        assert schedule_ticks(8, 4, 1) == 11
        # Interleaved: more ticks of 1/V-size chunks; critical-path stage
        # time (ticks/V) shrinks toward the ideal M chunks.
        assert schedule_ticks(4, 4, 2) == 11      # 5.5 C vs GPipe's 7 C
        assert schedule_ticks(8, 4, 2) == 19      # 9.5 C vs 11 C
        assert schedule_ticks(8, 4, 4) == 35      # 8.75 C vs 11 C
        # Bubble fraction: 1 - ideal/actual chunk-ticks.
        bubble = lambda m, p, v: 1 - m * v / schedule_ticks(m, p, v)
        assert bubble(8, 4, 1) > bubble(8, 4, 2) > bubble(8, 4, 4)

    @pytest.mark.parametrize("m", [4, 8])
    @_cpu_spmd_unsupported
    def test_interleaved_forward_matches_sequential(self, mesh_pp, rng, m):
        w, x = _operands(rng, stages=8)  # 8 layers: P=4 × V=2 chunks of 1
        stacked = stack_stage_params(w, 4, interleave=2)
        assert jax.tree.leaves(stacked)[0].shape == (4, 2, 1, 8, 8)
        got = spmd_pipeline(
            self._chunk_fn, stacked, x, mesh=mesh_pp, num_microbatches=m,
            interleave=2,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(_sequential(w, x)), atol=1e-5
        )

    @_cpu_spmd_unsupported
    def test_interleaved_grad_matches_sequential(self, mesh_pp, rng):
        w, x = _operands(rng, stages=8)

        def loss_pipe(w_):
            stacked = stack_stage_params(w_, 4, interleave=2)
            y = spmd_pipeline(
                self._chunk_fn, stacked, x, mesh=mesh_pp,
                num_microbatches=4, interleave=2,
            )
            return jnp.sum(y**2)

        def loss_seq(w_):
            return jnp.sum(_sequential(w_, x) ** 2)

        gp = jax.grad(loss_pipe)(w)
        gs = jax.grad(loss_seq)(w)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), atol=1e-4)

    def test_interleaved_chunk_layout(self):
        w = jnp.arange(8)[:, None, None] * jnp.ones((8, 2, 2))
        stacked = stack_stage_params(w, 4, interleave=2)
        # Device d, chunk v holds global layer block v*P + d.
        for d in range(4):
            for v in range(2):
                assert float(stacked[d, v, 0, 0, 0]) == v * 4 + d

    @_cpu_spmd_unsupported
    def test_interleaved_transformer(self, mesh_ppdp):
        """PipelinedTransformer at interleave=2 matches the plain block
        stack (4 layers over 2 stages × 2 chunks)."""
        import dataclasses

        from learning_jax_sharding_tpu.models.transformer import CONFIG_TINY

        cfg = dataclasses.replace(CONFIG_TINY, num_layers=4)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32
        )
        pp = PipelinedTransformer(
            cfg, mesh_ppdp, RULES_DP_TP, num_stages=2, num_microbatches=2,
            interleave=2,
        )
        params, _ = pp.init_sharded(jax.random.key(0), tokens)
        assert jax.tree.leaves(params["blocks"])[0].shape[:2] == (2, 2)
        got = np.asarray(pp.apply(params, tokens), np.float32)

        ref = PipelinedTransformer(
            cfg, mesh_ppdp, RULES_DP_TP, num_stages=2, num_microbatches=2,
        )
        # Same weights, contiguous layout: restack from the interleaved tree.
        flat = jax.tree.map(
            lambda p: jnp.swapaxes(p, 0, 1).reshape(-1, *p.shape[3:]),
            params["blocks"],
        )
        ref_params = {
            **params,
            "blocks": jax.tree.map(
                lambda p: p.reshape(2, 2, *p.shape[1:]), flat
            ),
        }
        want = np.asarray(ref.apply(ref_params, tokens), np.float32)
        np.testing.assert_allclose(got, want, atol=2e-5)
