"""Tenancy (round 12): zero-downtime weight hot-swap + multi-LoRA.

THE two acceptance oracles of the tenancy subsystem:

* **Mixed-tenant bit-identity** — one fused ``adapter_mixed_step``
  batch serving different tenants' adapters (and base rows) produces,
  for every request, EXACTLY the tokens a solo engine produces against
  that tenant's ``merge_lora``-folded weights — greedy and sampled.
* **Zero-downtime swap** — ``swap_weights`` under a saturated queue
  drops and fails NOTHING, every response is attributable to exactly
  one weight version, and each response is bit-identical to a pure run
  under its attributed version's weights.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_jax_sharding_tpu.models.serving import (
    ContinuousEngine,
    RequestFailure,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
)
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.tenancy import AdapterPool
from learning_jax_sharding_tpu.training.lora import (
    init_lora,
    merge_lora,
    zero_lora,
)

NEW = 5
RANK = 4


@pytest.fixture(scope="module")
def setup(mesh22):
    cfg = dataclasses.replace(CONFIG_TINY, dtype=jnp.float32)
    rng = np.random.default_rng(11)
    model = Transformer(cfg)
    probe = np.zeros((2, 8), np.int32)
    params = nn.meta.unbox(
        jax.jit(lambda r, t: model.init({"params": r}, t))(
            jax.random.key(3), probe
        )["params"]
    )
    prompts = [
        rng.integers(1, cfg.vocab_size, size=(n,)).astype(np.int32)
        for n in (3, 9, 5, 7, 4, 6)
    ]
    # Two tenants with deliberately NONZERO B (init_lora's B=0 would
    # make every tenant the base model and the oracle vacuous).
    ad1 = jax.tree.map(
        lambda x: x + 0.02, init_lora(jax.random.key(1), params, RANK)
    )
    ad2 = jax.tree.map(
        lambda x: x - 0.03, init_lora(jax.random.key(2), params, RANK)
    )
    return cfg, params, prompts, ad1, ad2


def _drive(eng, params, reqs, *, adapters=None, max_steps=400):
    for rid, p in reqs.items():
        eng.add_request(
            p, rid=rid,
            adapter=(adapters or {}).get(rid),
        )
    out, steps = {}, 0
    while eng.has_work():
        eng.step(params)
        out.update(eng.pop_finished())
        steps += 1
        assert steps <= max_steps, "engine wedged"
    out.update(eng.pop_finished())
    return out


def _solo(cfg, mesh, merged, prompts_by_rid, **kw):
    eng = ContinuousEngine(
        cfg, mesh, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
        refill_chunk=4, mixed=True, **kw,
    )
    out = _drive(eng, merged, prompts_by_rid)
    eng.close()
    return out


class TestMultiLora:
    @pytest.mark.parametrize(
        "sample_kw",
        [{}, {"temperature": 0.7, "top_k": 8}],
        ids=["greedy", "sampled"],
    )
    def test_mixed_tenants_bit_identical_to_solo(
        self, setup, mesh22, sample_kw
    ):
        """6 requests across base + two tenants through 2 slots in ONE
        fused multi-LoRA engine: every stream equals the stream a solo
        engine produces against that tenant's merge_lora-folded weights,
        bit for bit — greedy AND sampled (draws are keyed by (rid,
        position), so multi-tenant batching cannot change a token)."""
        cfg, params, prompts, ad1, ad2 = setup
        pool = AdapterPool(params, slots=4, rank=RANK)
        pool.add("t1", ad1, alpha=16.0)
        pool.add("t2", ad2, alpha=8.0)
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, adapter_pool=pool, **sample_kw,
        )
        names = {0: None, 1: "t1", 2: "t2", 3: "t1", 4: None, 5: "t2"}
        out = _drive(
            eng, params, dict(enumerate(prompts)), adapters=names
        )
        assert eng.compile_counts().get("adapter_mixed_step", 0) >= 1

        ref_base = _solo(
            cfg, mesh22, params,
            {r: prompts[r] for r, n in names.items() if n is None},
            **sample_kw,
        )
        ref_t1 = _solo(
            cfg, mesh22, merge_lora(params, ad1, alpha=16.0),
            {r: prompts[r] for r, n in names.items() if n == "t1"},
            **sample_kw,
        )
        ref_t2 = _solo(
            cfg, mesh22, merge_lora(params, ad2, alpha=8.0),
            {r: prompts[r] for r, n in names.items() if n == "t2"},
            **sample_kw,
        )
        ref = {**ref_base, **ref_t1, **ref_t2}
        assert sorted(out) == sorted(ref)
        for rid in out:
            np.testing.assert_array_equal(out[rid], ref[rid])
        eng.close()

    def test_zero_adapter_is_identity(self, setup, mesh22):
        """merge_lora with zero_lora returns the base tree unchanged —
        the slot-0 semantics the base rows of the fused batch rely on."""
        cfg, params, _, ad1, _ = setup
        merged = merge_lora(params, zero_lora(ad1), alpha=16.0)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            params, merged,
        )

    def test_speculative_adapter_engine_lossless(self, setup, mesh22):
        """Speculative decoding composes with the adapter pool: the
        draft proposes on BASE weights, the verifier applies each row's
        merged weights — outputs identical to the plain adapter engine
        (the speculative-is-lossless invariant, now per tenant)."""
        cfg, params, prompts, ad1, ad2 = setup
        d_cfg = dataclasses.replace(
            cfg, num_layers=1, hidden=64, dtype=jnp.float32
        )
        d_model = Transformer(d_cfg)
        d_params = nn.meta.unbox(
            d_model.init(
                {"params": jax.random.key(7)}, np.zeros((2, 8), np.int32)
            )["params"]
        )
        names = {0: None, 1: "t1", 2: "t2", 3: "t1"}
        reqs = {r: prompts[r] for r in names}

        def build(**kw):
            pool = AdapterPool(params, slots=4, rank=RANK)
            pool.add("t1", ad1, alpha=16.0)
            pool.add("t2", ad2, alpha=8.0)
            return ContinuousEngine(
                cfg, mesh22, RULES_DP_TP, batch_size=2,
                max_new_tokens=NEW, refill_chunk=4, mixed=True,
                adapter_pool=pool, **kw,
            )

        plain = build()
        ref = _drive(plain, params, dict(reqs), adapters=names)
        plain.close()
        spec = build(draft_config=d_cfg, num_draft=2)
        eng_out = {}
        for rid, p in reqs.items():
            spec.add_request(p, rid=rid, adapter=names[rid])
        steps = 0
        while spec.has_work():
            spec.step(params, d_params)
            eng_out.update(spec.pop_finished())
            steps += 1
            assert steps <= 400
        eng_out.update(spec.pop_finished())
        assert (
            spec.compile_counts().get("adapter_mixed_step", 0) >= 1
        )
        for rid in ref:
            np.testing.assert_array_equal(eng_out[rid], ref[rid])
        spec.close()

    def test_pool_lifecycle(self, setup, mesh22):
        """Residency mechanics: unknown tenants are rejected at
        admission (nothing enqueued), LRU eviction only takes
        refcount-0 tenants, hot-update keeps the slot, and a full pool
        of live tenants refuses instead of evicting."""
        cfg, params, prompts, ad1, ad2 = setup
        pool = AdapterPool(params, slots=3, rank=RANK)  # 2 named slots
        s1 = pool.add("t1", ad1)
        assert pool.add("t1", ad2) == s1          # hot-update, same slot
        pool.add("t2", ad2)
        pool.acquire("t1")
        pool.add("t3", ad1)                        # evicts LRU refcount-0: t2
        assert pool.names() == ["t1", "t3"]
        pool.acquire("t3")
        with pytest.raises(RuntimeError):
            pool.add("t4", ad2)                    # everyone live: refuse
        pool.release("t3")
        pool.add("t4", ad2)                        # t3 now evictable
        assert pool.names() == ["t1", "t4"]
        assert pool.stats()["pages_in_use"] == 2 * pool.pages_per_slot

        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True, adapter_pool=pool,
        )
        with pytest.raises(KeyError):
            eng.add_request(prompts[0], adapter="nope")
        assert not eng.has_work()
        # Engine config guards: the pool requires the fused path and a
        # contiguous cache, and refuses per-request adapters without a
        # pool.
        with pytest.raises(ValueError):
            ContinuousEngine(
                cfg, mesh22, RULES_DP_TP, batch_size=2,
                max_new_tokens=NEW, adapter_pool=pool,
            )
        plain = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        with pytest.raises(ValueError):
            plain.add_request(prompts[0], adapter="t1")
        eng.close()
        plain.close()


class TestHotSwap:
    def test_saturated_swap_zero_drops_exact_versions(
        self, setup, mesh22
    ):
        """THE swap acceptance oracle: a drain-mode swap under a
        SATURATED queue (6 requests, 2 slots, staged mid-stream) drops
        and fails nothing; every response carries exactly one version in
        ``finished_versions``; in-flight requests finish on the OLD
        version and post-commit admissions on the NEW one; and each
        response is bit-identical to a pure run under its attributed
        version's weights."""
        cfg, params, prompts, _, _ = setup
        new_params = jax.tree.map(lambda x: x * 1.01, params)
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        reqs = dict(enumerate(prompts))
        for rid, p in reqs.items():
            eng.add_request(p, rid=rid)
        eng.step(params)                      # slots full, queue deep
        occupied = [r for r in eng._req if r >= 0]
        assert len(occupied) == 2 and eng.queue_depth() == 4
        assert eng.swap_weights(new_params, version=3)
        assert eng.weights_version == 0       # occupied → still draining
        out, steps = {}, 0
        while eng.has_work():
            eng.step(params)                  # stale caller params:
            out.update(eng.pop_finished())    # installed tree overrides
            steps += 1
            assert steps <= 400
        out.update(eng.pop_finished())
        assert sorted(out) == sorted(reqs), "zero drops"
        assert not any(isinstance(v, RequestFailure) for v in out.values())
        versions = {rid: eng.finished_versions[rid] for rid in reqs}
        assert set(versions.values()) == {0, 3}
        # The two requests in flight at staging time finished old; the
        # queue behind them (admission paused while draining) new.
        assert all(versions[r] == 0 for r in occupied)
        assert all(
            versions[r] == 3 for r in reqs if r not in occupied
        )
        assert eng.weights_version == 3

        ref_old = _solo(
            cfg, mesh22, params,
            {r: reqs[r] for r, v in versions.items() if v == 0},
        )
        ref_new = _solo(
            cfg, mesh22, new_params,
            {r: reqs[r] for r, v in versions.items() if v == 3},
        )
        for rid, v in {**ref_old, **ref_new}.items():
            np.testing.assert_array_equal(out[rid], v)

        snap = eng.registry.snapshot()
        assert snap["engine_swap_commits_total"] == 1
        assert snap["engine_swap_staged_total"] == 1
        eng.close()

    def test_preempt_swap_recomputes_on_new_version(self, setup, mesh22):
        """Preempt mode: in-flight requests are requeued and RECOMPUTE
        under the new version — every response attributed to (and
        bit-identical under) the new weights, none dropped."""
        cfg, params, prompts, _, _ = setup
        new_params = jax.tree.map(lambda x: x * 0.99, params)
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        reqs = {r: prompts[r] for r in range(4)}
        for rid, p in reqs.items():
            eng.add_request(p, rid=rid)
        eng.step(params)
        assert eng.swap_weights(new_params, version=9, mode="preempt")
        assert eng.weights_version == 9       # immediate commit
        out, steps = {}, 0
        while eng.has_work():
            eng.step()                        # installed weights only
            out.update(eng.pop_finished())
            steps += 1
            assert steps <= 400
        out.update(eng.pop_finished())
        assert sorted(out) == sorted(reqs)
        assert {eng.finished_versions[r] for r in reqs} == {9}
        ref = _solo(cfg, mesh22, new_params, dict(reqs))
        for rid in reqs:
            np.testing.assert_array_equal(out[rid], ref[rid])
        eng.close()

    def test_double_stage_refused_and_stats(self, setup, mesh22):
        """One staged swap at a time; stall telemetry lands in the
        histogram; step() without params before any swap raises."""
        cfg, params, prompts, _, _ = setup
        eng = ContinuousEngine(
            cfg, mesh22, RULES_DP_TP, batch_size=2, max_new_tokens=NEW,
            refill_chunk=4, mixed=True,
        )
        with pytest.raises(TypeError):
            eng.step()
        eng.add_request(prompts[0], rid=0)
        eng.step(params)
        assert eng.swap_weights(params, version=1)
        with pytest.raises(RuntimeError):
            eng.swap_weights(params, version=2)
        while eng.has_work():
            eng.step(params)
        assert eng.weights_version == 1
        h = eng.registry.get("engine_swap_stall_seconds")
        assert h is not None and h.count == 1
        eng.close()


class TestRollingSwap:
    def test_fleet_rolls_with_zero_drops(self, setup, mesh22):
        """rolling_swap walks a 2-replica unified fleet one replica at a
        time under load: nothing drops or fails, both replicas commit,
        every response is attributable to exactly one version, and at no
        point is the whole fleet out of placement (the replica under
        swap is excluded while the other serves)."""
        from learning_jax_sharding_tpu.fleet import (
            FleetRouter,
            make_replicas,
        )

        cfg, params, prompts, _, _ = setup
        reps = make_replicas(
            cfg, RULES_DP_TP, params, count=2, mesh_shape=(1, 1),
            batch_size=2, max_new_tokens=NEW, refill_chunk=4,
        )
        router = FleetRouter(reps)
        for rid, p in enumerate(prompts):
            router.add_request(p, rid=rid)
        router.step()
        new_params = jax.tree.map(lambda x: x * 1.02, params)
        timeline = router.rolling_swap(new_params, version=5)
        assert [t["committed"] for t in timeline] == [True, True]
        assert all(r.engine.weights_version == 5 for r in reps)
        assert all(r.params is new_params for r in reps)
        assert router._swapping == set()
        out = router.drain(max_steps=400)
        out_all = {**out}
        assert sorted(out_all) == list(range(len(prompts)))
        assert not any(
            isinstance(v, RequestFailure) for v in out_all.values()
        )
        versions = {}
        for rep in reps:
            versions.update(rep.engine.finished_versions)
        assert set(versions) >= set(range(len(prompts)))
        assert all(v in (0, 5) for v in versions.values())
        assert (
            int(router.registry.counter("fleet_swaps_total").value) == 2
        )
        # Each response is bit-identical to a pure run under its
        # attributed version (single-device replica sub-meshes run the
        # same programs as a solo (1,1) engine).
        from learning_jax_sharding_tpu.parallel import build_mesh

        m11 = build_mesh(
            (1, 1), ("data", "model"), devices=jax.devices()[:1]
        )
        old_rids = [r for r in out_all if versions[r] == 0]
        new_rids = [r for r in out_all if versions[r] == 5]
        ref = {}
        if old_rids:
            ref.update(_solo(
                cfg, m11, params, {r: prompts[r] for r in old_rids},
            ))
        if new_rids:
            ref.update(_solo(
                cfg, m11, new_params, {r: prompts[r] for r in new_rids},
            ))
        for rid in out_all:
            np.testing.assert_array_equal(out_all[rid], ref[rid])
