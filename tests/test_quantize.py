"""Weight-only int8 quantization: error bounds, sharding, serving parity.

Oracles: per-channel dequant error ≤ scale/2; kernels/scales inherit the
kernel's NamedSharding; in-jit dequantized generation equals the eager
dequantize-then-generate path EXACTLY (same math, different placement of the
upcast); serving bytes halve vs bf16.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_jax_sharding_tpu.models.generate import make_generate_fn
from learning_jax_sharding_tpu.models.quantize import (
    dequantize_tree,
    quantize_leaf,
    quantize_tree,
    quantized_bytes,
)
from learning_jax_sharding_tpu.models.transformer import (
    CONFIG_TINY,
    Transformer,
    next_token_loss,
)
from learning_jax_sharding_tpu.parallel import mesh_sharding, put
from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
from learning_jax_sharding_tpu.training.pipeline import (
    make_train_step,
    sharded_train_state,
)


def _trained_params(mesh, rng, steps=3):
    model = Transformer(CONFIG_TINY)
    tokens = rng.integers(0, CONFIG_TINY.vocab_size, size=(8, 33)).astype(np.int32)
    sh = mesh_sharding(mesh, "data", None)
    batch = {"inputs": put(tokens[:, :-1], sh), "targets": put(tokens[:, 1:], sh)}
    state, state_sh = sharded_train_state(
        model, optax.adamw(3e-3), batch["inputs"],
        {"params": jax.random.key(0)}, mesh, RULES_DP_TP,
    )
    step = make_train_step(
        state_sh, {k: v.sharding for k, v in batch.items()}, mesh,
        RULES_DP_TP, loss_fn=next_token_loss, donate_state=False,
    )
    for _ in range(steps):
        state, _ = step(state, batch)
    return state.params, tokens


class TestQuantizeLeaf:
    def test_error_bounded_by_half_scale(self, rng):
        w = jnp.asarray(rng.standard_normal((64, 32)).astype(np.float32))
        node = quantize_leaf(w)
        assert node["q"].dtype == jnp.int8 and node["scale"].dtype == jnp.float32
        err = np.abs(np.asarray(w) - np.asarray(
            node["q"].astype(jnp.float32) * node["scale"]
        ))
        bound = np.asarray(node["scale"]) / 2 + 1e-7
        assert (err <= bound[None, :]).all()

    def test_zero_channel_safe(self):
        w = jnp.zeros((8, 4))
        node = quantize_leaf(w)
        assert not np.any(np.asarray(node["q"]))
        assert np.all(np.asarray(node["scale"]) == 1.0)  # no div-by-zero


class TestQuantizeTree:
    def test_kernels_quantized_rest_untouched(self, mesh22, rng):
        params, _ = _trained_params(mesh22, rng)
        qparams = quantize_tree(params)
        assert set(qparams["block_0"]["attn"]["query"]["kernel"]) == {"q", "scale"}
        assert set(qparams["lm_head"]["kernel"]) == {"q", "scale"}
        # Embedding / norms untouched.
        assert qparams["tok_embed"]["embedding"].dtype == jnp.float32
        assert qparams["block_0"]["ln_attn"]["scale"].dtype == jnp.float32

    def test_shardings_inherited(self, mesh22, rng):
        params, _ = _trained_params(mesh22, rng)
        qparams = quantize_tree(params)
        kernel = params["block_0"]["ff"]["up"]["kernel"]
        node = qparams["block_0"]["ff"]["up"]["kernel"]
        assert node["q"].sharding.spec == kernel.sharding.spec
        spec = tuple(kernel.sharding.spec) + (None,) * (2 - len(kernel.sharding.spec))
        assert tuple(node["scale"].sharding.spec) == (spec[1],)

    def test_bytes_halve_vs_bf16(self, mesh22, rng):
        params, _ = _trained_params(mesh22, rng)
        bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        qparams = quantize_tree(bf16)
        kernel_bytes_bf16 = sum(
            x.size * 2
            for p, x in jax.tree_util.tree_flatten_with_path(bf16)[0]
            if getattr(p[-1], "key", None) == "kernel"
        )
        saved = quantized_bytes(bf16) - quantized_bytes(qparams)
        # int8 + fp32 scale vs bf16: saves size*1 minus 4*out_channels per kernel.
        assert saved > 0.4 * kernel_bytes_bf16

    def test_dequantize_roundtrip_close(self, mesh22, rng):
        params, _ = _trained_params(mesh22, rng)
        deq = dequantize_tree(quantize_tree(params), jnp.float32)
        w = np.asarray(params["block_0"]["attn"]["out"]["kernel"])
        d = np.asarray(deq["block_0"]["attn"]["out"]["kernel"])
        assert np.abs(w - d).max() < np.abs(w).max() * 0.005


class TestQuantizeMoE:
    def test_expert_stacks_quantized_router_not(self, mesh22, rng):
        import dataclasses

        cfg = dataclasses.replace(CONFIG_TINY, num_experts=4)
        model = Transformer(cfg)
        tokens = rng.integers(0, cfg.vocab_size, size=(4, 17)).astype(np.int32)
        import flax.linen as nn

        params = nn.meta.unbox(
            model.init({"params": jax.random.key(0)}, tokens)["params"]
        )
        qparams = quantize_tree(params)
        moe = qparams["block_0"]["moe"]
        assert set(moe["up"]) == {"q", "scale"}
        assert set(moe["down"]) == {"q", "scale"}
        # Router kernel deliberately full precision (top-k flip risk).
        assert moe["router"]["kernel"].dtype == params["block_0"]["moe"]["router"]["kernel"].dtype
        # 3D scales: one per (expert, out_channel); error bound holds per slice.
        w = np.asarray(params["block_0"]["moe"]["up"], np.float32)
        node = moe["up"]
        deq = np.asarray(node["q"], np.float32) * np.asarray(node["scale"])[:, None, :]
        bound = np.asarray(node["scale"])[:, None, :] / 2 + 1e-7
        assert (np.abs(w - deq) <= bound).all()


class TestQuantizedServing:
    def test_in_jit_dequant_matches_eager_dequant(self, mesh22, rng):
        """The served program (int8 in HBM, per-step on-chip dequant) computes
        the same function as eagerly dequantizing and running the plain path."""
        params, tokens = _trained_params(mesh22, rng)
        bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        qparams = quantize_tree(bf16)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))

        gen_q = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=12,
            inference_dtype=jnp.bfloat16, dequantize=True,
        )
        gen_plain = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=12,
            inference_dtype=jnp.bfloat16,
        )
        out_q = np.asarray(gen_q(qparams, prompt, jax.random.key(1)))
        out_eager = np.asarray(
            gen_plain(dequantize_tree(qparams, jnp.bfloat16), prompt, jax.random.key(1))
        )
        np.testing.assert_array_equal(out_q, out_eager)

    def test_nonquantized_leaves_cast_with_dequantize(self, mesh22, rng):
        """With dequantize=True + inference_dtype=bf16, embeddings/norms of an
        fp32-trained tree are still cast eagerly: feeding the fp32 tree and a
        pre-cast tree must produce identical programs and outputs."""
        from learning_jax_sharding_tpu.models.quantize import map_unquantized

        params, tokens = _trained_params(mesh22, rng)
        qtree_fp32_rest = quantize_tree(params)  # embeddings stay fp32

        pre_cast = map_unquantized(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            qtree_fp32_rest,
        )
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        gen = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=8,
            inference_dtype=jnp.bfloat16, dequantize=True,
        )
        out_fp32_in = np.asarray(gen(qtree_fp32_rest, prompt, jax.random.key(1)))
        out_pre_cast = np.asarray(gen(pre_cast, prompt, jax.random.key(1)))
        np.testing.assert_array_equal(out_fp32_in, out_pre_cast)

    def test_quantized_output_tracks_full_precision(self, mesh22, rng):
        """Greedy decode from int8 weights stays close to the bf16 model: the
        first generated tokens agree (int8 error is ~0.4% per channel)."""
        params, tokens = _trained_params(mesh22, rng, steps=6)
        bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        gen_q = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=4,
            inference_dtype=jnp.bfloat16, dequantize=True,
        )
        gen = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=4,
            inference_dtype=jnp.bfloat16,
        )
        out_q = np.asarray(gen_q(quantize_tree(bf16), prompt, jax.random.key(1)))
        out_f = np.asarray(gen(bf16, prompt, jax.random.key(1)))
        # Prompt echoed identically; the first new token matches on most rows.
        np.testing.assert_array_equal(out_q[:, :8], out_f[:, :8])
        assert (out_q[:, 8] == out_f[:, 8]).mean() >= 0.75


class TestInt4:
    def test_error_bounded_by_half_group_scale(self, rng):
        from learning_jax_sharding_tpu.models.quantize import (
            dequantize_leaf_int4,
            quantize_leaf_int4,
        )

        w = jnp.asarray(rng.standard_normal((128, 32)).astype(np.float32))
        node = quantize_leaf_int4(w, group_size=32)
        assert node["q4"].dtype == jnp.uint8
        assert node["q4"].shape == (64, 32)       # two rows per byte
        assert node["scale"].shape == (4, 32)     # 128/32 groups
        deq = np.asarray(dequantize_leaf_int4(node, jnp.float32))
        err = np.abs(np.asarray(w) - deq)
        bound = np.repeat(np.asarray(node["scale"]), 32, axis=0) / 2 + 1e-6
        assert (err <= bound).all()

    def test_round_trip_exact_for_representable(self, rng):
        # Weights already on the int4 grid must survive pack/unpack exactly
        # (pins nibble order and the offset-binary encoding).
        from learning_jax_sharding_tpu.models.quantize import (
            dequantize_leaf_int4,
            quantize_leaf_int4,
        )

        grid = rng.integers(-7, 8, size=(16, 8)).astype(np.float32)
        grid[0] = 7.0  # pin absmax=7 per column → scale 1, grid exactly
        # representable (otherwise exactness depends on the rng seed)
        node = quantize_leaf_int4(jnp.asarray(grid), group_size=16)
        deq = np.asarray(dequantize_leaf_int4(node, jnp.float32))
        np.testing.assert_allclose(deq, grid, atol=1e-5)

    def test_odd_rows_and_bad_group_rejected(self):
        from learning_jax_sharding_tpu.models.quantize import quantize_leaf_int4

        with pytest.raises(ValueError, match="even"):
            quantize_leaf_int4(jnp.zeros((7, 4)))
        with pytest.raises(ValueError, match="group_size"):
            quantize_leaf_int4(jnp.zeros((64, 4)), group_size=48)

    def test_tree_bytes_quarter_vs_bf16(self, mesh22, rng):
        params, _ = _trained_params(mesh22, rng)
        q4 = quantize_tree(params, bits=4, group_size=32)
        # Every default-matched kernel became a packed node.
        assert "q4" in q4["block_0"]["attn"]["query"]["kernel"]
        k = params["block_0"]["attn"]["query"]["kernel"]
        packed = q4["block_0"]["attn"]["query"]["kernel"]["q4"]
        assert packed.size == k.size // 2 and packed.dtype == jnp.uint8
        # Sharding inherited from the kernel (specs name dims, not sizes).
        assert packed.sharding.spec == k.sharding.spec

    def test_int4_serving_runs_and_tracks_full_precision(self, mesh22, rng):
        params, tokens = _trained_params(mesh22, rng, steps=6)
        bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        prompt = put(tokens[:4, :8], mesh_sharding(mesh22, "data", None))
        gen_q = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=4,
            inference_dtype=jnp.bfloat16, dequantize=True,
        )
        gen = make_generate_fn(
            CONFIG_TINY, mesh22, RULES_DP_TP, max_new_tokens=4,
            inference_dtype=jnp.bfloat16,
        )
        q4 = quantize_tree(bf16, bits=4, group_size=32)
        out_q = np.asarray(gen_q(q4, prompt, jax.random.key(1)))
        out_f = np.asarray(gen(bf16, prompt, jax.random.key(1)))
        np.testing.assert_array_equal(out_q[:, :8], out_f[:, :8])
        # int4 is coarser than int8 — ask only for majority agreement on the
        # first new token.
        assert (out_q[:, 8] == out_f[:, 8]).mean() >= 0.5

    def test_bad_bits_rejected(self, mesh22, rng):
        params, _ = _trained_params(mesh22, rng)
        with pytest.raises(ValueError, match="bits"):
            quantize_tree(params, bits=2)


class TestFusedInt4:
    """Fused dequant-matmul serving (ops/int4_matmul.py + Int4Dense):
    packed nibbles stream into the dot; parity with the materializing
    dequant path is exact in structure (same int values, same scales)."""

    def test_kernel_matches_dequant(self, rng):
        from learning_jax_sharding_tpu.models.quantize import (
            dequantize_leaf_int4,
            quantize_leaf_int4,
        )
        from learning_jax_sharding_tpu.ops.int4_matmul import int4_matmul

        for k, n, g in [(64, 48, 16), (256, 128, 128), (64, 48, 64)]:
            w = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
            node = quantize_leaf_int4(w, group_size=g)
            x = jnp.asarray(rng.normal(size=(4, k)), jnp.float32)
            with jax.default_matmul_precision("float32"):
                got = int4_matmul(
                    x, node["q4"], node["scale"], group=min(g, k), interpret=True
                )
                want = x @ dequantize_leaf_int4(node, jnp.float32)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-4
            )

    def test_kernel_validation(self, rng):
        from learning_jax_sharding_tpu.ops.int4_matmul import int4_matmul

        x = jnp.zeros((2, 64))
        with pytest.raises(ValueError, match="contraction dim"):
            int4_matmul(x, jnp.zeros((16, 8), jnp.uint8), jnp.ones((4, 8)))
        with pytest.raises(ValueError, match="group"):
            # 3 scale groups over K=96: group 32 does not divide K/2=48.
            int4_matmul(
                jnp.zeros((2, 96)), jnp.zeros((48, 8), jnp.uint8),
                jnp.ones((3, 8)), group=32, interpret=True,
            )
        with pytest.raises(ValueError, match="quantized with a different"):
            # Tree built with group_size=64 (4 scale rows over K=256) but the
            # kernel told group=128: must fail loudly, not mis-scale.
            int4_matmul(
                jnp.zeros((2, 256)), jnp.zeros((128, 8), jnp.uint8),
                jnp.ones((4, 8)), group=128, interpret=True,
            )

    def test_w4a8_matches_integer_reference(self, rng):
        """w4a8 is a DETERMINISTIC integer computation: per-row int8
        activations × unpacked int4 weights → int32, rescaled by group and
        row scales. The kernel must match a numpy model of exactly that
        computation to float tolerance — not merely approximate the f32
        matmul."""
        from learning_jax_sharding_tpu.models.quantize import quantize_leaf_int4
        from learning_jax_sharding_tpu.ops.int4_matmul import (
            int4_matmul,
            quantize_rows_int8,
        )

        for m, k, n, g in [(4, 64, 48, 16), (5, 256, 128, 128), (4, 64, 48, 64)]:
            w = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
            node = quantize_leaf_int4(w, group_size=g)
            x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
            got = int4_matmul(
                x, node["q4"], node["scale"], group=min(g, k),
                interpret=True, w4a8=True,
            )
            xq, sx = quantize_rows_int8(x)
            p = np.asarray(node["q4"], np.int32)
            wq = np.concatenate([(p & 0xF) - 8, (p >> 4) - 8], axis=0)
            s = np.asarray(node["scale"], np.float64)       # (K/g, N)
            xqn = np.asarray(xq, np.int64)
            ng = s.shape[0]
            gg = k // ng
            want = np.zeros((m, n), np.float64)
            for gi in range(ng):
                rows = slice(gi * gg, (gi + 1) * gg)
                want += (xqn[:, rows] @ wq[rows]) * s[gi]
            want *= np.asarray(sx, np.float64)
            np.testing.assert_allclose(
                np.asarray(got, np.float64), want, rtol=2e-6, atol=1e-5
            )
            # The EXTRA error over the w4a16 kernel (i.e. vs the dequantized
            # weights) is only the int8 activation rounding — ~1% relative.
            from learning_jax_sharding_tpu.models.quantize import (
                dequantize_leaf_int4,
            )

            wdeq = np.asarray(dequantize_leaf_int4(node, jnp.float32), np.float64)
            a16 = np.asarray(x, np.float64) @ wdeq
            rel = np.abs(np.asarray(got) - a16).max() / np.abs(a16).max()
            assert rel < 0.02

    def test_w4a8_generate_close_to_dequant(self, mesh22):
        """End-to-end serving: fused_w4a8 greedy decode must agree with the
        dequantize path on most tokens (activation rounding can flip
        near-ties, so exact equality is not the oracle)."""
        import dataclasses

        import optax

        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
        )
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
        from learning_jax_sharding_tpu.training.pipeline import sharded_train_state

        cfg = dataclasses.replace(CONFIG_TINY, quantization_group=16)
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 8)),
            jnp.int32,
        )
        x = put(np.asarray(prompt), mesh_sharding(mesh22, "data", None))
        state, _ = sharded_train_state(
            Transformer(cfg), optax.sgd(1e-2), x,
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        import flax.linen as nn

        q4p = quantize_tree(nn.meta.unbox(state.params), bits=4, group_size=16)
        with jax.default_matmul_precision("float32"):
            out_deq = np.asarray(
                make_generate_fn(
                    cfg, mesh22, RULES_DP_TP, max_new_tokens=6, dequantize=True
                )(q4p, prompt)
            )
            out_w4a8 = np.asarray(
                make_generate_fn(
                    cfg, mesh22, RULES_DP_TP, max_new_tokens=6,
                    dequantize="fused_w4a8",
                )(q4p, prompt)
            )
        # Prompt echo is exact; generated tokens agree on a majority.
        np.testing.assert_array_equal(out_w4a8[:, :8], out_deq[:, :8])
        assert (out_w4a8[:, 8:] == out_deq[:, 8:]).mean() >= 0.5

    def test_qkv_triple_matches_three_calls(self, rng):
        """ops/int4_matmul.py::int4_matmul3 — three projections of one
        input in one launch must equal three int4_matmul calls exactly
        (same unpack, same dots)."""
        from learning_jax_sharding_tpu.models.quantize import quantize_leaf_int4
        from learning_jax_sharding_tpu.ops.int4_matmul import (
            int4_matmul,
            int4_matmul3,
        )

        for m, k, n, g in [(4, 64, 48, 16), (9, 256, 128, 128)]:
            nodes = [
                quantize_leaf_int4(
                    jnp.asarray(rng.normal(size=(k, n)), jnp.float32),
                    group_size=g,
                )
                for _ in range(3)
            ]
            x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
            with jax.default_matmul_precision("float32"):
                fused = int4_matmul3(
                    x, [(nd["q4"], nd["scale"]) for nd in nodes],
                    group=min(g, k), interpret=True,
                )
                singles = [
                    int4_matmul(
                        x, nd["q4"], nd["scale"], group=min(g, k),
                        interpret=True,
                    )
                    for nd in nodes
                ]
            for got, want in zip(fused, singles):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=1e-5
                )

    def test_fused_ff_kernel_matches_two_calls(self, rng):
        """ops/int4_ff.py: the whole-FF kernel (up → GELU → down in one
        pallas call) must equal gelu(x @ deq(up)) @ deq(down) on the same
        packed values — the two-call reference it replaces."""
        from learning_jax_sharding_tpu.models.quantize import (
            dequantize_leaf_int4,
            quantize_leaf_int4,
        )
        from learning_jax_sharding_tpu.ops.int4_ff import int4_ff

        # m=4 rides one tile; m=37 exercises the prefill row tiling (block_m
        # 16 → padded non-dividing tiles — the VMEM bound for long prompts).
        for m, bm, (k, hidden, g) in [
            (4, 128, (64, 256, 16)),
            (4, 128, (128, 256, 128)),
            (37, 16, (64, 128, 64)),
        ]:
            w1 = jnp.asarray(rng.normal(size=(k, hidden)), jnp.float32)
            w2 = jnp.asarray(rng.normal(size=(hidden, k)), jnp.float32)
            n1 = quantize_leaf_int4(w1, group_size=g)
            n2 = quantize_leaf_int4(w2, group_size=g)
            x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
            with jax.default_matmul_precision("float32"):
                got = int4_ff(
                    x, n1["q4"], n1["scale"], n2["q4"], n2["scale"],
                    group=g, block_h=64, block_m=bm, interpret=True,
                )
                import flax.linen as nn

                want = nn.gelu(
                    x @ dequantize_leaf_int4(n1, jnp.float32)
                ) @ dequantize_leaf_int4(n2, jnp.float32)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-4
            )

    def test_fused_ff_generate_single_device(self, rng):
        """End to end on ONE device (the config where FeedForward routes
        through int4_ff): fused generate ≡ the dequantize path."""
        import dataclasses

        import flax.linen as nn

        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
        )
        from learning_jax_sharding_tpu.parallel import build_mesh

        mesh1 = build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1])
        cfg = dataclasses.replace(CONFIG_TINY, quantization_group=16)
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, cfg.vocab_size, (2, 8)),
            jnp.int32,
        )
        model = Transformer(cfg)
        params = nn.meta.unbox(
            jax.jit(lambda r, t: model.init({"params": r}, t))(
                jax.random.key(0), prompt
            )["params"]
        )
        q4p = quantize_tree(params, bits=4, group_size=16)
        with jax.default_matmul_precision("float32"):
            out_deq = np.asarray(
                make_generate_fn(
                    cfg, mesh1, RULES_DP_TP, max_new_tokens=6, dequantize=True
                )(q4p, prompt)
            )
            out_fused = np.asarray(
                make_generate_fn(
                    cfg, mesh1, RULES_DP_TP, max_new_tokens=6,
                    dequantize="fused",
                )(q4p, prompt)
            )
        np.testing.assert_array_equal(out_deq[:, :8], out_fused[:, :8])
        assert (out_deq[:, 8:] == out_fused[:, 8:]).mean() >= 0.5

    def test_long_odd_prefill_rows(self, rng):
        """m beyond the VMEM row budget and not a multiple of 8 (advisor
        round-2 finding: the old divisor search hit m % 0). The caller pads
        to the tile and slices, so any odd prefill length must work."""
        from learning_jax_sharding_tpu.models.quantize import (
            dequantize_leaf_int4,
            quantize_leaf_int4,
        )
        from learning_jax_sharding_tpu.ops.int4_matmul import (
            _auto_block_m,
            int4_matmul,
        )

        assert _auto_block_m(1001, 3072, 2) > 0
        k, n = 3072, 128
        w = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
        node = quantize_leaf_int4(w, group_size=128)
        x = jnp.asarray(rng.normal(size=(1001, k)), jnp.float32)
        with jax.default_matmul_precision("float32"):
            got = int4_matmul(x, node["q4"], node["scale"], interpret=True)
            want = x @ dequantize_leaf_int4(node, jnp.float32)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-3, rtol=1e-4
        )

    def test_fused_generate_matches_dequant(self, mesh22):
        import dataclasses

        import optax

        from learning_jax_sharding_tpu.models.generate import make_generate_fn
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
        )
        from learning_jax_sharding_tpu.parallel import mesh_sharding, put
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP
        from learning_jax_sharding_tpu.training.pipeline import sharded_train_state

        cfg = dataclasses.replace(CONFIG_TINY, quantization_group=16)
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)),
            jnp.int32,
        )
        x = put(np.asarray(prompt), mesh_sharding(mesh22, "data", None))
        state, _ = sharded_train_state(
            Transformer(cfg), optax.sgd(1e-2), x,
            {"params": jax.random.key(0)}, mesh22, RULES_DP_TP,
        )
        import flax.linen as nn

        q4p = quantize_tree(nn.meta.unbox(state.params), bits=4, group_size=16)
        with jax.default_matmul_precision("float32"):
            out_deq = np.asarray(
                make_generate_fn(
                    cfg, mesh22, RULES_DP_TP, max_new_tokens=6, dequantize=True
                )(q4p, prompt)
            )
            out_fused = np.asarray(
                make_generate_fn(
                    cfg, mesh22, RULES_DP_TP, max_new_tokens=6,
                    dequantize="fused",
                )(q4p, prompt)
            )
        np.testing.assert_array_equal(out_deq, out_fused)

    def test_tp_fused_never_gathers_packed_weights(self, mesh22):
        """On a TP mesh the injected shard_map (make_int4_matmul_fn) keeps
        q4 columns local (column-parallel) or replicated (row-parallel) and
        gathers only ACTIVATIONS — the compiled program must contain no
        uint8 all-gather (packed weights are the only u8 arrays)."""
        import dataclasses
        import re

        import flax.linen as nn

        from learning_jax_sharding_tpu.models.generate import make_generate_fn
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
        )
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP

        cfg = dataclasses.replace(CONFIG_TINY, quantization_group=16)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 8)),
            jnp.int32,
        )
        params = nn.meta.unbox(
            Transformer(cfg).init({"params": jax.random.key(0)}, prompt)["params"]
        )
        q4p = quantize_tree(params, bits=4, group_size=16)
        gen = make_generate_fn(
            cfg, mesh22, RULES_DP_TP, max_new_tokens=4, dequantize="fused"
        )
        hlo = gen.jitted.lower(q4p, prompt, jax.random.key(1)).compile().as_text()
        gathers = re.findall(r"\bu8\[[^\]]*\][^\n]*all-gather", hlo)
        gathers += re.findall(r"all-gather[^\n]*\bu8\[", hlo)
        assert not gathers, f"packed int4 weights gathered: {gathers[:3]}"

    def test_fused_under_fsdp_rules(self, rng):
        """FSDP maps EMBED→data, colliding with the batch axis inside one
        spec — the injected shard_map drops the weight-side entry and the
        tokens still match the single-device fused path."""
        import dataclasses

        import flax.linen as nn

        from learning_jax_sharding_tpu.models.generate import make_generate_fn
        from learning_jax_sharding_tpu.models.transformer import (
            CONFIG_TINY,
            Transformer,
        )
        from learning_jax_sharding_tpu.parallel import build_mesh
        from learning_jax_sharding_tpu.parallel.logical import RULES_DP_TP, RULES_FSDP

        cfg = dataclasses.replace(CONFIG_TINY, quantization_group=16)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 8)),
            jnp.int32,
        )
        params = nn.meta.unbox(
            Transformer(cfg).init({"params": jax.random.key(0)}, prompt)["params"]
        )
        q4p = quantize_tree(params, bits=4, group_size=16)
        with jax.default_matmul_precision("float32"):
            single = make_generate_fn(
                cfg,
                build_mesh((1, 1), ("data", "model"), devices=jax.devices()[:1]),
                RULES_DP_TP, max_new_tokens=6, dequantize="fused",
            )
            fsdp = make_generate_fn(
                cfg, build_mesh((2, 4), ("data", "model")), RULES_FSDP,
                max_new_tokens=6, dequantize="fused",
            )
            np.testing.assert_array_equal(
                np.asarray(single(q4p, prompt)), np.asarray(fsdp(q4p, prompt))
            )
